"""Core dictionary metrics.

TPU-native re-implementation of the pure-math half of the reference's
`standard_metrics.py` (model-intervention metrics live in
`metrics/intervention.py`). Every metric is a jit-friendly pure function of a
`LearnedDict` pytree + data, so they can be vmapped across a whole sweep's
dicts at once — the reference evaluates dicts one by one in Python loops
(e.g. standard_metrics.py:711-756 spins up an mp.Pool over GPUs for what is a
single vmap here).
"""

from __future__ import annotations

from typing import Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.models.learned_dict import LearnedDict, normalize_rows

Array = jax.Array


# -- reconstruction quality --------------------------------------------------

def fraction_variance_unexplained(model: LearnedDict, batch: Array) -> Array:
    """FVU = E‖x − x̂‖² / E‖x − x̄‖² (reference: standard_metrics.py:310-314)."""
    x_hat = model.predict(batch)
    residuals = jnp.mean(jnp.square(batch - x_hat))
    total = jnp.mean(jnp.square(batch - jnp.mean(batch, axis=0)))
    return residuals / total


def fvu_top_activating(model: LearnedDict, batch: Array, n_top: int = 2) -> tuple[Array, Array]:
    """FVU split into top-n-mean-activation features vs the rest
    (reference: standard_metrics.py:316-342)."""
    c = model.encode(model.center(batch))
    order = jnp.argsort(-jnp.mean(c, axis=0))
    ranks = jnp.argsort(order)
    is_top = ranks < n_top
    c_top = jnp.where(is_top, c, 0.0)
    c_rest = jnp.where(is_top, 0.0, c)
    # NOTE: the reference compares in center-transformed space (":333-334"
    # applies center to the decode output); we mirror that.
    x_hat_top = model.center(model.decode(c_top))
    x_hat_rest = model.center(model.decode(c_rest))
    variance = jnp.mean(jnp.square(batch - jnp.mean(batch, axis=0)))
    return (jnp.mean(jnp.square(batch - x_hat_top)) / variance,
            jnp.mean(jnp.square(batch - x_hat_rest)) / variance)


def r_squared(model: LearnedDict, batch: Array) -> Array:
    """(reference: standard_metrics.py:344)."""
    return 1.0 - fraction_variance_unexplained(model, batch)


# -- sparsity / activity -----------------------------------------------------

def mean_nonzero_activations(model: LearnedDict, batch: Array) -> Array:
    """Per-feature firing frequency (reference: standard_metrics.py:305-308)."""
    c = model.encode(model.center(batch))
    return jnp.mean((c != 0).astype(jnp.float32), axis=0)


def mean_l0(model: LearnedDict, batch: Array) -> Array:
    """Mean active features per sample."""
    c = model.encode(model.center(batch))
    return jnp.mean(jnp.sum((c != 0).astype(jnp.float32), axis=-1))


def calc_feature_n_active(codes: Array) -> Array:
    """(reference: standard_metrics.py:441-444)."""
    return jnp.sum(codes != 0, axis=0)


def _iter_slabs(activations, batch_size: int):
    """Uniform slab iterator over the dataset-scale metric inputs: a
    ChunkStore streams one chunk at a time (bounded memory — the reference's
    whole-dataset sweeps stream chunk files the same way,
    standard_metrics.py:711-756); an in-RAM array is a single slab. Rows left
    over when batch_size doesn't divide a chunk CARRY into the next chunk, so
    the store path consumes exactly the same floor(total/batch_size)·batch
    rows, in order, as the in-RAM path — only the final dataset-level
    remainder is dropped."""
    from sparse_coding_tpu.data.chunk_store import ChunkStore

    import numpy as np

    if isinstance(activations, ChunkStore):
        left = None
        # chunks ship as f32 on purpose: measured on the axon tunnel,
        # sub-f32 device_put takes a slow conversion path (~200 MB/s vs
        # 1.2 GB/s for f32), and the host-side f16→f32 decode is cheap
        # (torch-bridged cast, data/native_io.fast_astype).
        # chunk_reader streams the NEXT chunk from disk while the current
        # one is being encoded on device. The remainder rows carry on the
        # HOST: only whole-batch-multiple prefixes are device_put, so for
        # equal-size chunks the yielded shape takes at most TWO values
        # (⌊C/b⌋·b and (⌊C/b⌋+1)·b) and the jitted per-slab scans compile at
        # most twice — a device-side carry re-concatenated every chunk both
        # copied the full slab and grew the shape set unboundedly.
        # ONE-slab device lookahead: jnp.asarray dispatches the host→device
        # transfer asynchronously, so slab i+1 streams over the tunnel while
        # the caller's scans run on slab i (the eval-side twin of the
        # training drivers' device_prefetch; holds ≤2 slabs in HBM).
        from collections import deque

        pending: deque = deque()
        for chunk in activations.chunk_reader(range(activations.n_chunks)):
            arr = np.asarray(chunk)
            if left is not None and left.shape[0]:
                arr = np.concatenate([left, arr], axis=0)
            n = (arr.shape[0] // batch_size) * batch_size
            left = arr[n:].copy()  # not a view: don't pin the whole chunk
            if n:
                pending.append(jnp.asarray(arr[:n]))
                if len(pending) > 1:
                    yield pending.popleft()
        while pending:
            yield pending.popleft()
    else:
        yield jnp.asarray(activations)


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _count_active_scan(model: LearnedDict, acts: Array,
                       batch_size: int) -> Array:
    # jit matters here: an EAGER lax.scan re-traces per call, which at
    # dataset scale costs ~1 s/chunk vs ~ms compiled (measured on the v5e)
    n = (acts.shape[0] // batch_size) * batch_size
    batches = acts[:n].reshape(-1, batch_size, acts.shape[-1])

    def body(count, batch):
        return count + calc_feature_n_active(model.encode(batch)), None

    counts, _ = jax.lax.scan(body, jnp.zeros(model.n_feats, jnp.int32),
                             batches)
    return counts


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _activity_moments_scan(model: LearnedDict, acts: Array, batch_size: int,
                           carry):
    """One slab of BOTH metric families in a single fused scan over ONE
    shared encode: ever-active counts (as _count_active_scan) and raw-moment
    sums (as _moment_sums_scan) — both count codes of the RAW batch, exactly
    like the separate scans. One pass over the activations instead of two —
    when the input streams from a ChunkStore this halves disk reads, f16
    decodes, and host→device transfers, which the r4 isolation A/B showed
    are the whole streaming-eval gap (VERDICT r4 weak #2)."""
    n = (acts.shape[0] // batch_size) * batch_size
    batches = acts[:n].reshape(-1, batch_size, acts.shape[-1])

    def body(carry, batch):
        counts, times_active, m1, m2, m3, m4 = carry
        c = model.encode(batch)
        counts = counts + calc_feature_n_active(c)
        return (counts,
                times_active + (jnp.mean(c, axis=0) != 0).astype(jnp.float32),
                m1 + jnp.mean(c, axis=0), m2 + jnp.mean(c**2, axis=0),
                m3 + jnp.mean(c**3, axis=0), m4 + jnp.mean(c**4, axis=0)), None

    carry, _ = jax.lax.scan(body, carry, batches)
    return carry, batches.shape[0]


def streaming_eval_sweep(model: LearnedDict, activations,
                         batch_size: int = 1000, threshold: int = 10):
    """Single-pass combined dataset sweep: returns
    (n_ever_active, (times_active, mean, var, skew, kurtosis, m4)) with
    semantics identical to `n_ever_active` + `calc_moments_streaming` run
    separately, but reading the dataset ONCE."""
    zeros = jnp.zeros(model.n_feats, jnp.float32)
    carry = (jnp.zeros(model.n_feats, jnp.int32),
             zeros, zeros, zeros, zeros, zeros)
    k = 0
    for slab in _iter_slabs(activations, batch_size):
        carry, k_slab = _activity_moments_scan(model, slab, batch_size, carry)
        k += k_slab
    counts = carry[0]
    return int(jnp.sum(counts > threshold)), _finalize_moments(carry[1:], k)


def _finalize_moments(carry, k: int):
    """Raw-moment sums → (times_active, mean, var, skew, kurtosis, m4) with
    the reference's population-variance (m2 − mean²) semantics
    (standard_metrics.py:482-511). Single home for the clipped-variance
    normalization shared by calc_moments_streaming, streaming_eval_sweep and
    geometry.kurtosis_sweep."""
    if k == 0:
        from sparse_coding_tpu.resilience.errors import UndersizedInputError

        # typed (still a ValueError for old callers): the same fail-loudly-
        # on-silent-NaN contract the training guardian enforces (§16)
        raise UndersizedInputError(
            "no full batch was consumed (dataset smaller than batch_size); "
            "moment statistics would be NaN — use a batch_size <= the row "
            "count (ADVICE r5 #4)")
    times_active, m1, m2, m3, m4 = carry
    mean, m2, m3, m4 = m1 / k, m2 / k, m3 / k, m4 / k
    var = m2 - mean**2
    skew = m3 / jnp.clip(var**1.5, 1e-8)
    kurtosis = m4 / jnp.clip(var**2, 1e-8)
    return times_active, mean, var, skew, kurtosis, m4


def n_ever_active(model: LearnedDict, activations, batch_size: int = 1000,
                  threshold: int = 10) -> int:
    """Number of features active more than `threshold` times across a dataset
    (reference: standard_metrics.py:446-454), scanned in fixed-size batches.
    `activations` may be an in-RAM array OR a ChunkStore, which streams chunk
    by chunk with bounded memory (a 40×2 GB store never materializes)."""
    counts = None
    for slab in _iter_slabs(activations, batch_size):
        c = _count_active_scan(model, slab, batch_size)
        counts = c if counts is None else counts + c
    return int(jnp.sum(counts > threshold))


# -- dictionary similarity ---------------------------------------------------

def mcs_duplicates(ground: LearnedDict, model: LearnedDict) -> Array:
    """Max cosine similarity of each model atom to any ground atom
    (reference: standard_metrics.py:270-274)."""
    sims = model.get_learned_dict() @ ground.get_learned_dict().T
    return jnp.max(sims, axis=-1)


def mmcs(model: LearnedDict, model2: LearnedDict) -> Array:
    """(reference: standard_metrics.py:276-277)."""
    return jnp.mean(mcs_duplicates(model2, model))


def mcs_to_fixed(model: LearnedDict, truth: Array) -> Array:
    """Max cos-sim of each model atom to a fixed (already normalized)
    ground-truth dictionary (reference: standard_metrics.py:279-282)."""
    sims = model.get_learned_dict() @ truth.T
    return jnp.max(sims, axis=-1)


def mmcs_to_fixed(model: LearnedDict, truth: Array) -> Array:
    return jnp.mean(mcs_to_fixed(model, truth))


def mmcs_from_list(dicts: Sequence[LearnedDict]) -> Array:
    """Symmetric pairwise MMCS matrix (reference: standard_metrics.py:287-297)."""
    n = len(dicts)
    out = np.eye(n, dtype=np.float32)
    for i in range(n):
        for j in range(i):
            out[i, j] = out[j, i] = float(mmcs(dicts[i], dicts[j]))
    return jnp.asarray(out)


def representedness(features: Array, model: LearnedDict) -> Array:
    """How well each ground-truth feature is represented in the dict
    (reference: standard_metrics.py:299-303)."""
    sims = features @ model.get_learned_dict().T
    return jnp.max(sims, axis=-1)


def hungarian_mcs(smaller: Array, larger: Array) -> Array:
    """One-to-one matched cosine similarities between a smaller and a larger
    dictionary via the Hungarian algorithm
    (reference: standard_metrics.py:811-842 `run_mmcs_with_larger` core)."""
    from scipy.optimize import linear_sum_assignment

    sims = np.asarray(normalize_rows(smaller) @ normalize_rows(larger).T)
    row, col = linear_sum_assignment(1.0 - sims)
    return jnp.asarray(sims[row, col])


def mmcs_with_larger_grid(learned_dict_grid: Sequence[Sequence[Array]],
                          threshold: float = 0.9):
    """For a [n_l1, n_sizes] grid of dictionaries, Hungarian-match each dict to
    the next-larger dict (reference: standard_metrics.py:811-842). Returns
    (mean mcs grid, % feats above threshold, per-cell similarity arrays)."""
    n_l1 = len(learned_dict_grid)
    n_sizes = len(learned_dict_grid[0])
    av = np.zeros((n_l1, n_sizes))
    above = np.zeros((n_l1, n_sizes))
    hists: list[list[Optional[np.ndarray]]] = [[None] * (n_sizes - 1) for _ in range(n_l1)]
    for i in range(n_l1):
        for j in range(n_sizes - 1):
            sims = np.asarray(hungarian_mcs(learned_dict_grid[i][j],
                                            learned_dict_grid[i][j + 1]))
            av[i, j] = sims.mean()
            above[i, j] = (sims > threshold).sum() / len(sims) * 100.0
            hists[i][j] = sims
    return av, above, hists


# -- feature statistics ------------------------------------------------------

def feature_moments(codes: Array) -> dict[str, Array]:
    """Per-feature mean/var and the reference's asymmetric (uncentered,
    variance-normalized) skew/kurtosis (standard_metrics.py:456-479)."""
    mean = jnp.mean(codes, axis=0)
    var = jnp.var(codes, axis=0, ddof=1)
    skew = jnp.mean(codes**3, axis=0) / jnp.clip(var**1.5, 1e-8)
    kurtosis = jnp.mean(codes**4, axis=0) / jnp.clip(var**2, 1e-8)
    return {"mean": mean, "var": var, "skew": skew, "kurtosis": kurtosis}


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _moment_sums_scan(model: LearnedDict, acts: Array, batch_size: int,
                      carry):
    """One slab's worth of the moment accumulation (jitted scan), threading
    the (times_active, m1..m4 sums) carry across slabs."""
    n = (acts.shape[0] // batch_size) * batch_size
    batches = acts[:n].reshape(-1, batch_size, acts.shape[-1])

    def body(carry, batch):
        times_active, m1, m2, m3, m4 = carry
        c = model.encode(batch)
        times_active = times_active + (jnp.mean(c, axis=0) != 0).astype(jnp.float32)
        return (times_active,
                m1 + jnp.mean(c, axis=0), m2 + jnp.mean(c**2, axis=0),
                m3 + jnp.mean(c**3, axis=0), m4 + jnp.mean(c**4, axis=0)), None

    carry, _ = jax.lax.scan(body, carry, batches)
    return carry, batches.shape[0]


def calc_moments_streaming(model: LearnedDict, activations,
                           batch_size: int = 1000):
    """Streaming raw-moment accumulation over a dataset, one jitted scan per
    slab (reference: standard_metrics.py:482-511). Returns
    (times_active, mean, var, skew, kurtosis, m4) with the reference's
    population-variance (m2 − mean²) semantics. `activations` may be an
    in-RAM array OR a ChunkStore (streams chunk by chunk, bounded memory)."""
    zeros = jnp.zeros(model.n_feats, jnp.float32)
    carry = (zeros, zeros, zeros, zeros, zeros)
    k = 0
    for slab in _iter_slabs(activations, batch_size):
        carry, k_slab = _moment_sums_scan(model, slab, batch_size, carry)
        k += k_slab
    return _finalize_moments(carry, k)


# -- geometry ----------------------------------------------------------------

def neurons_per_feature(model: LearnedDict) -> Array:
    """Mean inverse Simpson index of |dict| rows
    (reference: standard_metrics.py:347-352)."""
    d = model.get_learned_dict()
    d = d / jnp.sum(jnp.abs(d), axis=-1, keepdims=True)
    simpson = jnp.sum(jnp.square(d), axis=-1)
    return jnp.mean(1.0 / simpson)


def capacity_per_feature(model: LearnedDict) -> Array:
    """Scherlis et al. 2022 capacity: ‖dᵢ‖⁴ / Σⱼ⟨dᵢ,dⱼ⟩²
    (reference: standard_metrics.py:356-362)."""
    d = model.get_learned_dict()
    sq_dots = jnp.square(d @ d.T)
    return jnp.diag(sq_dots) / jnp.sum(sq_dots, axis=-1)


# -- supervised probes -------------------------------------------------------

def logistic_regression_auroc(activations: Array, labels: Array, **kwargs) -> float:
    """(reference: standard_metrics.py:254-260; sklearn on host, as the
    reference does)."""
    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import roc_auc_score

    x = np.asarray(activations)
    y = np.asarray(labels)
    clf = LogisticRegression(**kwargs).fit(x, y)
    return float(roc_auc_score(y, clf.decision_function(x)))


def ridge_regression_auroc(activations: Array, labels: Array, **kwargs) -> float:
    """(reference: standard_metrics.py:262-268)."""
    from sklearn.linear_model import RidgeClassifier
    from sklearn.metrics import roc_auc_score

    x = np.asarray(activations)
    y = np.asarray(labels)
    clf = RidgeClassifier(**kwargs).fit(x, y)
    return float(roc_auc_score(y, clf.decision_function(x)))
