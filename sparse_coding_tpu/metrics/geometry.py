"""Dictionary-geometry analyses: clustering and activity sweeps.

Covers the remaining standard_metrics.py surface:
- `cluster_vectors` (t-SNE + KMeans over dictionary atoms,
  reference: standard_metrics.py:534-568),
- `hierarchical_cluster_vectors` (reference: :570-580),
- `activity_sweep` — the per-layer dead/active-feature census the reference
  runs with an mp.Pool over GPUs (`calc_for_layer`/`calc_all_activities`,
  reference: :711-756) collapsed into one jitted scan per dict,
- `kurtosis_sweep` (reference: calc_kurtosis_for_layer/calc_all_kurtosis,
  :758-809).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.models.learned_dict import LearnedDict
from sparse_coding_tpu.utils.artifacts import load_learned_dicts


def cluster_vectors(model: LearnedDict, n_clusters: int = 100,
                    top_clusters: int = 10, perplexity: float = 30.0,
                    seed: int = 0,
                    save_loc: Optional[str | Path] = None) -> list[list[int]]:
    """t-SNE embed dictionary atoms, KMeans them, return the largest clusters'
    member indices (reference: standard_metrics.py:534-568)."""
    from sklearn.cluster import KMeans
    from sklearn.manifold import TSNE

    d = np.asarray(jax.device_get(model.get_learned_dict()))
    n = d.shape[0]
    perplexity = min(perplexity, max(2.0, (n - 1) / 3))
    emb = TSNE(n_components=2, perplexity=perplexity,
               random_state=seed).fit_transform(d)
    n_clusters = min(n_clusters, n)
    km = KMeans(n_clusters=n_clusters, random_state=seed, n_init=4).fit(emb)
    clusters: dict[int, list[int]] = {}
    for idx, label in enumerate(km.labels_):
        clusters.setdefault(int(label), []).append(idx)
    largest = sorted(clusters.values(), key=len, reverse=True)[:top_clusters]
    if save_loc is not None:
        Path(save_loc).parent.mkdir(parents=True, exist_ok=True)
        with open(save_loc, "w") as fh:
            for ci, members in enumerate(largest):
                fh.write(f"cluster {ci} (n={len(members)}): {members}\n")
    return largest


def hierarchical_cluster_vectors(vectors, n_clusters: int = 100) -> np.ndarray:
    """Agglomerative clustering labels over atom vectors
    (reference: standard_metrics.py:570-580)."""
    from sklearn.cluster import AgglomerativeClustering

    v = np.asarray(jax.device_get(vectors))
    n_clusters = min(n_clusters, v.shape[0])
    return AgglomerativeClustering(n_clusters=n_clusters).fit(v).labels_


def activity_sweep(dict_files: Sequence[str | Path], activations,
                   threshold: int = 10, batch_size: int = 1000) -> list[dict]:
    """Ever-active feature counts for every dict across artifact files — the
    reference's multi-GPU mp.Pool census (standard_metrics.py:711-756) as a
    serial loop of jitted scans. `activations` may be an array or a
    ChunkStore — the store path streams chunk by chunk per dict (bounded
    memory; re-reads ride the OS page cache across dicts)."""
    acts = (activations if _is_store(activations)
            else jnp.asarray(activations))
    dicts = [(ld, hyper, str(path), j) for path in dict_files
             for j, (ld, hyper) in enumerate(load_learned_dicts(path))]
    if not dicts:
        return []
    # chunk-outer / dict-inner: the store streams ONCE for the whole census
    # (disk + decode + transfer paid per chunk, not per dict); each dict's
    # jitted scan reuses the resident device slab. The reference re-reads
    # per (layer, dict) and hides it behind an mp.Pool of GPUs.
    from sparse_coding_tpu.metrics.core import _count_active_scan, _iter_slabs

    counts: list = [None] * len(dicts)
    for slab in _iter_slabs(acts, batch_size):
        for i, (ld, _, _, _) in enumerate(dicts):
            c = _count_active_scan(ld, slab, batch_size)
            counts[i] = c if counts[i] is None else counts[i] + c
    out = []
    for (ld, hyper, path, member), c in zip(dicts, counts):
        out.append({
            **{k: v for k, v in hyper.items()
               if isinstance(v, (int, float, str, bool))},
            "n_ever_active": int(jnp.sum(c > threshold)),
            "n_feats": int(ld.n_feats),
            # provenance so multi-file censuses can be partitioned back
            # (plotting/timeseries.py runs ONE census over all snapshots)
            "artifact": path,
            "member": member,
        })
    return out


def _is_store(activations) -> bool:
    from sparse_coding_tpu.data.chunk_store import ChunkStore

    return isinstance(activations, ChunkStore)


def kurtosis_sweep(dict_files: Sequence[str | Path], activations,
                   batch_size: int = 1000) -> list[dict]:
    """Per-dict feature-kurtosis summaries (reference:
    calc_kurtosis_for_layer, standard_metrics.py:758-809). `activations` may
    be an array or a ChunkStore (streamed, bounded memory)."""
    acts = (activations if _is_store(activations)
            else jnp.asarray(activations))
    dicts = [(ld, hyper) for path in dict_files
             for ld, hyper in load_learned_dicts(path)]
    if not dicts:
        return []
    # chunk-outer / dict-inner, one streaming pass for all dicts (see
    # activity_sweep)
    from sparse_coding_tpu.metrics.core import (
        _finalize_moments,
        _iter_slabs,
        _moment_sums_scan,
    )

    def zero_carry(ld):
        z = jnp.zeros(ld.n_feats, jnp.float32)
        return (z, z, z, z, z)

    carries = [zero_carry(ld) for ld, _ in dicts]
    k = 0
    for slab in _iter_slabs(acts, batch_size):
        for i, (ld, _) in enumerate(dicts):
            carries[i], k_slab = _moment_sums_scan(ld, slab, batch_size,
                                                   carries[i])
        k += k_slab
    out = []
    for (ld, hyper), carry in zip(dicts, carries):
        _, _, _, skew, kurt, _ = _finalize_moments(carry, k)
        out.append({
            **{k2: v for k2, v in hyper.items()
               if isinstance(v, (int, float, str, bool))},
            "mean_kurtosis": float(jnp.mean(kurt)),
            "median_kurtosis": float(jnp.median(kurt)),
            "mean_skew": float(jnp.mean(skew)),
        })
    return out
