"""Priority bin-packing of fleet runs onto mesh slices.

The fleet scheduler (pipeline/fleet.py) owns a pod's mesh carved into
``n_slices`` equal slices — the unit a run requests (a tenant's sweep
asking for 2 slices is asking for 2/n of the pod). This module is the
placement BRAIN and nothing else: a pure function from (run states,
slice count, concurrency cap) to the actions the scheduler should take
this tick. No clocks, no I/O, no randomness — tests drive it exactly,
and a replayed queue always re-derives the same plan
(docs/ARCHITECTURE.md §18).

Rules, in order:

- **priority classes** are ``serve/slo.py``'s ladder — the fleet and the
  serving front door mean the same thing by ``interactive`` >
  ``batch`` > ``scavenger`` (ties broken by enqueue order, so the plan
  is total-ordered and deterministic);
- **first-fit, no backfill**: queued runs are considered strictly in
  that order, and the first run that cannot start BLOCKS every run
  behind it. Backfilling small low-priority runs around a blocked big
  one would starve it forever on a busy pod — a blocked head run
  instead drains the pod until it fits;
- **preemption, scavenger-only victims**: when the blocked head run is
  ``interactive`` or ``batch``, running scavenger runs are SIGTERMed at
  their next chunk boundary (resilience/preempt.py — the checkpoint
  path, never a kill), most-recently-placed first, until the head run
  would fit. Preempted slices free only when the worker actually exits
  (the scheduler re-queues the run), so a preemption tick plans
  victims, and a later tick places the beneficiary;
- ``max_concurrent`` caps simultaneously-running workers below the
  slice count — this container admits ONE jax process at a time
  (CLAUDE.md), so its fleet runs with ``max_concurrent=1`` over any
  logical slice count, the same DAG a pod runs wide.
"""

from __future__ import annotations

from dataclasses import dataclass

from sparse_coding_tpu.serve.slo import SCAVENGER, priority_rank

# queue-replay run states (pipeline/fleet.py fold): the planner only
# reads these; every transition is a durable queue record
QUEUED = "queued"
PLACED = "placed"
PREEMPTING = "preempting"
TERMINAL = ("done", "halted", "failed")


@dataclass(frozen=True)
class RunState:
    """One run as the queue replay sees it."""

    name: str
    priority: str
    slices: int
    state: str
    seq: int          # first-enqueue order (the FIFO tiebreak)
    placed_seq: int = 0   # seq of the latest place record (victim order)
    attempts: int = 0     # how many place records the run has consumed
    # crash-requeue count ONLY (release outcome "requeued"): preemptions
    # and scheduler-restart reclaims are scheduling events, not failures,
    # and must never burn the run's crash-retry budget
    requeues: int = 0


@dataclass(frozen=True)
class PlacementPlan:
    """One tick's actions, in execution order."""

    place: tuple[str, ...]
    preempt: tuple[str, ...]
    blocked: tuple[str, ...]  # queued runs that could not start this tick


def plan_placement(runs: list[RunState], n_slices: int,
                   max_concurrent: int = 0) -> PlacementPlan:
    """The one placement decision. ``max_concurrent=0`` means "slice
    count is the only cap". Runs whose request can NEVER fit
    (``slices > n_slices``) are not planned — the scheduler fails them
    at enqueue validation, so here they simply block."""
    n_slices = int(n_slices)
    cap = int(max_concurrent) or n_slices
    active = [r for r in runs if r.state in (PLACED, PREEMPTING)]
    used = sum(r.slices for r in active)
    running = len(active)
    queued = sorted((r for r in runs if r.state == QUEUED),
                    key=lambda r: (priority_rank(r.priority), r.seq))

    place: list[str] = []
    preempt: list[str] = []
    blocked: list[str] = []
    # scavenger victims, most-recently-placed first; PREEMPTING runs are
    # already on their way out and must not be signaled twice
    victims = sorted((r for r in active
                      if r.state == PLACED and r.priority == SCAVENGER),
                     key=lambda r: -r.placed_seq)
    for run in queued:
        if blocked:
            blocked.append(run.name)  # no backfill behind a blocked head
            continue
        if used + run.slices <= n_slices and running < cap:
            place.append(run.name)
            used += run.slices
            running += 1
            continue
        if priority_rank(run.priority) < priority_rank(SCAVENGER):
            # drain scavengers until this head run WOULD fit (capacity
            # and concurrency); placement happens on a later tick, once
            # the preempted workers have checkpointed and exited.
            # Futility guard first: if draining EVERY scavenger still
            # could not fit the head run (capacity- or slot-wise), plan
            # no victims at all — SIGTERMing useful work that frees
            # nothing the head can use is pure loss
            need = used + run.slices - n_slices
            reclaimable = sum(v.slices for v in victims)
            if reclaimable >= need and running - len(victims) < cap:
                freed = 0
                while victims and (freed < need or running >= cap):
                    victim = victims.pop(0)
                    preempt.append(victim.name)
                    freed += victim.slices
                    running -= 1
        blocked.append(run.name)
    return PlacementPlan(place=tuple(place), preempt=tuple(preempt),
                         blocked=tuple(blocked))
