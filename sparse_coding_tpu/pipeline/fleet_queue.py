"""Durable fleet run queue: the scheduler's only memory.

One append-only ``fleet_queue.jsonl`` per fleet dir, carried by the same
atomic-append :class:`~sparse_coding_tpu.pipeline.journal.RunJournal`
machinery the per-run supervisor journal uses and the same
bitwise-replay discipline as ``data/ledger.py``: every run transition is
appended BEFORE the scheduler acts on it, records carry no wall-clock-
derived identity, and :func:`FleetQueue.replay` folds the file into the
exact same :class:`~sparse_coding_tpu.pipeline.placement.RunState` map
however many scheduler processes died along the way. The chaos matrix
SIGKILLs a real scheduler between a ``run.place`` record and the worker
spawn (crash barrier ``fleet.place``) and asserts exactly that — no run
lost, none double-placed (tests/test_pipeline_chaos.py).

Queue events (``step`` carries the run name):

=================  ========================================================
``run.enqueue``    a new run + its spec (priority, slices, kind, config);
                   re-enqueueing a known name is an idempotent no-op
``run.place``      the scheduler decided to spawn this run's worker; the
                   record is durable BEFORE the spawn (``fleet.place``
                   crash barrier sits between the two)
``run.preempt``    a SIGTERM is on its way to the run's worker (chunk-
                   boundary checkpoint path, resilience/preempt.py)
``run.release``    the placement ended: ``outcome`` ∈ done | halted |
                   failed (terminal) or preempted | reclaimed | requeued
                   (back to the queue)
``scheduler.*``    scheduler lifecycle breadcrumbs (start, takeover,
                   stale_kill, done) — ignored by the replay fold
=================  ========================================================

Spec schema (the ``run.enqueue`` record's ``spec``): ``priority``
(serve/slo.py class), ``slices`` (mesh-slice request), ``kind``
(``flat`` | ``sharded`` — pipeline/supervisor.py builders over
``config`` — or ``command``: a single resumable step from ``argv`` +
``done_path``, the cheap-child form the fleet unit tests drive), ``env``
(per-tenant step environment, e.g. a drill's fault plan), and
``max_attempts`` for the per-run worker's supervisor.

Import chain is jax-free (journal + placement + serve/slo constants):
``obs.report``'s fleet section replays the queue from a host with a
wedged TPU tunnel.
"""

from __future__ import annotations

import fcntl
import time
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from pathlib import Path

from sparse_coding_tpu.pipeline.journal import RunJournal
from sparse_coding_tpu.pipeline.placement import (
    PLACED,
    PREEMPTING,
    QUEUED,
    TERMINAL,
    RunState,
)
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.serve.slo import BATCH, priority_rank

QUEUE_NAME = "fleet_queue.jsonl"
RUN_KINDS = ("flat", "sharded", "group", "command")

register_fault_site("fleet.enqueue",
                    "fleet queue admission — the durable run.enqueue "
                    "append (pipeline/fleet_queue.py); an injected error "
                    "propagates to the caller with the queue untouched, "
                    "so a retried enqueue is byte-identical to a "
                    "never-failed one")


@dataclass
class FleetState:
    """One replayed queue: placement-facing run states + the specs the
    per-run workers build their pipelines from."""

    runs: dict[str, RunState] = field(default_factory=dict)
    specs: dict[str, dict] = field(default_factory=dict)
    # torn/corrupt queue lines skipped by the replay fold (scan_records
    # contract) — nonzero after a crash mid-append; fsck reports the tail
    skipped_lines: int = 0

    def terminal(self) -> bool:
        return all(r.state in TERMINAL for r in self.runs.values())

    def summary(self) -> dict[str, str]:
        return {name: r.state for name, r in sorted(self.runs.items())}


def validate_spec(name: str, spec: dict, n_slices: int) -> dict:
    """Front-door validation (everything downstream trusts the queue):
    returns the normalized spec or raises ``ValueError``."""
    if not name or not all(c.isalnum() or c in "._-" for c in name):
        raise ValueError(f"run name {name!r} must be non-empty and use "
                         "only [A-Za-z0-9._-] (it names files)")
    spec = dict(spec)
    priority_rank(spec.setdefault("priority", BATCH))  # raises on unknown
    slices = int(spec.setdefault("slices", 1))
    if not 1 <= slices <= int(n_slices):
        raise ValueError(f"run {name!r} requests {slices} slice(s); this "
                         f"fleet has {n_slices} — it could never place")
    kind = spec.setdefault("kind", "flat")
    if kind not in RUN_KINDS:
        raise ValueError(f"unknown run kind {kind!r} "
                         f"(supported: {RUN_KINDS})")
    if kind == "command":
        if not spec.get("argv") or not spec.get("done_path"):
            raise ValueError("kind='command' runs need argv and done_path")
    elif not isinstance(spec.get("config"), dict):
        raise ValueError(f"kind={kind!r} runs need a config dict "
                         "(pipeline/steps.py schema)")
    spec.setdefault("env", {})
    spec.setdefault("max_attempts", 2)
    # the worker Supervisor's hang window (pipeline/fleet.py run_worker)
    spec["heartbeat_stale_s"] = float(
        spec.setdefault("heartbeat_stale_s", 120.0))
    return spec


class FleetQueue:
    """Writer+reader for one fleet dir's queue file."""

    def __init__(self, path: str | Path, clock=time.time):
        self.journal = RunJournal(path, clock=clock)
        self.path = Path(path)

    @contextmanager
    def _locked(self):
        """Same-host append serialization: the journal's atomic append is
        read+rewrite, and the queue — unlike a per-run journal — has TWO
        legitimate writers (the live scheduler, and an operator enqueueing
        into a running fleet). An flock sidecar makes concurrent appends
        lose nothing; readers need no lock (the rewrite is atomic)."""
        lock_path = self.path.with_suffix(self.path.suffix + ".lock")
        with open(lock_path, "w") as fh:
            fcntl.flock(fh, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh, fcntl.LOCK_UN)

    def append(self, event: str, run: str = "", **detail) -> dict:
        with self._locked():
            return self.journal.append(event, run, **detail)

    def enqueue(self, name: str, spec: dict, n_slices: int) -> bool:
        """Admit one run; idempotent (a known name is left untouched, so
        an enqueue-then-crash caller can blindly re-enqueue). Fault site
        ``fleet.enqueue`` fires BEFORE the durable append."""
        spec = validate_spec(name, spec, n_slices)
        fault_point("fleet.enqueue")
        with self._locked():
            if name in self.replay().runs:
                return False
            self.journal.append("run.enqueue", name, spec=spec)
        return True

    def replay(self) -> FleetState:
        """Fold the queue file into the current state — the ONLY way any
        scheduler (first, restarted, or taken-over) knows the fleet.
        Torn-tail safe: a crash mid-append can leave an unterminated final
        line that still PARSES as JSON (a truncated ``{"seq": 12}`` reads
        as ``{"seq": 1}``), so the fold uses the strict newline-terminated
        reader and counts what it skipped instead of folding it."""
        recs, skipped = self.journal.scan_records()
        st = FleetState(skipped_lines=skipped)
        for rec in recs:
            event = rec.get("event", "")
            name = rec.get("step", "")
            detail = rec.get("detail", {}) or {}
            if event == "run.enqueue":
                if name in st.runs:
                    continue  # idempotent re-enqueue
                spec = detail.get("spec", {})
                st.specs[name] = spec
                st.runs[name] = RunState(
                    name=name, priority=spec.get("priority", BATCH),
                    slices=int(spec.get("slices", 1)), state=QUEUED,
                    seq=int(rec.get("seq", 0)))
            elif name not in st.runs:
                continue  # scheduler.* breadcrumbs and operator edits
            elif event == "run.place":
                st.runs[name] = replace(
                    st.runs[name], state=PLACED,
                    placed_seq=int(rec.get("seq", 0)),
                    attempts=st.runs[name].attempts + 1)
            elif event == "run.preempt":
                if st.runs[name].state == PLACED:
                    st.runs[name] = replace(st.runs[name], state=PREEMPTING)
            elif event == "run.release":
                outcome = str(detail.get("outcome", "failed"))
                new = outcome if outcome in TERMINAL else QUEUED
                st.runs[name] = replace(
                    st.runs[name], state=new,
                    requeues=st.runs[name].requeues
                    + (1 if outcome == "requeued" else 0))
        return st
