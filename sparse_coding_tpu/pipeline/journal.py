"""Append-only run journal: the supervisor's single source of truth.

Crash-only design rule: the supervisor keeps NO state in memory that it
cannot rebuild from disk, because the supervisor itself may be SIGKILLed
between any two instructions. Every observable step transition (spawned,
done, killed, failed, hung, lease takeover) is appended here *before* the
supervisor acts on it, so a restarted supervisor replays the journal and
continues exactly where the dead one stopped.

Appends are atomic (read + append + tmp/fsync/rename via
:mod:`resilience.atomic`): a reader — including a concurrently restarted
supervisor — only ever sees a complete journal, never a torn tail line.
Journals are small (a handful of records per step), so the rewrite-append
costs nothing measurable; in exchange there is no partial-line recovery
code to test.

Truth hierarchy on restart: *artifacts beat the journal*. A "done" record
whose completion artifact is missing means the artifact's durability
raced the record — the step re-runs (it is resumable by contract); the
journal is how the supervisor explains itself, the filesystem is what it
trusts.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.resilience.atomic import atomic_write_bytes


class RunJournal:
    """One journal file (``journal.jsonl``) for one pipeline run dir."""

    def __init__(self, path: str | Path, clock=time.time, run_id: str = ""):
        self.path = Path(path)
        self._clock = clock
        # correlation (docs/ARCHITECTURE.md §12): journal records carry
        # the run ID the supervisor minted, joining them with the obs
        # event stream and the child steps' lease beats
        self.run_id = run_id
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def append(self, event: str, step: str = "", **detail) -> dict:
        rec = {"seq": self._next_seq(), "ts": self._clock(),
               "pid": os.getpid(), "event": event, "step": step}
        if self.run_id:
            rec["run"] = self.run_id
        if detail:
            rec["detail"] = detail
        existing = self.path.read_bytes() if self.path.exists() else b""
        if existing and not existing.endswith(b"\n"):
            # an operator-edited journal may lack the trailing newline; a
            # new record must never merge into (and thus corrupt) that line
            existing += b"\n"
        atomic_write_bytes(self.path,
                           existing + json.dumps(rec).encode() + b"\n")
        return rec

    def records(self) -> list[dict]:
        """All records, oldest first. Tolerant of a malformed line (cannot
        happen under the atomic append, but a journal is also an operator-
        edited artifact during incident response — never die over it).
        Unlike :meth:`scan_records` this accepts an unterminated final
        line: an operator edit may legitimately drop the trailing newline,
        and the appender must still see that record to continue seq."""
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_bytes().splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    def scan_records(self) -> tuple[list[dict], int]:
        """``(records, skipped_lines)`` under the obs event readers'
        torn-tail contract (obs/sink.py::scan_events): only newline-
        terminated, JSON-parsing dict lines count; an unterminated tail
        is skipped and counted, never folded. The distinction matters
        because a TRUNCATED json line can still parse as valid JSON
        (``{"seq": 12}`` torn to ``{"seq": 1}``) — any reader folding the
        journal into state (fleet queue replay, fsck) must use this, not
        :meth:`records`."""
        if not self.path.exists():
            return [], 0
        raw = self.path.read_bytes()
        out: list[dict] = []
        skipped = 0
        if not raw:
            return out, skipped
        lines = raw.split(b"\n")
        torn_tail = lines.pop()  # b"" when the last append committed
        if torn_tail:
            skipped += 1
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
            else:
                skipped += 1
        return out, skipped

    def _next_seq(self) -> int:
        recs = self.records()
        return recs[-1]["seq"] + 1 if recs else 1

    def last_event(self, step: str) -> Optional[dict]:
        for rec in reversed(self.records()):
            if rec.get("step") == step:
                return rec
        return None

    def step_events(self, step: str) -> list[dict]:
        return [r for r in self.records() if r.get("step") == step]

    def done_steps(self) -> set[str]:
        return {r["step"] for r in self.records()
                if r.get("event") == "step.done"}
