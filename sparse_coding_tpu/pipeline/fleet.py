"""Fleet scheduler: many tenants' runs bin-packed onto one pod.

The crash-only supervisor (pipeline/supervisor.py) runs ONE
harvest→sweep→eval chain; production is many tenants' sweeps, scrubs,
and evals sharing the hardware. This module is the pod-scale successor
of the reference's ``cluster_runs.py`` ``dispatch_job_on_chunk``
one-GPU-per-job loop (PAPER.md §1 L4), built on the reliability
substrate the prior rounds established (docs/ARCHITECTURE.md §18):

- a **durable run queue** (:mod:`pipeline.fleet_queue` — atomic appends,
  bitwise replay) is the scheduler's ONLY memory: a restarted or
  taken-over scheduler folds the queue file and continues exactly;
- **placement** is :mod:`pipeline.placement`'s pure priority bin-packing
  over ``serve/slo.py``'s interactive/batch/scavenger classes; scavenger
  runs are preempted for higher classes via SIGTERM at chunk boundaries
  (resilience/preempt.py — a checkpoint, never a kill);
- each placed run gets a **per-run worker** subprocess (``python -m
  sparse_coding_tpu.pipeline.fleet worker``): a plain Supervisor over
  the run's OWN dir (``runs/<name>/`` — own journal, leases, obs stream,
  guardian ledger), so every per-run reliability contract the repo
  already proves keeps holding per tenant;
- **containment** is the headline: a tenant whose guardian halts
  (rollback ladder exhausted on poisoned data, §16) exits typed
  (``STEP_EXIT_HALTED``), the scheduler marks the run ``halted``,
  re-packs the freed slice, and every other tenant's work — and the
  serving pool — never notices;
- tenants SHARE one executable cache (``<fleet_dir>/xcache``, §13):
  tenant N+1's sweep warm-starts at zero backend compiles from the
  executables tenant N compiled ("Compiler-First ... Portable O(1)
  Autoregressive Caching", PAPERS.md — compile-once, serve-everyone);
- scheduler-level failure is itself in the harness: fault sites
  ``fleet.enqueue`` / ``fleet.place`` / ``fleet.preempt`` and the crash
  barrier ``fleet.place`` between queue durability and the worker spawn
  (SIGKILL there → restart replays the queue bitwise, no run lost or
  double-placed — tests/test_pipeline_chaos.py).

This container admits one jax process at a time (CLAUDE.md), so its
fleets run ``max_concurrent=1`` — the same queue, placement, and
containment logic a pod runs wide. The module's import chain is
jax-free: the scheduler process never touches the TPU tunnel its worker
children own.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional

from sparse_coding_tpu import obs
from sparse_coding_tpu.pipeline.fleet_queue import (
    QUEUE_NAME,
    FleetQueue,
    FleetState,
)
from sparse_coding_tpu.pipeline.placement import (
    PLACED,
    PREEMPTING,
    QUEUED,
    plan_placement,
)
from sparse_coding_tpu.pipeline.supervisor import (
    REPO_ROOT,
    STEP_EXIT_HALTED,
    STEP_EXIT_PREEMPTED,
    ConcurrentSupervisorError,
    StepHalted,
    StepPreempted,
    Supervisor,
    _kill_pid,
    build_pipeline,
    build_sharded_pipeline,
)
from sparse_coding_tpu.resilience import lease as lease_mod
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.lease import (
    Lease,
    lease_state,
    read_lease,
    seed_lease,
)
from sparse_coding_tpu.resilience.preempt import PreemptionGuard
from sparse_coding_tpu.serve.slo import SCAVENGER

register_fault_site("fleet.place",
                    "fleet placement decision — fires before the durable "
                    "run.place append (pipeline/fleet.py); an injected "
                    "error leaves the run queued and counted "
                    "(fleet.place_errors), re-planned next tick")
register_fault_site("fleet.preempt",
                    "fleet preemption — fires before the run.preempt "
                    "append + SIGTERM (pipeline/fleet.py); an injected "
                    "error leaves the victim running and counted "
                    "(fleet.preempt_errors), re-planned next tick")
register_crash_site("fleet.place",
                    "run.place queue record durable, the worker not yet "
                    "spawned (pipeline/fleet.py) — the no-run-lost/"
                    "none-double-placed instant")

# worker exit codes mirror the step codes (the worker's supervisor maps
# child exits onto typed errors; the worker maps those back to its own
# exit status for the scheduler)
WORKER_EXIT_PREEMPTED = STEP_EXIT_PREEMPTED
WORKER_EXIT_HALTED = STEP_EXIT_HALTED

SCHEDULER_LEASE = "fleet.json"


def worker_lease_path(fleet_dir: str | Path, name: str) -> Path:
    return Path(fleet_dir) / "leases" / f"run-{name}.json"


def run_dir_for(fleet_dir: str | Path, name: str) -> Path:
    return Path(fleet_dir) / "runs" / name


class FleetScheduler:
    """Run the fleet dir's queue to completion. Construction is cheap and
    disk-stateless; ``run()`` on a fresh instance over an old fleet dir
    IS the restart path (crash-only, like the supervisor it spawns)."""

    def __init__(self, fleet_dir: str | Path, *, n_slices: int = 1,
                 max_concurrent: int = 1, max_run_attempts: int = 2,
                 heartbeat_stale_s: float = 120.0, poll_s: float = 0.25,
                 max_wall_s: Optional[float] = None, clock=time.time):
        self.fleet_dir = Path(fleet_dir)
        self.n_slices = int(n_slices)
        self.max_concurrent = int(max_concurrent)
        self.max_run_attempts = int(max_run_attempts)
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.poll_s = float(poll_s)
        self.max_wall_s = max_wall_s
        self._clock = clock
        self.queue = FleetQueue(self.fleet_dir / QUEUE_NAME, clock=clock)
        self._workers: dict[str, subprocess.Popen] = {}
        self._sink: Optional[obs.EventSink] = None
        self._lease: Optional[Lease] = None
        for sub in ("leases", "logs", "runs", "obs"):
            (self.fleet_dir / sub).mkdir(parents=True, exist_ok=True)

    # -- queue front door -----------------------------------------------------

    def enqueue(self, name: str, config: Optional[dict] = None, *,
                priority: str = "batch", slices: int = 1,
                kind: str = "flat", env: Optional[dict] = None,
                max_attempts: int = 2, argv: Optional[list] = None,
                done_path: Optional[str | Path] = None,
                heartbeat_stale_s: Optional[float] = None) -> bool:
        """Admit one tenant run (idempotent on a known name). ``env``
        rides into every step of the run's pipeline — a tenant-scoped
        fault plan in a drill, a tenant's credentials in production.
        ``heartbeat_stale_s`` sets the worker Supervisor's hang window
        for this run's step children; it defaults to THIS scheduler's
        window so the two watchdog layers stay aligned."""
        spec = {"priority": priority, "slices": int(slices), "kind": kind,
                "env": dict(env or {}), "max_attempts": int(max_attempts),
                "heartbeat_stale_s": float(
                    heartbeat_stale_s if heartbeat_stale_s is not None
                    else self.heartbeat_stale_s)}
        if config is not None:
            spec["config"] = config
        if argv is not None:
            spec["argv"] = [str(a) for a in argv]
        if done_path is not None:
            spec["done_path"] = str(done_path)
        return self.queue.enqueue(name, spec, self.n_slices)

    # -- cold-state audit ------------------------------------------------------

    def fsck_sweep(self, repair: bool = False):
        """Audit the whole fleet tree — queue, scheduler leases, every
        tenant's ``runs/<name>/`` dir and its artifact roots — with fsck
        (docs/ARCHITECTURE.md §22) and leave a queue breadcrumb. Meant
        for a COLD fleet (no live scheduler lease); per-tenant rot then
        also halts at that tenant's own resume preflight, but the sweep
        sees cross-tenant state (orphan run dirs, queue⇔dir drift) no
        single worker can."""
        from sparse_coding_tpu.fsck.core import run_fsck

        report = run_fsck(self.fleet_dir, repair=repair)
        self.queue.append(
            "scheduler.fsck", findings=len(report.findings),
            fatal=[f.path for f in report.fatal],
            repaired=len(report.repaired))
        return report

    # -- scheduler lease (contention + takeover) ------------------------------

    @property
    def lease_path(self) -> Path:
        return self.fleet_dir / "leases" / SCHEDULER_LEASE

    def _acquire_lease(self) -> None:
        state = lease_state(self.lease_path, self.heartbeat_stale_s,
                            clock=self._clock)
        info = read_lease(self.lease_path)
        pid = info.pid if info is not None else -1
        if state == "live":
            raise ConcurrentSupervisorError(
                f"fleet dir {self.fleet_dir} has a live heartbeating "
                f"scheduler lease (pid {pid}); refusing to "
                "double-run the fleet")
        if state == "stale":
            self.queue.append("scheduler.stale_kill", pid=pid)
            _kill_pid(pid)
        elif state == "dead":
            self.queue.append("scheduler.takeover", pid=pid)
        self._lease = Lease(self.lease_path, step="fleet",
                            clock=self._clock)

    # -- the scheduling loop --------------------------------------------------

    def run(self) -> dict[str, str]:
        """Drive every queued run to a terminal state; returns
        ``{run: done|halted|failed}``. Crash-only: raising (or dying) at
        any instant leaves a queue a fresh ``run()`` resumes exactly."""
        self._acquire_lease()
        self._sink = obs.EventSink(
            self.fleet_dir / "obs" / f"fleet-{os.getpid()}.jsonl")
        self.queue.append("scheduler.start",
                          n_slices=self.n_slices,
                          max_concurrent=self.max_concurrent)
        t0 = obs.monotime()
        try:
            self._reclaim_orphans(self.queue.replay())
            while True:
                st = self.queue.replay()
                plan = plan_placement(list(st.runs.values()), self.n_slices,
                                      self.max_concurrent)
                for name in plan.preempt:
                    self._preempt(name)
                for name in plan.place:
                    self._place(name)
                self._poll_workers()
                st = self.queue.replay()
                if st.terminal() and not self._workers:
                    break
                if self.max_wall_s is not None and \
                        obs.monotime() - t0 > self.max_wall_s:
                    raise TimeoutError(
                        f"fleet did not drain within {self.max_wall_s}s "
                        f"(states: {st.summary()})")
                # the scheduler's own heartbeat: a second scheduler (or a
                # takeover probe) reads liveness off this lease
                self._lease.beat()
                time.sleep(self.poll_s)
            summary = st.summary()
            self.queue.append("scheduler.done", summary=summary)
            obs.record_span("fleet.run", obs.monotime() - t0,
                            sink=self._sink, summary=dict(summary))
            return summary
        finally:
            # abnormal exits (max_wall_s timeout, KeyboardInterrupt, a
            # queue I/O error) leave live worker groups behind — and THIS
            # process survives, so no future takeover would reclaim them
            # before, e.g., an orphaned jax child keeps owning the TPU
            # tunnel against the caller's next run. Crash-only makes the
            # kill free: SIGKILL the groups and release the placements so
            # the queue stays accurate for the next scheduler.
            self._shutdown_workers()
            obs.flush_metrics(sink=self._sink)
            self._sink.close()
            self._sink = None
            if self._lease is not None:
                self._lease.release()
                self._lease = None

    def _shutdown_workers(self) -> None:
        for name, proc in list(self._workers.items()):
            if proc.poll() is None:
                self._signal_group(name, signal.SIGKILL)
                _kill_pid(proc.pid)
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            del self._workers[name]
            self.queue.append("run.release", name, outcome="reclaimed",
                              note="scheduler shutdown")
            worker_lease_path(self.fleet_dir, name).unlink(missing_ok=True)
            obs.counter("fleet.reclaims").inc()

    # -- actions --------------------------------------------------------------

    def _place(self, name: str) -> None:
        assert name not in self._workers, f"double-place of {name!r}"
        try:
            fault_point("fleet.place")
        except Exception:  # noqa: BLE001 — injected/transient: re-plan next tick
            obs.counter("fleet.place_errors").inc()
            return
        st = self.queue.replay()
        attempt = st.runs[name].attempts + 1
        self.queue.append("run.place", name, attempt=attempt)
        # THE placement instant: the queue knows the run is placed, the
        # worker does not exist yet. A SIGKILL here must cost nothing —
        # the restarted scheduler reclaims the orphan placement and
        # re-places (the chaos matrix proves no loss, no double-place).
        crash_barrier("fleet.place")
        log_path = self.fleet_dir / "logs" / f"{name}.{attempt}.log"
        env = dict(os.environ)
        env[lease_mod.ENV_PATH] = str(worker_lease_path(self.fleet_dir,
                                                        name))
        # ONE executable cache for every tenant (§13): tenant N+1 loads
        # what tenant N compiled — the zero-compile warm start the drill
        # asserts. setdefault: an operator-pinned dir wins.
        from sparse_coding_tpu.xcache import ENV_DIR as _XCACHE_ENV_DIR

        env.setdefault(_XCACHE_ENV_DIR, str(self.fleet_dir / "xcache"))
        from sparse_coding_tpu.obs.ledger import ENV_LEDGER, LEDGER_NAME

        env.setdefault(ENV_LEDGER, str(self.fleet_dir / LEDGER_NAME))
        argv = [sys.executable, "-m", "sparse_coding_tpu.pipeline.fleet",
                "worker", "--fleet-dir", str(self.fleet_dir),
                "--run", name]
        with open(log_path, "ab") as log_fh:
            # own session/process group: a preemption SIGTERMs the GROUP,
            # so the worker's step children get the graceful checkpoint
            # signal directly (resilience/preempt.py)
            proc = subprocess.Popen(argv, cwd=str(REPO_ROOT), env=env,
                                    stdout=log_fh,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
        seed_lease(worker_lease_path(self.fleet_dir, name), proc.pid,
                   step=f"run-{name}", clock=self._clock)
        self._workers[name] = proc
        obs.counter("fleet.placements").inc()
        obs.emit_event("fleet.place", sink=self._sink, run_name=name,
                       attempt=attempt, pid=proc.pid)

    def _preempt(self, name: str) -> bool:
        try:
            fault_point("fleet.preempt")
        except Exception:  # noqa: BLE001 — injected/transient: re-plan next tick
            obs.counter("fleet.preempt_errors").inc()
            return False
        self.queue.append("run.preempt", name)
        self._signal_group(name, signal.SIGTERM)
        obs.counter("fleet.preemptions").inc()
        obs.emit_event("fleet.preempt", sink=self._sink, run_name=name)
        return True

    def reclaim_scavengers(self, max_slices: int) -> list[str]:
        """Elastic-plane reclaim (pipeline/plane.py): when the arbiter
        shrinks the fleet's share of the pod, SIGTERM-preempt
        most-recently-placed scavenger runs until the slices held by
        live scavengers fit ``max_slices``. Rides the exact ``_preempt``
        path (durable ``run.preempt`` + group SIGTERM at a chunk
        boundary), so a reclaimed sweep checkpoints and later resumes
        bitwise. Only scavengers are plane-reclaimable — higher classes
        keep their slices until they finish. Returns the names
        signaled."""
        st = self.queue.replay()
        # PREEMPTING runs are already on their way to freeing their
        # slices — counting them toward usage would cascade one extra
        # SIGTERM per tick onto still-useful sweeps while the first
        # victim drains (the futile-preemption class the placement
        # planner also guards against)
        victims = sorted((r for r in st.runs.values()
                          if r.state == PLACED
                          and r.priority == SCAVENGER),
                         key=lambda r: -r.placed_seq)
        usage = sum(r.slices for r in victims)
        signaled: list[str] = []
        for victim in victims:
            if usage <= max(0, int(max_slices)):
                break
            if self._preempt(victim.name):
                usage -= victim.slices
                signaled.append(victim.name)
        return signaled

    def _signal_group(self, name: str, sig: int) -> None:
        proc = self._workers.get(name)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            _kill_pid(proc.pid)

    # -- worker lifecycle -----------------------------------------------------

    def _poll_workers(self) -> None:
        st = None
        for name, proc in list(self._workers.items()):
            if proc.poll() is None:
                st = st or self.queue.replay()
                self._watch_live_worker(name, proc, st)
                continue
            del self._workers[name]
            st = st or self.queue.replay()
            run = st.runs.get(name)
            rc = proc.returncode
            outcome = self._classify_exit(rc, run)
            self.queue.append("run.release", name, outcome=outcome, rc=rc)
            worker_lease_path(self.fleet_dir, name).unlink(missing_ok=True)
            obs.counter("fleet.releases", outcome=outcome).inc()
            if outcome == "halted":
                obs.counter("fleet.halts").inc()
            obs.emit_event("fleet.release", sink=self._sink, run_name=name,
                           outcome=outcome, rc=rc)
            st = None  # release changed the state: re-fold next use

    def _classify_exit(self, rc: int, run) -> str:
        preempting = run is not None and run.state == PREEMPTING
        if rc == 0:
            # a preempted worker that still finished cleanly is done —
            # the SIGTERM raced completion; done beats re-queue
            return "done"
        if rc == WORKER_EXIT_HALTED:
            # contained: this tenant's guardian halted ITS run; the slice
            # frees and the queue re-packs — nobody else notices
            return "halted"
        if rc == WORKER_EXIT_PREEMPTED or preempting:
            return "preempted"
        # the crash budget counts CRASHES (prior "requeued" releases plus
        # this one), never place records: a preempted or reclaimed run has
        # consumed placements without failing, and must keep its retries
        crashes = (run.requeues if run is not None
                   else self.max_run_attempts) + 1
        if crashes >= self.max_run_attempts:
            return "failed"
        return "requeued"  # crash: the run is resumable by contract

    def _watch_live_worker(self, name: str, proc, st: FleetState) -> None:
        """A live worker owes heartbeats (its supervisor beats while
        babysitting a child); a stale one is hung — SIGKILL the group and
        let the exit path re-queue (crash-only: the run resumes). A
        PREEMPTING worker is re-signaled each tick: a step child spawned
        in the instant between the group SIGTERM and the worker noticing
        would otherwise never see the preemption."""
        run = st.runs.get(name)
        if run is not None and run.state == PREEMPTING:
            self._signal_group(name, signal.SIGTERM)
        path = worker_lease_path(self.fleet_dir, name)
        if lease_state(path, self.heartbeat_stale_s,
                       clock=self._clock) == "stale":
            self.queue.append("run.hung", name, pid=proc.pid)
            obs.counter("fleet.worker_hangs").inc()
            self._signal_group(name, signal.SIGKILL)
            _kill_pid(proc.pid)

    def _reclaim_orphans(self, st: FleetState) -> None:
        """Startup pass: runs the queue believes are placed but no worker
        of OURS exists. A dead/stale owner is reclaimed (re-queued — the
        run's done-markers make a re-run converge, so reclaim can never
        double-apply work); a live-heartbeating owner whose scheduler
        died is SIGKILLed first — two schedulers' workers must never
        share one run dir, and crash-only makes the kill free."""
        for name, run in st.runs.items():
            if run.state not in (PLACED, PREEMPTING) or \
                    name in self._workers:
                continue
            path = worker_lease_path(self.fleet_dir, name)
            state = lease_state(path, self.heartbeat_stale_s,
                                clock=self._clock)
            info = read_lease(path)
            if state in ("live", "stale") and info is not None:
                self.queue.append("run.orphan_kill", name, pid=info.pid,
                                  lease=state)
                try:
                    os.killpg(info.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    _kill_pid(info.pid)
            self.queue.append("run.release", name, outcome="reclaimed")
            path.unlink(missing_ok=True)
            obs.counter("fleet.reclaims").inc()


# -- the per-run worker -------------------------------------------------------


def build_run_steps(run_dir: Path, spec: dict) -> list:
    """The run's step DAG from its queue spec: the flat, sharded, or
    group-tenant builders over ``spec['config']``, or the single
    resumable command step the cheap-child tests drive. Tenant env rides
    every step. ``kind="group"`` is one Group-SAE tenant (§23): the
    sweep → eval (→ catalog) tail over its pooled store view, no harvest
    edge — ``groups.json`` was durable before enqueue."""
    from sparse_coding_tpu.pipeline.supervisor import (
        Step,
        build_group_tenant_pipeline,
    )

    kind = spec.get("kind", "flat")
    if kind == "command":
        done = Path(spec["done_path"])
        steps = [Step("main", [str(a) for a in spec["argv"]],
                      done=done.exists)]
    else:
        builder = (build_sharded_pipeline if kind == "sharded"
                   else build_group_tenant_pipeline if kind == "group"
                   else build_pipeline)
        steps = builder(run_dir, spec["config"])
    for step in steps:
        merged = dict(spec.get("env") or {})
        merged.update(step.env)
        step.env = merged
    return steps


def run_worker(fleet_dir: str | Path, name: str,
               guard: Optional[PreemptionGuard] = None) -> int:
    """One placed run, driven by a plain Supervisor over the run's own
    dir. Exit status is the scheduler's contract: 0 done,
    ``WORKER_EXIT_PREEMPTED`` checkpointed-and-resumable,
    ``WORKER_EXIT_HALTED`` guardian-contained, anything else a crash the
    queue re-judges. SIGTERM is trapped as a FLAG (resilience/preempt.py)
    — the worker must outlive its step child's graceful checkpoint exit,
    not die first and orphan it. (The CLI installs the guard at interpreter
    entry; a SIGTERM landing even earlier — mid-import — kills the worker,
    which the scheduler re-judges as a crash: re-queued, resumable.)"""
    fleet_dir = Path(fleet_dir)
    queue = FleetQueue(fleet_dir / QUEUE_NAME)
    spec = queue.replay().specs.get(name)
    if spec is None:
        print(f"fleet worker: unknown run {name!r}", file=sys.stderr)
        return 2
    lease_mod.configure_from_env(step=f"run-{name}")
    run_dir = run_dir_for(fleet_dir, name)
    guard = guard if guard is not None else PreemptionGuard()
    with guard:
        sup = Supervisor(
            run_dir, build_run_steps(run_dir, spec),
            max_attempts=int(spec.get("max_attempts", 2)),
            heartbeat_stale_s=float(spec.get("heartbeat_stale_s", 120.0)),
            preempt_flag=guard.signal_received)
        try:
            sup.run()
            return 0
        except StepPreempted:
            return WORKER_EXIT_PREEMPTED
        except StepHalted:
            return WORKER_EXIT_HALTED
        except Exception as e:  # noqa: BLE001 — typed for the log, coded for the queue
            if guard.requested:
                # the SIGTERM landed mid-step on a child without the
                # graceful path (or the retry raced the flag): the run is
                # still resumable — report preempted, not crashed
                print(f"fleet worker: preempted during {e!r}",
                      file=sys.stderr)
                return WORKER_EXIT_PREEMPTED
            print(f"fleet worker: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    # WORKER ONLY: trap SIGTERM before anything else — a preemption
    # arriving during argument parsing or queue replay must flag, not
    # kill (the guard is handed to run_worker so the flag survives into
    # the supervisor). The scheduler keeps default SIGTERM: an operator
    # stopping the fleet is not a preemption.
    raw = list(sys.argv[1:] if argv is None else argv)
    entry_guard = PreemptionGuard() if "worker" in raw[:1] else None
    if entry_guard is not None:
        entry_guard.__enter__()

    parser = argparse.ArgumentParser(
        prog="python -m sparse_coding_tpu.pipeline.fleet",
        description="fleet scheduler (docs/ARCHITECTURE.md §18)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sched = sub.add_parser("schedule", help="drive the fleet queue")
    sched.add_argument("--fleet-dir", required=True)
    sched.add_argument("--slices", type=int, default=1)
    sched.add_argument("--max-concurrent", type=int, default=1)
    sched.add_argument("--poll-s", type=float, default=0.25)
    sched.add_argument("--stale-s", type=float, default=120.0)
    sched.add_argument("--max-wall-s", type=float, default=None)
    worker = sub.add_parser("worker", help="run one placed run")
    worker.add_argument("--fleet-dir", required=True)
    worker.add_argument("--run", required=True)
    fsck = sub.add_parser("fsck", help="audit (and optionally repair) the "
                                       "whole fleet tree's durable state")
    fsck.add_argument("--fleet-dir", required=True)
    fsck.add_argument("--repair", action="store_true")
    args = parser.parse_args(argv)
    if args.cmd == "worker":
        return run_worker(args.fleet_dir, args.run, guard=entry_guard)
    if args.cmd == "fsck":
        report = FleetScheduler(args.fleet_dir).fsck_sweep(
            repair=args.repair)
        print(json.dumps({"findings": len(report.findings),
                          "fatal": len(report.fatal),
                          "repaired": len(report.repaired),
                          "clean": report.clean}, sort_keys=True))
        return 2 if report.fatal else (0 if report.clean else 1)
    summary = FleetScheduler(
        args.fleet_dir, n_slices=args.slices,
        max_concurrent=args.max_concurrent, poll_s=args.poll_s,
        heartbeat_stale_s=args.stale_s, max_wall_s=args.max_wall_s).run()
    print(" ".join(f"{k}={v}" for k, v in sorted(summary.items())))
    return 0 if all(v == "done" for v in summary.values()) else 3


if __name__ == "__main__":
    sys.exit(main())
