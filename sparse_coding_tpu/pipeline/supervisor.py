"""Crash-only pipeline supervisor: journaled harvest→sweep→eval DAG.

The paper's workflow is a long unattended chain — harvest activations,
train vmapped SAE ensembles, evaluate dictionaries — and at production
scale (ROADMAP north star; the ensembling papers in PAPERS.md multiply
sweep count) that chain must survive whole-process death and wedged
hardware, not only the in-process I/O faults §10 injects. The design is
**crash-only**: there is no graceful-shutdown path that recovery depends
on — recovery IS the normal start path.

- every step runs as a **child process** (the unit that dies); the
  supervisor itself holds no unrecoverable state (journal +
  artifacts rebuild everything, so the supervisor may also die);
- each step owns a **lease file** with progress heartbeats
  (:mod:`resilience.lease`): a restarted supervisor distinguishes
  "crashed" (owner pid dead → take over) from "hung" (owner alive,
  heartbeat stale → kill, diagnose) from "still running" (leave alone);
- a **watchdog** polls the live child's lease; when the heartbeat goes
  stale it runs the tunnel-wedge diagnosis (socket probe of ports
  2024/8082/8083, :mod:`resilience.watchdog`) before deciding
  retry / degrade-to-CPU / halt;
- steps are **resumable by contract**: harvest resumes from the durable
  chunk prefix, the sweep from §4/§10's checkpoints — so "retry" is
  always "respawn the same command", and a completed run's artifacts are
  bitwise-identical to an uninterrupted one (the chaos matrix,
  tests/test_pipeline_chaos.py, SIGKILLs a child at every named crash
  barrier and asserts exactly that).

Execution is deliberately SERIAL (topological order): this container
admits one jax process at a time (CLAUDE.md), and the DAG's edges here
are all data dependencies anyway.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional, Sequence

from sparse_coding_tpu import obs
from sparse_coding_tpu.pipeline.journal import RunJournal
from sparse_coding_tpu.resilience import lease as lease_mod
from sparse_coding_tpu.resilience import watchdog as watchdog_mod
from sparse_coding_tpu.resilience.errors import ResilienceError
from sparse_coding_tpu.resilience.lease import lease_state, read_lease, seed_lease
from sparse_coding_tpu.resilience.watchdog import (
    DEGRADE_CPU,
    HALT,
    RETRY,
    classify_hang,
    format_diagnosis,
)

REPO_ROOT = Path(__file__).resolve().parents[2]

# Typed step-child exit codes (pipeline/steps.py maps the two structured
# shutdown classes onto these; everything else is a plain failure). 75 =
# EX_TEMPFAIL: a SIGTERM-preempted step checkpointed at its chunk boundary
# and will resume bitwise (resilience/preempt.py) — retrying IN PLACE
# would undo the preemption, so the supervisor surfaces it typed instead.
# 78 = a guardian divergence halt (train/guardian.py DivergenceHaltError):
# deterministic — the ledger records the halt, so a retry would replay the
# same sweep to the same halt; the supervisor must not burn attempts on it.
STEP_EXIT_PREEMPTED = 75
STEP_EXIT_HALTED = 78

# set to "0" to skip the resume preflight audit (fsck §22) — the perf
# escape hatch for trees too large to re-digest on every restart
PREFLIGHT_ENV = "SPARSE_CODING_FSCK_PREFLIGHT"


def load_or_create_run_id(run_dir: str | Path) -> str:
    """The run's correlation ID (docs/ARCHITECTURE.md §12): minted once
    per run dir and persisted to ``<run_dir>/obs/run_id``, so a restarted
    supervisor — crash-only: restart IS the normal path — joins the same
    run instead of forking a new identity. Every event, journal record,
    and child-step env carries it."""
    import binascii

    run_dir = Path(run_dir)
    marker = run_dir / "obs" / "run_id"
    try:
        existing = marker.read_text().strip()
        if existing:
            return existing
    except OSError:
        pass
    from sparse_coding_tpu.resilience.atomic import atomic_write_text

    rid = f"{run_dir.name}-{binascii.hexlify(os.urandom(4)).decode()}"
    marker.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(marker, rid + "\n")
    return rid


class PipelineError(ResilienceError):
    """Base for typed supervisor failures."""


class StepFailed(PipelineError):
    """A step exhausted its attempt budget (crash, kill, or nonzero exit).
    The run journal holds the per-attempt record; re-running the
    supervisor resumes from the durable prefix."""

    def __init__(self, step: str, attempts: int, reason: str):
        super().__init__(f"step {step!r} failed after {attempts} "
                         f"attempt(s): {reason}")
        self.step = step
        self.attempts = attempts
        self.reason = reason


class StepHung(PipelineError):
    """The watchdog declared a step hung and the diagnosis said halting is
    the only safe move (tunnel endpoint reachable but our client wedged —
    the server-side lease only time clears; see docs/RUNBOOK_TUNNEL.md)."""

    def __init__(self, step: str, diagnosis: dict):
        super().__init__(f"step {step!r} hung; {format_diagnosis(diagnosis)}")
        self.step = step
        self.diagnosis = diagnosis


class StepPreempted(PipelineError):
    """A step child exited with ``STEP_EXIT_PREEMPTED``: a SIGTERM landed
    and it checkpointed at its chunk boundary (resilience/preempt.py).
    The run is RESUMABLE, not failed — the fleet scheduler re-queues it;
    a bare supervisor surfaces it typed so the operator decides."""

    def __init__(self, step: str):
        super().__init__(f"step {step!r} preempted (checkpointed at its "
                         "chunk boundary; re-run to resume)")
        self.step = step


class StepHalted(PipelineError):
    """A step child exited with ``STEP_EXIT_HALTED``: the training
    guardian raised its typed divergence halt (docs/ARCHITECTURE.md §16).
    The halt is deterministic — the guardian ledger already records it, a
    respawn replays the same sweep into the same halt — so the supervisor
    raises immediately instead of burning its attempt budget."""

    def __init__(self, step: str):
        super().__init__(
            f"step {step!r} halted by the training guardian "
            "(DivergenceHaltError; triage: docs/RUNBOOK_TUNNEL.md)")
        self.step = step


class ConcurrentSupervisorError(PipelineError):
    """A live, heartbeating lease for a step this supervisor wants to run:
    another supervisor (or a still-running orphan) owns the run. Refusing
    is the safe default — two writers on one run dir is undefined."""


class PreflightAuditError(PipelineError):
    """The resume preflight audit (fsck, docs/ARCHITECTURE.md §22) found
    durable state that contradicts itself — e.g. a completion artifact
    that exists but no longer verifies, chunk bytes not matching their
    recorded digests, or both checkpoint sets damaged. Resuming over it
    could silently diverge, so the supervisor halts typed, naming the
    rotted artifacts; the operator triages with
    ``python -m sparse_coding_tpu.fsck <run_dir>`` (and ``--repair`` for
    the provably-safe subset)."""

    def __init__(self, run_dir, findings):
        named = "; ".join(f"{f.path} ({f.kind}: {f.detail})"
                          for f in findings[:4])
        more = f" (+{len(findings) - 4} more)" if len(findings) > 4 else ""
        super().__init__(
            f"preflight audit of {run_dir} found {len(findings)} fatal "
            f"finding(s): {named}{more} — refusing to resume; triage "
            f"with `python -m sparse_coding_tpu.fsck {run_dir}`")
        self.run_dir = Path(run_dir)
        self.findings = list(findings)


@dataclass
class Step:
    """One journaled pipeline step.

    ``argv`` must be re-runnable from scratch at any instant (the crash-
    only contract); ``done()`` checks the completion artifact on disk —
    it, not the journal, is the truth a restarted supervisor trusts.
    ``degrade_argv`` (optional) is the command used after the watchdog
    decides degrade-to-CPU (e.g. bench's reduced-scale CPU fallback)."""

    name: str
    argv: list[str]
    done: Callable[[], bool]
    deps: tuple[str, ...] = ()
    degrade_argv: Optional[list[str]] = None
    env: dict = field(default_factory=dict)


def _toposort(steps: Sequence[Step]) -> list[Step]:
    by_name = {s.name: s for s in steps}
    if len(by_name) != len(steps):
        raise ValueError("duplicate step names")
    for s in steps:
        for d in s.deps:
            if d not in by_name:
                raise ValueError(f"step {s.name!r} depends on unknown "
                                 f"step {d!r}")
    order: list[Step] = []
    state: dict[str, int] = {}  # 0 visiting, 1 done

    def visit(s: Step):
        if state.get(s.name) == 1:
            return
        if state.get(s.name) == 0:
            raise ValueError(f"dependency cycle through {s.name!r}")
        state[s.name] = 0
        for d in s.deps:
            visit(by_name[d])
        state[s.name] = 1
        order.append(s)

    for s in steps:
        visit(s)
    return order


def stripped_cpu_env(env: dict) -> dict:
    """The degrade-to-CPU child environment: axon plugin stripped so the
    child can never touch the (diagnosed-dead) tunnel, jax pinned to CPU."""
    env = dict(env)
    env.pop(watchdog_mod.TUNNEL_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


class Supervisor:
    """Run a step DAG with journaling, leases, kill-recovery and a hang
    watchdog. Construction is cheap and stateless on disk; ``run()`` may
    be called on a fresh instance over an old run dir — that IS the
    restart path."""

    def __init__(self, run_dir: str | Path, steps: Sequence[Step], *,
                 max_attempts: int = 2, heartbeat_stale_s: float = 120.0,
                 poll_s: float = 0.25, cpu_only: bool = False,
                 prober=None, clock=time.time,
                 preempt_flag: Optional[Callable[[], bool]] = None):
        self.run_dir = Path(run_dir)
        self.steps = _toposort(steps)
        self.max_attempts = int(max_attempts)
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.poll_s = float(poll_s)
        self.cpu_only = bool(cpu_only)
        # a fleet worker's cooperative preemption hook (pipeline/fleet.py,
        # resilience/preempt.py): checked between steps and between
        # attempts, so a SIGTERM that lands while NO child is running
        # still stops the run typed instead of spawning fresh work
        self._preempt_flag = preempt_flag
        self._prober = prober or watchdog_mod.probe_tunnel
        self._clock = clock
        # the run's correlation identity: journal records carry it, child
        # steps inherit it (with the shared event dir) through the env, so
        # every process's events join up in obs.report (§12)
        self.run_id = load_or_create_run_id(self.run_dir)
        self.obs_dir = self.run_dir / "obs"
        # a PER-INSTANCE sink (not the module-global one, which tests and
        # a hosting process may own): opened for the duration of run() and
        # closed in its finally, so idle/dead supervisors hold no fd
        self._sink: Optional[obs.EventSink] = None
        self.journal = RunJournal(self.run_dir / "journal.jsonl", clock=clock,
                                  run_id=self.run_id)
        (self.run_dir / "logs").mkdir(parents=True, exist_ok=True)
        (self.run_dir / "leases").mkdir(parents=True, exist_ok=True)

    def _record_span(self, name: str, dur_s: float, ok: bool = True,
                     error: str = "", **attrs) -> None:
        """The single home of the supervisor-side emit plumbing: every
        span goes to this instance's sink stamped with this run's ID —
        never to the module-global sink, which would lose both."""
        obs.record_span(name, dur_s, ok=ok, error=error, sink=self._sink,
                        run=self.run_id, **attrs)

    # -- paths ---------------------------------------------------------------

    def lease_path(self, step: Step) -> Path:
        return self.run_dir / "leases" / f"{step.name}.json"

    def _log_path(self, step: Step, attempt: int) -> Path:
        return self.run_dir / "logs" / f"{step.name}.{attempt}.log"

    # -- run -----------------------------------------------------------------

    def run(self) -> dict[str, str]:
        """Execute every step not already complete; returns
        ``{step: "done" | "skipped"}``. Raises typed errors on failure —
        after which calling ``run()`` again (same or new process) resumes."""
        # BEFORE the first journal append: append normalizes an
        # unterminated tail by terminating it, which would commit a
        # torn (possibly still-parsing) line the audit should see raw
        self._preflight_audit()
        self.journal.append("run.start",
                            detail_steps=[s.name for s in self.steps])
        self._sink = obs.EventSink(
            self.obs_dir / f"supervisor-{os.getpid()}.jsonl")
        t_run = obs.monotime()
        summary: dict[str, str] = {}
        try:
            for step in self.steps:
                if step.done():
                    # artifact present: complete, whether or not a journal
                    # record survived (artifacts beat the journal)
                    if step.name not in self.journal.done_steps():
                        self.journal.append("step.done", step.name,
                                            note="artifact present at startup")
                    summary[step.name] = "skipped"
                    continue
                self._check_preempted(step.name)
                self._takeover_lease(step)
                self._run_step(step)
                summary[step.name] = "done"
        except BaseException as e:
            self._record_span("pipeline.run", obs.monotime() - t_run,
                              ok=False, error=type(e).__name__)
            raise
        else:
            self.journal.append("run.done")
            self._record_span("pipeline.run", obs.monotime() - t_run,
                              summary=dict(summary))
            self._append_perf_ledger()
            return summary
        finally:
            obs.flush_metrics(sink=self._sink)
            self._sink.close()
            self._sink = None

    def _preflight_audit(self) -> None:
        """Resume preflight (docs/ARCHITECTURE.md §22): a run dir that
        already holds journal records is a RESUME over cold durable
        state, and the supervisor's own ``done()`` probes only check
        existence — so before admitting any work, fsck the run's whole
        durable footprint. Fatal findings (INCONSISTENT state a resume
        could silently diverge over) halt typed via
        :class:`PreflightAuditError` — never silently. Scan-only:
        repair stays an explicit operator action.
        ``SPARSE_CODING_FSCK_PREFLIGHT=0`` disables (perf escape hatch
        for trees too large to re-digest every restart)."""
        if os.environ.get(PREFLIGHT_ENV, "1") == "0":
            return
        jpath = self.run_dir / "journal.jsonl"
        try:
            if not jpath.exists() or jpath.stat().st_size == 0:
                return  # fresh run: nothing durable to audit yet
        except OSError:
            return
        from sparse_coding_tpu.fsck.core import run_fsck

        t0 = obs.monotime()
        report = run_fsck(self.run_dir, repair=False)
        self.journal.append(
            "run.fsck", findings=len(report.findings),
            fatal=[f.path for f in report.fatal])
        self._record_span("pipeline.preflight_fsck",
                          obs.monotime() - t0,
                          ok=not report.fatal,
                          findings=len(report.findings))
        if report.fatal:
            raise PreflightAuditError(self.run_dir, report.fatal)

    def _append_perf_ledger(self) -> None:
        """One durable perf summary row per completed run (ISSUE 12):
        the run's MFU gauges, kernel-path mix, and step walls distilled
        from its own merged report — the row obs.report --diff compares
        round over round. Bookkeeping: a failure here is counted, never
        fatal to the run that just succeeded."""
        from sparse_coding_tpu.obs import ledger as ledger_mod
        from sparse_coding_tpu.obs.report import build_report

        try:
            row = ledger_mod.run_summary_row(build_report(self.run_dir),
                                             run_id=self.run_id)
            row["run_dir"] = str(self.run_dir)
            ledger_mod.append_row(
                row, ledger_mod.ledger_path(self.run_dir))
        except Exception:  # noqa: BLE001 — bookkeeping is never fatal
            obs.get_registry().counter("obs.ledger.dropped").inc()

    # -- lease takeover ------------------------------------------------------

    def _takeover_lease(self, step: Step) -> None:
        path = self.lease_path(step)
        state = lease_state(path, self.heartbeat_stale_s, clock=self._clock)
        if state == "missing":
            return
        info = read_lease(path)
        if state == "live":
            raise ConcurrentSupervisorError(
                f"step {step.name!r} has a live heartbeating lease "
                f"(pid {info.pid}); refusing to double-run the pipeline")
        if state == "stale":
            # owner alive but not progressing: a hung orphan from a dead
            # supervisor. SIGKILL it (crash-only: it is resumable) so two
            # processes never write one step's artifacts.
            self.journal.append("lease.stale_kill", step.name, pid=info.pid,
                                beat_age_s=round(self._clock() - info.beat_at,
                                                 3))
            _kill_pid(info.pid)
        else:  # dead
            self.journal.append("lease.takeover", step.name, pid=info.pid)
        path.unlink(missing_ok=True)

    # -- one step ------------------------------------------------------------

    def _child_env(self, step: Step, degraded: bool) -> dict:
        env = dict(os.environ)
        for key, val in step.env.items():
            if val is None:  # None = delete (e.g. un-pin JAX_PLATFORMS)
                env.pop(key, None)
            else:
                env[key] = val
        env[lease_mod.ENV_PATH] = str(self.lease_path(step))
        # correlation propagation (§12): the child's spans/events/metrics
        # land in the run's shared obs dir, stamped with this run's ID and
        # its step name — obs.report joins them with the supervisor's own
        env[obs.ENV_RUN_ID] = self.run_id
        env[obs.ENV_OBS_DIR] = str(self.obs_dir)
        env[obs.ENV_STEP] = step.name
        # executable-cache propagation (§13): every step child of this run
        # — including each RESPAWN of the same step, the crash-only normal
        # case — shares one cache dir, so attempt 2 loads what attempt 1
        # compiled instead of recompiling. setdefault: an operator- or
        # step-level dir wins. Degrade-to-CPU retries share the dir safely
        # because every cache key carries the backend (per-backend keying).
        from sparse_coding_tpu.xcache import ENV_DIR as _XCACHE_ENV_DIR

        env.setdefault(_XCACHE_ENV_DIR, str(self.run_dir / "xcache"))
        # perf-ledger propagation (§12, ISSUE 12): every child of this
        # run — bench included — appends its summary rows to ONE durable
        # per-run ledger, which obs.report --diff reads across runs
        from sparse_coding_tpu.obs.ledger import ENV_LEDGER, LEDGER_NAME

        env.setdefault(ENV_LEDGER, str(self.run_dir / LEDGER_NAME))
        if self.cpu_only or degraded:
            env = stripped_cpu_env(env)
        return env

    def _check_preempted(self, step_name: str) -> None:
        if self._preempt_flag is not None and self._preempt_flag():
            self.journal.append("step.preempted", step_name,
                                note="flag checked before spawn")
            raise StepPreempted(step_name)

    def _run_step(self, step: Step) -> None:
        degraded = False
        last_reason = "never spawned"
        for attempt in range(1, self.max_attempts + 1):
            if attempt > 1:
                self._check_preempted(step.name)
            argv = (step.degrade_argv
                    if degraded and step.degrade_argv else step.argv)
            log_path = self._log_path(step, attempt)
            env = self._child_env(step, degraded)
            spawn_argv = list(argv)
            if env.get(watchdog_mod.TUNNEL_ENV):
                # tunnel-touching child: serialize on the repo-wide flock
                # (CLAUDE.md; util-linux flock execs the command in place,
                # so signal/exit semantics pass through). AXON_LOCK_HELD=1
                # tells bench.py-style children their lock is already held
                # (re-acquiring on a second fd of the same file would
                # self-deadlock). If another holder (e.g. tunnel_watch.sh
                # mid-measurement) blocks us past heartbeat_stale_s, the
                # watchdog treats it as a hang and the probe decides —
                # which is the correct posture toward a busy tunnel.
                import shutil as _shutil

                if _shutil.which("flock"):
                    env["AXON_LOCK_HELD"] = "1"
                    spawn_argv = ["flock", watchdog_mod.TUNNEL_LOCK] \
                        + spawn_argv
            self.journal.append("step.spawn", step.name, attempt=attempt,
                                argv=shlex.join(spawn_argv),
                                degraded=degraded)
            t_attempt = obs.monotime()
            with open(log_path, "ab") as log_fh:
                proc = subprocess.Popen(spawn_argv, cwd=str(REPO_ROOT),
                                        env=env, stdout=log_fh,
                                        stderr=subprocess.STDOUT)
            seed_lease(self.lease_path(step), proc.pid, step=step.name,
                       clock=self._clock, run=self.run_id)
            verdict = self._watch(step, proc)

            def _span(outcome: str, ok: bool) -> None:
                # one span per attempt: the supervisor-side wall clock of
                # the child, labeled with how the attempt ended
                self._record_span("pipeline.step",
                                  obs.monotime() - t_attempt, ok=ok,
                                  error="" if ok else outcome,
                                  step=step.name, attempt=attempt,
                                  outcome=outcome, degraded=degraded)

            if verdict is None:  # exited on its own
                rc = proc.returncode
                if rc == 0 and step.done():
                    self.journal.append("step.done", step.name,
                                        attempt=attempt)
                    self.lease_path(step).unlink(missing_ok=True)
                    _span("done", ok=True)
                    return
                if rc == 0:
                    last_reason = ("exit 0 but completion artifact missing "
                                   "(crash between artifact and marker?)")
                    self.journal.append("step.failed", step.name,
                                        attempt=attempt, rc=0,
                                        reason=last_reason)
                    _span("failed", ok=False)
                elif rc == STEP_EXIT_PREEMPTED:
                    # graceful SIGTERM shutdown: checkpointed, resumable —
                    # typed out instead of burning the attempt budget
                    self.journal.append("step.preempted", step.name,
                                        attempt=attempt)
                    self.lease_path(step).unlink(missing_ok=True)
                    _span("preempted", ok=False)
                    raise StepPreempted(step.name)
                elif rc == STEP_EXIT_HALTED:
                    # guardian divergence halt: deterministic, a respawn
                    # replays into the same halt — never retried
                    self.journal.append("step.halted", step.name,
                                        attempt=attempt, log=str(log_path))
                    self.lease_path(step).unlink(missing_ok=True)
                    _span("halted", ok=False)
                    raise StepHalted(step.name)
                elif rc < 0:
                    last_reason = f"killed by signal {-rc}"
                    self.journal.append("step.killed", step.name,
                                        attempt=attempt, signal=-rc,
                                        log=str(log_path))
                    _span("killed", ok=False)
                else:
                    last_reason = f"exit code {rc}"
                    self.journal.append("step.failed", step.name,
                                        attempt=attempt, rc=rc,
                                        log=str(log_path))
                    _span("failed", ok=False)
            else:  # watchdog declared it hung and killed it
                action = verdict["action"]
                last_reason = f"hung ({action})"
                _span("hung", ok=False)
                if action == HALT:
                    raise StepHung(step.name, verdict)
                if action == DEGRADE_CPU:
                    degraded = True
        raise StepFailed(step.name, self.max_attempts, last_reason)

    def _watch(self, step: Step, proc: subprocess.Popen) -> Optional[dict]:
        """Poll child + lease. Returns None when the child exited by
        itself, or the hang diagnosis dict after killing a hung child.
        The lease the CHILD rewrites is the progress signal; the seed
        lease stamped at spawn opens the staleness window immediately, so
        a child wedged before its first beat (backend init — the known
        tunnel failure mode) is caught too."""
        path = self.lease_path(step)
        while True:
            if proc.poll() is not None:
                return None
            # the supervisor's OWN heartbeat: when this supervisor is a
            # fleet per-run worker (pipeline/fleet.py), the scheduler
            # watches a worker lease exported through the env — babysitting
            # a live child IS progress; a no-op outside a fleet
            lease_mod.beat()
            state = lease_state(path, self.heartbeat_stale_s,
                                clock=self._clock)
            if state == "stale" or state == "missing":
                probe = self._prober()
                diag = {"probe": probe, "action": classify_hang(probe),
                        "runbook": watchdog_mod.RUNBOOK}
                self.journal.append("step.hung", step.name, **diag)
                _kill_pid(proc.pid)
                proc.wait()
                path.unlink(missing_ok=True)
                return diag
            time.sleep(self.poll_s)


def _kill_pid(pid: int) -> None:
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    except PermissionError:
        pass


# -- canonical pipelines -----------------------------------------------------


def step_argv(step_name: str, config_path: str | Path) -> list[str]:
    """Child command for a built-in step (pipeline/steps.py entrypoint)."""
    return [sys.executable, "-m", "sparse_coding_tpu.pipeline.steps",
            step_name, "--config", str(config_path)]


def build_pipeline(run_dir: str | Path, config: dict,
                   only: Optional[Sequence[str]] = None) -> list[Step]:
    """The harvest → sweep → eval DAG over a single config dict (see
    pipeline/steps.py for the per-step config keys). The config is
    persisted into the run dir so a restarted supervisor — or an operator
    — can rebuild the exact same pipeline from disk.

    ``only`` prunes the DAG to a subset (deps on pruned steps are
    dropped): an operator re-running just the eval over finished sweep
    artifacts — or the chaos matrix seeding a case from golden copies —
    names the steps it wants."""
    cfg_path, anchor = _persist_pipeline_config(run_dir, config)
    dataset = anchor(config["harvest"]["dataset_folder"])
    steps = [
        Step("harvest", step_argv("harvest", cfg_path),
             done=lambda: (dataset / "meta.json").exists()),
    ] + _sweep_eval_steps(cfg_path, config, anchor, sweep_dep="harvest")
    return _prune(steps, only)


def _persist_pipeline_config(run_dir: str | Path, config: dict):
    """Shared builder preamble: persist the config into the run dir (a
    restarted supervisor or an operator rebuilds the exact pipeline from
    disk) and return ``(cfg_path, anchor)``."""
    import json

    from sparse_coding_tpu.resilience.atomic import atomic_write_text

    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    cfg_path = run_dir / "pipeline.json"
    atomic_write_text(cfg_path, json.dumps(config, indent=2))

    def anchor(p) -> Path:
        # children run with cwd=REPO_ROOT, so the supervisor-side done()
        # probes must resolve relative config paths against the same root
        # — not against wherever the operator launched the supervisor
        p = Path(p)
        return p if p.is_absolute() else REPO_ROOT / p

    return cfg_path, anchor


def _sweep_eval_steps(cfg_path: Path, config: dict, anchor,
                      sweep_dep: Optional[str]) -> list[Step]:
    """The sweep → eval DAG tail, shared by every pipeline builder so the
    step argv, dependency shape, and done() markers cannot drift between
    the flat and sharded data planes. ``sweep_dep=None`` drops the
    harvest edge entirely — the group-tenant case (§23): the pooled
    store the tenant trains on is already durable before enqueue."""
    sweep_out = anchor(config["sweep"]["ensemble"]["output_folder"])
    eval_out = anchor(config["eval"]["output_folder"])
    name = config["sweep"].get("experiment", "dense_l1_range")
    steps = [
        Step("sweep", step_argv("sweep", cfg_path),
             deps=(sweep_dep,) if sweep_dep is not None else (),
             done=lambda: (sweep_out / "final"
                           / f"{name}_learned_dicts.pkl").exists()),
        Step("eval", step_argv("eval", cfg_path), deps=("sweep",),
             done=lambda: (eval_out / "eval.json").exists()),
    ]
    if "catalog" in config:
        # opt-in DAG tail (§20): configs without a "catalog" section keep
        # the exact sweep → eval shape they always had
        cat_out = anchor(config["catalog"]["output_folder"])
        steps.append(
            Step("catalog", step_argv("catalog", cfg_path), deps=("eval",),
                 done=lambda: (cat_out / "index.json").exists()))
    return steps


def _prune(steps: list[Step], only: Optional[Sequence[str]]) -> list[Step]:
    if only is None:
        return steps
    keep = set(only)
    unknown = keep - {s.name for s in steps}
    if unknown:
        raise ValueError(f"unknown pipeline steps in only=: {sorted(unknown)}")
    pruned = []
    for s in steps:
        if s.name in keep:
            s.deps = tuple(d for d in s.deps if d in keep)
            pruned.append(s)
    return pruned


def _manifest_matches(dataset: Path, n_shards: int) -> bool:
    from sparse_coding_tpu.data.shard_store import read_store_manifest

    m = read_store_manifest(dataset)
    return m is not None and int(m.get("n_shards", -1)) == n_shards


def build_sharded_pipeline(run_dir: str | Path, config: dict,
                           only: Optional[Sequence[str]] = None) -> list[Step]:
    """The sharded data-plane DAG (ISSUE 8 tentpole):

        harvest-<i> (one writer child per shard, no edges between them)
          → manifest (aggregate sealed shards, backend-free)
          → scrub (digest re-verify + quarantine/repair, backend-free)
          → sweep → eval

    ``config["harvest"]["n_shards"]`` sets the writer count. The shard
    writers carry NO dependency edges on each other — on a pod they run
    concurrently (each owns its shard directory and nothing else); this
    container's supervisor executes them serially, which is the same DAG
    under the one-jax-process rule. Each writer is the flat harvest's
    crash-only contract scoped to its shard: durable chunk prefix + row
    skip on resume, ``shard.finalize`` crash barrier at the seal.
    ``done()`` for a writer is its shard's SEAL (digest after meta), for
    the manifest the store-level ``manifest.json``, for the scrub the
    RUN-scoped ``<run_dir>/scrub.done.json`` (store-resident markers
    would make every later run over the same store skip its scrub)."""
    from sparse_coding_tpu.data.shard_store import (
        SHARD_DIGEST_NAME,
        shard_name,
    )
    from sparse_coding_tpu.pipeline.steps import SCRUB_MARKER_NAME

    cfg_path, anchor = _persist_pipeline_config(run_dir, config)
    dataset = anchor(config["harvest"]["dataset_folder"])
    # RUN-scoped (unlike every store-resident marker above/below): a
    # later run over the same store must scrub again — see run_scrub
    scrub_done = Path(run_dir) / SCRUB_MARKER_NAME
    n_shards = int(config["harvest"]["n_shards"])

    def sealed(i: int) -> Callable[[], bool]:
        d = dataset / shard_name(i)
        return lambda: ((d / "meta.json").exists()
                        and (d / SHARD_DIGEST_NAME).exists())

    writers = [Step(f"harvest-{i}",
                    step_argv("shard_harvest", cfg_path)
                    + ["--shard", str(i)],
                    done=sealed(i))
               for i in range(n_shards)]
    steps = writers + [
        Step("manifest", step_argv("manifest", cfg_path),
             deps=tuple(w.name for w in writers),
             # presence is not enough: a manifest from a run with a
             # different n_shards lists a stale shard subset — the step
             # rebuilds it (run_store_manifest applies the same check)
             done=lambda: _manifest_matches(dataset, n_shards)),
        Step("scrub", step_argv("scrub", cfg_path), deps=("manifest",),
             done=scrub_done.exists),
    ] + _sweep_eval_steps(cfg_path, config, anchor, sweep_dep="scrub")
    return _prune(steps, only)


def build_group_pipeline(run_dir: str | Path, config: dict,
                         only: Optional[Sequence[str]] = None) -> list[Step]:
    """The Group-SAE data-plane DAG (§23):

        harvest-<i> (one multi-TAP writer child per layer — taps ARE
                     shards, no edges between the writers)
          → manifest (aggregate sealed shards, backend-free)
          → scrub (digest re-verify + quarantine/repair, backend-free)
          → group (similarity + greedy assignment → ``groups.json``,
                   backend-free; done() = the digest-sound marker)
          [→ sweep → eval (→ catalog) — opt-in: a config WITH a "sweep"
             section trains one pooled-store sweep inline; the usual
             shape instead enqueues one fleet tenant PER group after the
             ``group`` step finalizes (groups/tenants.py)]

    ``config["harvest"]["layers"]`` sets the writer count: writer ``i``
    harvests layer ``layers[i]`` into ``shard-<i>/``, replaying the SAME
    producer stream as every other writer so rows stay aligned across
    layers (the similarity pass's contract). Everything below the
    writers reuses the sharded plane verbatim — same manifest/scrub
    steps, same done() markers."""
    from sparse_coding_tpu.data.shard_store import (
        SHARD_DIGEST_NAME,
        shard_name,
    )
    from sparse_coding_tpu.groups.assign import GROUPS_NAME
    from sparse_coding_tpu.pipeline.steps import SCRUB_MARKER_NAME, _resolve_layers

    cfg_path, anchor = _persist_pipeline_config(run_dir, config)
    dataset = anchor(config["harvest"]["dataset_folder"])
    scrub_done = Path(run_dir) / SCRUB_MARKER_NAME
    n_layers = len(_resolve_layers(config["harvest"]))

    def sealed(i: int) -> Callable[[], bool]:
        d = dataset / shard_name(i)
        return lambda: ((d / "meta.json").exists()
                        and (d / SHARD_DIGEST_NAME).exists())

    writers = [Step(f"harvest-{i}",
                    step_argv("group_harvest", cfg_path)
                    + ["--shard", str(i)],
                    done=sealed(i))
               for i in range(n_layers)]
    steps = writers + [
        Step("manifest", step_argv("manifest", cfg_path),
             deps=tuple(w.name for w in writers),
             done=lambda: _manifest_matches(dataset, n_layers)),
        Step("scrub", step_argv("scrub", cfg_path), deps=("manifest",),
             done=scrub_done.exists),
        Step("group", step_argv("group", cfg_path), deps=("scrub",),
             done=lambda: (dataset / GROUPS_NAME).exists()),
    ]
    if "sweep" in config:
        steps += _sweep_eval_steps(cfg_path, config, anchor,
                                   sweep_dep="group")
    return _prune(steps, only)


def build_group_tenant_pipeline(run_dir: str | Path, config: dict,
                                only: Optional[Sequence[str]] = None,
                                ) -> list[Step]:
    """One group tenant's DAG (fleet ``kind="group"``, §23): just the
    sweep → eval (→ catalog) tail over the group's pooled store view —
    no harvest edge, because ``groups.json`` (and every pooled manifest
    under it) was durable before the tenant could be enqueued
    (groups/tenants.py reads the finalized assignment). Guardian halts
    stay contained to this tenant's run dir exactly as for flat
    tenants."""
    cfg_path, anchor = _persist_pipeline_config(run_dir, config)
    return _prune(_sweep_eval_steps(cfg_path, config, anchor,
                                    sweep_dep=None), only)


def supervise_bench(run_dir: str | Path, *, max_attempts: int = 2,
                    heartbeat_stale_s: Optional[float] = None) -> Path:
    """bench.py's ``--supervised`` mode: run the bench as a journaled,
    leased, watchdogged child. The child writes its one-line JSON record
    to ``<run_dir>/bench.json`` (``BENCH_RESULT_PATH``); a hang — the
    classic tunnel wedge during backend init — is diagnosed by socket
    probe, and when the tunnel endpoint is down the retry degrades to the
    bench's own reduced-scale ``--cpu-fallback`` with the plugin stripped.
    Returns the result path; the caller prints its content (the stdout
    contract stays one JSON line)."""
    run_dir = Path(run_dir)
    result_path = run_dir / "bench.json"
    # a benchmark result is per-INVOCATION: the marker is crash-resume
    # state within one supervised run, never a cache across runs — a
    # stale bench.json must not masquerade as a fresh measurement
    result_path.unlink(missing_ok=True)
    bench_py = str(REPO_ROOT / "bench.py")
    if heartbeat_stale_s is None:
        heartbeat_stale_s = float(os.environ.get("BENCH_HANG_S", "420"))
    env: dict = {"BENCH_RESULT_PATH": str(result_path)}
    axon = os.environ.get("BENCH_SUPERVISED_AXON", "").strip()
    if axon:
        # the parent re-exec'd itself plugin-stripped + cpu-pinned
        # (bench.py _supervised_main); the CHILD is the one tunnel client,
        # so it gets the pool IPs back and the cpu pin removed
        env["PALLAS_AXON_POOL_IPS"] = axon
        env["JAX_PLATFORMS"] = None
        env["BENCH_SUPERVISED_REEXEC"] = None
    step = Step(
        "bench", [sys.executable, bench_py],
        done=result_path.exists,
        degrade_argv=[sys.executable, bench_py, "--cpu-fallback"],
        env=env)
    sup = Supervisor(run_dir, [step], max_attempts=max_attempts,
                     heartbeat_stale_s=heartbeat_stale_s)
    sup.run()
    return result_path
