"""Elastic resource plane: serving and training trade one pod's slices.

The gateway's replica pool (serve/gateway.py) and the fleet scheduler
(pipeline/fleet.py) used to own static splits of the mesh. This module
is the ONE arbiter over both (docs/ARCHITECTURE.md §21): a control loop
that reads the serving front door's typed load snapshot
(:class:`~sparse_coding_tpu.serve.slo.LoadSignals`) and moves whole
replica-sized slice blocks between the two consumers —

- **scale-up** (traffic rising): shrink the fleet's share FIRST —
  scavenger-class tenants are SIGTERM-preempted at their next chunk
  boundary through the scheduler's existing checkpoint path
  (:meth:`FleetScheduler.reclaim_scavengers`) — then activate warm
  gateway spares at ZERO compiles via the xcache warmup manifest
  (``ServingGateway.scale_up`` → ``warmup_from_manifest``);
- **scale-down** (traffic ebbing): drain the least-healthy actives out
  of the routing order (``ServingGateway.scale_down``), release them to
  the spare set a tick later (the drain window), and hand the freed
  slices back to the fleet, where the preempted sweep resumes from its
  checkpoint bitwise.

Robustness is the design, not a feature:

- every rebalance is a **durable, bitwise-replayable record** in the
  fleet queue journal (``plane.rebalance`` events with ``step=""`` —
  the run-state fold ignores them by construction, so old readers keep
  working); :func:`replay_split` folds the journal into the current
  split, and a restarted arbiter acts on exactly what the dead one
  decided;
- the rebalance seam is fault-sited (``plane.rebalance`` before the
  durable append, ``plane.scale`` before each gateway action) and
  crash-barriered (``plane.rebalance``: record durable, NEITHER
  consumer resized yet). The chaos matrix SIGKILLs a real arbiter at
  that barrier and proves a restart reconciles — no slice
  double-booked, no tenant lost (tests/test_pipeline_chaos.py);
- **convergent apply**: every tick re-applies the replayed split to
  both consumers (idempotent — a no-op when they already match), so a
  failed or killed action self-heals on the next tick instead of
  needing compensation logic;
- **hysteresis**: a scale move needs ``hold_ticks`` CONSECUTIVE
  same-direction votes (mirroring the admission controller's
  count-gating), so a flapping load signal cannot thrash scavenger
  preemptions.

Pure decision logic (:func:`desired_replicas`, :class:`Hysteresis`,
:func:`replay_split`) reads no clocks and does no I/O — tests drive it
exactly. The import chain is jax-free: the arbiter shares the fleet
scheduler's host process and must never touch the TPU tunnel its
workers and replicas own.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

from sparse_coding_tpu import obs
from sparse_coding_tpu.pipeline.fleet_queue import QUEUE_NAME, FleetQueue
from sparse_coding_tpu.resilience import lease as lease_mod
from sparse_coding_tpu.resilience.crash import (
    crash_barrier,
    register_crash_site,
)
from sparse_coding_tpu.resilience.faults import (
    fault_point,
    register_fault_site,
)
from sparse_coding_tpu.serve.slo import LoadSignals

register_fault_site("plane.scale",
                    "elastic plane — fires before applying one gateway "
                    "replica scale action (pipeline/plane.py); an "
                    "injected error leaves the replica set unchanged "
                    "and counted (plane.scale_errors), re-applied next "
                    "tick")
register_fault_site("plane.rebalance",
                    "elastic plane — fires before the durable "
                    "plane.rebalance record append (pipeline/plane.py); "
                    "an injected error leaves the journal untouched and "
                    "counted (plane.rebalance_errors), re-voted next "
                    "tick")
register_crash_site("plane.rebalance",
                    "rebalance record durable in the fleet queue "
                    "journal, NEITHER consumer resized yet "
                    "(pipeline/plane.py) — restart must reconcile to "
                    "the recorded split with no slice double-booked")

# journal event name; ``step`` stays "" so pipeline/fleet_queue.py's
# run-state fold skips these records by its existing unknown-run guard
REBALANCE_EVENT = "plane.rebalance"


@dataclass(frozen=True)
class PlaneConfig:
    """The arbiter's static contract: pod size, replica granularity,
    scale envelope, and the load thresholds + hysteresis window."""

    n_slices: int                  # the whole pod, in mesh slices
    replica_slices: int = 1        # slices one gateway replica occupies
    min_replicas: int = 1          # the front door never scales below
    max_replicas: int = 0          # 0 = whatever the slice budget allows
    # scale votes read the SMOOTHED queue depth (LoadTracker EWMA):
    # above up_queued_rows (or any brownout rung) votes up, below
    # down_queued_rows with the ladder open votes down
    up_queued_rows: float = 64.0
    down_queued_rows: float = 8.0
    hold_ticks: int = 2            # consecutive same-direction votes

    def __post_init__(self):
        if self.n_slices < 1 or self.replica_slices < 1:
            raise ValueError("n_slices and replica_slices must be >= 1")
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1 (the front door "
                             "never scales to zero)")
        if self.min_replicas * self.replica_slices > self.n_slices:
            raise ValueError("min_replicas cannot outgrow the pod")
        if not 0 <= self.down_queued_rows <= self.up_queued_rows:
            raise ValueError("need 0 <= down_queued_rows <= "
                             "up_queued_rows")
        if self.hold_ticks < 1:
            raise ValueError("hold_ticks must be >= 1")

    def replica_cap(self) -> int:
        """Most replicas the pod (and max_replicas) allows."""
        by_slices = self.n_slices // self.replica_slices
        if self.max_replicas > 0:
            return min(by_slices, self.max_replicas)
        return by_slices

    def clamp(self, replicas: int) -> int:
        return max(self.min_replicas, min(self.replica_cap(), replicas))


@dataclass(frozen=True)
class PlaneSplit:
    """One durable serve/train division of the pod."""

    serve_slices: int
    fleet_slices: int
    seq: int = 0       # journal seq of the record that set it (0 = base)


def desired_replicas(signals: LoadSignals, current: int,
                     cfg: PlaneConfig) -> int:
    """Pure scale vote for ONE tick: ``current`` ±1, clamped. Reads only
    the typed snapshot — smoothed queue depth against the two
    thresholds, plus the brownout rung (a browning-out gateway is
    starved for capacity whatever the queue says). One step per tick:
    the plane trades whole replica blocks, and hysteresis (not vote
    magnitude) is the flap guard."""
    if (signals.queue_depth_ewma > cfg.up_queued_rows
            or signals.admission_level > 0):
        return cfg.clamp(current + 1)
    if (signals.queue_depth_ewma < cfg.down_queued_rows
            and signals.queued_rows == 0
            and signals.admission_level == 0):
        return cfg.clamp(current - 1)
    return cfg.clamp(current)


class Hysteresis:
    """Direction filter: emits a move only after ``hold_ticks``
    CONSECUTIVE ticks vote the same direction (the admission
    controller's count-gating idiom, serve/slo.py). A changed or
    neutral vote resets the streak, so one noisy tick can never flip
    the split back and forth."""

    def __init__(self, hold_ticks: int):
        self._hold = max(1, int(hold_ticks))
        self._direction = 0
        self._streak = 0

    def vote(self, direction: int) -> int:
        """Feed one tick's vote (-1 / 0 / +1); returns the confirmed
        move (0 until the streak completes; completing resets it)."""
        direction = (direction > 0) - (direction < 0)
        if direction == 0 or direction != self._direction:
            self._direction = direction
            self._streak = 1 if direction else 0
            confirm = direction != 0 and self._streak >= self._hold
        else:
            self._streak += 1
            confirm = self._streak >= self._hold
        if confirm:
            self._streak = 0
            return direction
        return 0


def replay_split(queue: FleetQueue, cfg: PlaneConfig) -> PlaneSplit:
    """Fold the fleet queue journal into the current split — the ONLY
    way any arbiter (first, restarted, or taken-over) knows the
    division. Pure over the journal bytes: the last durable
    ``plane.rebalance`` record wins; with none, the base split is
    ``min_replicas`` worth of serving and the rest fleet."""
    serve = cfg.min_replicas * cfg.replica_slices
    split = PlaneSplit(serve_slices=serve,
                       fleet_slices=cfg.n_slices - serve, seq=0)
    for rec in queue.journal.records():
        if rec.get("event") != REBALANCE_EVENT:
            continue
        detail = rec.get("detail", {}) or {}
        split = PlaneSplit(
            serve_slices=int(detail.get("serve_slices", serve)),
            fleet_slices=int(detail.get("fleet_slices",
                                        cfg.n_slices - serve)),
            seq=int(rec.get("seq", 0)))
    return split


class ElasticPlane:
    """The arbiter. Owns no slices itself — it reads load, appends
    durable rebalance records, and drives both consumers toward the
    recorded split every tick (convergent apply).

    ``gateway`` / ``fleet`` are duck-typed and each optional (a
    fleet-only arbiter still tracks serving's share; tests and the
    chaos drill exploit this to stay jax-free). ``signals_fn`` defaults
    to ``gateway.load_signals`` and is injectable, so a scripted load
    trace drives the decision path deterministically."""

    def __init__(self, fleet_dir: str | Path, config: PlaneConfig, *,
                 gateway=None, fleet=None,
                 signals_fn: Optional[Callable[[], LoadSignals]] = None,
                 clock=time.time):
        self.fleet_dir = Path(fleet_dir)
        self.cfg = config
        self.gateway = gateway
        self.fleet = fleet
        if fleet is not None:
            self.queue = fleet.queue
        else:
            self.queue = FleetQueue(self.fleet_dir / QUEUE_NAME,
                                    clock=clock)
        if signals_fn is None:
            if gateway is None:
                raise ValueError("need a gateway or an explicit "
                                 "signals_fn to read load from")
            signals_fn = gateway.load_signals
        self._signals_fn = signals_fn
        self._hyst = Hysteresis(config.hold_ticks)
        # replicas drained by the last scale-down, released (DRAINING →
        # SPARE) one tick later: the drain window in which their
        # in-flight dispatches finish
        self._draining: list[str] = []
        self._ticks = 0

    # -- durable state --------------------------------------------------------

    def split(self) -> PlaneSplit:
        return replay_split(self.queue, self.cfg)

    def target_replicas(self, split: Optional[PlaneSplit] = None) -> int:
        split = split if split is not None else self.split()
        return split.serve_slices // self.cfg.replica_slices

    def reconcile(self) -> PlaneSplit:
        """The restart path: fold the journal and drive both consumers
        to the last durable split (idempotent — a no-op on a clean
        handover). The chaos case SIGKILLs an arbiter between its
        rebalance record and the apply; THIS is what makes that record
        the truth instead of a lost update."""
        split = self.split()
        self._apply(split)
        obs.counter("plane.reconciles").inc()
        return split

    # -- the control loop -----------------------------------------------------

    def tick(self) -> dict:
        """One arbiter pass: release drained replicas, read signals,
        vote through hysteresis, maybe append a rebalance record, then
        converge both consumers on the (possibly new) split. Returns a
        breadcrumb dict for operators and tests."""
        self._ticks += 1
        self._release_drained()
        signals = self._signals_fn()
        split = self.split()
        current = self.target_replicas(split)
        vote = desired_replicas(signals, current, self.cfg) - current
        move = self._hyst.vote(vote)
        rebalanced = False
        if move:
            target = self.cfg.clamp(current + move)
            if target != current:
                new_split = self._rebalance(target, signals)
                if new_split is not None:
                    split, rebalanced = new_split, True
        self._apply(split)
        # the ladder swap rides the arbiter tick (§24): one
        # derive→hold→swap pass per tick, duck-typed so jax-free
        # fleet-only arbiters (and test doubles without the method) are
        # untouched. maybe_swap_ladder never raises — failures are
        # counted skips inside the gateway.
        ladder_swap = None
        swap_fn = getattr(self.gateway, "maybe_swap_ladder", None)
        if swap_fn is not None:
            ladder_swap = swap_fn()
        if ladder_swap is not None:
            obs.counter("plane.ladder_swaps").inc()
        return {"tick": self._ticks, "signals": signals, "split": split,
                "replicas": self.target_replicas(split), "vote": vote,
                "rebalanced": rebalanced,
                "ladder_swapped": ladder_swap is not None}

    def run(self, *, poll_s: float = 0.25,
            max_wall_s: Optional[float] = None,
            stop: Optional[Callable[[], bool]] = None) -> None:
        """Drive ticks until ``stop()`` (or ``max_wall_s``). The arbiter
        is a pipeline work loop: it beats the process lease at its
        progress point so the hang watchdog can tell a slow rebalance
        from a dead one (beat-coverage, analysis/beats.py)."""
        t0 = obs.monotime()
        while not (stop is not None and stop()):
            self.tick()
            if max_wall_s is not None and obs.monotime() - t0 > max_wall_s:
                break
            lease_mod.beat()
            time.sleep(poll_s)

    # -- the rebalance seam ---------------------------------------------------

    def _rebalance(self, replicas: int,
                   signals: LoadSignals) -> Optional[PlaneSplit]:
        """Make one confirmed scale move durable. Order is the whole
        contract: fault site → journal append → crash barrier → (the
        caller applies). An injected fault leaves the journal untouched
        (the hysteresis-confirmed vote re-forms next ticks); a SIGKILL
        at the barrier leaves a durable record a restarted arbiter
        reconciles to."""
        serve = replicas * self.cfg.replica_slices
        fleet_share = self.cfg.n_slices - serve
        direction = "up" if serve > self.split().serve_slices else "down"
        try:
            fault_point("plane.rebalance")
        except Exception:  # noqa: BLE001 — injected/transient: re-vote next tick
            obs.counter("plane.rebalance_errors").inc()
            return None
        rec = self.queue.append(
            REBALANCE_EVENT,
            serve_slices=serve, fleet_slices=fleet_share,
            replicas=replicas, reason=direction,
            queued_rows=signals.queued_rows,
            queue_depth_ewma=round(signals.queue_depth_ewma, 3),
            admission_level=signals.admission_level)
        # THE rebalance instant: the decision is durable, neither
        # consumer has been resized. A SIGKILL here must cost nothing —
        # reconcile() on restart applies this exact record (the chaos
        # matrix proves no double-booking, no lost tenant).
        crash_barrier("plane.rebalance")
        obs.counter("plane.rebalances").inc()
        obs.counter("plane.scale_ups" if direction == "up"
                    else "plane.scale_downs").inc()
        obs.emit_event("plane.rebalance", serve_slices=serve,
                       fleet_slices=fleet_share, reason=direction)
        return PlaneSplit(serve_slices=serve, fleet_slices=fleet_share,
                          seq=int(rec.get("seq", 0)))

    # -- convergent apply -----------------------------------------------------

    def _apply(self, split: PlaneSplit) -> None:
        """Drive both consumers TO the split (idempotent). Shrink-first
        ordering keeps the pod never over-committed in the ledger: the
        fleet's share is capped (and over-share scavengers preempted
        into their checkpoint path) BEFORE the gateway widens, and the
        gateway narrows by drain before the fleet's share grows —
        freed slices flow through the queue's release records, never a
        double-booking."""
        if self.fleet is not None:
            self.fleet.n_slices = split.fleet_slices
            reclaimed = self.fleet.reclaim_scavengers(split.fleet_slices)
            if reclaimed:
                obs.counter("plane.reclaims").inc(len(reclaimed))
        if self.gateway is not None:
            target = self.target_replicas(split)
            active = len(self.gateway.active_replica_names())
            try:
                if active != target:
                    fault_point("plane.scale")
                if active < target:
                    self.gateway.scale_up(target - active)
                elif active > target:
                    self._draining.extend(
                        self.gateway.scale_down(active - target))
            except Exception:  # noqa: BLE001 — injected/transient: re-applied next tick
                obs.counter("plane.scale_errors").inc()
        obs.gauge("plane.serve_slices").set(split.serve_slices)
        obs.gauge("plane.fleet_slices").set(split.fleet_slices)
        obs.gauge("plane.replicas").set(self.target_replicas(split))

    def _release_drained(self) -> None:
        """The drain window closed (one full tick): return replicas the
        plane drained to the spare set, warm for the next scale-up.
        A replica the self-healing pass re-drained or re-activated in
        the meantime is simply skipped."""
        if not self._draining or self.gateway is None:
            return
        for name in self._draining:
            try:
                self.gateway.reinstate(name)
                obs.counter("plane.replicas_released").inc()
            except (KeyError, ValueError):
                continue
        self._draining = []
