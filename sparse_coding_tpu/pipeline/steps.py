"""Built-in pipeline step children: harvest / sweep / eval.

Each step is a subprocess entrypoint (``python -m
sparse_coding_tpu.pipeline.steps <step> --config pipeline.json``) obeying
the crash-only contract the supervisor depends on:

- **re-runnable from scratch at any instant**: harvest resumes from the
  durable chunk prefix (``complete_chunk_count`` + producer-row skip, or
  ``skip_chunks`` on the LM path), the sweep resumes from §4/§10's
  checkpoint sets (``resume=True``), eval is idempotent behind its output
  marker — so a SIGKILL anywhere costs only the in-flight unit of work
  and the completed run is bitwise-identical to an uninterrupted one;
- **heartbeats from the work loop** (:mod:`resilience.lease`): the lease
  configured from ``SPARSE_CODING_LEASE_PATH`` is beaten at chunk/window
  granularity by the host modules, so a wedged process goes visibly
  stale;
- **every durable transition sits behind a named crash barrier**
  (:mod:`resilience.crash`), which is how the chaos matrix kills real
  children at exactly the worst instants.

Config file: one JSON object with ``harvest`` / ``sweep`` / ``eval``
sections (see each step function). All seeds are explicit — two runs of
the same config must produce byte-identical artifacts.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site

register_crash_site("eval.write",
                    "pipeline eval step — results computed, output file "
                    "not yet written")


def run_harvest(config: dict) -> None:
    """``config["harvest"]`` keys — common: ``mode`` ("synthetic" | "lm"),
    ``dataset_folder`` (the chunk store the sweep reads; completion marker
    is its ``meta.json``), ``seed``. Synthetic: ``activation_dim``,
    ``n_ground_truth_features``, ``feature_num_nonzero``,
    ``feature_prob_decay``, ``dataset_size``, ``n_chunks``,
    ``batch_rows``. LM: ``arch``, ``layer``, ``layer_loc``, ``n_rows``,
    ``context_len``, ``model_batch_size``, ``chunk_size_gb`` — the
    dataset_folder must be the TAP subfolder the harvester writes."""
    from sparse_coding_tpu.data.chunk_store import clean_write_debris

    cfg = config["harvest"]
    folder = Path(cfg["dataset_folder"])
    if (folder / "meta.json").exists():
        return  # complete store: nothing to do (idempotent)
    folder.mkdir(parents=True, exist_ok=True)
    clean_write_debris(folder)  # tmp debris from a killed writer
    if cfg.get("mode", "synthetic") == "synthetic":
        _synthetic_harvest(cfg)
    else:
        _lm_harvest(cfg)


def _synthetic_harvest(cfg: dict) -> None:
    """Deterministic synthetic activation store with crash-resume: the
    generator stream is replayed from its seed and the rows already
    covered by durable chunks are skipped, so the finished store —
    chunks, digests, meta — is byte-identical however many times the
    process died along the way."""
    import jax

    from sparse_coding_tpu.data.chunk_store import (
        ChunkWriter,
        complete_chunk_count,
    )
    from sparse_coding_tpu.data.synthetic import RandomDatasetGenerator

    folder = Path(cfg["dataset_folder"])
    dim = int(cfg["activation_dim"])
    total = int(cfg["dataset_size"])
    n_chunks = int(cfg.get("n_chunks", 4))
    seed = int(cfg.get("seed", 0))
    dtype = cfg.get("dtype", "float16")
    rows_per_chunk = total // n_chunks
    bytes_per_row = dim * np.dtype(np.float16 if dtype == "float16"
                                   else np.float32).itemsize
    k = complete_chunk_count(folder)
    gen = RandomDatasetGenerator.create(
        jax.random.PRNGKey(seed), dim, int(cfg["n_ground_truth_features"]),
        int(cfg.get("feature_num_nonzero", 5)),
        float(cfg.get("feature_prob_decay", 0.99)),
        correlated=bool(cfg.get("correlated_components", False)))
    writer = ChunkWriter(folder, dim,
                         chunk_size_gb=rows_per_chunk * bytes_per_row / 2**30,
                         dtype=dtype, start_index=k)
    skip_rows = k * writer.rows_per_chunk
    key = jax.random.PRNGKey(seed + 1)
    batch_rows = int(cfg.get("batch_rows", 8192))
    produced = 0
    while produced < total:
        key, sub = jax.random.split(key)
        n = min(total - produced, batch_rows)
        if produced + n > skip_rows:
            batch = np.asarray(jax.device_get(gen.batch(sub, n)))
            lo = max(0, skip_rows - produced)
            writer.add(batch[lo:])
        produced += n
        lease.beat()
    writer.finalize({"synthetic": True, "seed": seed})


def _lm_harvest(cfg: dict) -> None:
    """Tiny-LM harvest through the REAL ``harvest_activations`` path
    (random-init weights, seeded token rows — no network), resuming via
    ``skip_chunks`` from the durable chunk prefix."""
    import jax

    from sparse_coding_tpu.data.chunk_store import complete_chunk_count
    from sparse_coding_tpu.data.harvest import harvest_activations
    from sparse_coding_tpu.lm.model_config import tiny_test_config

    folder = Path(cfg["dataset_folder"])  # the tap subfolder
    arch = cfg.get("arch", "gptneox")
    lm_cfg = tiny_test_config(arch)
    if arch == "gptneox":
        from sparse_coding_tpu.lm.gptneox import init_params
    else:
        from sparse_coding_tpu.lm.gpt2 import init_params
    seed = int(cfg.get("seed", 0))
    params = init_params(jax.random.PRNGKey(seed), lm_cfg)
    rng = np.random.default_rng(seed)
    token_rows = rng.integers(
        0, lm_cfg.vocab_size,
        (int(cfg["n_rows"]), int(cfg.get("context_len", 16))))
    harvest_activations(
        params, lm_cfg, token_rows, [int(cfg.get("layer", 1))],
        cfg.get("layer_loc", "residual"), folder.parent,
        model_batch_size=int(cfg.get("model_batch_size", 2)),
        chunk_size_gb=float(cfg["chunk_size_gb"]),
        skip_chunks=complete_chunk_count(folder),
        dtype=cfg.get("dtype", "float16"))


def run_sweep(config: dict) -> None:
    """``config["sweep"]`` keys: ``experiment`` (EXPERIMENTS registry
    name), ``ensemble`` (EnsembleArgs fields), ``log_every``. Always runs
    ``resume=True`` — a fresh run resumes from nothing, a killed run from
    its newest complete checkpoint set (§10 fallback chain included).

    The completion marker is written HERE, not by ``sweep()``'s periodic
    artifact saves: ``<output>/final/<name>_learned_dicts.pkl`` is
    derived from the (restored or live) end state, so it exists even when
    the resume had zero chunks left to train — the property that makes
    "retry after any kill" converge instead of looping."""
    import sparse_coding_tpu.train.sweep as sweep_mod
    from sparse_coding_tpu.config import EnsembleArgs
    from sparse_coding_tpu.train.experiments import EXPERIMENTS
    from sparse_coding_tpu.utils.artifacts import save_learned_dicts

    cfg = config["sweep"]
    ens_cfg = EnsembleArgs(**cfg["ensemble"])
    result = sweep_mod.sweep(EXPERIMENTS[cfg.get("experiment",
                                                 "dense_l1_range")],
                             ens_cfg, resume=True,
                             log_every=int(cfg.get("log_every", 100)),
                             image_metrics_every=None)
    final = Path(ens_cfg.output_folder) / "final"
    for name, tagged in result.items():
        save_learned_dicts(tagged, final / f"{name}_learned_dicts.pkl")


def run_eval(config: dict) -> None:
    """``config["eval"]`` keys: ``output_folder``, ``n_eval_rows``,
    ``seed``. Scores every dictionary in the sweep's final artifact (FVU +
    mean L0 on a seeded slice of chunk 0) and writes ``eval.json``
    atomically behind the ``eval.write`` crash barrier."""
    import jax.numpy as jnp

    from sparse_coding_tpu.data.chunk_store import ChunkStore
    from sparse_coding_tpu.metrics.core import (
        fraction_variance_unexplained,
        mean_l0,
    )
    from sparse_coding_tpu.utils.artifacts import load_learned_dicts

    cfg = config["eval"]
    out = Path(cfg["output_folder"])
    marker = out / "eval.json"
    if marker.exists():
        return
    out.mkdir(parents=True, exist_ok=True)
    name = config["sweep"].get("experiment", "dense_l1_range")
    pkl = (Path(config["sweep"]["ensemble"]["output_folder"]) / "final"
           / f"{name}_learned_dicts.pkl")
    tagged = load_learned_dicts(pkl)
    store = ChunkStore(config["harvest"]["dataset_folder"])
    chunk = store.load_chunk(0)
    rng = np.random.default_rng(int(cfg.get("seed", 0)))
    rows = rng.permutation(chunk.shape[0])[:int(cfg.get("n_eval_rows", 2048))]
    eval_batch = jnp.asarray(chunk[rows], jnp.float32)
    records = []
    for ld, hyper in tagged:
        records.append({
            **{k: v for k, v in hyper.items()
               if isinstance(v, (int, float, str, bool))},
            "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
            "l0": float(mean_l0(ld, eval_batch))})
        lease.beat()
    crash_barrier("eval.write")
    atomic_write_text(marker, json.dumps(
        {"experiment": name, "n_eval_rows": int(len(rows)),
         "dicts": records}, indent=2))


STEPS = {"harvest": run_harvest, "sweep": run_sweep, "eval": run_eval}


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 3 or argv[1] != "--config" or argv[0] not in STEPS:
        raise SystemExit(
            f"usage: python -m sparse_coding_tpu.pipeline.steps "
            f"{{{'|'.join(STEPS)}}} --config pipeline.json")
    step, config_path = argv[0], argv[2]
    # claim the lease before any real work: from here on, silence = hang
    lease.configure_from_env(step=step)
    # join the run's observability stream (no-op outside a supervisor):
    # the env carries SPARSE_CODING_RUN_ID / _OBS_DIR / _OBS_STEP, so this
    # child's spans, XLA probe counters, and metrics snapshots land in the
    # same obs dir as the supervisor's and merge in obs.report (§12)
    obs.configure_sink_from_env(step)
    obs.install_jax_probes()
    # persistent executable cache (§13): the supervisor propagates one
    # shared SPARSE_CODING_XCACHE_DIR per run, so a respawned attempt of
    # this step loads executables instead of recompiling (no-op when the
    # env is unset — bare step invocations stay cache-free)
    from sparse_coding_tpu import xcache

    xcache.enable_from_env()
    config = json.loads(Path(config_path).read_text())
    try:
        with obs.span(f"step.{step}"):
            STEPS[step](config)
    finally:
        obs.update_memory_gauges()
        obs.flush_metrics()
        obs.close_sink()


if __name__ == "__main__":
    main()
