"""Built-in pipeline step children: harvest / sweep / eval.

Each step is a subprocess entrypoint (``python -m
sparse_coding_tpu.pipeline.steps <step> --config pipeline.json``) obeying
the crash-only contract the supervisor depends on:

- **re-runnable from scratch at any instant**: harvest resumes from the
  durable chunk prefix (``complete_chunk_count`` + producer-row skip, or
  ``skip_chunks`` on the LM path), the sweep resumes from §4/§10's
  checkpoint sets (``resume=True``), eval is idempotent behind its output
  marker — so a SIGKILL anywhere costs only the in-flight unit of work
  and the completed run is bitwise-identical to an uninterrupted one;
- **heartbeats from the work loop** (:mod:`resilience.lease`): the lease
  configured from ``SPARSE_CODING_LEASE_PATH`` is beaten at chunk/window
  granularity by the host modules, so a wedged process goes visibly
  stale;
- **every durable transition sits behind a named crash barrier**
  (:mod:`resilience.crash`), which is how the chaos matrix kills real
  children at exactly the worst instants.

Config file: one JSON object with ``harvest`` / ``sweep`` / ``eval``
sections (see each step function). All seeds are explicit — two runs of
the same config must produce byte-identical artifacts.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Optional

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site

register_crash_site("eval.write",
                    "pipeline eval step — results computed, output file "
                    "not yet written")


class HarvestConfigError(ValueError):
    """Typed harvest-config contradiction: ``layer`` and ``layers``
    given inconsistently, or a ``dataset_folder`` that is not the
    primary tap subfolder the multi-layer harvester will write."""


def run_harvest(config: dict) -> None:
    """``config["harvest"]`` keys — common: ``mode`` ("synthetic" | "lm"),
    ``dataset_folder`` (the chunk store the sweep reads; completion marker
    is its ``meta.json``), ``seed``. Synthetic: ``activation_dim``,
    ``n_ground_truth_features``, ``feature_num_nonzero``,
    ``feature_prob_decay``, ``dataset_size``, ``n_chunks``,
    ``batch_rows``. LM: ``arch``, ``layer``, ``layer_loc``, ``n_rows``,
    ``context_len``, ``model_batch_size``, ``chunk_size_gb`` — the
    dataset_folder must be the TAP subfolder the harvester writes."""
    from sparse_coding_tpu.data.chunk_store import clean_write_debris

    cfg = config["harvest"]
    folder = Path(cfg["dataset_folder"])
    if (folder / "meta.json").exists():
        return  # complete store: nothing to do (idempotent)
    folder.mkdir(parents=True, exist_ok=True)
    clean_write_debris(folder)  # tmp debris from a killed writer
    if cfg.get("mode", "synthetic") == "synthetic":
        _synthetic_harvest(cfg)
    else:
        _lm_harvest(cfg)


def _synthetic_harvest(cfg: dict, folder: Path = None,
                       row_range: tuple = None, transform=None,
                       extra_meta: dict = None) -> None:
    """Deterministic synthetic activation store with crash-resume: the
    generator stream is replayed from its seed and the rows already
    covered by durable chunks are skipped, so the finished store —
    chunks, digests, meta — is byte-identical however many times the
    process died along the way.

    ``row_range=(lo, hi)`` writes only that slice of the generator stream
    into ``folder`` — the sharded-writer case: every shard writer replays
    the SAME seeded stream and keeps its own rows, so N writers sharing
    nothing produce a store whose concatenation is bitwise the unsharded
    harvest's.

    ``transform`` (row-wise, pure numpy, deterministic) maps kept rows
    before they are written — the multi-TAP writer case (group harvest):
    every layer writer replays the same stream and applies its own
    layer mix, so rows stay positionally aligned across layers.
    ``extra_meta`` merges into the finalize metadata (tap identity)."""
    import jax

    from sparse_coding_tpu.data.chunk_store import (
        ChunkWriter,
        complete_chunk_count,
    )
    from sparse_coding_tpu.data.synthetic import RandomDatasetGenerator

    folder = Path(cfg["dataset_folder"]) if folder is None else folder
    dim = int(cfg["activation_dim"])
    total = int(cfg["dataset_size"])
    n_chunks = int(cfg.get("n_chunks", 4))
    seed = int(cfg.get("seed", 0))
    dtype = cfg.get("dtype", "float16")
    rows_per_chunk = total // n_chunks
    bytes_per_row = dim * np.dtype(np.float16 if dtype == "float16"
                                   else np.float32).itemsize
    lo_row, hi_row = row_range if row_range is not None else (0, total)
    k = complete_chunk_count(folder)
    gen = RandomDatasetGenerator.create(
        jax.random.PRNGKey(seed), dim, int(cfg["n_ground_truth_features"]),
        int(cfg.get("feature_num_nonzero", 5)),
        float(cfg.get("feature_prob_decay", 0.99)),
        correlated=bool(cfg.get("correlated_components", False)))
    writer = ChunkWriter(folder, dim,
                         chunk_size_gb=rows_per_chunk * bytes_per_row / 2**30,
                         dtype=dtype, start_index=k)
    skip_rows = lo_row + k * writer.rows_per_chunk
    key = jax.random.PRNGKey(seed + 1)
    batch_rows = int(cfg.get("batch_rows", 8192))
    produced = 0
    while produced < hi_row:
        key, sub = jax.random.split(key)
        n = min(total - produced, batch_rows)
        if produced + n > skip_rows:
            batch = np.asarray(jax.device_get(gen.batch(sub, n)))
            b_lo = max(0, skip_rows - produced)
            b_hi = min(n, hi_row - produced)
            if b_hi > b_lo:
                kept = batch[b_lo:b_hi]
                writer.add(transform(kept) if transform is not None
                           else kept)
        produced += n
        lease.beat()
    writer.finalize({"synthetic": True, "seed": seed,
                     **({"row_range": [lo_row, hi_row]}
                        if row_range is not None else {}),
                     **(extra_meta or {})})


def _resolve_layers(cfg: dict) -> list[int]:
    """The harvest layer list: ``layers`` (DataArgs.layers semantics,
    multi-tap) with ``layer`` kept as the single-tap back-compat alias.
    Giving both is fine only when they agree — a config saying
    ``layer: 3`` but ``layers: [1, 2]`` would silently harvest the wrong
    tap under one reading, so it raises typed instead."""
    layers, layer = cfg.get("layers"), cfg.get("layer")
    if layers is None:
        return [int(layer if layer is not None else 1)]
    layers = [int(v) for v in layers]
    if not layers:
        raise HarvestConfigError("harvest.layers must be non-empty")
    if layer is not None and int(layer) not in layers:
        raise HarvestConfigError(
            f"harvest.layer={int(layer)} contradicts "
            f"harvest.layers={layers} — drop the alias or include it")
    return layers


def _lm_harvest(cfg: dict, tap_dirs: dict = None) -> None:
    """Tiny-LM harvest through the REAL ``harvest_activations`` path
    (random-init weights, seeded token rows — no network), resuming via
    ``skip_chunks`` from the durable chunk prefix. Multi-tap when
    ``layers`` lists several: one forward pass writes every tap's
    subfolder of ``dataset_folder``'s parent (``dataset_folder`` itself
    must be the PRIMARY — first — tap subfolder, the step's completion
    marker); ``tap_dirs`` remaps tap → folder (group harvest shards)."""
    import jax

    from sparse_coding_tpu.data.chunk_store import complete_chunk_count
    from sparse_coding_tpu.data.harvest import harvest_activations
    from sparse_coding_tpu.lm.hooks import tap_name, taps_for
    from sparse_coding_tpu.lm.model_config import tiny_test_config

    folder = Path(cfg["dataset_folder"])  # the PRIMARY tap subfolder
    layers = _resolve_layers(cfg)
    layer_loc = cfg.get("layer_loc", "residual")
    taps = taps_for(layers, layer_loc)
    tap_dirs = dict(tap_dirs or {})
    if not tap_dirs and folder.name != tap_name(layers[0], layer_loc):
        raise HarvestConfigError(
            f"harvest.dataset_folder must be the primary tap subfolder "
            f"{tap_name(layers[0], layer_loc)!r} the harvester writes "
            f"(got {folder.name!r})")
    arch = cfg.get("arch", "gptneox")
    lm_cfg = tiny_test_config(arch)
    if arch == "gptneox":
        from sparse_coding_tpu.lm.gptneox import init_params
    else:
        from sparse_coding_tpu.lm.gpt2 import init_params
    seed = int(cfg.get("seed", 0))
    params = init_params(jax.random.PRNGKey(seed), lm_cfg)
    rng = np.random.default_rng(seed)
    token_rows = rng.integers(
        0, lm_cfg.vocab_size,
        (int(cfg["n_rows"]), int(cfg.get("context_len", 16))))
    # resume from the SHORTEST durable tap prefix: one forward feeds all
    # writers, so a tap ahead of the others just re-seals idempotently
    skip = min(complete_chunk_count(Path(tap_dirs.get(t, folder.parent / t)))
               for t in taps)
    harvest_activations(
        params, lm_cfg, token_rows, layers, layer_loc, folder.parent,
        model_batch_size=int(cfg.get("model_batch_size", 2)),
        chunk_size_gb=float(cfg["chunk_size_gb"]),
        skip_chunks=skip,
        dtype=cfg.get("dtype", "float16"),
        tap_dirs=tap_dirs or None)


def run_shard_harvest(config: dict, shard: int) -> None:
    """One PARALLEL harvest writer owning one shard (ISSUE 8 tentpole):
    ``config["harvest"]`` plus ``n_shards`` — this child writes
    ``<dataset_folder>/shard-<i>/`` and NOTHING else, so shard writers
    share no files and can run as concurrent supervisor children on a
    pod (this container runs them serially — one jax process at a time,
    CLAUDE.md — but the DAG carries no edges between them).

    The shard covers rows ``[i*per_shard, (i+1)*per_shard)`` of the same
    seeded generator stream the unsharded harvest replays, so the store's
    shard-major concatenation is bitwise the unsharded harvest. Resume is
    the flat harvest's contract per shard: durable chunk prefix + row
    skip; a finished shard re-seals idempotently (``shard.finalize``
    crash barrier inside ``write_shard_digest``)."""
    from sparse_coding_tpu.data.shard_store import shard_name, write_shard_digest

    cfg = config["harvest"]
    if cfg.get("mode", "synthetic") != "synthetic":
        raise ValueError(
            "sharded harvest currently supports mode='synthetic' only "
            "(the LM path needs a token-row partitioner first)")
    n_shards = int(cfg["n_shards"])
    shard = int(shard)
    if not 0 <= shard < n_shards:
        raise ValueError(f"shard {shard} out of range [0, {n_shards})")
    total = int(cfg["dataset_size"])
    n_chunks = int(cfg.get("n_chunks", 4))
    if total % n_chunks or n_chunks % n_shards:
        raise ValueError(
            f"dataset_size={total} must divide into n_chunks={n_chunks} "
            f"and n_chunks into n_shards={n_shards} for bitwise-stable "
            "shard boundaries")
    folder = Path(cfg["dataset_folder"]) / shard_name(shard)
    per_shard = total // n_shards
    if not (folder / "meta.json").exists():
        from sparse_coding_tpu.data.chunk_store import clean_write_debris

        folder.mkdir(parents=True, exist_ok=True)
        clean_write_debris(folder)  # tmp debris from a killed writer
        _synthetic_harvest(cfg, folder=folder,
                           row_range=(shard * per_shard,
                                      (shard + 1) * per_shard))
    # seal (idempotent): meta durable -> crash barrier -> shard.digest
    write_shard_digest(folder)


def _layer_mixer(dim: int, layer: int, seed: int, phase_step: float):
    """Deterministic per-layer mix for the synthetic multi-tap harvest:
    ``x ↦ cos(φ)·x + sin(φ)·(x·Q)`` with one orthogonal Q shared by all
    layers and φ = phase_step·layer, so two layers' rows subtend angle
    ≈ |φ_i − φ_j| and adjacent layers are measurably more similar — the
    Group-SAE premise (arXiv 2410.21508 §3), reproduced synthetically.
    Pure rowwise numpy, a function of (dim, layer, seed) only — resume
    replays bitwise."""
    q, _ = np.linalg.qr(
        np.random.default_rng(int(seed) + 7919).normal(size=(dim, dim)))
    q = q.astype(np.float32)
    c, s = np.float32(np.cos(phase_step * layer)), \
        np.float32(np.sin(phase_step * layer))

    def mix(rows: np.ndarray) -> np.ndarray:
        x = rows.astype(np.float32, copy=False)
        return c * x + s * (x @ q)

    return mix


def run_group_harvest(config: dict, shard: int) -> None:
    """One PARALLEL multi-TAP writer owning one layer (= one shard of
    the multi-tap store): ``config["harvest"]`` plus ``layers`` — child
    ``i`` harvests layer ``layers[i]`` into
    ``<dataset_folder>/shard-<i>/`` and NOTHING else. Taps ARE shards:
    the sealed-shard layout, manifest step, scrub and fsck shard
    checkers carry the multi-tap store unchanged, and the DAG carries no
    edges between the writers (this container runs them serially — one
    jax process at a time, CLAUDE.md).

    Every writer replays the SAME producer stream over all rows, so row
    ``r`` of shard ``i`` and row ``r`` of shard ``j`` are the same input
    observed at two depths — the row alignment
    ``groups/similarity.py`` depends on. Synthetic mode applies the
    deterministic per-layer rotation (``_layer_mixer``); LM mode runs
    the real ``harvest_activations`` with this child's tap remapped to
    its shard dir. Resume/seal contract is ``run_shard_harvest``'s:
    durable chunk prefix + row skip, idempotent re-seal behind the
    ``shard.finalize`` crash barrier."""
    from sparse_coding_tpu.data.shard_store import shard_name, write_shard_digest
    from sparse_coding_tpu.lm.hooks import tap_name

    cfg = config["harvest"]
    layers = _resolve_layers(cfg)
    shard = int(shard)
    if not 0 <= shard < len(layers):
        raise ValueError(f"shard {shard} out of range [0, {len(layers)})")
    layer = layers[shard]
    layer_loc = cfg.get("layer_loc", "residual")
    tap = tap_name(layer, layer_loc)
    folder = Path(cfg["dataset_folder"]) / shard_name(shard)
    if not (folder / "meta.json").exists():
        from sparse_coding_tpu.data.chunk_store import clean_write_debris

        folder.mkdir(parents=True, exist_ok=True)
        clean_write_debris(folder)  # tmp debris from a killed writer
        if cfg.get("mode", "synthetic") == "synthetic":
            mixer = _layer_mixer(int(cfg["activation_dim"]), layer,
                                 int(cfg.get("seed", 0)),
                                 float(cfg.get("phase_step", 0.35)))
            _synthetic_harvest(cfg, folder=folder, transform=mixer,
                               extra_meta={"tap": tap, "layer": layer,
                                           "layer_loc": layer_loc})
        else:
            _lm_harvest({**cfg, "layers": [layer], "layer": layer,
                         "dataset_folder": str(folder)},
                        tap_dirs={tap: folder})
    # seal (idempotent): meta durable -> crash barrier -> shard.digest
    write_shard_digest(folder)


def run_group(config: dict) -> None:
    """``config["group"]`` keys: ``n_groups``, optional
    ``n_sample_chunks`` / ``n_sample_rows`` / ``seed``. Similarity pass
    + greedy adjacent assignment over the multi-tap store, finalizing
    ``groups.json`` (docs/ARCHITECTURE.md §23). Backend-free —
    ``groups/`` never imports jax, so like scrub/catalog this step runs
    against a wedged tunnel. Idempotent behind a digest-SOUND
    ``groups.json`` (a rotted marker is rebuilt, byte-deterministic);
    a killed build rebuilds identically (crash barrier
    ``groups.finalize``)."""
    from sparse_coding_tpu.groups.assign import (
        GroupBuildError,
        build_groups,
        load_groups,
    )

    cfg = config.get("group", {})
    store = Path(config["harvest"]["dataset_folder"])
    try:
        load_groups(store)
        return  # digest-sound completion marker: idempotent skip
    except (FileNotFoundError, GroupBuildError):
        pass  # absent or rotted: (re)build overwrites atomically
    build_groups(store, n_groups=int(cfg.get("n_groups", 2)),
                 n_sample_chunks=int(cfg.get("n_sample_chunks", 1)),
                 n_sample_rows=int(cfg.get("n_sample_rows", 2048)),
                 seed=int(cfg.get("seed", 0)))


def run_store_manifest(config: dict) -> None:
    """Aggregate the sealed shards into the store-level manifest (the
    sharded store's completeness marker). Backend-free — never touches a
    jax device, so the step runs against a wedged tunnel. A manifest
    that already matches the configured shard count is idempotent-skip;
    one from a run with a DIFFERENT n_shards is rebuilt (byte-
    deterministic) — silently training on the stale subset it lists
    would ignore the shards this run just harvested."""
    from sparse_coding_tpu.data.shard_store import (
        build_store_manifest,
        read_store_manifest,
    )

    cfg = config["harvest"]
    folder = Path(cfg["dataset_folder"])
    # sharded harvest: explicit n_shards; group (multi-tap) harvest:
    # one shard per layer — taps ARE shards
    n_shards = (int(cfg["n_shards"]) if "n_shards" in cfg
                else len(_resolve_layers(cfg)))
    existing = read_store_manifest(folder)
    if existing is not None and int(existing.get("n_shards", -1)) == n_shards:
        return  # complete store at THIS shard count: idempotent
    build_store_manifest(folder, expect_shards=n_shards)


SCRUB_MARKER_NAME = "scrub.done.json"


def scrub_marker_path() -> Optional[Path]:
    """RUN-scoped scrub completion marker: ``<run_dir>/scrub.done.json``,
    derived from the obs dir the supervisor exports to every child
    (``<run_dir>/obs``). None outside a supervised run (bare
    ``run_scrub`` invocations just run — the scrub is idempotent)."""
    obs_dir = os.environ.get(obs.ENV_OBS_DIR)
    if not obs_dir:
        return None
    return Path(obs_dir).parent / SCRUB_MARKER_NAME


def run_scrub(config: dict) -> None:
    """Scrub DAG node: re-verify every chunk digest between harvest and
    sweep, quarantine/repair corrupt chunks, emit the re-harvest
    worklist. Backend-free (data/scrub.py) — schedulable while the
    tunnel is wedged. ``config["scrub"]``: ``repair`` (default true).

    The completion marker is RUN-scoped (``<run_dir>/scrub.done.json``),
    never the store-resident report: a finished run's report must not
    make a LATER run over the same store skip its scrub — re-verifying a
    store that has had time to rot (and clearing ledger entries for
    chunks a re-harvest healed) is the step's whole point. Within one
    run the marker keeps the resume idempotent; the scrub itself is
    idempotent and byte-deterministic anyway."""
    from sparse_coding_tpu.data.scrub import scrub_store

    cfg = config.get("scrub", {})
    store = Path(config["harvest"]["dataset_folder"])
    marker = scrub_marker_path()
    if marker is not None and marker.exists():
        return  # resume within THIS run: already scrubbed
    report = scrub_store(store, repair=bool(cfg.get("repair", True)))
    if marker is not None:
        atomic_write_text(marker,
                          json.dumps(report, indent=2, sort_keys=True))


def run_sweep(config: dict) -> None:
    """``config["sweep"]`` keys: ``experiment`` (EXPERIMENTS registry
    name), ``ensemble`` (EnsembleArgs fields), ``log_every``. Always runs
    ``resume=True`` — a fresh run resumes from nothing, a killed run from
    its newest complete checkpoint set (§10 fallback chain included).

    The completion marker is written HERE, not by ``sweep()``'s periodic
    artifact saves: ``<output>/final/<name>_learned_dicts.pkl`` is
    derived from the (restored or live) end state, so it exists even when
    the resume had zero chunks left to train — the property that makes
    "retry after any kill" converge instead of looping."""
    import sparse_coding_tpu.train.sweep as sweep_mod
    from sparse_coding_tpu.config import EnsembleArgs
    from sparse_coding_tpu.train.experiments import EXPERIMENTS
    from sparse_coding_tpu.utils.artifacts import save_learned_dicts

    cfg = config["sweep"]
    ens_cfg = EnsembleArgs(**cfg["ensemble"])
    result = sweep_mod.sweep(EXPERIMENTS[cfg.get("experiment",
                                                 "dense_l1_range")],
                             ens_cfg, resume=True,
                             log_every=int(cfg.get("log_every", 100)),
                             image_metrics_every=None)
    final = Path(ens_cfg.output_folder) / "final"
    for name, tagged in result.items():
        save_learned_dicts(tagged, final / f"{name}_learned_dicts.pkl")


def run_eval(config: dict) -> None:
    """``config["eval"]`` keys: ``output_folder``, ``n_eval_rows``,
    ``seed``. Scores every dictionary in the sweep's final artifact (FVU +
    mean L0 on a seeded slice of chunk 0) and writes ``eval.json``
    atomically behind the ``eval.write`` crash barrier."""
    import jax.numpy as jnp

    from sparse_coding_tpu.data.shard_store import (
        first_sound_chunk,
        open_store,
    )
    from sparse_coding_tpu.metrics.core import (
        fraction_variance_unexplained,
        mean_l0,
    )
    from sparse_coding_tpu.utils.artifacts import load_learned_dicts

    cfg = config["eval"]
    out = Path(cfg["output_folder"])
    marker = out / "eval.json"
    if marker.exists():
        return
    out.mkdir(parents=True, exist_ok=True)
    name = config["sweep"].get("experiment", "dense_l1_range")
    pkl = (Path(config["sweep"]["ensemble"]["output_folder"]) / "final"
           / f"{name}_learned_dicts.pkl")
    tagged = load_learned_dicts(pkl)
    store = open_store(config["harvest"]["dataset_folder"],
                       quarantine_corrupt=True)
    # first non-quarantined chunk: a scrub-repaired store must still
    # evaluate (the self-healing contract), it just skips the holes
    chunk = store.load_chunk(first_sound_chunk(store))
    rng = np.random.default_rng(int(cfg.get("seed", 0)))
    rows = rng.permutation(chunk.shape[0])[:int(cfg.get("n_eval_rows", 2048))]
    eval_batch = jnp.asarray(chunk[rows], jnp.float32)
    records = []
    for ld, hyper in tagged:
        records.append({
            **{k: v for k, v in hyper.items()
               if isinstance(v, (int, float, str, bool))},
            "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
            "l0": float(mean_l0(ld, eval_batch))})
        lease.beat()
    crash_barrier("eval.write")
    atomic_write_text(marker, json.dumps(
        {"experiment": name, "n_eval_rows": int(len(rows)),
         "dicts": records}, indent=2))


def run_catalog(config: dict) -> None:
    """``config["catalog"]`` keys: ``output_folder``, optional
    ``dead_threshold``. Builds the feature-intelligence index
    (docs/ARCHITECTURE.md §20) from the sweep's final artifact set + the
    harvest chunk store. Backend-free — catalog/build.py never imports
    jax, so like ``scrub`` this step runs against a wedged tunnel.
    Idempotent behind ``index.json`` (the build's own completion marker,
    written behind the ``catalog.finalize`` crash barrier); a killed
    build rebuilds byte-identically."""
    from sparse_coding_tpu.catalog.build import build_catalog

    cfg = config["catalog"]
    out = Path(cfg["output_folder"])
    if (out / "index.json").exists():
        return
    name = config["sweep"].get("experiment", "dense_l1_range")
    pkl = (Path(config["sweep"]["ensemble"]["output_folder"]) / "final"
           / f"{name}_learned_dicts.pkl")
    build_catalog(pkl, config["harvest"]["dataset_folder"], out,
                  dead_threshold=float(cfg.get("dead_threshold", 0.0)),
                  experiment=name, group=cfg.get("group"))


STEPS = {"harvest": run_harvest, "shard_harvest": run_shard_harvest,
         "group_harvest": run_group_harvest, "group": run_group,
         "manifest": run_store_manifest, "scrub": run_scrub,
         "sweep": run_sweep, "eval": run_eval, "catalog": run_catalog}

_SHARDED_STEPS = {"shard_harvest", "group_harvest"}


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    shard = None
    if "--shard" in argv:
        at = argv.index("--shard")
        if at + 1 >= len(argv) or not argv[at + 1].lstrip("-").isdigit():
            raise SystemExit("--shard requires an integer value")
        shard = int(argv[at + 1])
        del argv[at:at + 2]
    if len(argv) != 3 or argv[1] != "--config" or argv[0] not in STEPS \
            or (argv[0] in _SHARDED_STEPS) != (shard is not None):
        raise SystemExit(
            f"usage: python -m sparse_coding_tpu.pipeline.steps "
            f"{{{'|'.join(STEPS)}}} --config pipeline.json "
            "[--shard I  (shard_harvest/group_harvest only)]")
    step, config_path = argv[0], argv[2]
    # claim the lease before any real work: from here on, silence = hang
    lease.configure_from_env(step=step)
    # join the run's observability stream (no-op outside a supervisor):
    # the env carries SPARSE_CODING_RUN_ID / _OBS_DIR / _OBS_STEP, so this
    # child's spans, XLA probe counters, and metrics snapshots land in the
    # same obs dir as the supervisor's and merge in obs.report (§12)
    obs.configure_sink_from_env(step)
    obs.install_jax_probes()
    # persistent executable cache (§13): the supervisor propagates one
    # shared SPARSE_CODING_XCACHE_DIR per run, so a respawned attempt of
    # this step loads executables instead of recompiling (no-op when the
    # env is unset — bare step invocations stay cache-free)
    from sparse_coding_tpu import xcache

    xcache.enable_from_env()
    config = json.loads(Path(config_path).read_text())
    try:
        with obs.span(f"step.{step}"):
            if shard is not None:
                STEPS[step](config, shard)
            else:
                STEPS[step](config)
    except BaseException as e:
        # the two STRUCTURED shutdown classes leave as typed exit codes
        # (pipeline/supervisor.py maps them back): a SIGTERM preemption
        # checkpointed at its chunk boundary and resumes bitwise; a
        # guardian divergence halt is deterministic and must not be
        # retried. Everything else propagates as a plain failure.
        from sparse_coding_tpu.pipeline.supervisor import (
            STEP_EXIT_HALTED,
            STEP_EXIT_PREEMPTED,
        )
        from sparse_coding_tpu.resilience.errors import DivergenceHaltError
        from sparse_coding_tpu.resilience.preempt import SweepPreempted

        if isinstance(e, SweepPreempted):
            print(f"step {step}: {e}", file=sys.stderr)
            raise SystemExit(STEP_EXIT_PREEMPTED) from e
        if isinstance(e, DivergenceHaltError):
            print(f"step {step}: {e}", file=sys.stderr)
            raise SystemExit(STEP_EXIT_HALTED) from e
        raise
    finally:
        obs.update_memory_gauges()
        obs.flush_metrics()
        obs.close_sink()


if __name__ == "__main__":
    main()
