"""Crash-only pipeline supervision: journaled harvest→sweep→eval.

- :mod:`journal`    — append-only run journal (the supervisor's only
  memory; atomic appends, artifact-beats-journal recovery);
- :mod:`supervisor` — the step DAG runner: child processes, lease
  takeover, SIGKILL recovery, hang watchdog with tunnel diagnosis,
  degrade-to-CPU, plus ``supervise_bench`` (bench.py ``--supervised``);
- :mod:`steps`      — the built-in resumable step children.

Design + formats: docs/ARCHITECTURE.md §11; wedged-tunnel operations:
docs/RUNBOOK_TUNNEL.md; kill coverage: tests/test_pipeline_chaos.py.
"""

from sparse_coding_tpu.pipeline.journal import RunJournal
from sparse_coding_tpu.pipeline.supervisor import (
    ConcurrentSupervisorError,
    PipelineError,
    Step,
    StepFailed,
    StepHung,
    Supervisor,
    build_pipeline,
    build_sharded_pipeline,
    load_or_create_run_id,
    step_argv,
    supervise_bench,
)

__all__ = [
    "ConcurrentSupervisorError",
    "PipelineError",
    "RunJournal",
    "Step",
    "StepFailed",
    "StepHung",
    "Supervisor",
    "build_pipeline",
    "build_sharded_pipeline",
    "load_or_create_run_id",
    "step_argv",
    "supervise_bench",
]
