"""Crash-only pipeline supervision: journaled runs, one pod, many tenants.

- :mod:`journal`     — append-only run journal (the supervisor's only
  memory; atomic appends, artifact-beats-journal recovery);
- :mod:`supervisor`  — the step DAG runner: child processes, lease
  takeover, SIGKILL recovery, hang watchdog with tunnel diagnosis,
  degrade-to-CPU, plus ``supervise_bench`` (bench.py ``--supervised``);
- :mod:`steps`       — the built-in resumable step children;
- :mod:`fleet` / :mod:`fleet_queue` / :mod:`placement` — the fleet
  scheduler (docs/ARCHITECTURE.md §18): a durable bitwise-replay run
  queue bin-packed onto mesh slices with serve/slo.py's priority
  classes, per-run worker subprocesses (one Supervisor each), chunk-
  boundary SIGTERM preemption, per-tenant guardian-halt containment,
  and one shared executable cache across tenants;
- :mod:`plane`      — the elastic resource plane (docs/ARCHITECTURE.md
  §21): ONE arbiter trading mesh slices between the serving gateway's
  replica pool and the fleet's scavenger tenants, with durable
  bitwise-replayable rebalance records in the fleet queue journal,
  zero-compile warm-spare scale-up, SIGTERM-checkpoint reclaim, and
  hysteresis against flapping load.

Design + formats: docs/ARCHITECTURE.md §11 + §18; wedged-tunnel
operations: docs/RUNBOOK_TUNNEL.md; kill coverage:
tests/test_pipeline_chaos.py.
"""

import importlib

# Lazy attribute resolution (PEP 562, mirroring the package root and
# serve/): `python -m sparse_coding_tpu.pipeline.fleet` is a runpy
# entrypoint — an eager `from .fleet import ...` here would import the
# module a second time under runpy and trip its double-execution
# warning in every worker log.
_LAZY_ATTRS = {
    "FleetScheduler": ("sparse_coding_tpu.pipeline.fleet",
                       "FleetScheduler"),
    "run_worker": ("sparse_coding_tpu.pipeline.fleet", "run_worker"),
    "FleetQueue": ("sparse_coding_tpu.pipeline.fleet_queue", "FleetQueue"),
    "FleetState": ("sparse_coding_tpu.pipeline.fleet_queue", "FleetState"),
    "RunJournal": ("sparse_coding_tpu.pipeline.journal", "RunJournal"),
    "PlacementPlan": ("sparse_coding_tpu.pipeline.placement",
                      "PlacementPlan"),
    "RunState": ("sparse_coding_tpu.pipeline.placement", "RunState"),
    "plan_placement": ("sparse_coding_tpu.pipeline.placement",
                       "plan_placement"),
    "ElasticPlane": ("sparse_coding_tpu.pipeline.plane", "ElasticPlane"),
    "PlaneConfig": ("sparse_coding_tpu.pipeline.plane", "PlaneConfig"),
    "PlaneSplit": ("sparse_coding_tpu.pipeline.plane", "PlaneSplit"),
    "desired_replicas": ("sparse_coding_tpu.pipeline.plane",
                         "desired_replicas"),
    "replay_split": ("sparse_coding_tpu.pipeline.plane", "replay_split"),
}
for _name in ("STEP_EXIT_HALTED", "STEP_EXIT_PREEMPTED",
              "ConcurrentSupervisorError", "PipelineError",
              "PreflightAuditError", "Step",
              "StepFailed", "StepHalted", "StepHung", "StepPreempted",
              "Supervisor", "build_group_pipeline",
              "build_group_tenant_pipeline", "build_pipeline",
              "build_sharded_pipeline",
              "load_or_create_run_id", "step_argv", "supervise_bench"):
    _LAZY_ATTRS[_name] = ("sparse_coding_tpu.pipeline.supervisor", _name)

__all__ = sorted(_LAZY_ATTRS)


def __getattr__(name):
    if name in _LAZY_ATTRS:
        module, attr = _LAZY_ATTRS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'sparse_coding_tpu.pipeline' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_ATTRS))
