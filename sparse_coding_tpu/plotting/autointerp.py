"""Auto-interpretation comparison plots.

Consolidates the reference's six plot_autointerp_vs_* variants and the
violin-plot results reader (reference: plotting/plot_autointerp_vs_baselines.py,
interpret.py:691-761 `read_results`). Axis conventions match the reference:
score range −0.2…0.6 (interpret.py:720-722), per-location mean-score caps 0.2
(residual) / 0.35 (MLP) (plot_autointerp_vs_baselines.py:60-62).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import numpy as np

SCORE_RANGE = (-0.2, 0.6)  # reference: interpret.py:720-722
MEAN_SCORE_CAP = {"residual": 0.2, "mlp": 0.35}  # plot_autointerp_vs_baselines.py:60-62


def plot_score_violins(scores_by_transform: dict[str, Sequence[float]],
                       save_path: Optional[str | Path] = None,
                       title: str = "auto-interpretation scores"):
    """Violin plot with bootstrap CIs per transform
    (reference: read_results, interpret.py:691-761)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    names = sorted(scores_by_transform)
    data = [np.asarray(scores_by_transform[n], float) for n in names]
    fig, ax = plt.subplots(figsize=(1.2 * len(names) + 3, 5))
    ax.violinplot(data, showmeans=True)
    for i, vals in enumerate(data, start=1):
        boot = [np.mean(np.random.default_rng(s).choice(vals, len(vals)))
                for s in range(200)]
        lo, hi = np.percentile(boot, [2.5, 97.5])
        ax.plot([i, i], [lo, hi], color="black", lw=2)
    ax.set_xticks(range(1, len(names) + 1), names, rotation=30, ha="right")
    ax.set_ylim(*SCORE_RANGE)
    ax.set_ylabel("top-and-random correlation score")
    ax.set_title(title)
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)
    return {n: (float(np.mean(d)), float(np.std(d))) for n, d in
            zip(names, data)}


def plot_autointerp_vs_baselines(results_root: str | Path,
                                 save_path: Optional[str | Path] = None,
                                 layer_loc: str = "residual"):
    """Read per-transform score folders and render the comparison
    (reference: plot_autointerp_vs_baselines.py:35-62)."""
    from sparse_coding_tpu.interp.run import read_transform_scores

    scores = read_transform_scores(results_root)
    summary = plot_score_violins(scores, save_path=save_path,
                                 title=f"autointerp vs baselines ({layer_loc})")
    return summary
