"""FVU-vs-sparsity frontier plots + score generation.

Consolidates the reference's per-model plot scripts
(reference: plotting/fvu_sparsity_plot.py:104-186 `generate_scores` and its
`_gpt2sm` / `_mlp_center` clones) into one parameterized module: a score
generator that evaluates every saved dict on an eval slab, and a frontier
renderer. Matplotlib is imported lazily so headless metric-only use never
touches a display backend.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.metrics.core import fraction_variance_unexplained, mean_l0
from sparse_coding_tpu.utils.artifacts import load_learned_dicts


def generate_scores(dict_files: Sequence[str | Path], eval_batch,
                    out_path: Optional[str | Path] = None) -> list[dict]:
    """FVU + L0 for every (dict, hyperparams) across artifact files
    (reference: fvu_sparsity_plot.py:104-186)."""
    eval_batch = jnp.asarray(eval_batch)
    scores = []
    for path in dict_files:
        for ld, hyper in load_learned_dicts(path):
            scores.append({
                "file": str(path),
                **{k: v for k, v in hyper.items()
                   if isinstance(v, (int, float, str, bool))},
                "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
                "l0": float(mean_l0(ld, eval_batch)),
            })
    if out_path is not None:
        from sparse_coding_tpu.resilience.atomic import atomic_write_text

        Path(out_path).parent.mkdir(parents=True, exist_ok=True)
        atomic_write_text(out_path, json.dumps(scores, indent=2))
    return scores


def plot_fvu_sparsity(scores: Sequence[dict], group_by: str = "dict_size",
                      save_path: Optional[str | Path] = None, show: bool = False,
                      title: str = "FVU vs sparsity"):
    """Frontier scatter: x = L0, y = FVU, one series per group
    (reference: fvu_sparsity_plot.py rendering loop)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    groups: dict = {}
    for s in scores:
        groups.setdefault(s.get(group_by, "all"), []).append(s)
    for key in sorted(groups, key=str):
        pts = sorted(groups[key], key=lambda s: s["l0"])
        ax.plot([p["l0"] for p in pts], [p["fvu"] for p in pts],
                marker="o", ms=4, label=f"{group_by}={key}")
    ax.set_xlabel("mean L0 (active features/sample)")
    ax.set_ylabel("fraction of variance unexplained")
    ax.set_title(title)
    ax.set_xscale("log")
    ax.legend(fontsize=8)
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    if show:  # pragma: no cover
        plt.show()
    plt.close(fig)
    return fig
