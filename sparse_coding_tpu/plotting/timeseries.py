"""Time-series figures over training snapshots.

One-call counterparts of the reference's ready-to-run time-series scripts
(reference: plotting/plot_autointerp_across_chunks.py — mean autointerp
score per training-snapshot transform with 95% CIs;
plotting/plot_n_active_over_time.py — active-feature counts per dict over
training epochs/snapshots). Both compose drivers that already exist here:
`interp.run.interpret_across_chunks` output trees and
`metrics.geometry.activity_sweep` over the sweep's `_N/` snapshot folders.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import numpy as np


def _snapshot_dirs(root: str | Path) -> list[Path]:
    """`_N` snapshot folders in training order (the sweep driver saves at
    power-of-2 chunk counts; reference: big_sweep.py:378-384)."""
    dirs = [p for p in Path(root).glob("_*")
            if p.is_dir() and p.name[1:].isdigit()]
    return sorted(dirs, key=lambda p: int(p.name[1:]))


def plot_autointerp_across_chunks(interp_output_root: str | Path,
                                  save_path: Optional[str | Path] = None,
                                  score_key: str = "top_random_score"):
    """Mean autointerp score ± 95% CI per training snapshot, one series per
    ensemble member (reference: plot_autointerp_across_chunks.py:16-60).

    Reads the folder tree `interp.run.interpret_across_chunks` writes
    (`<output_folder>/_N/<artifact>_<i>/feature_*/scores.json`). Returns
    {member: {"snapshots": [...], "mean": [...], "ci95": [...]}} and renders
    the figure when `save_path` is given."""
    from sparse_coding_tpu.interp.run import read_scores

    series: dict[str, dict[str, list]] = {}
    for snap in _snapshot_dirs(interp_output_root):
        for member_dir in sorted(p for p in snap.iterdir() if p.is_dir()):
            scores = [rec[score_key]
                      for rec in read_scores(member_dir).values()
                      if score_key in rec]
            if not scores:
                continue
            s = series.setdefault(member_dir.name,
                                  {"snapshots": [], "mean": [], "ci95": []})
            vals = np.asarray(scores, float)
            s["snapshots"].append(int(snap.name[1:]))
            s["mean"].append(float(vals.mean()))
            s["ci95"].append(
                float(1.96 * vals.std(ddof=1) / np.sqrt(len(vals)))
                if len(vals) > 1 else 0.0)
    if save_path is not None and series:
        from sparse_coding_tpu.plotting.helpers import get_pyplot, save_figure

        fig, ax = get_pyplot().subplots(figsize=(7, 4.5))
        for name, s in sorted(series.items()):
            ax.errorbar(s["snapshots"], s["mean"], yerr=s["ci95"],
                        marker="o", capsize=3, label=name)
        ax.set_xlabel("training snapshot (chunks seen)")
        ax.set_ylabel(f"mean {score_key}")
        ax.set_title("auto-interpretation over training")
        ax.legend(fontsize=7)
        fig.tight_layout()
        save_figure(fig, save_path)
    return series


def plot_n_active_over_time(sweep_output: str | Path, activations,
                            threshold: int = 10, batch_size: int = 1000,
                            save_path: Optional[str | Path] = None):
    """Active-feature counts for every ensemble member at every saved
    training snapshot (reference: plot_n_active_over_time.py:31-96, which
    torch-loads each epoch's learned_dicts.pt and counts ever-active
    features over one chunk).

    `sweep_output` is a sweep output tree with `_N/` snapshot folders;
    `activations` is an array or ChunkStore (the census streams it once per
    snapshot via activity_sweep). Returns
    {member_label: {"snapshots": [...], "n_active": [...]}} and renders one
    line per member when `save_path` is given."""
    from sparse_coding_tpu.metrics.geometry import activity_sweep

    # ONE census over every snapshot's artifacts: the activations (often a
    # multi-GB ChunkStore) stream from disk once total, not once per
    # snapshot; recs partition back by their artifact provenance
    file_snapshot: dict[str, int] = {}
    all_files: list = []
    for snap in _snapshot_dirs(sweep_output):
        for f in sorted(snap.glob("*_learned_dicts.pkl")):
            file_snapshot[str(f)] = int(snap.name[1:])
            all_files.append(f)
    recs = activity_sweep(all_files, activations, threshold=threshold,
                          batch_size=batch_size) if all_files else []

    series: dict[str, dict[str, list]] = {}
    for rec in recs:
        hyper_bits = [f"{k}={rec[k]}" for k in ("l1_alpha", "dict_size")
                      if k in rec]
        # the member index disambiguates seed-replicate members that share
        # every hyperparameter — identical labels must not merge series
        label = (" ".join(hyper_bits) or "member") + \
            f" (n={rec['n_feats']}) #{rec['member']}"
        s = series.setdefault(label, {"snapshots": [], "n_active": []})
        s["snapshots"].append(file_snapshot[rec["artifact"]])
        s["n_active"].append(int(rec["n_ever_active"]))
    if save_path is not None and series:
        from sparse_coding_tpu.plotting.helpers import get_pyplot, save_figure

        fig, ax = get_pyplot().subplots(figsize=(7, 4.5))
        for name, s in sorted(series.items()):
            ax.plot(s["snapshots"], s["n_active"], marker="o", label=name)
        ax.set_xlabel("training snapshot (chunks seen)")
        ax.set_ylabel(f"features active > {threshold} times")
        ax.set_title("active features over training")
        ax.legend(fontsize=7)
        fig.tight_layout()
        save_figure(fig, save_path)
    return series
