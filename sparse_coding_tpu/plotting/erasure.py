"""Concept-erasure comparison plots
(reference: plotting/erasure_plot.py:12-342 — probe-ability vs edit magnitude
vs KL, with the LEACE point; consumes metrics/erasure.py outputs)."""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence


def plot_erasure_tradeoff(curve: Sequence[dict], leace: Optional[dict] = None,
                          x_key: str = "edit_magnitude", y_key: str = "auroc",
                          save_path: Optional[str | Path] = None,
                          title: str = "concept erasure tradeoff"):
    """Probe AUROC (or KL) vs edit magnitude along the feature-erasure curve,
    with LEACE as a reference point (erasure_plot.py:198-278)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    pts = sorted(curve, key=lambda r: r[x_key])
    ax.plot([p[x_key] for p in pts], [p[y_key] for p in pts], marker="o",
            label="feature erasure")
    for p in pts:
        ax.annotate(str(p.get("n_erased", "")), (p[x_key], p[y_key]),
                    fontsize=7, xytext=(3, 3), textcoords="offset points")
    if leace is not None and x_key in leace and y_key in leace:
        ax.scatter([leace[x_key]], [leace[y_key]], marker="*", s=150,
                   color="crimson", label="LEACE", zorder=3)
    ax.set_xlabel(x_key)
    ax.set_ylabel(y_key)
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)
