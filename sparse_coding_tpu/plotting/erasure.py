"""Concept-erasure comparison plots
(reference: plotting/erasure_plot.py:12-342 — probe-ability vs edit magnitude
vs KL, with the LEACE point; consumes metrics/erasure.py outputs)."""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence


def plot_erasure_tradeoff(curve: Sequence[dict], leace: Optional[dict] = None,
                          x_key: str = "edit_magnitude", y_key: str = "auroc",
                          save_path: Optional[str | Path] = None,
                          title: str = "concept erasure tradeoff"):
    """Probe AUROC (or KL) vs edit magnitude along the feature-erasure curve,
    with LEACE as a reference point (erasure_plot.py:198-278)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    pts = sorted(curve, key=lambda r: r[x_key])
    ax.plot([p[x_key] for p in pts], [p[y_key] for p in pts], marker="o",
            label="feature erasure")
    for p in pts:
        ax.annotate(str(p.get("n_erased", "")), (p[x_key], p[y_key]),
                    fontsize=7, xytext=(3, 3), textcoords="offset points")
    if leace is not None and x_key in leace and y_key in leace:
        ax.scatter([leace[x_key]], [leace[y_key]], marker="*", s=150,
                   color="crimson", label="LEACE", zorder=3)
    ax.set_xlabel(x_key)
    ax.set_ylabel(y_key)
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)


def plot_task_ablation_curve(curve: dict, ranking=None,
                             save_path: Optional[str | Path] = None,
                             title: str = "task metric vs features ablated",
                             ylabel: str = "task metric (IOI logit diff)"):
    """Task-erasure figure over a
    tasks/feature_ident.py::cumulative_ablation_curve result: the task
    metric as the top-m ranked features are jointly ablated, with the
    unablated base as a reference line. Completes the task-probe analogue
    of the concept-erasure tradeoff family above."""
    from sparse_coding_tpu.plotting.helpers import get_pyplot, save_figure

    fig, ax = get_pyplot().subplots(figsize=(7, 4.5))
    m = len(curve["metrics"])
    xs = range(1, m + 1)
    ax.plot(xs, curve["metrics"], marker="o", label="top-m ablated")
    ax.axhline(curve["base_metric"], color="gray", ls="--",
               label="base (no ablation)")
    if ranking is not None:
        for x, feat in zip(xs, ranking):
            ax.annotate(str(int(feat)), (x, float(curve["metrics"][x - 1])),
                        fontsize=7, xytext=(3, 3),
                        textcoords="offset points")
    ax.set_xlabel("features ablated (ranked by causal effect)")
    ax.set_ylabel(ylabel)
    if m <= 30:  # per-point ticks unreadable beyond that
        ax.set_xticks(list(xs))
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    # always closed (like every sibling plotter here): no pyplot-registry
    # leak across sweep loops, and no ambiguous returned-but-closed figure
    if save_path is not None:
        save_figure(fig, save_path)
    else:
        get_pyplot().close(fig)
