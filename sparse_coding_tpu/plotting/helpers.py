"""Small render-to-image helpers used by loggers and notebooks
(reference: standard_metrics.py:411-439 plot_hist/plot_scatter, :514-531
plot_grid, :364-408 capacity plots; plotting/plot_kl_div.py,
plotting/bottleneck_plot.py)."""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


def _fig_to_array(fig) -> np.ndarray:
    """Rasterize a figure to an RGB array (the reference renders to PIL for
    wandb image panels, standard_metrics.py:418-424)."""
    fig.canvas.draw()
    buf = np.asarray(fig.canvas.buffer_rgba())
    return buf[..., :3].copy()


class _NoopPlt:
    """Stands in for pyplot so helpers never mutate the process-global
    backend (Figure+Agg canvas render headlessly on their own)."""

    @staticmethod
    def close(fig):  # Figure objects are GC'd; nothing to close
        pass


def _new_fig(**kwargs):
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    fig = Figure(**kwargs)
    FigureCanvasAgg(fig)
    ax = fig.subplots()
    return _NoopPlt, (fig, ax)


def plot_hist(scores, x_label: str = "", y_label: str = "", bins: int = 50,
              save_path: Optional[str | Path] = None, **kwargs) -> np.ndarray:
    """(reference: standard_metrics.py:411-424)."""
    plt, (fig, ax) = _new_fig(figsize=(5, 4))
    ax.hist(np.asarray(jax.device_get(scores)).ravel(), bins=bins, **kwargs)
    ax.set_xlabel(x_label)
    ax.set_ylabel(y_label)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def plot_scatter(scores_x, scores_y, x_label: str = "", y_label: str = "",
                 save_path: Optional[str | Path] = None, **kwargs) -> np.ndarray:
    """(reference: standard_metrics.py:426-439)."""
    plt, (fig, ax) = _new_fig(figsize=(5, 4))
    ax.scatter(np.asarray(jax.device_get(scores_x)).ravel(),
               np.asarray(jax.device_get(scores_y)).ravel(), s=6, **kwargs)
    ax.set_xlabel(x_label)
    ax.set_ylabel(y_label)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def plot_grid(scores: np.ndarray, first_tick_labels, second_tick_labels,
              first_label: str, second_label: str,
              save_path: Optional[str | Path] = None, **kwargs) -> np.ndarray:
    """Annotated heatmap (reference: standard_metrics.py:514-531)."""
    plt, (fig, ax) = _new_fig(figsize=(6, 5))
    im = ax.imshow(np.asarray(scores), origin="lower", aspect="auto", **kwargs)
    ax.set_xticks(range(len(first_tick_labels)), first_tick_labels,
                  rotation=45, fontsize=7)
    ax.set_yticks(range(len(second_tick_labels)), second_tick_labels, fontsize=7)
    ax.set_xlabel(first_label)
    ax.set_ylabel(second_label)
    fig.colorbar(im)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def plot_capacities(dicts: List[Tuple[Any, Dict]], save_path: Optional[str | Path] = None):
    """Capacity distribution per dict (reference: standard_metrics.py:364-381)."""
    from sparse_coding_tpu.metrics.core import capacity_per_feature

    plt, (fig, ax) = _new_fig(figsize=(7, 5))
    for ld, hyper in dicts:
        caps = np.sort(np.asarray(jax.device_get(capacity_per_feature(ld))))[::-1]
        label = ", ".join(f"{k}={v:.2g}" if isinstance(v, float) else f"{k}={v}"
                          for k, v in hyper.items()
                          if isinstance(v, (int, float)))
        ax.plot(caps, label=label)
    ax.set_xlabel("feature rank")
    ax.set_ylabel("capacity")
    ax.legend(fontsize=7)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def plot_capacity_scatter(dicts: List[Tuple[Any, Dict]], eval_batch,
                          save_path: Optional[str | Path] = None):
    """Capacity vs firing frequency per feature
    (reference: standard_metrics.py:382-408)."""
    from sparse_coding_tpu.metrics.core import (
        capacity_per_feature,
        mean_nonzero_activations,
    )

    plt, (fig, ax) = _new_fig(figsize=(6, 5))
    for ld, hyper in dicts:
        caps = np.asarray(jax.device_get(capacity_per_feature(ld)))
        freq = np.asarray(jax.device_get(mean_nonzero_activations(ld, eval_batch)))
        ax.scatter(freq, caps, s=4, alpha=0.5,
                   label=str(hyper.get("l1_alpha", "")))
    ax.set_xlabel("firing frequency")
    ax.set_ylabel("capacity")
    ax.set_xscale("symlog", linthresh=1e-4)
    ax.legend(fontsize=7)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def plot_kl_div(records: Sequence[dict], x_key: str = "l0", kl_key: str = "kl",
                save_path: Optional[str | Path] = None):
    """KL-divergence-under-patching curves (reference: plotting/plot_kl_div.py)."""
    plt, (fig, ax) = _new_fig(figsize=(6, 4))
    pts = sorted(records, key=lambda r: r[x_key])
    ax.plot([p[x_key] for p in pts], [p[kl_key] for p in pts], marker="o")
    ax.set_xlabel(x_key)
    ax.set_ylabel("KL divergence")
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def bottleneck_plot(series: Dict[str, Sequence[Tuple[float, float]]],
                    x_label: str = "bottleneck size", y_label: str = "metric",
                    save_path: Optional[str | Path] = None):
    """Metric-vs-bottleneck-size comparison (reference:
    plotting/bottleneck_plot.py)."""
    plt, (fig, ax) = _new_fig(figsize=(6, 4))
    for name, pts in sorted(series.items()):
        pts = sorted(pts)
        ax.plot([p[0] for p in pts], [p[1] for p in pts], marker="o", label=name)
    ax.set_xlabel(x_label)
    ax.set_ylabel(y_label)
    ax.set_xscale("log")
    ax.legend(fontsize=8)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    img = _fig_to_array(fig)
    plt.close(fig)
    return img


def get_pyplot():
    """Headless-safe pyplot (Agg backend): the single home for the
    matplotlib-setup dance the file-figure plotters share
    (plotting/sweeps.py, plotting/timeseries.py)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def save_figure(fig, save_path) -> None:
    """mkdir-parents + savefig(dpi=150) + close, shared by the file-figure
    plotters."""
    plt = get_pyplot()
    Path(save_path).parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(save_path, dpi=150)
    plt.close(fig)
