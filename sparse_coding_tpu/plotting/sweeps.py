"""Sweep-grid, activity, and dead-feature plots.

Consolidates the reference's plot_sweep_results.py:28-104, the seven
plot_n_active* variants, and num_dead_plot.py into parameterized functions.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.metrics.core import mean_nonzero_activations
from sparse_coding_tpu.utils.artifacts import load_learned_dicts


def _plt():
    from sparse_coding_tpu.plotting.helpers import get_pyplot

    return get_pyplot()


def sweep_grid(scores: Sequence[dict], x_key: str = "l1_alpha",
               y_key: str = "dict_size", value_key: str = "fvu") -> tuple:
    """Pivot sweep scores into a (x_vals, y_vals, grid) heatmap input
    (reference: plot_sweep_results.py:28-104)."""
    xs = sorted({s[x_key] for s in scores})
    ys = sorted({s[y_key] for s in scores})
    grid = np.full((len(ys), len(xs)), np.nan)
    for s in scores:
        grid[ys.index(s[y_key]), xs.index(s[x_key])] = s[value_key]
    return xs, ys, grid


def plot_sweep_grid(scores, x_key="l1_alpha", y_key="dict_size",
                    value_key="fvu", save_path: Optional[str | Path] = None):
    plt = _plt()
    xs, ys, grid = sweep_grid(scores, x_key, y_key, value_key)
    fig, ax = plt.subplots(figsize=(7, 5))
    im = ax.imshow(grid, aspect="auto", origin="lower", cmap="viridis")
    ax.set_xticks(range(len(xs)), [f"{x:.1e}" if isinstance(x, float) else x
                                   for x in xs], rotation=45, fontsize=7)
    ax.set_yticks(range(len(ys)), ys, fontsize=7)
    ax.set_xlabel(x_key)
    ax.set_ylabel(y_key)
    fig.colorbar(im, label=value_key)
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)
    return xs, ys, grid


def n_active_features(dict_files: Sequence[str | Path], eval_batch,
                      threshold: float = 0.0) -> list[dict]:
    """Active-feature counts per dict (reference: plot_n_active*.py family)."""
    eval_batch = jnp.asarray(eval_batch)
    out = []
    for path in dict_files:
        for ld, hyper in load_learned_dicts(path):
            freq = mean_nonzero_activations(ld, eval_batch)
            out.append({
                **{k: v for k, v in hyper.items()
                   if isinstance(v, (int, float, str, bool))},
                "n_active": int(jnp.sum(freq > threshold)),
                "n_feats": int(ld.n_feats),
            })
    return out


def plot_n_active(records: Sequence[dict], x_key: str = "l1_alpha",
                  save_path: Optional[str | Path] = None):
    plt = _plt()
    fig, ax = plt.subplots(figsize=(7, 5))
    pts = sorted(records, key=lambda r: r[x_key])
    ax.plot([p[x_key] for p in pts], [p["n_active"] for p in pts], marker="o")
    ax.plot([p[x_key] for p in pts], [p["n_feats"] for p in pts], ls="--",
            color="gray", label="dict size")
    ax.set_xscale("log")
    ax.set_xlabel(x_key)
    ax.set_ylabel("active features")
    ax.legend()
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)


def plot_num_dead(records: Sequence[dict], x_key: str = "l1_alpha",
                  save_path: Optional[str | Path] = None):
    """Dead-feature counts (reference: num_dead_plot.py)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(7, 5))
    pts = sorted(records, key=lambda r: r[x_key])
    ax.plot([p[x_key] for p in pts],
            [p["n_feats"] - p["n_active"] for p in pts], marker="o", color="crimson")
    ax.set_xscale("log")
    ax.set_xlabel(x_key)
    ax.set_ylabel("dead features")
    fig.tight_layout()
    if save_path is not None:
        Path(save_path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(save_path, dpi=150)
    plt.close(fig)
