"""Crash-safe JSONL event sink: append-only, line-atomic, torn-tail-tolerant.

The observability write path must obey two rules the journal's
read+rewrite-atomic append cannot afford at event rates:

1. **The host workload is never collateral.** A failing event write
   (disk full, injected fault) drops THAT event, counts the drop, and
   returns — it must not kill a sweep. The write sits behind the named
   fault site ``obs.sink.write`` (docs/ARCHITECTURE.md §10) so the
   fault-matrix suite drives both the error-drop and the corrupt-line
   paths deterministically.
2. **A torn tail is data loss, never corruption.** Events append to a
   per-process file (no writer ever shares a file, so O_APPEND ordering
   is trivial) in two writes: the JSON payload, then the ``\\n`` commit
   byte. A SIGKILL or power cut between the two — the instant the
   ``obs.sink.write`` crash barrier pins for the chaos matrix — leaves an
   unterminated (or, after an OS-level partial flush, truncated) last
   line that :func:`scan_events` skips by contract: a reader only
   accepts newline-terminated lines that parse as JSON.

fsync policy: every ``fsync_every`` events (default 1 — each committed
line is durable; raise it on hot paths where losing the last few events
to a power cut is acceptable). ``close()`` always syncs.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.resilience.crash import crash_barrier
from sparse_coding_tpu.resilience.faults import fault_point

from sparse_coding_tpu.obs.registry import get_registry

ENV_OBS_DIR = "SPARSE_CODING_OBS_DIR"
FAULT_SITE = "obs.sink.write"  # pre-registered in resilience.faults/crash


class EventSink:
    """One process's append-only event file. ``emit(dict)`` writes exactly
    one JSON line; returns False (and counts ``obs.sink.dropped``) when
    the write failed — never raises into the host workload."""

    def __init__(self, path: str | Path, fsync_every: int = 1):
        self.path = Path(path)
        self.fsync_every = max(0, int(fsync_every))
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fd: Optional[int] = os.open(
            str(self.path), os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        self._lock = threading.Lock()
        self._since_sync = 0

    def emit(self, record: dict) -> bool:
        try:
            data = json.dumps(record, default=_json_default).encode()
        except (TypeError, ValueError):
            get_registry().counter("obs.sink.dropped").inc()
            return False
        with self._lock:
            if self._fd is None:
                get_registry().counter("obs.sink.dropped").inc()
                return False
            try:
                # the fault site covers the whole line write; corrupt-mode
                # flips a payload byte (the reader must then skip the line)
                data = fault_point(FAULT_SITE, data)
                os.write(self._fd, data)
                # the worst instant: payload written, commit byte not — a
                # kill here leaves the torn tail scan_events() must skip
                crash_barrier(FAULT_SITE)
                os.write(self._fd, b"\n")
                self._since_sync += 1
                if self.fsync_every and self._since_sync >= self.fsync_every:
                    os.fsync(self._fd)
                    self._since_sync = 0
            except OSError:
                get_registry().counter("obs.sink.dropped").inc()
                return False
        return True

    def flush(self) -> None:
        with self._lock:
            if self._fd is not None and self._since_sync:
                try:
                    os.fsync(self._fd)
                    self._since_sync = 0
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fd is None:
                return
            try:
                os.fsync(self._fd)
            except OSError:
                pass
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_default(obj):
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


def scan_events(path: str | Path) -> tuple[list[dict], int]:
    """Read one event file: ``(events, skipped_lines)``. Only newline-
    terminated, JSON-parsing lines are events; an unterminated tail (the
    SIGKILL case) and corrupt lines are counted, skipped, and can never
    poison a report."""
    path = Path(path)
    if not path.exists():
        return [], 0
    raw = path.read_bytes()
    events: list[dict] = []
    skipped = 0
    if not raw:
        return events, skipped
    lines = raw.split(b"\n")
    torn_tail = lines.pop()  # b"" when the file ends with the commit byte
    if torn_tail:
        skipped += 1
    for line in lines:
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(rec, dict):
            events.append(rec)
        else:
            skipped += 1
    return events, skipped


def read_events(path: str | Path) -> list[dict]:
    """Events only (scan_events without the skip count)."""
    return scan_events(path)[0]


# -- module-global sink (the per-process default spans/metrics write to) ------

_active: Optional[EventSink] = None
_env_checked = False
_lock = threading.Lock()


def configure(sink: Optional[EventSink]) -> Optional[EventSink]:
    """Install (or with None, clear) the process sink; returns the
    previous one. Explicit configuration wins over the env lookup."""
    global _active, _env_checked
    with _lock:
        prev, _active = _active, sink
        _env_checked = True
    return prev


def configure_from_env(name: str = "") -> Optional[EventSink]:
    """Create the process sink inside ``SPARSE_CODING_OBS_DIR`` (no-op
    returning None when unset). The file name is ``<name>-<pid>.jsonl`` so
    every process of a run owns its file — no cross-process interleaving,
    and a restarted attempt (new pid) never appends to a dead process's
    possibly-torn file."""
    folder = os.environ.get(ENV_OBS_DIR, "").strip()
    if not folder:
        configure(None)
        return None
    label = name or os.environ.get("SPARSE_CODING_OBS_STEP", "") or "proc"
    sink = EventSink(Path(folder) / f"{label}-{os.getpid()}.jsonl")
    configure(sink)
    return sink


def active_sink() -> Optional[EventSink]:
    """The configured sink; lazily self-configures from the env once so
    library code needs no supervisor plumbing (mirrors ``lease.beat``)."""
    global _env_checked
    with _lock:
        if _active is not None or _env_checked:
            return _active
    return configure_from_env()


def close() -> None:
    sink = configure(None)
    global _env_checked
    _env_checked = False
    if sink is not None:
        sink.close()
