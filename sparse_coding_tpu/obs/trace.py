"""Crash-safe managed jax.profiler capture (§12).

The bare ``jax.profiler.start_trace``/``stop_trace`` pairs this replaces
had no exception-path guarantee (the sweep's stop sat 200 lines from its
start) and wrote the trace straight into its final directory — a SIGKILL
mid-capture left a half-written artifact indistinguishable from a real
one. Here every capture is:

- **bounded and explicit** — :class:`TraceCapture` is the begin()/end()
  state machine for loop hosts (the sweep opens the window at one step
  boundary and closes it N steps later); :func:`capture` is the
  context-manager sugar with try/finally semantics;
- **fault-isolated** — the named fault site ``obs.trace.capture`` covers
  begin AND finalize: any error is a counted skip
  (``obs.trace.skipped``) that never kills the sweep it was profiling;
- **atomic on disk** — the profiler writes into a tmp sibling of the
  destination; ``end()`` stops the profiler, crosses the
  ``obs.trace.capture`` crash barrier (tmp durable, final name not yet
  present — the worst instant the chaos matrix SIGKILLs at,
  tests/test_pipeline_chaos.py), then renames tmp into place. A reader
  can only ever see a complete capture or none, and a torn capture
  leaves the run's training artifacts bitwise identical.

This module is the ONLY place allowed to call the raw profiler API —
``tests/test_profiler_lint.py`` enforces it mechanically (escape hatch
``# lint: allow-raw-profiler <why>``).
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Iterator, Optional

import contextlib

from sparse_coding_tpu.obs.registry import get_registry
from sparse_coding_tpu.obs.spans import emit_event, monotime
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site

SITE = "obs.trace.capture"

register_fault_site(SITE,
                    "managed profiler capture — begin and atomic finalize "
                    "(obs/trace.py); error = counted skip, never fatal")
register_crash_site(SITE,
                    "profiler stopped, trace tmp dir durable, final "
                    "rename not yet performed (obs/trace.py)")


class TraceCapture:
    """One managed capture window into ``out_dir``.

    ``begin()`` returns whether profiling actually started (False = a
    counted skip — the host should stop re-trying the window);
    ``end()`` is idempotent and safe in a host's finally. A failed or
    torn capture never raises into the host and never leaves a partial
    artifact under the final name."""

    def __init__(self, out_dir: str | Path):
        self.out_dir = Path(out_dir)
        self._tmp = self.out_dir.parent / \
            f".{self.out_dir.name}.tmp.{os.getpid()}"
        self._active = False
        self._t0 = 0.0

    @property
    def active(self) -> bool:
        return self._active

    def _skip(self, stage: str) -> None:
        get_registry().counter("obs.trace.skipped").inc()
        emit_event("trace.skipped", dir=str(self.out_dir), stage=stage)
        shutil.rmtree(self._tmp, ignore_errors=True)

    def begin(self) -> bool:
        """Start the profiler into the tmp dir. Returns False (counted,
        tmp cleaned) on any error — profiling must never kill the host
        workload."""
        if self._active:
            return True
        try:
            import jax

            # clean debris from a KILLED capture (dead pid's tmp dir):
            # one capture host per out_dir by contract, so any sibling
            # tmp is an orphan, never a live writer's
            for stale in self.out_dir.parent.glob(
                    f".{self.out_dir.name}.tmp.*"):
                shutil.rmtree(stale, ignore_errors=True)
            self._tmp.mkdir(parents=True, exist_ok=True)
            fault_point(SITE)
            jax.profiler.start_trace(str(self._tmp))  # lint: allow-raw-profiler the managed wrapper itself
        except Exception:  # noqa: BLE001 — counted skip by contract
            self._skip("begin")
            return False
        self._active = True
        self._t0 = monotime()
        return True

    def end(self) -> Optional[Path]:
        """Stop the profiler and atomically finalize the artifact into
        ``out_dir``; returns the final path, or None for a no-op/failed
        finalize (counted). Idempotent."""
        if not self._active:
            return None
        self._active = False
        try:
            import jax

            jax.profiler.stop_trace()  # lint: allow-raw-profiler the managed wrapper itself
            # the worst instant: the capture is whole in tmp, the final
            # name absent — a SIGKILL here must cost only the trace
            crash_barrier(SITE)
            fault_point(SITE)
            if self.out_dir.exists():
                # recapture into the same destination: the old artifact
                # is replaced whole (never merged with the new one)
                shutil.rmtree(self.out_dir)
            self._tmp.rename(self.out_dir)
        except Exception:  # noqa: BLE001 — counted skip by contract
            self._skip("finalize")
            return None
        dur = monotime() - self._t0
        get_registry().counter("obs.trace.captured").inc()
        emit_event("trace.captured", dir=str(self.out_dir),
                   dur_s=round(dur, 3))
        return self.out_dir


@contextlib.contextmanager
def capture(out_dir: str | Path) -> Iterator[TraceCapture]:
    """Context-manager form: profile the body into ``out_dir`` with
    guaranteed stop+finalize on ANY exit path (the body's exception still
    propagates; the steps it did capture stay viewable)."""
    cap = TraceCapture(out_dir)
    cap.begin()
    try:
        yield cap
    finally:
        cap.end()
