"""Spans: structured start/end/error events with run/step/span correlation.

A **span** is one timed region of host work (``span("sweep.chunk",
index=ci)``). Entering emits a ``span.start`` event, leaving emits
``span.end`` with a monotonic duration and ok/error status, and the
duration lands in the registry histogram ``span.<name>.dur_s`` (errors in
the counter ``span.<name>.errors``) — so one call site feeds both the
event stream the report merges and the cheap in-process snapshot.

Correlation contract (docs/ARCHITECTURE.md §12): every event carries

- ``run``  — the run ID, minted once per run dir by the pipeline
  supervisor (persisted to ``<run_dir>/obs/run_id`` so a restarted
  supervisor joins, not forks, the run) and propagated to child steps via
  ``SPARSE_CODING_RUN_ID``;
- ``step`` — the supervisor step name (``SPARSE_CODING_OBS_STEP``);
- ``pid`` / ``seq`` — process identity and per-process event order;
- ``span_id`` / ``parent`` — this span and its enclosing span (a
  thread-local stack), so nested regions reconstruct.

Events from the supervisor, its child steps, journal records, and lease
beats of one run all join on ``run`` (plus the run dir itself — the
coarse correlation scope).

Timing uses :func:`monotime` — the repo's single raw-clock read for hot
paths (``tests/test_obs_lint.py`` enforces that data/train/serve/pipeline
code reads clocks through here).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from sparse_coding_tpu.obs import sink as sink_mod
from sparse_coding_tpu.obs.registry import Registry, get_registry

ENV_RUN_ID = "SPARSE_CODING_RUN_ID"
ENV_STEP = "SPARSE_CODING_OBS_STEP"

monotime = time.perf_counter  # the sanctioned monotonic clock read


def run_id() -> str:
    return os.environ.get(ENV_RUN_ID, "")


def step_name() -> str:
    return os.environ.get(ENV_STEP, "")


_seq_lock = threading.Lock()
_seq = 0
_stack = threading.local()  # per-thread open-span id stack


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def _current_parent() -> Optional[str]:
    stack = getattr(_stack, "ids", None)
    return stack[-1] if stack else None


def mint_trace_id(prefix: str = "req") -> str:
    """A process-unique correlation id for one request's critical path
    (minted at gateway admission, carried queue → flush → dispatch →
    hedge; §12). Same identity scheme as span ids."""
    return f"{prefix}-{os.getpid()}-{_next_seq()}"


def emit_event(kind: str, *, sink: Optional[sink_mod.EventSink] = None,
               **fields) -> bool:
    """One correlated event to the given (or process-default) sink.
    No-op returning False when no sink is configured — library code calls
    this unconditionally, supervisor-agnostic (mirrors ``lease.beat``)."""
    target = sink if sink is not None else sink_mod.active_sink()
    if target is None:
        return False
    rec = {"ts": time.time(), "kind": kind, "run": run_id(),
           "step": step_name(), "pid": os.getpid(), "seq": _next_seq()}
    rec.update(fields)
    return target.emit(rec)


def record_span(name: str, dur_s: float, ok: bool = True,
                error: str = "", sink: Optional[sink_mod.EventSink] = None,
                registry: Optional[Registry] = None, **attrs) -> None:
    """Record a completed span from an externally-measured duration (loop
    bodies that cannot wrap themselves in a context manager). Feeds the
    registry histogram AND emits the ``span.end`` event."""
    reg = registry if registry is not None else get_registry()
    reg.histogram(f"span.{name}.dur_s").observe(dur_s)
    if not ok:
        reg.counter(f"span.{name}.errors").inc()
    emit_event("span.end", sink=sink, span=name, dur_s=round(dur_s, 6),
               ok=ok, **({"error": error} if error else {}), **attrs)


class span:
    """Context manager form: emits paired start/end events with nesting.

    >>> with span("sweep.chunk", index=ci):
    ...     train_one_chunk()
    """

    def __init__(self, name: str, sink: Optional[sink_mod.EventSink] = None,
                 registry: Optional[Registry] = None, **attrs):
        self.name = name
        self.attrs = attrs
        self._sink = sink
        self._registry = registry
        self._t0 = 0.0
        self.span_id = ""

    def __enter__(self) -> "span":
        self.span_id = f"{os.getpid()}-{_next_seq()}"
        parent = _current_parent()
        stack = getattr(_stack, "ids", None)
        if stack is None:
            stack = _stack.ids = []
        stack.append(self.span_id)
        emit_event("span.start", sink=self._sink, span=self.name,
                   span_id=self.span_id,
                   **({"parent": parent} if parent else {}), **self.attrs)
        self._t0 = monotime()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = monotime() - self._t0
        stack = getattr(_stack, "ids", None)
        if stack and stack[-1] == self.span_id:
            stack.pop()
        reg = self._registry if self._registry is not None else get_registry()
        reg.histogram(f"span.{self.name}.dur_s").observe(dur)
        if exc_type is not None:
            reg.counter(f"span.{self.name}.errors").inc()
        emit_event("span.end", sink=self._sink, span=self.name,
                   span_id=self.span_id, dur_s=round(dur, 6),
                   ok=exc_type is None,
                   **({"error": exc_type.__name__} if exc_type else {}),
                   **self.attrs)


def flush_metrics(sink: Optional[sink_mod.EventSink] = None,
                  registry: Optional[Registry] = None) -> bool:
    """Emit the registry snapshot as one ``metrics`` event. Called at
    durable boundaries (chunk trained, step finished) so a crashed
    process still leaves its last counters in the event stream — the
    crash-only twin of an exit handler, which SIGKILL never runs."""
    reg = registry if registry is not None else get_registry()
    return emit_event("metrics", sink=sink, registry=reg.snapshot())
