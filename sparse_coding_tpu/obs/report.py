"""Merge a run's event files into one human-readable summary.

``python -m sparse_coding_tpu.obs.report <run_dir>`` scans
``<run_dir>/obs/*.jsonl`` — one file per process that took part in the
run (supervisor + every child-step attempt) — and joins them on the run
ID the supervisor propagated (obs/spans.py correlation contract):

- per-span duration stats (count, errors, p50/p95/p99, total wall) from
  ``span.end`` events, exact — the raw durations are in the events;
- merged registry counters (summed across processes: retraces, compiles,
  rows harvested, sink drops, …), gauges (latest by wall clock:
  throughput, memory), histograms (bin-for-bin fixed-bucket merge) from
  each file's LAST ``metrics`` event — the crash-safe snapshot the hosts
  flush at durable boundaries;
- compile/cache evidence (``compile_cache`` — docs/ARCHITECTURE.md §13):
  persistent-compilation-cache and executable-store hit/miss counts,
  total compile seconds, and the estimated compile seconds a warm start
  saved (summed from each loaded entry's recorded compile time);
- hygiene: files scanned, torn/corrupt lines skipped (a SIGKILLed
  writer's tail is skipped by the reader contract, so it can never
  corrupt this report), run IDs seen (one, unless files from different
  runs were mixed into the directory);
- device-time perf evidence (§12, ISSUE 12): sampled per-kernel-path
  MFU, device step walls, the predicted-vs-achieved roofline gap, the
  request critical-path stage decomposition, and trace-capture tallies.
  ``--diff <run_a> <run_b>`` compares two runs' perf sections and flags
  MFU/latency regressions (label-exact matching plus run-backend
  detection, so cpu-fallback rows never compare against on-chip rows).

Diagnostics go to the returned dict / stdout only — this module never
touches jax, so the CLI runs on a host with a wedged tunnel.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Optional

import threading

from sparse_coding_tpu.obs.registry import Histogram
from sparse_coding_tpu.obs.sink import scan_events


def _quantile(values: list[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


def split_labels(name: str) -> tuple[str, dict]:
    """``"base{k=v,k2=v2}"`` → ``(base, {k: v, k2: v2})`` (``{}`` for a
    bare name) — the ONE parser of the registry's instrument-label
    encoding (obs/registry._label_key), shared by every section below."""
    if "{" not in name:
        return name, {}
    base = name[:name.index("{")]
    labels = dict(pair.partition("=")[::2]
                  for pair in name[name.index("{") + 1:-1].split(","))
    return base, labels


def build_report(run_dir: str | Path, obs_subdir: str = "obs") -> dict:
    """The merged summary dict for one run directory."""
    run_dir = Path(run_dir)
    obs_dir = run_dir / obs_subdir
    files = sorted(obs_dir.glob("*.jsonl")) if obs_dir.exists() else []
    spans: dict[str, dict] = {}
    counters: dict[str, int] = {}
    gauges: dict[str, dict] = {}  # name -> {"value", "max", "ts"}
    merged: dict[str, Histogram] = {}
    run_ids: set[str] = set()
    steps: set[str] = set()
    perf_backends: set[str] = set()
    skipped_total = 0
    n_events = 0
    errors: dict[str, int] = {}

    for path in files:
        events, skipped = scan_events(path)
        skipped_total += skipped
        n_events += len(events)
        last_metrics: Optional[dict] = None
        for ev in events:
            if ev.get("run"):
                run_ids.add(ev["run"])
            if ev.get("step"):
                steps.add(ev["step"])
            kind = ev.get("kind")
            if kind == "span.end":
                s = spans.setdefault(ev.get("span", "?"), {
                    "count": 0, "errors": 0, "dur_s": []})
                s["count"] += 1
                if not ev.get("ok", True):
                    s["errors"] += 1
                    err = ev.get("error", "Error")
                    errors[err] = errors.get(err, 0) + 1
                if isinstance(ev.get("dur_s"), (int, float)):
                    s["dur_s"].append(float(ev["dur_s"]))
            elif kind == "perf.sample":
                # which backend(s) this run's device-time samples were
                # measured on — the diff's cross-backend guard reads it
                # even when a sample carried no MFU (zero-flops costs)
                if ev.get("backend"):
                    perf_backends.add(str(ev["backend"]))
            elif kind == "metrics":
                last_metrics = ev
        if last_metrics is not None:
            snap = last_metrics.get("registry", {})
            for name, v in snap.get("counters", {}).items():
                counters[name] = counters.get(name, 0) + int(v)
            ts = float(last_metrics.get("ts", 0.0))
            for name, g in snap.get("gauges", {}).items():
                if name not in gauges or ts >= gauges[name]["ts"]:
                    gauges[name] = {"value": g.get("value"),
                                    "max": g.get("max"), "ts": ts}
            for name, h in snap.get("histograms", {}).items():
                hist = merged.get(name)
                if hist is None:
                    hist = merged[name] = Histogram(threading.Lock(),
                                                    bounds=h.get("bounds"))
                try:
                    hist.merge_snapshot(h)
                except ValueError:
                    pass  # bounds drifted between processes: skip, not die

    span_stats = {}
    for name, s in sorted(spans.items()):
        durs = s["dur_s"]
        span_stats[name] = {
            "count": s["count"], "errors": s["errors"],
            "total_s": round(sum(durs), 6),
            "p50_s": _quantile(durs, 0.50), "p95_s": _quantile(durs, 0.95),
            "p99_s": _quantile(durs, 0.99),
        }
    histograms = {name: {**h.snapshot(),
                         "p50": h.quantile(0.50), "p95": h.quantile(0.95),
                         "p99": h.quantile(0.99)}
                  for name, h in merged.items()}

    def _hist_sum(name: str) -> float:
        h = histograms.get(name)
        return round(float(h["sum"]), 3) if h else 0.0

    # compile/cache evidence (docs/ARCHITECTURE.md §13): the two cache
    # layers xcache.enable() turns on. "persistent" = jax's compilation
    # cache (hits are disk loads inside a compile); "store" = the
    # serialized-executable store (hits skip the backend compile
    # entirely); saved_s sums the compile seconds each loaded entry
    # replaced — the headline number of a warm restart.
    compile_cache = {
        "persistent_hits": counters.get("jax.cache_hits", 0),
        "persistent_misses": counters.get("jax.cache_misses", 0),
        "store_hits": counters.get("xcache.hits", 0),
        "store_misses": counters.get("xcache.misses", 0),
        "store_errors": counters.get("xcache.errors", 0),
        "store_evictions": counters.get("xcache.evictions", 0),
        "compile_time_s": _hist_sum("jax.compile_dur_s"),
        "saved_s": _hist_sum("xcache.saved_s"),
    }

    # gateway evidence (docs/ARCHITECTURE.md §14): the self-healing
    # front door's hedge / shed / failover / spare-activation story in
    # one place, so a replica incident reads out of the SAME merged
    # report as its latency and compile evidence
    def _by_label(prefix: str, label: str) -> dict:
        out = {}
        for name, v in counters.items():
            base, labels = split_labels(name)
            if base == prefix and label in labels:
                out[labels[label]] = out.get(labels[label], 0) + int(v)
        return out

    gateway = {
        "hedges_fired": counters.get("gateway.hedges_fired", 0),
        "hedges_won": counters.get("gateway.hedges_won", 0),
        "hedges_wasted": counters.get("gateway.hedges_wasted", 0),
        "hedges_abandoned": counters.get("gateway.hedges_abandoned", 0),
        "failovers": counters.get("gateway.failovers", 0),
        "route_errors": counters.get("gateway.route_errors", 0),
        "spare_activations": counters.get("gateway.spare_activations", 0),
        "spare_activation_errors":
            counters.get("gateway.spare_activation_errors", 0),
        "spare_exhausted": counters.get("gateway.spare_exhausted", 0),
        "shed": _by_label("gateway.shed", "priority"),
        "served": _by_label("gateway.served", "priority"),
        "routes": _by_label("gateway.routes", "replica"),
        "replica_errors": _by_label("gateway.replica_errors", "replica"),
        "dispatch_timeouts": _by_label("gateway.dispatch_timeouts",
                                       "replica"),
        "admission_level":
            gauges.get("gateway.admission_level", {}).get("value"),
    }

    # traffic-shaped ladder evidence (docs/ARCHITECTURE.md §24): the
    # ACTIVE rung set (published as idx-labeled gauges at every swap),
    # the swap/hold/skip tallies, the continuous-rebatching outcome, and
    # the pad-waste the ladder exists to shrink — Σ over buckets of
    # (batches x bucket − rows served). One section answers "did the
    # derived ladder actually pay": rungs match traffic, wasted pad
    # falls, swaps are counted not flapping
    active_rungs = []
    for name, g in gauges.items():
        base, labels = split_labels(name)
        if base == "gateway.ladder.rung" and "idx" in labels:
            v = g.get("value")
            if v:
                active_rungs.append((int(labels["idx"]), int(v)))
    wasted_pad_rows = 0
    served_rows = _by_label("serve.rows", "bucket")
    for b, n_batches in _by_label("serve.batches", "bucket").items():
        try:
            wasted_pad_rows += (int(b) * int(n_batches)
                                - int(served_rows.get(b, 0)))
        except (TypeError, ValueError):
            continue
    ladder = {
        "rungs": [r for _, r in sorted(active_rungs)],
        "swaps": counters.get("gateway.ladder.swaps", 0),
        "held": counters.get("gateway.ladder.held", 0),
        "derive_errors": counters.get("gateway.ladder.derive_errors", 0),
        "swap_errors": counters.get("gateway.ladder.swap_errors", 0),
        "rebatch_joined": counters.get("serve.rebatch.joined", 0),
        "rebatch_joined_rows": counters.get("serve.rebatch.joined_rows", 0),
        "rebatch_rejected": counters.get("serve.rebatch.rejected", 0),
        # every joined row is a pad row the dispatched batch would have
        # burned anyway — the rebatcher's direct savings
        "pad_rows_saved": counters.get("serve.rebatch.joined_rows", 0),
        "wasted_pad_rows": wasted_pad_rows,
    }
    # data-plane evidence (docs/ARCHITECTURE.md §15): the async ingest
    # pipeline's per-stage walls (decode vs host→device staging vs the
    # whole sweep.chunk block — "compute-bound" means decode stops
    # dominating sweep.chunk), stream-death degradations, and the scrub's
    # verify/quarantine tallies — one place an operator reads a data
    # incident out of, alongside the latency and compile evidence
    def _span_wall(name: str) -> float:
        s = span_stats.get(name)
        return float(s["total_s"]) if s else 0.0

    ingest = {
        "decode_s": _span_wall("ingest.decode"),
        "transfer_s": _span_wall("ingest.transfer"),
        "sweep_chunk_s": _span_wall("sweep.chunk"),
        "decoded_chunks": span_stats.get("ingest.decode", {}).get("count", 0),
        "degraded_streams": counters.get("ingest.degraded", 0),
        "scrub_checked": counters.get("scrub.chunks_checked", 0),
        "scrub_quarantined": counters.get("scrub.chunks_quarantined", 0),
    }
    # kernel-path evidence (ISSUE 11): every Ensemble._resolve_step
    # decision is a counted event — which program each bucket's steps ran
    # (two_stage / train_step / the feature-tiled variants / autodiff)
    # and why (roofline | forced | no_admissible_tile | ...) — so a sweep
    # that quietly fell back to autodiff is visible in every run report
    # instead of invisible in all artifacts
    kernel_paths: dict = {}
    for name, v in counters.items():
        base, labels = split_labels(name)
        if base != "ensemble.path_resolved" or not labels:
            continue
        ent = kernel_paths.setdefault(labels.get("path", "?"),
                                      {"count": 0, "reasons": {}})
        ent["count"] += int(v)
        reason = labels.get("reason", "?")
        ent["reasons"][reason] = ent["reasons"].get(reason, 0) + int(v)

    # device-time perf evidence (docs/ARCHITECTURE.md §12, ISSUE 12): the
    # sampled probe's measured MFU per kernel path (backend-labeled —
    # cpu rows are reference numbers, never compared against on-chip
    # rows), per-path device step walls, the predicted-vs-achieved
    # roofline gap, the request critical-path stage decomposition, and
    # the managed-trace capture tallies — the section --diff compares
    # between runs
    def _hist_stats(h: dict) -> dict:
        return {"count": h["count"], "p50": h.get("p50"),
                "p95": h.get("p95"), "p99": h.get("p99")}

    perf_mfu: dict = {}
    for name, g in gauges.items():
        if split_labels(name)[0] in ("train.mfu", "serve.mfu"):
            perf_mfu[name] = g["value"]
    device_steps: dict = {}
    gaps: dict = {}
    stages: dict = {}
    for name, h in histograms.items():
        base, labels = split_labels(name)
        if base in ("train.device_step_s", "serve.device_step_s"):
            device_steps[name] = _hist_stats(h)
        elif base == "perf.roofline_gap":
            gaps[name] = _hist_stats(h)
        elif base == "serve.stage_s":
            stages[labels.get("stage", "?")] = _hist_stats(h)
    perf = {
        "mfu": perf_mfu,
        "device_step_s": device_steps,
        "roofline_gap": gaps,
        "request_stages": stages,
        "backends": sorted(perf_backends),
        "samples": sum(v for n, v in counters.items()
                       if n.startswith("perf.samples")),
        "trace_captured": counters.get("obs.trace.captured", 0),
        "trace_skipped": counters.get("obs.trace.skipped", 0),
    }

    # elastic-plane evidence (docs/ARCHITECTURE.md §21): the arbiter's
    # rebalance story — how often serving and the fleet traded slices,
    # which direction, what it cost (scavenger reclaims), what failed
    # (fault-sited errors, retried next tick) — and the current split
    # gauges, so one merged report shows a whole tide cycle next to the
    # latency, compile, and preemption evidence it produced
    plane = {
        "rebalances": counters.get("plane.rebalances", 0),
        "scale_ups": counters.get("plane.scale_ups", 0),
        "scale_downs": counters.get("plane.scale_downs", 0),
        "reclaims": counters.get("plane.reclaims", 0),
        "reconciles": counters.get("plane.reconciles", 0),
        "replicas_released": counters.get("plane.replicas_released", 0),
        "rebalance_errors": counters.get("plane.rebalance_errors", 0),
        "scale_errors": counters.get("plane.scale_errors", 0),
        "serve_slices": gauges.get("plane.serve_slices", {}).get("value"),
        "fleet_slices": gauges.get("plane.fleet_slices", {}).get("value"),
        "replicas": gauges.get("plane.replicas", {}).get("value"),
    }

    # guardian evidence (docs/ARCHITECTURE.md §16): the sweep's divergence
    # ladder — member quarantines, chunk quarantines, rollbacks, typed
    # halts — plus the boundary-check and rollback walls, so one merged
    # report tells the whole incident story next to the throughput and
    # ingest evidence it disturbed
    guardian = {
        "members_quarantined":
            counters.get("guardian.members_quarantined", 0),
        "chunks_quarantined": counters.get("guardian.chunks_quarantined", 0),
        "rollbacks": counters.get("guardian.rollbacks", 0),
        "halts": counters.get("guardian.halts", 0),
        "checks": span_stats.get("guardian.check", {}).get("count", 0),
        "check_s": _span_wall("guardian.check"),
        "rollback_s": _span_wall("guardian.rollback"),
    }
    return {
        "run_dir": str(run_dir),
        "run_ids": sorted(run_ids),
        "steps": sorted(steps),
        "files": [p.name for p in files],
        "events": n_events,
        "skipped_lines": skipped_total,
        "spans": span_stats,
        "counters": dict(sorted(counters.items())),
        "gauges": {k: {"value": v["value"], "max": v["max"]}
                   for k, v in sorted(gauges.items())},
        "histograms": histograms,
        "span_errors": errors,
        "retraces": counters.get("jax.retraces", 0),
        "compiles": counters.get("jax.compiles", 0),
        "compile_cache": compile_cache,
        "gateway": gateway,
        "ladder": ladder,
        "plane": plane,
        "ingest": ingest,
        "guardian": guardian,
        "kernel_paths": kernel_paths,
        "perf": perf,
        "dropped_events": counters.get("obs.sink.dropped", 0),
    }


def is_fleet_dir(path: str | Path) -> bool:
    """A fleet dir is recognized by its queue file — the CLI auto-routes
    to the fleet section (one report command, whatever the layout)."""
    from sparse_coding_tpu.pipeline.fleet_queue import QUEUE_NAME

    return (Path(path) / QUEUE_NAME).exists()


def build_fleet_report(fleet_dir: str | Path) -> dict:
    """The multi-tenant merge (docs/ARCHITECTURE.md §18): replay the
    fleet queue (jax-free — runs against a wedged tunnel) and build each
    tenant's OWN merged report over its run dir, plus the scheduler's
    placement/preemption/containment counters from the fleet-level event
    files. One command answers the incident questions: which tenant
    halted, what did it cost everyone else (nothing), and did the next
    tenant warm-start from the shared cache."""
    from sparse_coding_tpu.pipeline.fleet_queue import QUEUE_NAME, FleetQueue

    fleet_dir = Path(fleet_dir)
    state = FleetQueue(fleet_dir / QUEUE_NAME).replay()
    tenants = {}
    for name, run in sorted(state.runs.items()):
        report = build_report(fleet_dir / "runs" / name)
        tenants[name] = {
            "state": run.state, "priority": run.priority,
            "slices": run.slices, "attempts": run.attempts,
            "report": report,
        }
    # the scheduler's own evidence stream (obs/fleet-<pid>.jsonl files)
    sched = build_report(fleet_dir)
    counters = sched.get("counters", {})
    releases = {}
    for cname, v in counters.items():
        base, labels = split_labels(cname)
        if base == "fleet.releases" and "outcome" in labels:
            releases[labels["outcome"]] = releases.get(
                labels["outcome"], 0) + int(v)
    # plane.rebalance records are plane-level journal events (step=""),
    # invisible to the run-state fold by design — surface them here so
    # the fleet report shows the tide cycle the tenants lived through
    rebalances = [
        {"seq": int(r.get("seq", 0)),
         "serve_slices": int((r.get("detail") or {}).get(
             "serve_slices", 0)),
         "fleet_slices": int((r.get("detail") or {}).get(
             "fleet_slices", 0)),
         "reason": (r.get("detail") or {}).get("reason", "?")}
        for r in FleetQueue(fleet_dir / QUEUE_NAME).journal.records()
        if r.get("event") == "plane.rebalance"]
    return {
        "fleet_dir": str(fleet_dir),
        "states": state.summary(),
        "tenants": tenants,
        "plane": {**sched.get("plane", {}), "records": rebalances},
        "scheduler": {
            "placements": counters.get("fleet.placements", 0),
            "preemptions": counters.get("fleet.preemptions", 0),
            "halts": counters.get("fleet.halts", 0),
            "reclaims": counters.get("fleet.reclaims", 0),
            "worker_hangs": counters.get("fleet.worker_hangs", 0),
            "place_errors": counters.get("fleet.place_errors", 0),
            "preempt_errors": counters.get("fleet.preempt_errors", 0),
            "releases": releases,
            "events": sched.get("events", 0),
        },
    }


def format_fleet_report(fleet: dict) -> str:
    sched = fleet["scheduler"]
    lines = [f"fleet {fleet['fleet_dir']} — "
             f"{len(fleet['tenants'])} tenant(s)",
             f"scheduler: {sched['placements']} placement(s), "
             f"{sched['preemptions']} preemption(s), "
             f"{sched['halts']} halt(s), {sched['reclaims']} reclaim(s), "
             f"{sched['worker_hangs']} hung worker(s); releases "
             + (", ".join(f"{k}={v}"
                          for k, v in sorted(sched["releases"].items()))
                or "-")]
    plane = fleet.get("plane", {})
    if plane.get("records") or plane.get("rebalances"):
        lines.append(
            f"plane: {plane.get('rebalances', 0)} rebalance(s) "
            f"({plane.get('scale_ups', 0)} up/"
            f"{plane.get('scale_downs', 0)} down), "
            f"{plane.get('reclaims', 0)} scavenger reclaim(s), "
            f"{plane.get('rebalance_errors', 0)}+"
            f"{plane.get('scale_errors', 0)} error(s); split "
            f"serve={plane.get('serve_slices', '-')}/"
            f"fleet={plane.get('fleet_slices', '-')} slice(s)")
    for name, t in fleet["tenants"].items():
        rep = t["report"]
        gd = rep.get("guardian", {})
        cc = rep.get("compile_cache", {})
        lines.append(
            f"tenant {name}: {t['state']} ({t['priority']}, "
            f"{t['slices']} slice(s), {t['attempts']} attempt(s)) — "
            f"guardian {gd.get('halts', 0)} halt(s)/"
            f"{gd.get('rollbacks', 0)} rollback(s), xcache "
            f"{cc.get('store_hits', 0)}h/{cc.get('store_misses', 0)}m, "
            f"{rep.get('events', 0)} event(s)")
    lines.append("per-tenant detail: python -m sparse_coding_tpu.obs."
                 "report <fleet_dir>/runs/<tenant>")
    return "\n".join(lines)


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def format_report(report: dict) -> str:
    lines = [f"run {', '.join(report['run_ids']) or '(no run id)'} — "
             f"{len(report['files'])} event file(s), {report['events']} "
             f"events, {report['skipped_lines']} torn/corrupt line(s) "
             f"skipped",
             f"steps: {', '.join(report['steps']) or '-'}"]
    if report["spans"]:
        lines.append("spans (count/err  p50  p95  p99  total):")
        for name, s in report["spans"].items():
            lines.append(
                f"  {name:<28} {s['count']}/{s['errors']}  "
                f"{_fmt_s(s['p50_s'])}  {_fmt_s(s['p95_s'])}  "
                f"{_fmt_s(s['p99_s'])}  {_fmt_s(s['total_s'])}")
    throughput = {k: v for k, v in report["gauges"].items()
                  if k.endswith("per_sec")}
    if throughput:
        lines.append("throughput:")
        for name, g in throughput.items():
            lines.append(f"  {name:<28} {g['value']:.1f} (max {g['max']:.1f})")
    lines.append(f"xla: {report['retraces']} retrace(s), "
                 f"{report['compiles']} compile(s)")
    cc = report.get("compile_cache", {})
    if any(cc.get(k) for k in ("persistent_hits", "persistent_misses",
                               "store_hits", "store_misses",
                               "store_errors")):
        lines.append(
            f"compile cache: persistent {cc['persistent_hits']}h/"
            f"{cc['persistent_misses']}m, store {cc['store_hits']}h/"
            f"{cc['store_misses']}m ({cc['store_errors']} bad), "
            f"{cc['compile_time_s']:.1f}s compiling, "
            f"~{cc['saved_s']:.1f}s saved")
    gw = report.get("gateway", {})
    if any(v for k, v in gw.items()
           if k != "admission_level" and (v if isinstance(v, int)
                                          else sum(v.values()))):
        shed = ", ".join(f"{p}={n}" for p, n in sorted(gw["shed"].items()))
        routes = ", ".join(f"{r}={n}"
                           for r, n in sorted(gw["routes"].items()))
        lines.append(
            f"gateway: hedges {gw['hedges_fired']}f/{gw['hedges_won']}w/"
            f"{gw['hedges_wasted']}x, failovers {gw['failovers']}, "
            f"spares {gw['spare_activations']} activated "
            f"({gw['spare_activation_errors']} failed), "
            f"admission level {gw['admission_level']}")
        if shed:
            lines.append(f"  shed: {shed}")
        if routes:
            lines.append(f"  routes: {routes}")
    lad = report.get("ladder", {})
    if lad.get("rungs") or any(
            lad.get(k) for k in ("swaps", "held", "derive_errors",
                                 "swap_errors", "rebatch_joined",
                                 "rebatch_rejected")):
        rungs = ",".join(str(r) for r in lad.get("rungs", [])) or "?"
        lines.append(
            f"ladder: active [{rungs}], {lad['swaps']} swap(s) "
            f"({lad['held']} held, {lad['derive_errors']} derive err, "
            f"{lad['swap_errors']} swap err); rebatch "
            f"{lad['rebatch_joined']} joined "
            f"(+{lad['rebatch_joined_rows']} rows) / "
            f"{lad['rebatch_rejected']} rejected; pad rows "
            f"{lad['wasted_pad_rows']} wasted / "
            f"{lad['pad_rows_saved']} saved")
    ing = report.get("ingest", {})
    if any(ing.get(k) for k in ("decoded_chunks", "degraded_streams",
                                "scrub_checked", "scrub_quarantined")):
        lines.append(
            f"ingest: {ing['decoded_chunks']} async decode(s) "
            f"({_fmt_s(ing['decode_s'])} decoding, "
            f"{_fmt_s(ing['transfer_s'])} staging, "
            f"{_fmt_s(ing['sweep_chunk_s'])} sweep.chunk), "
            f"{ing['degraded_streams']} stream death(s) degraded; "
            f"scrub {ing['scrub_checked']} checked / "
            f"{ing['scrub_quarantined']} quarantined")
    gd = report.get("guardian", {})
    if any(gd.get(k) for k in ("members_quarantined", "chunks_quarantined",
                               "rollbacks", "halts")):
        lines.append(
            f"guardian: {gd['members_quarantined']} member(s) quarantined, "
            f"{gd['chunks_quarantined']} chunk(s) quarantined, "
            f"{gd['rollbacks']} rollback(s), {gd['halts']} halt(s) "
            f"({gd['checks']} checks, {_fmt_s(gd['check_s'])} checking, "
            f"{_fmt_s(gd['rollback_s'])} restoring)")
    kp = report.get("kernel_paths", {})
    if kp:
        parts = []
        for path, ent in sorted(kp.items()):
            reasons = ",".join(f"{r}={n}"
                               for r, n in sorted(ent["reasons"].items()))
            parts.append(f"{path}={ent['count']} [{reasons}]")
        lines.append("kernel paths (step-path resolutions): "
                     + ", ".join(parts))
    pf = report.get("perf", {})
    if pf.get("samples") or pf.get("trace_captured") or pf.get(
            "trace_skipped"):
        lines.append(
            f"perf: {pf['samples']} device-time sample(s), traces "
            f"{pf['trace_captured']} captured / {pf['trace_skipped']} "
            "skipped")
        for name, v in sorted(pf.get("mfu", {}).items()):
            lines.append(f"  {name:<40} {v:.4f}")
        for name, s in sorted(pf.get("device_step_s", {}).items()):
            lines.append(f"  {name:<40} p50 {_fmt_s(s['p50'])}  "
                         f"p95 {_fmt_s(s['p95'])}  ({s['count']})")
        for name, s in sorted(pf.get("roofline_gap", {}).items()):
            lines.append(f"  {name:<40} x{s['p50']:.2f} measured/"
                         f"predicted  ({s['count']})")
        if pf.get("request_stages"):
            stage_bits = "  ".join(
                f"{st}={_fmt_s(s['p50'])}/{_fmt_s(s['p95'])}/"
                f"{_fmt_s(s['p99'])}"
                for st, s in sorted(pf["request_stages"].items()))
            lines.append(f"  request stages (p50/p95/p99): {stage_bits}")
    interesting = {k: v for k, v in report["counters"].items()
                   if not k.startswith(("jax.retraces", "jax.compiles"))}
    if interesting:
        lines.append("counters:")
        for name, v in interesting.items():
            lines.append(f"  {name:<28} {v}")
    if report["span_errors"]:
        lines.append(f"errors: {report['span_errors']}")
    return "\n".join(lines)


def _perf_backends(perf: dict) -> set:
    """The backends a run's perf samples were measured on: the
    ``perf.sample`` events' backend field (present even for zero-flops
    samples that set no MFU gauge) unioned with the backend-labeled MFU
    gauge names."""
    out = set(perf.get("backends", []))
    for name in perf.get("mfu", {}):
        backend = split_labels(name)[1].get("backend")
        if backend:
            out.add(backend)
    return out


def diff_reports(report_a: dict, report_b: dict,
                 threshold: float = 0.10) -> dict:
    """Compare two runs' perf evidence (A = baseline, B = candidate):
    MFU drops and latency/step-wall increases beyond ``threshold`` are
    flagged as regressions. A cpu-fallback run never compares against an
    on-chip run (docs/RUNBOOK_TUNNEL.md): backend-labeled rows only
    match their exact label twin, and when the two runs' detected
    backends differ, every backend-UNLABELED metric (step walls,
    roofline gaps, request stages, latency histograms) is skipped and
    counted instead of flagged as a bogus cross-backend regression."""
    pa, pb = report_a.get("perf", {}), report_b.get("perf", {})
    ba, bb = _perf_backends(pa), _perf_backends(pb)
    cross_backend = bool(ba) and bool(bb) and ba != bb
    regressions: list[str] = []
    improvements: list[str] = []
    compared = 0
    skipped_cross_backend = 0

    def _flag(name: str, a: float, b: float, higher_is_better: bool,
              fmt: str = "{:.4f}", backend_labeled: bool = False) -> None:
        nonlocal compared, skipped_cross_backend
        if not a or a <= 0 or b is None:
            return
        if cross_backend and not backend_labeled:
            skipped_cross_backend += 1
            return
        compared += 1
        rel = (b - a) / a
        worse = rel < -threshold if higher_is_better else rel > threshold
        better = rel > threshold if higher_is_better else rel < -threshold
        line = (f"{name}: {fmt.format(a)} -> {fmt.format(b)} "
                f"({rel * 100.0:+.1f}%)")
        if worse:
            regressions.append(line)
        elif better:
            improvements.append(line)

    for name, a in pa.get("mfu", {}).items():
        b = pb.get("mfu", {}).get(name)
        if b is not None:
            _flag(name, a, b, higher_is_better=True,
                  backend_labeled="backend" in split_labels(name)[1])
    for section, stat in (("device_step_s", "p50"),
                          ("roofline_gap", "p50"),
                          ("request_stages", "p95")):
        for name, sa in pa.get(section, {}).items():
            sb = pb.get(section, {}).get(name)
            if sb is not None and sa.get(stat) and sb.get(stat) is not None:
                _flag(f"{section}:{name}:{stat}", sa[stat], sb[stat],
                      higher_is_better=False, fmt="{:.6f}")
    for hist in ("gateway.latency_s",):
        ha = report_a.get("histograms", {}).get(hist)
        hb = report_b.get("histograms", {}).get(hist)
        if ha and hb and ha.get("p95") and hb.get("p95") is not None:
            _flag(f"{hist}:p95", ha["p95"], hb["p95"],
                  higher_is_better=False, fmt="{:.6f}")
    return {"run_a": report_a.get("run_dir"), "run_b": report_b.get("run_dir"),
            "threshold": threshold, "compared": compared,
            "backends_a": sorted(ba), "backends_b": sorted(bb),
            "skipped_cross_backend": skipped_cross_backend,
            "regressions": regressions, "improvements": improvements}


def _unit_higher_is_better(unit: str) -> Optional[bool]:
    """Direction semantics of a ledger row's unit: rates (``.../s``) and
    ratios improve upward; walls (``s``/``ms``) and overhead percentages
    improve downward. ``None`` = unknown semantics — never gated on."""
    u = (unit or "").strip()
    if "/s" in u or u == "ratio":
        return True
    head = u.split()[0] if u else ""
    if head in ("s", "ms") or u.startswith("%"):
        return False
    return None


def diff_ledger_suites(prior_rows: list[dict], new_rows: list[dict],
                       threshold: float = 0.10) -> dict:
    """Compare a bench run's suite rows against the last prior ledger row
    with the same (suite, variant, unit, backend) — the round-over-round
    regression gate (ROADMAP item 3(b); bench_suite.py exits nonzero on
    a flagged regression). Backend is part of the key, so a cpu-fallback
    round never compares against an on-chip round (the same guard
    ``diff_reports`` applies per-run); rows with no prior twin are listed
    as ``fresh``, not flagged; units with unknown direction semantics are
    skipped and counted."""
    def _key(r: dict) -> tuple:
        return (r.get("suite"), json.dumps(r.get("variant"), sort_keys=True,
                                           default=repr),
                r.get("unit"), r.get("backend"))

    baseline: dict[tuple, dict] = {}
    for r in prior_rows:
        if r.get("kind") == "suite" and isinstance(r.get("value"),
                                                   (int, float)):
            baseline[_key(r)] = r  # last prior row per key = the baseline
    regressions: list[str] = []
    improvements: list[str] = []
    fresh: list[str] = []
    compared = 0
    skipped = 0
    for r in new_rows:
        if r.get("kind") != "suite" or not isinstance(r.get("value"),
                                                      (int, float)):
            continue
        variant = r.get("variant")
        label = (f"{r.get('suite')}[{variant}]" if variant is not None
                 else str(r.get("suite")))
        label += f" ({r.get('unit')}, {r.get('backend')})"
        prior = baseline.get(_key(r))
        if prior is None:
            fresh.append(label)
            continue
        higher = _unit_higher_is_better(r.get("unit") or "")
        a, b = float(prior["value"]), float(r["value"])
        if higher is None or a <= 0:
            skipped += 1
            continue
        compared += 1
        rel = (b - a) / a
        line = f"{label}: {a:g} -> {b:g} ({rel * 100.0:+.1f}%)"
        worse = rel < -threshold if higher else rel > threshold
        better = rel > threshold if higher else rel < -threshold
        if worse:
            regressions.append(line)
        elif better:
            improvements.append(line)
    return {"threshold": threshold, "compared": compared,
            "skipped": skipped, "fresh": fresh,
            "regressions": regressions, "improvements": improvements}


def format_ledger_diff(diff: dict) -> str:
    lines = [f"bench gate: {diff['compared']} suite row(s) compared "
             f"against the perf ledger (threshold "
             f"{diff['threshold'] * 100:.0f}%, {len(diff['fresh'])} "
             f"fresh, {diff['skipped']} skipped)"]
    for r in diff["regressions"]:
        lines.append(f"  REGRESSION  {r}")
    for i in diff["improvements"]:
        lines.append(f"  improvement {i}")
    if not diff["regressions"] and not diff["improvements"]:
        lines.append("  no significant change vs prior rounds")
    return "\n".join(lines)


def format_diff(diff: dict) -> str:
    lines = [f"perf diff {diff['run_a']} -> {diff['run_b']} "
             f"({diff['compared']} metric(s) compared, threshold "
             f"{diff['threshold'] * 100:.0f}%)"]
    if diff.get("skipped_cross_backend"):
        lines.append(
            f"  note: runs measured on different backends "
            f"({','.join(diff['backends_a']) or '?'} vs "
            f"{','.join(diff['backends_b']) or '?'}); "
            f"{diff['skipped_cross_backend']} backend-unlabeled metric(s) "
            "skipped, not compared (docs/RUNBOOK_TUNNEL.md)")
    for r in diff["regressions"]:
        lines.append(f"  REGRESSION  {r}")
    for i in diff["improvements"]:
        lines.append(f"  improvement {i}")
    if not diff["regressions"] and not diff["improvements"]:
        lines.append("  no significant change")
    return "\n".join(lines)


def _print_report(payload: dict, formatter, as_json: bool) -> None:
    """The one CLI emit path: JSON or formatted, `| head`-tolerant."""
    try:
        print(json.dumps(payload, indent=2, default=float) if as_json
              else formatter(payload))
    except BrokenPipeError:
        # `... | head` closed the pipe: normal CLI usage, not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--diff" in argv:
        argv.remove("--diff")
        threshold = 0.10
        if "--threshold" in argv:
            i = argv.index("--threshold")
            try:
                threshold = float(argv[i + 1])
            except (IndexError, ValueError):
                raise SystemExit(
                    "--threshold needs a numeric value (e.g. "
                    "--threshold 0.1)") from None
            del argv[i:i + 2]
        if len(argv) != 2:
            raise SystemExit(
                "usage: python -m sparse_coding_tpu.obs.report --diff "
                "<run_a> <run_b> [--threshold 0.1] [--json]")
        diff = diff_reports(build_report(argv[0]), build_report(argv[1]),
                            threshold=threshold)
        print(json.dumps(diff, indent=2, default=float) if as_json
              else format_diff(diff))
        return
    if len(argv) != 1:
        raise SystemExit(
            "usage: python -m sparse_coding_tpu.obs.report "
            "<run_dir|fleet_dir> [--json] | --diff <run_a> <run_b>")
    if is_fleet_dir(argv[0]):
        _print_report(build_fleet_report(argv[0]), format_fleet_report,
                      as_json)
        return
    _print_report(build_report(argv[0]), format_report, as_json)


if __name__ == "__main__":
    main()
