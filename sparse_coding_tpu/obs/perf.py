"""Device-time performance evidence: sampled MFU, roofline gap (§12).

The obs layer timed only host walls, bench.py computed MFU once per
round, and PR 11's roofline model predicted per-path HBM-bytes/MXU-flops
that nothing ever checked against reality. This module closes the loop
for EVERY supervised run and serve flush:

- :class:`DeviceStepProbe` — a sampling probe: on a configurable cadence
  (``every``-th window; 0 disables) the host brackets one dispatched
  train window / serve flush with ``block_until_ready`` timing, so
  steady-state dispatch pipelining is unperturbed between samples. Each
  sample lands as

  * ``<prefix>.device_step_s{path=...}`` histograms — measured device
    wall per step, per resolved kernel path;
  * ``<prefix>.mfu`` / ``<prefix>.mfu{backend=,path=}`` gauges —
    model-flops utilization. The numerator is the SHARED FLOP model
    (``ops/roofline.model_flops_per_activation`` — the same function
    bench.py divides by, so bench MFU and runtime MFU are one number at
    one shape); the denominator is the attached chip's bf16 peak, or the
    roofline's v5e reference peak off-chip (the figure is then a
    cross-chip reference number, not a utilization — the ``backend``
    label marks it, and report/diff never compare across backends);
  * a counted ``perf.roofline_gap{path=,tile=}`` histogram + event —
    measured/predicted device seconds against the resolved
    ``KernelPlan.est_s``, making the calibration constants
    (``KERNEL_MXU_EFF`` etc.) checkable instead of folklore.

- :class:`StepCost` — the plain-data description of what one measured
  region was worth (model flops, resolved path label, roofline
  prediction). Hosts build it from their resolved plans
  (``Ensemble.step_cost``, ``roofline.serve_flush_plan``) so the probe
  itself stays shape-agnostic.

Import discipline: jax is imported at call time only (the obs package
contract); constructing a probe is device-free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

from sparse_coding_tpu.obs.registry import Registry, get_registry
from sparse_coding_tpu.obs.spans import emit_event, monotime

# bf16 MXU peak flops/s by TPU generation (public spec sheets) — the
# single home of the MFU denominator table (bench.py reads it from here)
TPU_PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
}

DEFAULT_PROBE_EVERY = 32


def device_peak_flops(default: Optional[float] = None) -> Optional[float]:
    """bf16 MXU peak of the attached device's generation, ``default``
    when the device kind matches no known TPU (CPU hosts). Call-time jax
    import; longest-tag-first so "v5 lite" wins over "v5"."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for tag, peak in sorted(TPU_PEAK_FLOPS.items(),
                            key=lambda kv: -len(kv[0])):
        if tag in kind:
            return peak
    return default


def _default_backend() -> str:
    import jax

    return jax.default_backend()


@dataclasses.dataclass(frozen=True)
class StepCost:
    """What one measured dispatch was worth: ``flops`` is the MFU
    numerator (model-REQUIRED flops, per the shared FLOP model — never
    the executed count, so kernel recompute can't inflate utilization);
    ``predicted_s`` is the roofline model's device seconds for the same
    region (0 = no prediction, gap not emitted); ``path``/``tile`` label
    the resolved kernel program."""

    flops: float = 0.0
    path: str = "autodiff"
    predicted_s: float = 0.0
    hbm_bytes: float = 0.0
    tile: str = ""
    activations: int = 0


def combine_costs(costs: Sequence[StepCost]) -> StepCost:
    """Aggregate the per-ensemble costs of one training window (flops and
    predictions add; a window whose buckets resolved different programs
    is labeled ``mixed``)."""
    costs = [c for c in costs if c is not None]
    if not costs:
        return StepCost()
    paths = {c.path for c in costs}
    tiles = {c.tile for c in costs}
    return StepCost(
        flops=sum(c.flops for c in costs),
        path=paths.pop() if len(paths) == 1 else "mixed",
        predicted_s=sum(c.predicted_s for c in costs),
        hbm_bytes=sum(c.hbm_bytes for c in costs),
        tile=tiles.pop() if len(tiles) == 1 else "mixed",
        activations=sum(c.activations for c in costs))


class DeviceStepProbe:
    """Sampling device-time probe for one stream of dispatches.

    Call :meth:`should_sample` once per dispatched window; on the
    cadence it returns True and the host either wraps the dispatch in
    :meth:`measure` (sync → time → sync) or times it itself and calls
    :meth:`record`. ``every=0`` disables sampling entirely (the probe
    then costs one integer increment per window)."""

    def __init__(self, prefix: str, every: int = DEFAULT_PROBE_EVERY,
                 registry: Optional[Registry] = None,
                 peak_flops: Optional[float] = None,
                 backend: Optional[str] = None, warmup: int = 2):
        self.prefix = prefix
        self.every = max(0, int(every))
        # first `warmup` windows are never sampled: they carry XLA
        # compile/dispatch warmth, and one compile through the tunnel
        # would dominate every histogram this probe feeds (the same
        # policy as StepTimer's warmup)
        self.warmup = max(0, int(warmup))
        self._registry = registry
        self._peak = peak_flops
        self._peak_checked = peak_flops is not None
        self._backend = backend
        self._count = 0
        self.samples = 0

    @property
    def registry(self) -> Registry:
        return self._registry if self._registry is not None \
            else get_registry()

    def _resolve_peak(self) -> Optional[float]:
        if not self._peak_checked:
            # off-chip: the v5e reference peak keeps the arithmetic
            # populated; the backend label marks the row as
            # not-a-utilization (docs/RUNBOOK_TUNNEL.md)
            from sparse_coding_tpu.ops.roofline import MXU_PEAK_FLOPS

            self._peak = device_peak_flops(default=MXU_PEAK_FLOPS)
            self._peak_checked = True
        return self._peak

    def _resolve_backend(self) -> str:
        if self._backend is None:
            self._backend = _default_backend()
        return self._backend

    def should_sample(self) -> bool:
        """One call per dispatched window; True every ``every``-th call
        past the warmup (the first post-warmup window samples
        immediately, so short runs still yield evidence)."""
        if self.every == 0:
            return False
        self._count += 1
        if self._count <= self.warmup:
            return False
        return (self._count - self.warmup - 1) % self.every == 0

    def measure(self, dispatch: Callable[[], object],
                cost: Optional[StepCost] = None, steps: int = 1,
                block_before=None):
        """The bracketed sample: drain in-flight device work
        (``block_before`` — typically the state the step mutates), time
        ``dispatch()`` to ``block_until_ready`` completion, record, and
        return the dispatch's value."""
        import jax

        if block_before is not None:
            jax.block_until_ready(block_before)
        t0 = monotime()
        out = dispatch()
        jax.block_until_ready(out)
        self.record(monotime() - t0, cost=cost, steps=steps)
        return out

    def record(self, device_s: float, cost: Optional[StepCost] = None,
               steps: int = 1) -> None:
        """Fold one measured device wall into the evidence: per-path
        ``device_step_s`` histogram, ``mfu`` gauges, and (when the cost
        carries a roofline prediction) the counted
        ``perf.roofline_gap{path,tile}`` ratio."""
        reg = self.registry
        self.samples += 1
        steps = max(1, int(steps))
        # cost (flops, predicted_s) describes ONE step; the measured
        # window ran `steps` of them — every figure below is per-step
        per_step_s = device_s / steps
        path = (cost.path if cost is not None else "") or "autodiff"
        backend = self._resolve_backend()
        reg.histogram(f"{self.prefix}.device_step_s",
                      path=path).observe(per_step_s)
        reg.counter("perf.samples", stream=self.prefix).inc()
        mfu = None
        peak = self._resolve_peak()
        if cost is not None and cost.flops > 0 and device_s > 0 and peak:
            mfu = cost.flops / per_step_s / peak
            reg.gauge(f"{self.prefix}.mfu").set(mfu)
            reg.gauge(f"{self.prefix}.mfu", backend=backend,
                      path=path).set(mfu)
        ratio = None
        if (cost is not None and cost.predicted_s > 0 and device_s > 0):
            ratio = per_step_s / cost.predicted_s
            reg.histogram("perf.roofline_gap", path=path,
                          tile=cost.tile or "-").observe(ratio)
        emit_event("perf.sample", stream=self.prefix, path=path,
                   backend=backend, steps=steps,
                   device_s=round(device_s, 6),
                   **({"mfu": round(mfu, 4)} if mfu is not None else {}),
                   **({"roofline_gap": round(ratio, 3),
                       "predicted_s": round(cost.predicted_s, 6),
                       "tile": cost.tile or "-"}
                      if ratio is not None else {}))
