"""Durable perf regression ledger: one JSONL row per measurement round.

The round-over-round perf story used to live in scattered artifacts
(BENCH_r0N.json snapshots, BENCH_VARIANTS.json overwritten each round,
gauges that die with the run dir). The ledger is the append-only spine:
bench.py (every emit path, cpu-fallback included), bench_suite.py (every
scenario row), and the pipeline supervisor (one summary row per
completed run) append rows here, and ``obs.report --diff`` reads them
back alongside the per-run reports.

Row schema (``kind`` discriminates):

    {"kind": "bench" | "suite" | "run", "ts": <unix>, "run": <run id>,
     "backend": "tpu" | "cpu" | "cpu-fallback", "variant": {...} | str,
     "mfu": float | None, "value": float, "unit": str,
     "paths": {<kernel path>: count, ...},       # the run's path mix
     "step_wall_p50_s": float | None, ...}       # free-form extras ride

Write discipline: rows append through one ``O_APPEND`` write of a full
line + fsync (multi-process safe — bench children and the supervisor
share the file), behind the named fault site ``obs.ledger.append``: a
failing append drops THAT row, counts ``obs.ledger.dropped``, and
returns False — the ledger must never fail a bench or a run over
bookkeeping. Readers tolerate torn tails by the same contract as the
event sink (``scan_events``).

Path resolution: ``SPARSE_CODING_PERF_LEDGER`` wins; otherwise
``<default_dir>/perf_ledger.jsonl`` when a caller anchors one (the
supervisor anchors its run dir), falling back to the repo root (the
durable cross-round artifact bench.py appends to).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.obs.registry import get_registry
from sparse_coding_tpu.obs.sink import scan_events
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site

ENV_LEDGER = "SPARSE_CODING_PERF_LEDGER"
LEDGER_NAME = "perf_ledger.jsonl"
SITE = "obs.ledger.append"

register_fault_site(SITE, "perf-ledger row append (obs/ledger.py)")

_REPO_ROOT = Path(__file__).resolve().parents[2]


def ledger_path(default_dir: Optional[str | Path] = None) -> Path:
    """The ledger file this process should append to: the env override
    (the supervisor propagates one per run), else ``default_dir``'s, else
    the repo-root cross-round ledger."""
    env = os.environ.get(ENV_LEDGER, "").strip()
    if env:
        return Path(env)
    if default_dir is not None:
        return Path(default_dir) / LEDGER_NAME
    return _REPO_ROOT / LEDGER_NAME


def append_row(row: dict, path: Optional[str | Path] = None) -> bool:
    """Append one row (``ts`` stamped if absent) as a single atomic
    O_APPEND line+fsync. Returns False — counting ``obs.ledger.dropped``
    — on any failure; never raises into the measurement it records."""
    target = Path(path) if path is not None else ledger_path()
    record = dict(row)
    record.setdefault("ts", time.time())
    try:
        data = (json.dumps(record, default=repr) + "\n").encode()
        target.parent.mkdir(parents=True, exist_ok=True)
        fault_point(SITE)
        fd = os.open(str(target), os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                     0o644)
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
    except Exception:  # noqa: BLE001 — bookkeeping is never fatal
        get_registry().counter("obs.ledger.dropped").inc()
        return False
    return True


def read_rows(path: Optional[str | Path] = None) -> list[dict]:
    """All readable rows (torn tail / corrupt lines skipped by the event
    sink's reader contract)."""
    target = Path(path) if path is not None else ledger_path()
    return scan_events(target)[0]


def run_summary_row(report: dict, run_id: str = "",
                    kind: str = "run") -> dict:
    """One supervisor summary row distilled from a ``build_report`` dict:
    the run's MFU gauges, kernel-path mix, and step walls — the shape
    ``obs.report --diff`` compares between runs."""
    gauges = report.get("gauges", {})
    mfu = {name: g.get("value") for name, g in gauges.items()
           if name == "train.mfu" or name.startswith("train.mfu{")
           or name == "serve.mfu" or name.startswith("serve.mfu{")}
    paths = {p: ent.get("count", 0)
             for p, ent in report.get("kernel_paths", {}).items()}
    chunk = report.get("spans", {}).get("sweep.chunk", {})
    return {"kind": kind, "run": run_id or ",".join(report.get("run_ids", [])),
            "mfu": mfu, "paths": paths,
            "step_wall_p50_s": chunk.get("p50_s"),
            "events": report.get("events", 0)}
