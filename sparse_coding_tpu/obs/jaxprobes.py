"""JAX runtime probes: retraces, compiles, compile time, device memory.

XLA's costs are invisible to host-side timers — a retrace (a jitted
function seeing a new shape/dtype) silently inserts seconds of trace +
compile into what looks like a steady-state loop, and through the axon
tunnel a single unplanned compile dwarfs whole measurement windows. These
probes surface that behavior as ordinary registry instruments, with the
serving engine's steady-state invariant (0 retraces after warmup —
docs/ARCHITECTURE.md §8) now assertable for EVERY hot path:

- ``jax.retraces``       counter — one per jaxpr trace
  (``/jax/core/compile/jaxpr_trace_duration`` events);
- ``jax.compiles``       counter — one per backend (XLA) compile;
- ``jax.compile_dur_s`` / ``jax.trace_dur_s`` histograms — where compile
  wall time went;
- ``jax.cache_hits`` / ``jax.cache_misses`` counters — the persistent
  compilation cache. Dormant until something enables that cache:
  ``xcache.enable()`` (docs/ARCHITECTURE.md §13) is what turns it on —
  tests/test_xcache.py holds the regression test that a second identical
  jit in a fresh process increments ``jax.cache_hits`` in the merged
  report;
- ``jax.mem.<stat>{device=i}`` gauges — ``device.memory_stats()``
  (``bytes_in_use``, peaks; absent on CPU, where the gauge family is
  simply not created).

Installation uses ``jax.monitoring``'s public listener hooks and is
idempotent; the listener is a few dict ops per *compile* (never per
step), so the zero-overhead guarantee of the compiled path is untouched
— ``tests/test_tpu_lowering.py`` asserts the lowered HLO is bitwise
identical with probes installed.
"""

from __future__ import annotations

from typing import Optional

from sparse_coding_tpu.obs.registry import Registry, get_registry

# duration-event suffixes -> (counter, histogram) names
_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": ("jax.retraces",
                                               "jax.trace_dur_s"),
    "/jax/core/compile/backend_compile_duration": ("jax.compiles",
                                                   "jax.compile_dur_s"),
}
_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits": "jax.cache_hits",
    "/jax/compilation_cache/cache_misses": "jax.cache_misses",
}

_installed = False
_listeners: list = []


def _on_event(event: str, **kwargs) -> None:
    name = _COUNT_EVENTS.get(event)
    if name is not None:
        get_registry().counter(name).inc()


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    names = _DURATION_EVENTS.get(event)
    if names is None:
        return
    counter, hist = names
    reg = get_registry()
    reg.counter(counter).inc()
    reg.histogram(hist).observe(duration_secs)


def install() -> bool:
    """Register the monitoring listeners once per process. Returns True
    when (already) installed, False when this jax build lacks the hooks
    (the probes then degrade to absent instruments, never an error)."""
    global _installed
    if _installed:
        return True
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _listeners.extend([_on_event, _on_duration])
    except (ImportError, AttributeError):
        return False
    _installed = True
    return True


def uninstall() -> None:
    """Best-effort removal (tests — the public API has no unregister, so
    this reaches for the private helpers and tolerates their absence)."""
    global _installed
    if not _installed:
        return
    try:
        from jax._src import monitoring as _m

        _m._unregister_event_listener_by_callback(_on_event)
        _m._unregister_event_duration_listener_by_callback(_on_duration)
    except Exception:
        pass
    _listeners.clear()
    _installed = False


def update_memory_gauges(registry: Optional[Registry] = None) -> int:
    """Sample ``memory_stats()`` of every local device into gauges;
    returns how many devices reported (0 on CPU, whose runtime returns
    None). Call at span boundaries — it is a device-runtime query, not
    free, so it does not belong inside per-batch loops."""
    import jax

    reg = registry if registry is not None else get_registry()
    n = 0
    for i, dev in enumerate(jax.local_devices()):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats:
            continue
        n += 1
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                reg.gauge(f"jax.mem.{key}", device=i).set(stats[key])
    return n
