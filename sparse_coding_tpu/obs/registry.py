"""Process-wide registry of typed instruments: counters, gauges, histograms.

Everything here is plain host-side Python (one lock, dicts) — instruments
must be touchable from any hot loop without adding device dispatches, and
`snapshot()` must be cheap enough to emit at chunk/window granularity.
Instrument identity is ``(name, sorted labels)``: the same call site asked
twice returns the same object, so hosts write
``registry.counter("serve.rows", bucket=8).inc(n)`` with no setup phase.

Histograms are **fixed-bucket and mergeable** by construction: two
snapshots with the same bucket bounds add bin-for-bin, which is what lets
``obs.report`` fuse event files from several processes of one run into a
single latency distribution without ever shipping raw samples.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Optional, Sequence

# default duration buckets: 100 µs .. ~100 s, geometric (x√10 per step) —
# wide enough for a tunnel dispatch (~54 ms) and a whole sweep chunk
DEFAULT_BUCKETS = tuple(10.0 ** (e / 2.0) for e in range(-8, 5))


def _label_key(labels: dict) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={labels[k]}" for k in sorted(labels)) + "}"


class Counter:
    """Monotonic count. ``inc`` only; resets only with the registry."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (plus a high-water mark, for queue depths)."""

    __slots__ = ("_lock", "_value", "_max")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0
        self._max = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            if v > self._max:
                self._max = float(v)

    def add(self, dv: float) -> float:
        with self._lock:
            self._value += float(dv)
            if self._value > self._max:
                self._max = self._value
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def max(self) -> float:
        with self._lock:
            return self._max


class Histogram:
    """Fixed-bound bucket histogram with sum/count/min/max.

    ``bounds`` are the upper edges of the first ``len(bounds)`` bins; one
    overflow bin catches everything larger. Quantiles are estimated by
    linear interpolation inside the covering bin — exact enough for
    p50/p95/p99 reporting, and (unlike a sample reservoir) mergeable
    across processes.
    """

    __slots__ = ("_lock", "bounds", "counts", "sum", "count", "min", "max")

    def __init__(self, lock: threading.Lock,
                 bounds: Optional[Sequence[float]] = None):
        self._lock = lock
        self.bounds = tuple(float(b) for b in (bounds or DEFAULT_BUCKETS))
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError("histogram bounds must be ascending")
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for i, b in enumerate(self.bounds):  # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.bounds)
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def merge_snapshot(self, snap: dict) -> None:
        """Fold another histogram's ``snapshot()`` dict into this one
        (bin-for-bin; bounds must match — the fixed-bucket contract)."""
        with self._lock:
            if tuple(snap["bounds"]) != self.bounds:
                raise ValueError(
                    f"cannot merge histograms with different bounds: "
                    f"{snap['bounds']} vs {list(self.bounds)}")
            for i, c in enumerate(snap["counts"]):
                self.counts[i] += int(c)
            self.sum += float(snap["sum"])
            self.count += int(snap["count"])
            if snap["count"]:
                self.min = min(self.min, float(snap["min"]))
                self.max = max(self.max, float(snap["max"]))

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if self.count == 0:
                return None
            target = q * self.count
            seen = 0
            for i, c in enumerate(self.counts):
                if seen + c >= target and c > 0:
                    lo = 0.0 if i == 0 else self.bounds[i - 1]
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else max(self.max, lo))
                    lo = max(lo, self.min)
                    hi = min(hi, self.max) if self.max >= lo else hi
                    frac = (target - seen) / c
                    return lo + (hi - lo) * min(1.0, max(0.0, frac))
                seen += c
            return self.max

    def snapshot(self) -> dict:
        with self._lock:
            return {"bounds": list(self.bounds), "counts": list(self.counts),
                    "sum": self.sum, "count": self.count,
                    "min": self.min if self.count else None,
                    "max": self.max if self.count else None}


class Registry:
    """One process's instrument table. ``snapshot()`` is the only bulk
    read surface and returns plain JSON-serializable data."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        key = name + _label_key(labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(threading.Lock())
            return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = name + _label_key(labels)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(threading.Lock())
            return g

    def histogram(self, name: str, bounds: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        key = name + _label_key(labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(
                    threading.Lock(), bounds=tuple(bounds) if bounds else None)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: {"value": g.value, "max": g.max}
                       for k, g in gauges.items()},
            "histograms": {k: h.snapshot() for k, h in histograms.items()},
        }

    def clear(self) -> None:
        """Drop every instrument (tests; a fresh process never needs it)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# -- process-wide default ----------------------------------------------------

_default = Registry()


def get_registry() -> Registry:
    return _default


def set_registry(registry: Registry) -> Registry:
    """Swap the process default (tests); returns the previous one."""
    global _default
    prev, _default = _default, registry
    return prev
