"""Unified observability: instruments, spans, crash-safe events, probes.

One subsystem replaces the three disjoint telemetry fragments (StepTimer
walls, MetricsLogger JSONL, serving counters) with correlated,
crash-surviving evidence — because with the TPU tunnel wedging for whole
sessions (docs/RUNBOOK_TUNNEL.md), every on-chip minute must yield a
complete profile on the first try:

- :mod:`registry`  — typed instruments (counters, gauges, mergeable
  fixed-bucket histograms) behind a process-wide default registry;
- :mod:`spans`     — ``span("sweep.chunk", **attrs)`` context managers
  emitting start/end/error events with monotonic durations and the
  run/step/span correlation IDs the pipeline supervisor propagates via
  env (``SPARSE_CODING_RUN_ID`` / ``SPARSE_CODING_OBS_DIR`` /
  ``SPARSE_CODING_OBS_STEP``);
- :mod:`sink`      — append-only line-atomic JSONL event files, one per
  process, SIGKILL-truncation-tolerant reader, named fault/crash site
  ``obs.sink.write``;
- :mod:`jaxprobes` — XLA retrace/compile counters, compile-time
  histograms, device memory gauges via ``jax.monitoring`` hooks
  (host-side only: the lowered HLO is bitwise identical with probes
  installed — tests/test_tpu_lowering.py);
- :mod:`report`    — ``python -m sparse_coding_tpu.obs.report <run_dir>``
  merges a run's event files into per-step p50/p95/p99 durations,
  throughput, retrace and error counts, and the device-time ``perf``
  section (``--diff`` compares two runs);
- :mod:`perf`      — sampling :class:`~perf.DeviceStepProbe`: measured
  device walls, MFU gauges against the shared roofline FLOP model, and
  the counted predicted-vs-achieved ``perf.roofline_gap`` ratio;
- :mod:`trace`     — crash-safe managed profiler capture (bounded
  window, tmp-then-atomic finalize, counted skip on error) — the only
  module allowed to touch ``jax.profiler`` (tests/test_profiler_lint.py);
- :mod:`ledger`    — the durable ``perf_ledger.jsonl`` every bench
  round, suite scenario, and supervised run appends a summary row to.

Import discipline: this package (minus :mod:`jaxprobes`) never imports
jax, so the serving metrics path and the report CLI stay device-free;
``install_jax_probes`` and the :mod:`perf`/:mod:`trace` entry points
defer the jax import to call time.

Design: docs/ARCHITECTURE.md §12. Raw-clock reads in hot paths
(data/train/serve/pipeline) go through :func:`monotime` — enforced
mechanically by tests/test_obs_lint.py (escape hatch:
``# lint: allow-raw-timer <why>``).
"""

from __future__ import annotations

from typing import Optional

from sparse_coding_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from sparse_coding_tpu.obs.sink import (
    ENV_OBS_DIR,
    EventSink,
    active_sink,
    configure as configure_sink,
    configure_from_env as configure_sink_from_env,
    close as close_sink,
    read_events,
    scan_events,
)
from sparse_coding_tpu.obs.spans import (
    ENV_RUN_ID,
    ENV_STEP,
    emit_event,
    flush_metrics,
    mint_trace_id,
    monotime,
    record_span,
    run_id,
    span,
    step_name,
)
from sparse_coding_tpu.obs import ledger, perf, trace
from sparse_coding_tpu.obs.perf import DeviceStepProbe, StepCost, combine_costs
from sparse_coding_tpu.obs.trace import TraceCapture


def counter(name: str, **labels) -> Counter:
    return get_registry().counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return get_registry().gauge(name, **labels)


def histogram(name: str, bounds=None, **labels) -> Histogram:
    return get_registry().histogram(name, bounds=bounds, **labels)


def install_jax_probes() -> bool:
    """Install the XLA retrace/compile/memory probes (idempotent; defers
    the jax import so obs stays importable device-free)."""
    from sparse_coding_tpu.obs import jaxprobes

    return jaxprobes.install()


def uninstall_jax_probes() -> None:
    from sparse_coding_tpu.obs import jaxprobes

    jaxprobes.uninstall()


def update_memory_gauges(registry: Optional[Registry] = None) -> int:
    from sparse_coding_tpu.obs import jaxprobes

    return jaxprobes.update_memory_gauges(registry)


__all__ = [
    "Counter",
    "DeviceStepProbe",
    "ENV_OBS_DIR",
    "ENV_RUN_ID",
    "ENV_STEP",
    "EventSink",
    "Gauge",
    "Histogram",
    "Registry",
    "StepCost",
    "TraceCapture",
    "active_sink",
    "close_sink",
    "combine_costs",
    "configure_sink",
    "configure_sink_from_env",
    "counter",
    "emit_event",
    "flush_metrics",
    "gauge",
    "get_registry",
    "histogram",
    "install_jax_probes",
    "ledger",
    "mint_trace_id",
    "monotime",
    "perf",
    "read_events",
    "record_span",
    "run_id",
    "scan_events",
    "set_registry",
    "span",
    "step_name",
    "trace",
    "uninstall_jax_probes",
    "update_memory_gauges",
]
