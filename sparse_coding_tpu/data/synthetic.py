"""Synthetic sparse-dictionary datasets.

Pure-JAX re-design of the reference's generators
(reference: sc_datasets/random_dataset.py): ground-truth unit-norm feature
dictionaries, sparse codes with geometric-decay inclusion probabilities,
optionally correlated via a Gaussian copula, plus covariance noise. Everything
is a jitted pure function of a PRNG key — batches are generated *on device*
(no host→device copies in the training loop, unlike the torch version which
samples on device but drives from Python).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.struct as struct
import jax
import jax.numpy as jnp
from jax.scipy.stats import norm as jnorm

Array = jax.Array


def generate_rand_feats(key: Array, feat_dim: int, num_feats: int,
                        dtype=jnp.float32) -> Array:
    """Unit-norm ground-truth feature dictionary [num_feats, feat_dim]
    (reference: random_dataset.py:248-261)."""
    feats = jax.random.normal(key, (num_feats, feat_dim), dtype)
    return feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)


def generate_corr_matrix(key: Array, num_feats: int, dtype=jnp.float32) -> Array:
    """Random symmetric PSD-projected correlation matrix
    (reference: random_dataset.py:264-279)."""
    m = jax.random.uniform(key, (num_feats, num_feats), dtype)
    m = (m + m.T) / 2.0
    min_eig = jnp.min(jnp.linalg.eigvalsh(m))
    return jnp.where(min_eig < 0,
                     m - 1.001 * min_eig * jnp.eye(num_feats, dtype=dtype), m)


@partial(jax.jit, static_argnames=("batch_size",))
def _rand_batch(key: Array, feats: Array, component_probs: Array,
                batch_size: int) -> tuple[Array, Array]:
    """Uncorrelated sparse batch (reference: random_dataset.py:160-188).
    Returns (codes, data)."""
    n = feats.shape[0]
    k_thresh, k_vals, k_strength = jax.random.split(key, 3)
    thresh = jax.random.uniform(k_thresh, (batch_size, n))
    values = jax.random.uniform(k_vals, (batch_size, n))
    codes = jnp.where(thresh <= component_probs, values, 0.0)
    strengths = jax.random.uniform(k_strength, (batch_size, n))
    data = (codes * strengths) @ feats
    return codes, data


@partial(jax.jit, static_argnames=("batch_size",))
def _correlated_batch(key: Array, feats: Array, corr_chol: Array, decay: Array,
                      frac_nonzero: float, batch_size: int) -> tuple[Array, Array]:
    """Correlated sparse batch via Gaussian copula
    (reference: random_dataset.py:191-245). Returns (codes, data)."""
    n = feats.shape[0]
    k_mvn, k_thresh, k_vals, k_fix, k_strength = jax.random.split(key, 5)
    corr_sample = corr_chol @ jax.random.normal(k_mvn, (n,))
    cdf = jnorm.cdf(corr_sample)
    component_probs = cdf * decay
    component_probs = component_probs * (frac_nonzero / jnp.mean(component_probs))

    thresh = jax.random.uniform(k_thresh, (batch_size, n))
    values = jax.random.uniform(k_vals, (batch_size, n))
    codes = jnp.where(thresh <= component_probs, values, 0.0)

    # ensure no all-zero rows: flip one random coefficient on for empty samples
    empty = jnp.sum(codes > 0, axis=-1) == 0
    rand_idx = jax.random.randint(k_fix, (batch_size,), 0, n)
    fix = jax.nn.one_hot(rand_idx, n) * empty[:, None]
    codes = jnp.where(fix > 0, 1.0, codes)

    strengths = jax.random.uniform(k_strength, (batch_size, n))
    data = (codes * strengths) @ feats
    return codes, data


@partial(jax.jit, static_argnames=("batch_size",))
def _noise_batch(key: Array, noise_chol: Array, scale: float,
                 batch_size: int) -> Array:
    """Multivariate-normal noise (reference: random_dataset.py:145-157)."""
    d = noise_chol.shape[0]
    return scale * (jax.random.normal(key, (batch_size, d)) @ noise_chol.T)


class RandomDatasetGenerator(struct.PyTreeNode):
    """Sparse-code dataset with geometric-decay feature probabilities
    (reference: random_dataset.py:17-73). Usage:

        gen = RandomDatasetGenerator.create(key, d, n, num_nonzero, decay, corr)
        key, sub = jax.random.split(key)
        batch = gen.batch(sub, batch_size)
    """

    feats: Array  # [n, d] ground-truth dictionary
    decay: Array  # [n]
    corr_chol: Optional[Array]  # Cholesky of the copula correlation (if correlated)
    frac_nonzero: float = struct.field(pytree_node=False, default=0.0)
    correlated: bool = struct.field(pytree_node=False, default=False)

    @classmethod
    def create(cls, key: Array, activation_dim: int, n_ground_truth_components: int,
               feature_num_nonzero: int, feature_prob_decay: float,
               correlated: bool = False) -> "RandomDatasetGenerator":
        k_feats, k_corr = jax.random.split(key)
        n = n_ground_truth_components
        feats = generate_rand_feats(k_feats, activation_dim, n)
        decay = feature_prob_decay ** jnp.arange(n, dtype=jnp.float32)
        corr_chol = None
        if correlated:
            corr = generate_corr_matrix(k_corr, n)
            corr_chol = jnp.linalg.cholesky(corr)
        return cls(feats=feats, decay=decay, corr_chol=corr_chol,
                   frac_nonzero=feature_num_nonzero / n, correlated=correlated)

    def batch_with_codes(self, key: Array, batch_size: int) -> tuple[Array, Array]:
        if self.correlated:
            return _correlated_batch(key, self.feats, self.corr_chol, self.decay,
                                     self.frac_nonzero, batch_size)
        component_probs = self.decay * self.frac_nonzero
        return _rand_batch(key, self.feats, component_probs, batch_size)

    def batch(self, key: Array, batch_size: int) -> Array:
        return self.batch_with_codes(key, batch_size)[1]


class SparseMixDataset(struct.PyTreeNode):
    """Correlated sparse codes + covariance noise
    (reference: random_dataset.py:77-142)."""

    base: RandomDatasetGenerator
    noise_chol: Array  # [d, d]
    noise_magnitude_scale: float = struct.field(pytree_node=False, default=0.0)

    @classmethod
    def create(cls, key: Array, activation_dim: int, n_sparse_components: int,
               feature_num_nonzero: int, feature_prob_decay: float,
               noise_magnitude_scale: float,
               noise_covariance: Optional[Array] = None) -> "SparseMixDataset":
        k_base, _ = jax.random.split(key)
        base = RandomDatasetGenerator.create(
            k_base, activation_dim, n_sparse_components, feature_num_nonzero,
            feature_prob_decay, correlated=True)
        if noise_covariance is None:
            noise_chol = jnp.eye(activation_dim)
        else:
            noise_chol = jnp.linalg.cholesky(noise_covariance)
        return cls(base=base, noise_chol=noise_chol,
                   noise_magnitude_scale=noise_magnitude_scale)

    @property
    def feats(self) -> Array:
        return self.base.feats

    def batch(self, key: Array, batch_size: int) -> Array:
        k_sparse, k_noise = jax.random.split(key)
        sparse = self.base.batch(k_sparse, batch_size)
        noise = _noise_batch(k_noise, self.noise_chol,
                             self.noise_magnitude_scale, batch_size)
        return sparse + noise
