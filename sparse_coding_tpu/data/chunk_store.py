"""Chunked on-disk activation store with device prefetch.

Replaces the reference's `torch.save(i.pt)` chunk files
(reference: activation_dataset.py:499-503 `save_activation_chunk`, 2 GB fp16
chunks per :25-27) and its shared-memory DataLoader trick
(cluster_runs.py:26-32) with:

- `.npy` chunk files named `0.npy, 1.npy, …` (same cursor-style contract as
  the reference's `0.pt …`), float16 or bfloat16 on disk;
- a `ChunkStore` reader that mmaps chunks and yields shuffled fixed-size
  batches;
- `device_prefetch`, a double-buffering iterator that keeps the TPU fed by
  overlapping host→device transfer of batch i+1 with compute on batch i —
  the TPU-native replacement for pinned shared memory.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_DTYPES = {"float16": np.float16, "float32": np.float32,
           "bfloat16": jnp.bfloat16}  # ml_dtypes-backed numpy dtype


class ChunkWriter:
    """Accumulates [n, d] activation slabs and flushes ~chunk_size_gb files
    (reference: make_activation_dataset_tl's accumulate-and-save loop,
    activation_dataset.py:371-389)."""

    def __init__(self, folder: str | Path, activation_dim: int,
                 chunk_size_gb: float = 2.0, dtype: str = "bfloat16",
                 start_index: int = 0, round_rows_to: int = 1,
                 center: bool = False):
        self.folder = Path(folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self.activation_dim = activation_dim
        self.dtype = np.dtype(_DTYPES[dtype])
        bytes_per_row = activation_dim * self.dtype.itemsize
        self.rows_per_chunk = int(chunk_size_gb * 2**30 / bytes_per_row)
        if round_rows_to > 1:
            # align chunk boundaries to producer batch boundaries so
            # skip_chunks-style resume maps exactly onto input offsets
            self.rows_per_chunk = max(round_rows_to,
                                      self.rows_per_chunk // round_rows_to * round_rows_to)
        self._buffer: list[np.ndarray] = []
        self._buffered_rows = 0
        self.chunk_index = start_index
        # center=True: the FIRST flushed chunk's mean is subtracted from every
        # chunk written (including that first one), so on-disk data is
        # actually centered — the reference's first-chunk centering
        # (activation_dataset.py:379-381). The mean lands in center.npy at
        # finalize for exports that need the translation. A skip_chunks-style
        # resume (start_index>0) MUST reuse the original run's mean, or the
        # two halves of the dataset would be centered by different
        # translations.
        self.center = center
        self._center_mean: Optional[np.ndarray] = None
        if center and start_index > 0:
            prior = self.folder / "center.npy"
            if not prior.exists():
                raise ValueError(
                    f"resuming a centered harvest at chunk {start_index} but "
                    f"{prior} is missing — the original centering mean is "
                    "unrecoverable; re-harvest from chunk 0")
            self._center_mean = np.load(prior)

    def add(self, acts) -> None:
        arr = np.asarray(acts).reshape(-1, self.activation_dim).astype(self.dtype)
        self._buffer.append(arr)
        self._buffered_rows += arr.shape[0]
        while self._buffered_rows >= self.rows_per_chunk:
            self._flush_chunk()

    def _write(self, arr: np.ndarray) -> None:
        if self.center:
            f32 = arr.astype(np.float32)
            if self._center_mean is None:
                self._center_mean = f32.mean(axis=0)
            arr = (f32 - self._center_mean).astype(self.dtype)
        # np.save can't round-trip ml_dtypes bfloat16 — store the raw bit
        # pattern as uint16; ChunkStore views it back via meta["dtype"]
        if self.dtype == jnp.bfloat16:
            arr = arr.view(np.uint16)
        np.save(self.folder / f"{self.chunk_index}.npy", arr)
        self.chunk_index += 1

    def _flush_chunk(self) -> None:
        flat = np.concatenate(self._buffer, axis=0)
        chunk, rest = flat[:self.rows_per_chunk], flat[self.rows_per_chunk:]
        self._write(chunk)
        self._buffer = [rest] if rest.size else []
        self._buffered_rows = rest.shape[0] if rest.size else 0

    def finalize(self, metadata: Optional[dict] = None) -> int:
        """Flush the tail (the reference's HF path loses it to a precedence
        bug, activation_dataset.py:474 — we keep it) and write metadata.
        Returns the number of chunks written."""
        if self._buffered_rows:
            flat = np.concatenate(self._buffer, axis=0)
            self._write(flat)
            self._buffer, self._buffered_rows = [], 0
        if self._center_mean is not None:
            np.save(self.folder / "center.npy", self._center_mean)
        centered = self.center and self._center_mean is not None
        meta = {"activation_dim": self.activation_dim,
                "dtype": str(np.dtype(self.dtype)),
                "n_chunks": self.chunk_index,
                "centered": centered,
                # format marker: distinguishes stores whose chunks are
                # ACTUALLY mean-subtracted on disk from any older artifact
                # that stamped centered=true without subtracting
                **({"center_format": "subtracted-v2"} if centered else {})}
        meta.update(metadata or {})
        (self.folder / "meta.json").write_text(json.dumps(meta, indent=2))
        return self.chunk_index


class ChunkStore:
    """Reader over a chunk folder (reference counterpart: the torch.load
    loops at big_sweep.py:357-364 and basic_l1_sweep.py:86-105).

    Reads native `.npy` stores, and — for reference-artifact interop — raw
    reference chunk folders of torch-saved `<i>.pt` tensors
    (activation_dataset.py:499-503) directly, without conversion. The .pt
    path has no native readahead (torch deserialization is not a raw file
    read); convert via utils.ref_interop.import_reference_chunks when
    streaming throughput matters."""

    def __init__(self, folder: str | Path):
        self.folder = Path(folder)
        self.chunk_paths = sorted(
            (p for p in self.folder.glob("*.npy") if p.stem.isdigit()),
            key=lambda p: int(p.stem))
        self.format = "npy"
        if not self.chunk_paths:
            self.chunk_paths = sorted(
                (p for p in self.folder.glob("*.pt") if p.stem.isdigit()),
                key=lambda p: int(p.stem))
            self.format = "pt"
        if not self.chunk_paths:
            raise FileNotFoundError(f"no .npy or .pt chunks in {self.folder}")
        meta_path = self.folder / "meta.json"
        self.meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
        if self.format == "pt":
            if "activation_dim" in self.meta:
                self.activation_dim = int(self.meta["activation_dim"])
            else:
                from sparse_coding_tpu.utils.ref_interop import read_pt_chunk

                # on-disk dtype (no float32 blow-up) just to read the width;
                # reference chunks can be ~2 GB fp16
                self.activation_dim = int(
                    read_pt_chunk(self.chunk_paths[0],
                                  dtype=np.float16).shape[-1])
        else:
            first = np.load(self.chunk_paths[0], mmap_mode="r")
            self.activation_dim = int(first.shape[-1])

    @property
    def n_chunks(self) -> int:
        return len(self.chunk_paths)

    def load_chunk(self, i: int, dtype=np.float32) -> np.ndarray:
        if self.format == "pt":
            from sparse_coding_tpu.utils.ref_interop import read_pt_chunk

            return read_pt_chunk(self.chunk_paths[i], dtype=dtype)
        from sparse_coding_tpu.data.native_io import (
            DEFAULT_THREADS,
            read_npy_native,
        )

        # foreground reads: threaded pread only beats np.load with real
        # cores to spread over — the native layer's 1-CPU value is the
        # BACKGROUND overlap in chunk_reader, not raw read speed
        raw = read_npy_native(self.chunk_paths[i]) if DEFAULT_THREADS > 1 else None
        if raw is None:  # no compiler / native lib / single-CPU host
            raw = np.load(self.chunk_paths[i])
        return self._finish_raw(raw, dtype, self.chunk_paths[i])

    def chunk_mean(self, i: int = 0) -> np.ndarray:
        """Mean of one chunk — the reference's first-chunk centering
        (activation_dataset.py:379-381, big_sweep.py:359-364)."""
        return self.load_chunk(i).mean(axis=0)

    @property
    def center(self) -> Optional[np.ndarray]:
        """The translation subtracted at harvest when the store was written
        with center=True (center.npy), else None. Chunks on disk are ALREADY
        centered — this is for exports needing the original-space offset
        (e.g. models/pca.py get_centering_transform translations). Refuses
        legacy stores that claim centered=true without the subtracted-v2
        format marker (their chunks were written WITHOUT subtraction)."""
        path = self.folder / "center.npy"
        if not path.exists():
            return None
        if (self.meta.get("centered")
                and self.meta.get("center_format") != "subtracted-v2"):
            raise ValueError(
                f"{self.folder} claims centered=true but lacks the "
                "subtracted-v2 marker: it predates on-disk centering and its "
                "chunks are raw; re-harvest it (or subtract center.npy "
                "manually and stamp center_format)")
        return np.load(path)

    def batches(self, chunk: np.ndarray, batch_size: int,
                rng: np.random.Generator, drop_last: bool = True) -> Iterator[np.ndarray]:
        """Shuffled fixed-size batches from an in-RAM chunk (reference:
        BatchSampler(RandomSampler), cluster_runs.py:26-32)."""
        return shuffled_batches(chunk, batch_size, rng, drop_last)

    def _finish_raw(self, raw: np.ndarray, dtype, path) -> np.ndarray:
        """Single dtype gate for BOTH the numpy and native-prefetch paths:
        uint16 data is bfloat16 bit patterns only if meta.json says so —
        otherwise fail loudly (likely an interrupted harvest)."""
        if raw.dtype == np.uint16:
            if self.meta.get("dtype") != "bfloat16":
                raise ValueError(
                    f"{path} holds uint16 (bfloat16 bit patterns) but "
                    "meta.json is missing or lacks dtype=bfloat16 — likely an "
                    "interrupted harvest; re-run it or write meta.json by hand")
            raw = raw.view(jnp.bfloat16)
        from sparse_coding_tpu.data.native_io import fast_astype

        return fast_astype(raw, dtype)

    def chunk_reader(self, indices, dtype=np.float32) -> Iterator[np.ndarray]:
        """Yield in-RAM chunks for the given index sequence with disk
        readahead: the NEXT chunk's file streams from disk on native
        background threads while the caller trains on the current one
        (native/chunkio.cpp; silently sequential without it). Holds at most
        two chunks in host RAM (current + in-flight)."""
        if self.format == "pt":
            # torch deserialization isn't a raw pread — no native readahead
            for ci in indices:
                yield self.load_chunk(int(ci), dtype)
            return
        from sparse_coding_tpu.data.native_io import NativePrefetcher

        indices = [int(i) for i in indices]
        prefetcher = NativePrefetcher()
        try:
            prefetching = (prefetcher.start(self.chunk_paths[indices[0]])
                           if indices else False)
            for pos, ci in enumerate(indices):
                raw = prefetcher.wait() if prefetching else None
                chunk = (self._finish_raw(raw, dtype, self.chunk_paths[ci])
                         if raw is not None else self.load_chunk(ci, dtype))
                # _finish_raw copied: drop the on-disk dtype buffer before
                # the yield (keeps the documented two-chunk RAM bound)
                raw = None
                if pos + 1 < len(indices):
                    prefetching = prefetcher.start(
                        self.chunk_paths[indices[pos + 1]])
                yield chunk
        finally:
            # early generator exit must not leak the in-flight native read
            prefetcher.cancel()

    def epoch(self, batch_size: int, rng: np.random.Generator,
              n_repetitions: int = 1, dtype=np.float32) -> Iterator[np.ndarray]:
        """Stream batches over all chunks, chunk order shuffled per repetition
        (reference: big_sweep.py:349-357), with chunk_reader's disk
        readahead."""
        order = np.concatenate([rng.permutation(self.n_chunks)
                                for _ in range(n_repetitions)])
        for chunk in self.chunk_reader(order, dtype):
            yield from self.batches(chunk, batch_size, rng)


def shuffled_batches(chunk: np.ndarray, batch_size: int,
                     rng: np.random.Generator,
                     drop_last: bool = True) -> Iterator[np.ndarray]:
    """Shuffled fixed-size batches over an in-RAM array (shared by ChunkStore
    and train/dispatch.py)."""
    n = chunk.shape[0]
    perm = rng.permutation(n)
    end = n - (n % batch_size) if drop_last else n
    for lo in range(0, end, batch_size):
        yield chunk[perm[lo:lo + batch_size]]


def window_stacks(batches: Iterable[np.ndarray], k: int) -> Iterator[np.ndarray]:
    """Group [B, d] host batches into [K, B, d] stacks for scanned training
    windows (Ensemble.run_steps / cfg.scan_steps). The final short window
    flushes with however many batches remain, so every batch trains (it
    compiles its own scan length at most once per run)."""
    buf: list[np.ndarray] = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield np.stack(buf)
            buf = []
    if buf:
        yield np.stack(buf)


def device_prefetch(batches: Iterable[np.ndarray], sharding=None,
                    buffer_size: int = 2) -> Iterator[Array]:
    """Double-buffered host→device pipeline: batch i+1 transfers while batch i
    computes. jax.device_put is async, so a small lookahead queue suffices."""
    from collections import deque

    queue: deque[Array] = deque()
    it = iter(batches)

    def put(x):
        x = jnp.asarray(x) if sharding is None else jax.device_put(x, sharding)
        return x

    try:
        for _ in range(buffer_size):
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
