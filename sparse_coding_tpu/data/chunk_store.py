"""Chunked on-disk activation store with device prefetch.

Replaces the reference's `torch.save(i.pt)` chunk files
(reference: activation_dataset.py:499-503 `save_activation_chunk`, 2 GB fp16
chunks per :25-27) and its shared-memory DataLoader trick
(cluster_runs.py:26-32) with:

- `.npy` chunk files named `0.npy, 1.npy, …` (same cursor-style contract as
  the reference's `0.pt …`), float16 or bfloat16 on disk;
- a `ChunkStore` reader that mmaps chunks and yields shuffled fixed-size
  batches;
- `device_prefetch`, a double-buffering iterator that keeps the TPU fed by
  overlapping host→device transfer of batch i+1 with compute on batch i —
  the TPU-native replacement for pinned shared memory.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Iterable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.data.ledger import load_quarantine, record_quarantine
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.atomic import atomic_save_npy, atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.errors import ChunkCorruptionError
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import array_sha256
from sparse_coding_tpu.resilience.retry import retry_io

Array = jax.Array

logger = logging.getLogger(__name__)

register_fault_site("chunk.read",
                    "ChunkStore._finish_raw — every chunk load, both the "
                    "numpy and native-prefetch paths")
register_fault_site("chunk.write",
                    "ChunkWriter._write — every chunk flush (inside the "
                    "bounded-retry scope)")
register_crash_site("chunk.flushed",
                    "ChunkWriter._write — a chunk file + digest just became "
                    "durable; the next instruction never runs")
register_crash_site("store.finalize",
                    "ChunkWriter.finalize — all chunks durable, meta.json "
                    "(the completeness marker) not yet written")

_DTYPES = {"float16": np.float16, "float32": np.float32,
           "bfloat16": jnp.bfloat16}  # ml_dtypes-backed numpy dtype


class ChunkWriter:
    """Accumulates [n, d] activation slabs and flushes ~chunk_size_gb files
    (reference: make_activation_dataset_tl's accumulate-and-save loop,
    activation_dataset.py:371-389)."""

    def __init__(self, folder: str | Path, activation_dim: int,
                 chunk_size_gb: float = 2.0, dtype: str = "bfloat16",
                 start_index: int = 0, round_rows_to: int = 1,
                 center: bool = False, io_retries: int = 3):
        self.folder = Path(folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self.io_retries = int(io_retries)
        # per-chunk content digests, recorded at write and stamped into
        # meta.json at finalize so ChunkStore can detect silent corruption.
        # A skip_chunks-style resume inherits the original run's digests
        # for the chunks it keeps.
        self._digests: dict[str, str] = {}
        if start_index > 0:
            prior_meta = self.folder / "meta.json"
            if prior_meta.exists():
                self._digests = dict(
                    json.loads(prior_meta.read_text()).get(
                        "chunk_digests", {}))
            else:
                # crash-resume: the previous harvest died before finalize
                # (no meta.json), so the kept chunks' digests were never
                # recorded — recompute them from the durable files so the
                # finished store's meta is byte-identical to an
                # uninterrupted harvest's (the chaos-matrix contract).
                for i in range(start_index):
                    p = self.folder / f"{i}.npy"
                    if p.exists():
                        self._digests[str(i)] = array_sha256(np.load(p))
        self.activation_dim = activation_dim
        self.dtype = np.dtype(_DTYPES[dtype])
        bytes_per_row = activation_dim * self.dtype.itemsize
        self.rows_per_chunk = int(chunk_size_gb * 2**30 / bytes_per_row)
        if round_rows_to > 1:
            # align chunk boundaries to producer batch boundaries so
            # skip_chunks-style resume maps exactly onto input offsets
            self.rows_per_chunk = max(round_rows_to,
                                      self.rows_per_chunk // round_rows_to * round_rows_to)
        self._buffer: list[np.ndarray] = []
        self._buffered_rows = 0
        self.chunk_index = start_index
        # center=True: the FIRST flushed chunk's mean is subtracted from every
        # chunk written (including that first one), so on-disk data is
        # actually centered — the reference's first-chunk centering
        # (activation_dataset.py:379-381). The mean lands in center.npy at
        # finalize for exports that need the translation. A skip_chunks-style
        # resume (start_index>0) MUST reuse the original run's mean, or the
        # two halves of the dataset would be centered by different
        # translations.
        self.center = center
        self._center_mean: Optional[np.ndarray] = None
        if center and start_index > 0:
            prior = self.folder / "center.npy"
            if not prior.exists():
                raise ValueError(
                    f"resuming a centered harvest at chunk {start_index} but "
                    f"{prior} is missing — the original centering mean is "
                    "unrecoverable; re-harvest from chunk 0")
            self._center_mean = np.load(prior)

    def add(self, acts) -> None:
        arr = np.asarray(acts).reshape(-1, self.activation_dim).astype(self.dtype)
        self._buffer.append(arr)
        self._buffered_rows += arr.shape[0]
        while self._buffered_rows >= self.rows_per_chunk:
            self._flush_chunk()

    def _write(self, arr: np.ndarray) -> None:
        t0 = obs.monotime()
        if self.center:
            f32 = arr.astype(np.float32)
            if self._center_mean is None:
                self._center_mean = f32.mean(axis=0)
            arr = (f32 - self._center_mean).astype(self.dtype)
        # np.save can't round-trip ml_dtypes bfloat16 — store the raw bit
        # pattern as uint16; ChunkStore views it back via meta["dtype"]
        if self.dtype == jnp.bfloat16:
            arr = arr.view(np.uint16)
        path = self.folder / f"{self.chunk_index}.npy"

        def _write_once():
            fault_point("chunk.write")
            atomic_save_npy(path, arr)

        # tmp+fsync+rename: a crash mid-write can never leave a truncated
        # chunk at the final name; transient I/O errors get a bounded retry
        retry_io(_write_once, attempts=self.io_retries)
        self._digests[str(self.chunk_index)] = array_sha256(arr)
        self.chunk_index += 1
        lease.beat()  # a durable chunk is the harvest's unit of progress
        # chunk granularity matches the lease beat: one span event + the
        # row counter per durable chunk, never per batch
        obs.counter("chunk.rows_written").inc(int(arr.shape[0]))
        obs.record_span("chunk.write", obs.monotime() - t0,
                        index=self.chunk_index - 1,
                        rows=int(arr.shape[0]))
        crash_barrier("chunk.flushed")

    def _flush_chunk(self) -> None:
        flat = np.concatenate(self._buffer, axis=0)
        chunk, rest = flat[:self.rows_per_chunk], flat[self.rows_per_chunk:]
        self._write(chunk)
        self._buffer = [rest] if rest.size else []
        self._buffered_rows = rest.shape[0] if rest.size else 0

    def finalize(self, metadata: Optional[dict] = None) -> int:
        """Flush the tail (the reference's HF path loses it to a precedence
        bug, activation_dataset.py:474 — we keep it) and write metadata.
        Returns the number of chunks written."""
        if self._buffered_rows:
            flat = np.concatenate(self._buffer, axis=0)
            self._write(flat)
            self._buffer, self._buffered_rows = [], 0
        if self._center_mean is not None:
            atomic_save_npy(self.folder / "center.npy", self._center_mean)
        centered = self.center and self._center_mean is not None
        meta = {"activation_dim": self.activation_dim,
                "dtype": str(np.dtype(self.dtype)),
                "n_chunks": self.chunk_index,
                "centered": centered,
                "chunk_digests": dict(self._digests),
                # format marker: distinguishes stores whose chunks are
                # ACTUALLY mean-subtracted on disk from any older artifact
                # that stamped centered=true without subtracting
                **({"center_format": "subtracted-v2"} if centered else {})}
        meta.update(metadata or {})
        # meta.json is written LAST and atomically: its presence certifies
        # a complete store (every chunk + center.npy already durable) — a
        # kill at this barrier leaves a resumable, visibly-incomplete store
        crash_barrier("store.finalize")
        atomic_write_text(self.folder / "meta.json", json.dumps(meta, indent=2))
        return self.chunk_index

    def abort(self) -> None:
        """Drop buffered rows and sweep up any orphaned tmp files so an
        aborted harvest leaves only whole chunks and NO meta.json (the
        absence of which marks the store incomplete)."""
        self._buffer, self._buffered_rows = [], 0
        for tmp in self.folder.glob(".*.tmp.*"):
            tmp.unlink(missing_ok=True)


class ChunkStore:
    """Reader over a chunk folder (reference counterpart: the torch.load
    loops at big_sweep.py:357-364 and basic_l1_sweep.py:86-105).

    Reads native `.npy` stores, and — for reference-artifact interop — raw
    reference chunk folders of torch-saved `<i>.pt` tensors
    (activation_dataset.py:499-503) directly, without conversion. The .pt
    path has no native readahead (torch deserialization is not a raw file
    read); convert via utils.ref_interop.import_reference_chunks when
    streaming throughput matters."""

    def __init__(self, folder: str | Path, quarantine_corrupt: bool = False,
                 verify_digests: bool = True, verify_finite: bool = True,
                 io_retries: int = 3,
                 retry_base_delay_s: float = 0.01):
        # quarantine_corrupt=True: streaming readers (chunk_reader/epoch)
        # skip a corrupt chunk with one logged warning instead of raising —
        # the opt-in mode for long unattended sweeps where losing one chunk
        # beats losing the run. load_chunk always raises (a direct caller
        # asked for THAT chunk).
        self.quarantine_corrupt = bool(quarantine_corrupt)
        self.verify_digests = bool(verify_digests)
        # decode-side finite guard (docs/ARCHITECTURE.md §16): a chunk
        # whose decoded rows contain NaN/Inf is typed corruption exactly
        # like a digest mismatch — a harvest that wrote garbage passes
        # every digest, and non-finite activations silently poison any
        # member that trains on them. Verified once per chunk per process
        # (same cache rationale as _digest_verified).
        self.verify_finite = bool(verify_finite)
        self._finite_verified: set[str] = set()
        self.io_retries = int(io_retries)
        self.retry_base_delay_s = float(retry_base_delay_s)
        # chunks whose digest already verified this process: a sha256 over
        # a multi-GB chunk costs ~1s serial with training, so epoch
        # repetitions must not re-pay it — first read still catches
        # on-disk corruption, which is the threat model (a chunk damaged
        # AFTER a clean in-process read implies failing RAM, not disk)
        self._digest_verified: set[str] = set()
        self.folder = Path(folder)
        meta_path = self.folder / "meta.json"
        self.meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
        by_index = {int(p.stem): p for p in self.folder.glob("*.npy")
                    if p.stem.isdigit()}
        self.format = "npy"
        if not by_index:
            pt = {int(p.stem): p for p in self.folder.glob("*.pt")
                  if p.stem.isdigit()}
            if pt:
                by_index = pt
                self.format = "pt"
        if not by_index and self.meta.get("n_chunks") is None:
            raise FileNotFoundError(f"no .npy or .pt chunks in {self.folder}")
        # index -> path tolerates GAPS — or a fully EMPTY live set when
        # meta.json declares the store: a scrub-repaired store keeps its
        # positional index space (meta n_chunks) with the quarantined
        # chunks' files moved aside — readers yield None at those
        # positions instead of shifting every later chunk down one (or
        # refusing to open a store the scrub just finished healing)
        self._paths_by_index = by_index
        self.chunk_paths = [by_index[i] for i in sorted(by_index)]
        declared = self.meta.get("n_chunks")
        self._n_chunks = (int(declared) if declared is not None
                          else max(by_index) + 1)
        # durable quarantine ledger (data/ledger.py): chunks a previous
        # process proved corrupt are known at open, so a supervised resume
        # never re-pays (or retries forever on) a known-bad chunk
        self.quarantined: set[int] = set(load_quarantine(self.folder))
        if self.format == "pt":
            if "activation_dim" in self.meta:
                self.activation_dim = int(self.meta["activation_dim"])
            else:
                from sparse_coding_tpu.utils.ref_interop import read_pt_chunk

                # on-disk dtype (no float32 blow-up) just to read the width;
                # reference chunks can be ~2 GB fp16
                self.activation_dim = int(
                    read_pt_chunk(self.chunk_paths[0],
                                  dtype=np.float16).shape[-1])
        elif self.chunk_paths:
            first = np.load(self.chunk_paths[0], mmap_mode="r")
            self.activation_dim = int(first.shape[-1])
        else:  # empty live set: the meta that admitted us carries the dim
            self.activation_dim = int(self.meta["activation_dim"])

    @property
    def n_chunks(self) -> int:
        """The store's POSITIONAL chunk count (meta.json's n_chunks when
        finalized): indices of scrub-quarantined chunks whose files were
        moved aside still count — they read as None/corrupt, they do not
        shift later chunks down."""
        return self._n_chunks

    def _path(self, i: int) -> Path:
        """Path of chunk ``i``; a missing file (scrub moved it aside, or
        the store was damaged) is typed corruption, never an IndexError."""
        p = self._paths_by_index.get(int(i))
        if p is None:
            raise ChunkCorruptionError(
                int(i), self.folder / f"{i}.{self.format}",
                "chunk file missing (quarantined by scrub, or damaged "
                "store)")
        return p

    def load_chunk(self, i: int, dtype=np.float32) -> np.ndarray:
        if self.format == "pt":
            from sparse_coding_tpu.utils.ref_interop import read_pt_chunk

            path = self._path(i)
            arr = read_pt_chunk(path, dtype=dtype)
            # same finite gate as _finish_raw: reference-interop chunks
            # have no digests at all, so NaN rows are the ONLY corruption
            # this path can even detect
            stem = str(path.stem)
            if self.verify_finite and stem not in self._finite_verified:
                if not np.isfinite(arr).all():
                    raise ChunkCorruptionError(
                        int(path.stem), path,
                        "non-finite values in decoded rows")
                self._finite_verified.add(stem)
            return arr
        from sparse_coding_tpu.data.native_io import (
            DEFAULT_THREADS,
            read_npy_native,
        )

        path = self._path(i)

        def _load_once() -> np.ndarray:
            try:
                # foreground reads: threaded pread only beats np.load with
                # real cores to spread over — the native layer's 1-CPU value
                # is the BACKGROUND overlap in chunk_reader, not raw speed
                raw = (read_npy_native(path) if DEFAULT_THREADS > 1
                       else None)
                if raw is None:  # no compiler / native lib / 1-CPU host
                    raw = np.load(path)
            except (ValueError, EOFError) as e:
                # truncated header/payload: structural damage, not a
                # transient hiccup — typed, named, never retried
                raise ChunkCorruptionError(
                    int(path.stem), path, f"unreadable npy: {e}") from e
            return self._finish_raw(raw, dtype, path)

        # transient I/O errors (OSError family) get a bounded backoff
        # retry; ChunkCorruptionError is not transient and passes through
        return retry_io(_load_once, attempts=self.io_retries,
                        base_delay_s=self.retry_base_delay_s)

    def chunk_mean(self, i: int = 0) -> np.ndarray:
        """Mean of one chunk — the reference's first-chunk centering
        (activation_dataset.py:379-381, big_sweep.py:359-364)."""
        return self.load_chunk(i).mean(axis=0)

    @property
    def center(self) -> Optional[np.ndarray]:
        """The translation subtracted at harvest when the store was written
        with center=True (center.npy), else None. Chunks on disk are ALREADY
        centered — this is for exports needing the original-space offset
        (e.g. models/pca.py get_centering_transform translations). Refuses
        legacy stores that claim centered=true without the subtracted-v2
        format marker (their chunks were written WITHOUT subtraction)."""
        path = self.folder / "center.npy"
        if not path.exists():
            return None
        if (self.meta.get("centered")
                and self.meta.get("center_format") != "subtracted-v2"):
            raise ValueError(
                f"{self.folder} claims centered=true but lacks the "
                "subtracted-v2 marker: it predates on-disk centering and its "
                "chunks are raw; re-harvest it (or subtract center.npy "
                "manually and stamp center_format)")
        return np.load(path)

    def batches(self, chunk: np.ndarray, batch_size: int,
                rng: np.random.Generator, drop_last: bool = True) -> Iterator[np.ndarray]:
        """Shuffled fixed-size batches from an in-RAM chunk (reference:
        BatchSampler(RandomSampler), cluster_runs.py:26-32)."""
        return shuffled_batches(chunk, batch_size, rng, drop_last)

    def _finish_raw(self, raw: np.ndarray, dtype, path) -> np.ndarray:
        """Single dtype + integrity gate for BOTH the numpy and
        native-prefetch paths: the chunk's content digest (recorded in
        meta.json at finalize) is verified here, so a bit flip anywhere
        between the writer's buffer and this read raises a typed
        ChunkCorruptionError naming the chunk; uint16 data is bfloat16 bit
        patterns only if meta.json says so — otherwise fail loudly
        (likely an interrupted harvest)."""
        raw = fault_point("chunk.read", raw)
        stem = str(path.stem)
        expected = ((self.meta.get("chunk_digests") or {}).get(stem)
                    if self.verify_digests and stem not in self._digest_verified
                    else None)
        if expected is not None:
            got = array_sha256(raw)
            if got != expected:
                raise ChunkCorruptionError(
                    int(path.stem), path,
                    f"content digest mismatch ({got[:12]}… != "
                    f"{expected[:12]}…)")
            self._digest_verified.add(stem)
        if raw.dtype == np.uint16:
            if self.meta.get("dtype") != "bfloat16":
                raise ValueError(
                    f"{path} holds uint16 (bfloat16 bit patterns) but "
                    "meta.json is missing or lacks dtype=bfloat16 — likely an "
                    "interrupted harvest; re-run it or write meta.json by hand")
            raw = raw.view(jnp.bfloat16)
        if self.verify_finite and stem not in self._finite_verified:
            # checked on the on-disk dtype (f16/bf16/f32 — np.isfinite
            # handles the ml_dtypes bfloat16 view) BEFORE the cast, so
            # garbage never reaches the training step via any read path
            if not np.isfinite(raw).all():
                raise ChunkCorruptionError(
                    int(path.stem), path,
                    "non-finite values in decoded rows")
            self._finite_verified.add(stem)
        from sparse_coding_tpu.data.native_io import fast_astype

        return fast_astype(raw, dtype)

    def chunk_reader(self, indices,
                     dtype=np.float32) -> Iterator[Optional[np.ndarray]]:
        """Yield in-RAM chunks for the given index sequence with disk
        readahead: the NEXT chunk's file streams from disk on native
        background threads while the caller trains on the current one
        (native/chunkio.cpp; silently sequential without it). Holds at most
        two chunks in host RAM (current + in-flight). With
        ``quarantine_corrupt=True`` a corrupt chunk yields ``None`` in its
        position (one warning logged, see ``_quarantine``) so positional
        consumers stay aligned with ``indices``."""
        if self.format == "pt":
            # torch deserialization isn't a raw pread — no native readahead
            # to cancel, but the rest of the raw branch's contract holds:
            # ledger-known chunks are skipped unread, and every delivered
            # chunk beats the lease so a WEDGED torch deserialize stops
            # the beats and the supervisor's hang watchdog catches it
            for ci in indices:
                ci = int(ci)
                if self.quarantine_corrupt and ci in self.quarantined:
                    # a quarantined position is still reader progress —
                    # beat like the raw branch does, or a long run of
                    # ledger-known chunks starves the hang watchdog
                    lease.beat()
                    yield None
                    continue
                try:
                    chunk = self.load_chunk(ci, dtype)
                except ChunkCorruptionError as e:
                    if not self.quarantine_corrupt:
                        raise
                    self._quarantine(e)
                    chunk = None
                lease.beat()
                yield chunk
            return
        from sparse_coding_tpu.data.native_io import NativePrefetcher

        indices = [int(i) for i in indices]
        prefetcher = NativePrefetcher()

        def _start(ci) -> bool:
            # never prefetch a ledger-known chunk (a resume must not
            # re-pay a known-corrupt read), and a truncated/corrupt
            # header must not crash the reader from the prefetch side:
            # degrade to the foreground path, which types the failure
            # (ChunkCorruptionError) properly
            if self.quarantine_corrupt and ci in self.quarantined:
                return False
            try:
                return prefetcher.start(self._path(ci))
            except (ChunkCorruptionError, ValueError, EOFError, OSError):
                return False

        try:
            prefetching = _start(indices[0]) if indices else False
            for pos, ci in enumerate(indices):
                raw = prefetcher.wait() if prefetching else None
                if self.quarantine_corrupt and ci in self.quarantined:
                    # ledger-known corrupt (possibly from a previous
                    # process): skip without paying the read
                    chunk = None
                else:
                    try:
                        try:
                            chunk = (self._finish_raw(raw, dtype,
                                                      self._path(ci))
                                     if raw is not None
                                     else self.load_chunk(ci, dtype))
                        except OSError:
                            # transient failure on the prefetched buffer:
                            # re-read through load_chunk's bounded retry
                            chunk = self.load_chunk(ci, dtype)
                    except ChunkCorruptionError as e:
                        if not self.quarantine_corrupt:
                            raise
                        self._quarantine(e)
                        chunk = None
                # _finish_raw copied: drop the on-disk dtype buffer before
                # the yield (keeps the documented two-chunk RAM bound)
                raw = None
                if pos + 1 < len(indices):
                    prefetching = _start(indices[pos + 1])
                # a delivered chunk is reader progress (throttled inside)
                lease.beat()
                # a quarantined chunk yields None (never silently dropped):
                # positional consumers — the sweep zips chunk indices with
                # this stream — must stay aligned with the index sequence
                yield chunk
        finally:
            # early generator exit must not leak the in-flight native read
            prefetcher.cancel()

    # the foreground single-stream contract path: data/ingest.py's
    # multi-stream chunk_stream delegates here for streams<=1 / pt stores
    # and degrades here when a stream worker dies mid-epoch
    serial_chunk_reader = chunk_reader

    def _quarantine(self, err: ChunkCorruptionError) -> None:
        """Record + warn about a corrupt chunk exactly once; later visits
        (n_repetitions > 1) skip silently. The quarantine is DURABLE
        (data/ledger.py): the ledger next to meta.json is rewritten
        atomically, so a supervised resume — a fresh process — opens the
        store already knowing and never re-pays the read. A ledger write
        failure (read-only store, full disk) only loses the durability,
        never the run: the in-memory set still protects this process."""
        if err.chunk_index not in self.quarantined:
            logger.warning(
                "quarantining corrupt chunk %d (%s): %s — skipping it for "
                "the rest of this run", err.chunk_index, err.path, err.reason)
            self.quarantined.add(err.chunk_index)
            try:
                record_quarantine(self.folder, err.chunk_index, err.reason,
                                  err.path.name)
            except OSError as write_err:
                logger.warning(
                    "quarantine ledger write failed for chunk %d (%s) — "
                    "the quarantine holds in-memory only this run",
                    err.chunk_index, write_err)

    def epoch(self, batch_size: int, rng: np.random.Generator,
              n_repetitions: int = 1, dtype=np.float32) -> Iterator[np.ndarray]:
        """Stream batches over all chunks, chunk order shuffled per repetition
        (reference: big_sweep.py:349-357), with chunk_reader's disk
        readahead."""
        order = np.concatenate([rng.permutation(self.n_chunks)
                                for _ in range(n_repetitions)])
        for chunk in self.chunk_reader(order, dtype):
            if chunk is None:  # quarantined (quarantine_corrupt=True)
                continue
            yield from self.batches(chunk, batch_size, rng)


def complete_chunk_count(folder: str | Path) -> int:
    """Number of leading complete chunks (``0.npy .. k-1.npy``) in a
    possibly-unfinalized store. Chunk writes are sequential and atomic, so
    after a crash the durable prefix is exactly the resumable work:
    ``ChunkWriter(..., start_index=complete_chunk_count(folder))`` plus
    skipping the producer rows those chunks cover continues the harvest
    bitwise-identically (tmp debris never matches ``<i>.npy``)."""
    folder = Path(folder)
    k = 0
    while (folder / f"{k}.npy").exists():
        k += 1
    return k


def clean_write_debris(folder: str | Path) -> int:
    """Remove orphaned atomic-write tmp files (``.<name>.tmp.<pid>``) a
    killed writer left behind; returns how many were removed. Safe by
    construction: no complete chunk ever has a dotted tmp name."""
    folder = Path(folder)
    n = 0
    for tmp in folder.glob(".*.tmp.*"):
        tmp.unlink(missing_ok=True)
        n += 1
    return n


def shuffled_batches(chunk: np.ndarray, batch_size: int,
                     rng: np.random.Generator,
                     drop_last: bool = True) -> Iterator[np.ndarray]:
    """Shuffled fixed-size batches over an in-RAM array (shared by ChunkStore
    and train/dispatch.py)."""
    n = chunk.shape[0]
    perm = rng.permutation(n)
    end = n - (n % batch_size) if drop_last else n
    for lo in range(0, end, batch_size):
        yield chunk[perm[lo:lo + batch_size]]


def window_stacks(batches: Iterable[np.ndarray], k: int) -> Iterator[np.ndarray]:
    """Group [B, d] host batches into [K, B, d] stacks for scanned training
    windows (Ensemble.run_steps / cfg.scan_steps). The final short window
    flushes with however many batches remain, so every batch trains (it
    compiles its own scan length at most once per run)."""
    buf: list[np.ndarray] = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield np.stack(buf)
            buf = []
    if buf:
        yield np.stack(buf)


def device_prefetch(batches: Iterable[np.ndarray], sharding=None,
                    buffer_size: int = 2) -> Iterator[Array]:
    """Double-buffered host→device pipeline: batch i+1 transfers while batch i
    computes. One implementation, hardened: delegates to
    ``data.ingest.device_batches`` (fault site ``ingest.transfer``, bounded
    retry, lease beats, stage span), so every caller — big_sae, dispatch,
    basic_sweep — rides the same contract as the sweep hot loop."""
    from sparse_coding_tpu.data.ingest import device_batches

    yield from device_batches(batches, sharding, buffer_size=buffer_size)
