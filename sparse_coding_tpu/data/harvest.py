"""Activation harvesting: LM forward → on-disk chunk store.

TPU-native replacement for the reference's three harvesting paths
(`make_activation_dataset_tl` activation_dataset.py:323-391,
`make_activation_dataset_hf` :393-496, baukit `make_activation_dataset`
:263-320): one jitted multi-tap forward per token batch, with
`stop_at_layer` pruning and all requested layers captured in a single pass.
Batches are data-sharded over the mesh for multi-chip harvesting.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.config import DataArgs
from sparse_coding_tpu.data.chunk_store import ChunkStore, ChunkWriter
from sparse_coding_tpu.lm import hooks
from sparse_coding_tpu.lm.model_config import LMConfig
from sparse_coding_tpu.resilience import lease


def make_harvest_fn(params, cfg: LMConfig, taps: Sequence[str], forward=None,
                    mesh=None, scan_batches: int = 1):
    """Jitted tokens[b,s] -> {tap: [b*s, width]} harvesting step
    (the reference's run_with_cache + rearrange "b s n -> (b s) n",
    activation_dataset.py:361-368).

    `scan_batches=K > 1` returns a fn taking a [K, b, s] token STACK and
    running K forwards inside one device program (lax.scan) — the same
    dispatch-amortization lever as training's scan_steps: through the axon
    tunnel each dispatch costs ~54 ms (TUNE.json r4), which at the
    reference's model_batch_size=4 dwarfs the forward itself; fusing K
    batches also turns K small device→host activation pulls into one large
    one (small transfers ride the tunnel ~6x slower than bulk).

    With a mesh, contexts run SEQUENCE-PARALLEL (lm/long_context.py): the
    sequence axis shards over the mesh's data axis with ring attention, so
    harvesting contexts can exceed a single chip's memory — long-context
    support the reference lacks (its contexts cap at 256-2048 tokens)."""
    if mesh is not None:
        if forward is not None:
            raise ValueError(
                "forward= and mesh= are mutually exclusive: the mesh path "
                "always uses the sequence-parallel GPT-NeoX forward "
                "(lm/long_context.py)")
        if scan_batches > 1:
            raise ValueError(
                "scan_batches > 1 is a single-chip dispatch-amortization "
                "lever; the mesh (sequence-parallel) path runs one large "
                "sharded forward per dispatch instead")
        from sparse_coding_tpu.lm.long_context import sequence_parallel_forward

        stop = hooks.max_tap_layer(taps) + 1

        def harvest_sp(tokens):
            _, tapped = sequence_parallel_forward(params, tokens, cfg, mesh,
                                                  taps=taps, stop_at_layer=stop)
            return {name: acts.reshape(-1, acts.shape[-1])
                    for name, acts in tapped.items()}

        return jax.jit(harvest_sp)

    if forward is None:
        from sparse_coding_tpu.lm.convert import forward_fn
        forward = forward_fn(cfg)
    stop = hooks.max_tap_layer(taps) + 1

    def harvest(tokens):
        _, tapped = forward(params, tokens, cfg, taps=taps, stop_at_layer=stop)
        return {name: acts.reshape(-1, acts.shape[-1])
                for name, acts in tapped.items()}

    if scan_batches > 1:
        def harvest_scan(token_stack):  # [K, b, s]
            _, tapped = jax.lax.scan(
                lambda _, toks: (None, harvest(toks)), None, token_stack)
            # {tap: [K, b*s, w]} -> [K*b*s, w], scan order = batch order
            return {name: a.reshape(-1, a.shape[-1])
                    for name, a in tapped.items()}

        return jax.jit(harvest_scan)
    return jax.jit(harvest)


def harvest_activations(
    params,
    cfg: LMConfig,
    token_rows: np.ndarray,
    layers: Sequence[int],
    layer_loc: str,
    output_folder: str | Path,
    model_batch_size: int = 4,
    chunk_size_gb: float = 2.0,
    n_chunks: Optional[int] = None,
    skip_chunks: int = 0,
    center: bool = False,
    dtype: str = "bfloat16",
    forward=None,
    mesh=None,
    scan_batches: int = 1,
    tap_dirs: Optional[dict] = None,
) -> dict[str, int]:
    """Run the LM over packed token rows, streaming each tap's activations to
    its own chunk folder `{output_folder}/{tap}/`. Multi-layer in one pass
    (as the reference does, activation_dataset.py:323-391).

    ``tap_dirs`` remaps a tap's chunk folder (``{tap: Path}``) — the
    group harvest writes tap i into the multi-tap store's ``shard-<i>/``
    instead of a tap-named subfolder; unmapped taps keep the default.
    Every tap's finalize metadata carries its identity (``tap``,
    ``layer``) so the grouping pass can read layer order from the store.

    Returns {tap_name: n_chunks_written}. `skip_chunks` resumes mid-dataset
    by skipping already-harvested leading chunks (reference:
    activation_dataset.py:348,433). `scan_batches=K` fuses K model batches
    into one device program (dispatch amortization through the tunnel; see
    make_harvest_fn) — results are bit-identical to K=1, only the dispatch
    granularity changes; the tail falls back to single-batch dispatches so
    every full model batch is harvested either way."""
    if scan_batches > 1 and mesh is not None:
        raise ValueError("scan_batches > 1 is not supported on the mesh "
                         "(sequence-parallel) harvesting path")
    taps = hooks.taps_for(layers, layer_loc)
    harvest = make_harvest_fn(params, cfg, taps, forward=forward, mesh=mesh)
    harvest_window = (make_harvest_fn(params, cfg, taps, forward=forward,
                                      scan_batches=scan_batches)
                      if scan_batches > 1 else None)
    width = hooks.get_activation_size(layer_loc, cfg)

    seq_len = token_rows.shape[1]
    # chunk boundaries aligned to whole model batches so skip_chunks resume
    # maps exactly onto token-row offsets (no duplicated/shifted data)
    tap_dirs = dict(tap_dirs or {})
    writers = {
        t: ChunkWriter(Path(tap_dirs.get(t, Path(output_folder) / t)), width,
                       chunk_size_gb=chunk_size_gb, dtype=dtype,
                       start_index=skip_chunks,
                       round_rows_to=model_batch_size * seq_len,
                       center=center)
        for t in taps
    }

    n_rows = token_rows.shape[0]
    target_rows_per_chunk = next(iter(writers.values())).rows_per_chunk
    skip_rows = skip_chunks * (target_rows_per_chunk // seq_len)
    if n_chunks is not None:
        # never feed rows past the chunk cap: a scan window crossing the
        # final chunk boundary would leave buffered rows that finalize()
        # flushes as an overshooting extra chunk (rows_per_chunk is rounded
        # to whole model batches, so this bound is batch-aligned and the
        # K=1 / K>1 paths consume identical rows)
        n_rows = min(n_rows,
                     skip_rows + n_chunks * (target_rows_per_chunk // seq_len))

    # device→host double buffering: batch i's activations stream back while
    # batch i+1 computes, so the host-side chunk writer never stalls the LM
    from collections import deque

    pending: deque = deque()

    drained_rows = obs.counter("harvest.rows_drained")

    def drain_one() -> bool:
        tapped = pending.popleft()
        for name, acts in tapped.items():
            host = np.asarray(acts)
            writers[name].add(host)
            drained_rows.inc(int(host.shape[0]))
        # progress heartbeat per drained forward (supervised runs): a
        # drained batch proves the LM, the device→host pull, and the
        # writer all advanced — a wedged tunnel stops these beats cold
        lease.beat()
        return (n_chunks is not None and all(
            w.chunk_index - skip_chunks >= n_chunks for w in writers.values()))

    done = False
    lo = skip_rows
    t_harvest = obs.monotime()
    try:
        while lo < n_rows and not done:
            n_avail = (n_rows - lo) // model_batch_size  # full batches left
            if n_avail == 0:
                break  # keep shapes static for jit (partial batch dropped)
            if harvest_window is not None and n_avail >= scan_batches:
                step_rows = model_batch_size * scan_batches
                stack = jnp.asarray(token_rows[lo:lo + step_rows].reshape(
                    scan_batches, model_batch_size, seq_len))
                tapped = harvest_window(stack)
            else:
                # the tail (< scan_batches full batches) reuses the compiled
                # single-batch program — at most two compilations total
                step_rows = model_batch_size
                tapped = harvest(jnp.asarray(token_rows[lo:lo + step_rows]))
            for acts in tapped.values():
                acts.copy_to_host_async()
            pending.append(tapped)
            lo += step_rows
            if len(pending) > 1:
                done = drain_one()
        while pending and not done:
            done = drain_one()
    except BaseException:
        # a crashed harvest must leave only whole chunk files and NO
        # meta.json — its absence marks the store incomplete, and abort()
        # sweeps up any in-flight tmp file (chunk writes are tmp+rename,
        # so a torn final chunk is impossible either way)
        for w in writers.values():
            w.abort()
        obs.record_span("harvest.run", obs.monotime() - t_harvest, ok=False,
                        error="aborted", taps=list(taps))
        raise

    # centering happens INSIDE the writers (first flushed chunk's mean
    # subtracted from every chunk, reference: activation_dataset.py:379-381);
    # the writer stamps the truthful "centered" flag and saves center.npy
    result = {name: w.finalize({"model": cfg.arch, "layer_loc": layer_loc,
                                "tap": name,
                                "layer": hooks.parse_tap_name(name)[1]})
              for name, w in writers.items()}
    obs.record_span("harvest.run", obs.monotime() - t_harvest,
                    taps=list(taps), rows=int(n_rows - skip_rows),
                    chunks={k: int(v) for k, v in result.items()})
    return result


def make_one_chunk_per_layer(params, lm_cfg: LMConfig, token_rows: np.ndarray,
                             layers: Sequence[int], layer_loc: str,
                             output_folder: str | Path,
                             chunk_size_gb: float = 0.5,
                             model_batch_size: int = 4,
                             forward=None) -> dict[str, int]:
    """One eval chunk per layer for metric sweeps
    (reference: standard_metrics.py:582-619 make_one_chunk_per_layer[_gpt2sm])."""
    return harvest_activations(params, lm_cfg, token_rows, layers, layer_loc,
                               output_folder, model_batch_size=model_batch_size,
                               chunk_size_gb=chunk_size_gb, n_chunks=1,
                               forward=forward)


def setup_data(cfg: DataArgs, params, lm_cfg: LMConfig, texts, tokenizer,
               forward=None) -> dict[str, int]:
    """End-to-end orchestrator: tokenize/pack then harvest
    (reference: setup_data, activation_dataset.py:544-604)."""
    from sparse_coding_tpu.data.tokenize import chunk_and_tokenize

    rows, _ = chunk_and_tokenize(texts, tokenizer, max_length=cfg.context_len,
                                 eos_token_id=lm_cfg.eos_token_id,
                                 max_docs=cfg.max_docs)
    return harvest_activations(
        params, lm_cfg, rows, cfg.layers, cfg.layer_loc, cfg.dataset_folder,
        model_batch_size=cfg.model_batch_size, chunk_size_gb=cfg.chunk_size_gb,
        n_chunks=cfg.n_chunks, skip_chunks=cfg.skip_chunks,
        center=cfg.center_dataset, dtype=cfg.activation_dtype, forward=forward,
        scan_batches=cfg.scan_batches)
