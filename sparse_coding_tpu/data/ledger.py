"""Durable quarantine ledger for chunk folders.

A reader that discovers a corrupt chunk must not keep that knowledge in
process memory only: a supervised resume (crash-only contract, docs/
ARCHITECTURE.md §11) would re-pay the multi-GB read + digest of a chunk
that is KNOWN bad — or, with ``quarantine_corrupt=False``, retry it
forever. The ledger is that knowledge on disk: ``quarantine.json`` next
to ``meta.json``, one entry per quarantined chunk index, rewritten
atomically (tmp+fsync+rename) on every addition and loaded by
``ChunkStore.__init__`` so a fresh process starts already knowing.

Deliberately jax-free (and import-light): the scrub step
(:mod:`sparse_coding_tpu.data.scrub`) reads and writes the same ledger
from a process that must be able to run against a wedged TPU tunnel.

Entry values record only the failure ``reason`` and the chunk's file
NAME — never an absolute path, so a store moved between hosts (or a
chaos-matrix golden copy) keeps a byte-identical ledger.
"""

from __future__ import annotations

import json
from pathlib import Path

from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.errors import LedgerCorruptionError
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import (
    check_payload_digest,
    embed_payload_digest,
)

LEDGER_NAME = "quarantine.json"

register_fault_site("ledger.write",
                    "durable quarantine-ledger rewrite (data/ledger.py "
                    "record_quarantine) — ChunkStore._quarantine degrades "
                    "to in-memory-only on failure (read-only store, full "
                    "disk); the scrub propagates, so a re-run converges")


def ledger_path(folder: str | Path) -> Path:
    return Path(folder) / LEDGER_NAME


def load_quarantine(folder: str | Path) -> dict[int, dict]:
    """``{chunk_index: {"reason": ..., "file": ...}}`` from the folder's
    ledger; ``{}`` when missing. Atomic writes make torn ledgers
    impossible, so an unreadable file means no valid ledger — treated as
    empty rather than poisoning the reader (the chunk digests themselves
    still catch any corruption the lost ledger knew about). A ledger that
    PARSES but fails its embedded payload digest is different: the file
    is lying about which chunks are quarantined, and acting on it could
    un-hole a poisoned chunk — raise a typed
    :class:`LedgerCorruptionError` instead (fsck reports the same file
    as ``INCONSISTENT``). Digest-less legacy ledgers load unverified."""
    path = ledger_path(folder)
    try:
        raw = json.loads(path.read_text())
        chunks = {int(k): dict(v) for k, v in raw.get("chunks", {}).items()}
    except (OSError, ValueError, TypeError, AttributeError):
        return {}
    if check_payload_digest(raw) == "mismatch":
        raise LedgerCorruptionError(path, "payload digest mismatch")
    return chunks


def record_quarantine(folder: str | Path, chunk_index: int, reason: str,
                      file_name: str = "") -> dict[int, dict]:
    """Add (or overwrite) one ledger entry and rewrite the ledger
    atomically; returns the updated entry map. Writing the same entry
    twice produces byte-identical ledgers (sorted keys, stable dump) —
    the idempotence the scrub resume path depends on."""
    folder = Path(folder)
    entries = load_quarantine(folder)
    entries[int(chunk_index)] = {"reason": str(reason),
                                 "file": str(file_name)}
    _rewrite(folder, entries)
    return entries


def clear_quarantine(folder: str | Path,
                     chunk_index: int) -> dict[int, dict]:
    """Drop one ledger entry — the chunk HEALED (a re-harvest put a sound
    file back at its position and a scrub verified it). Rewrites the
    ledger atomically; when the last entry goes, the ledger file itself
    is removed (readers treat a missing ledger as empty, and a
    fully-healed store is byte-identical to one that never rotted).
    Clearing an absent entry is a no-op. Returns the updated map."""
    folder = Path(folder)
    entries = load_quarantine(folder)
    if entries.pop(int(chunk_index), None) is not None:
        _rewrite(folder, entries)
    return entries


def _rewrite(folder: Path, entries: dict[int, dict]) -> None:
    path = ledger_path(folder)
    if not entries:
        try:
            path.unlink()
        except FileNotFoundError:
            pass
        return
    payload = embed_payload_digest(
        {"version": 1,
         "chunks": {str(k): entries[k] for k in sorted(entries)}})
    fault_point("ledger.write")
    atomic_write_text(path, json.dumps(payload, indent=2, sort_keys=True))
