"""ctypes bindings for the native chunk-IO library (native/chunkio.cpp).

Auto-builds `libchunkio.so` with g++ on first use (cached next to the
source); everything degrades gracefully to numpy IO when no compiler is
available, so the native layer is a pure acceleration, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libchunkio.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

# threaded pread only pays with real cores; on a 1-CPU host the slices just
# contend (measured 170 MB/s vs np.load's 1.4 GB/s warm-cache on this image).
# sched_getaffinity respects cgroup/taskset pinning where cpu_count() reports
# all host cores.
def _usable_cpus() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux
        return os.cpu_count() or 1


DEFAULT_THREADS = max(1, min(8, _usable_cpus()))


def fast_astype(raw: np.ndarray, dtype) -> np.ndarray:
    """Chunk-dtype conversion for the load path. numpy's half/bfloat16 →
    float32 converters are SCALAR loops (~140 MB/s measured here — slower
    than the disk read they follow); torch's are vectorized (~460 MB/s on
    the same single core), so the hot f16/bf16 → f32 conversions route
    through the CPU torch bridge when torch is importable. Semantics are
    identical to raw.astype(dtype) (widening casts are exact)."""
    dtype = np.dtype(dtype)
    if dtype != np.float32 or raw.dtype == np.float32:
        return raw.astype(dtype)
    try:
        import torch
    except ImportError:
        return raw.astype(dtype)

    def torch_ready(a: np.ndarray) -> np.ndarray:
        # torch.from_numpy needs a writable C-contiguous buffer (read-only
        # np.load mmaps and strided views are neither); one host copy keeps
        # the vectorized cast path available. Only the torch branches pay
        # it — fall-through dtypes go straight to astype.
        if a.flags.c_contiguous and a.flags.writeable:
            return a
        return a.copy()

    if raw.dtype == np.float16:
        return torch.from_numpy(torch_ready(raw)).to(torch.float32).numpy()
    if raw.dtype.itemsize == 2 and raw.dtype.name == "bfloat16":
        t = torch.from_numpy(torch_ready(raw).view(np.int16)).view(torch.bfloat16)
        return t.to(torch.float32).numpy()
    return raw.astype(dtype)


def _build() -> bool:
    src = _NATIVE_DIR / "chunkio.cpp"
    if not src.exists():
        return False
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(src),
           "-o", str(_LIB_PATH), "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if not _LIB_PATH.exists() and not _build():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError:
            _lib_failed = True
            return None
        lib.chunkio_read.restype = ctypes.c_int64
        lib.chunkio_read.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.c_int]
        lib.chunkio_file_size.restype = ctypes.c_int64
        lib.chunkio_file_size.argtypes = [ctypes.c_char_p]
        lib.chunkio_prefetch_start.restype = ctypes.c_void_p
        lib.chunkio_prefetch_start.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                               ctypes.c_int64, ctypes.c_int64,
                                               ctypes.c_int]
        lib.chunkio_prefetch_wait.restype = ctypes.c_int64
        lib.chunkio_prefetch_wait.argtypes = [ctypes.c_void_p]
        lib.chunkio_prefetch_cancel.restype = None
        lib.chunkio_prefetch_cancel.argtypes = [ctypes.c_void_p]
        # chunkio_prefetch_poll: a stale prebuilt .so may predate it —
        # poll degrades to "unknown" (None) rather than making the whole
        # library unusable
        try:
            lib.chunkio_prefetch_poll.restype = ctypes.c_int
            lib.chunkio_prefetch_poll.argtypes = [ctypes.c_void_p]
        except AttributeError:
            pass
        _lib = lib
        return _lib


def _npy_header(path: Path) -> tuple[np.dtype, tuple, int]:
    """Parse a .npy header; returns (dtype, shape, payload offset)."""
    with open(path, "rb") as fh:
        version = np.lib.format.read_magic(fh)
        shape, fortran, dtype = np.lib.format._read_array_header(fh, version)
        if fortran:
            raise ValueError(f"{path}: fortran-order arrays unsupported")
        return dtype, shape, fh.tell()


def read_npy_native(path: str | Path,
                    nthreads: int = DEFAULT_THREADS) -> Optional[np.ndarray]:
    """Threaded read of a .npy file; None when the native lib is missing
    (caller falls back to np.load)."""
    lib = get_lib()
    if lib is None:
        return None
    path = Path(path)
    dtype, shape, offset = _npy_header(path)
    out = np.empty(shape, dtype)
    size = out.nbytes
    n = lib.chunkio_read(str(path).encode(),
                         out.ctypes.data_as(ctypes.c_char_p),
                         offset, size, nthreads)
    if n != size:
        return None
    return out


class NativePrefetcher:
    """Background-thread prefetch of the next chunk file into a caller-owned
    numpy buffer (zero-copy): `start(path)` while the current chunk trains,
    `wait()` to get the array."""

    def __init__(self, nthreads: int = DEFAULT_THREADS):
        self.nthreads = nthreads
        self._handle = None
        self._buffer: Optional[np.ndarray] = None  # keeps dst alive for C
        self._size = 0

    def start(self, path: str | Path) -> bool:
        lib = get_lib()
        if lib is None or self._handle is not None:
            return False
        path = Path(path)
        dtype, shape, offset = _npy_header(path)
        out = np.empty(shape, dtype)
        handle = lib.chunkio_prefetch_start(
            str(path).encode(), out.ctypes.data_as(ctypes.c_char_p),
            offset, out.nbytes, self.nthreads)
        if not handle:
            return False
        self._handle = handle
        self._buffer = out
        self._size = out.nbytes
        return True

    def poll(self) -> Optional[bool]:
        """Non-blocking readiness check for the in-flight prefetch: True
        when ``wait()`` will not block, False while the read is still in
        flight, None when nothing is in flight or the loaded library
        predates the poll entry point. Readiness primitive for a consumer
        keeping several handles outstanding (chunk_stream currently
        multiplexes pool threads over blocking ``load_chunk`` instead, so
        no production path calls this yet)."""
        if self._handle is None:
            return None
        lib = get_lib()
        if not hasattr(lib, "chunkio_prefetch_poll"):
            return None
        return bool(lib.chunkio_prefetch_poll(ctypes.c_void_p(self._handle)))

    def wait(self) -> Optional[np.ndarray]:
        if self._handle is None:
            return None
        n = get_lib().chunkio_prefetch_wait(ctypes.c_void_p(self._handle))
        out = self._buffer if n == self._size else None
        self._handle, self._buffer, self._size = None, None, 0
        return out

    def cancel(self) -> None:
        if self._handle is not None:
            get_lib().chunkio_prefetch_cancel(ctypes.c_void_p(self._handle))
            self._handle, self._buffer, self._size = None, None, 0

    def __del__(self):  # last-resort leak guard
        try:
            self.cancel()
        except Exception:
            pass
