"""Store scrub: re-verify chunk digests, quarantine/repair, emit a
re-harvest worklist.

Digests are verified on READ (`ChunkStore._finish_raw`), which means a
chunk that rotted on disk is only discovered when a sweep trips over it —
mid-run, on the hot path. The scrub moves that discovery to a dedicated,
restartable step (standalone CLI or a supervisor DAG node between
harvest and sweep): it re-reads every chunk against the digests in
`meta.json`, records failures in the durable quarantine ledger
(data/ledger.py), optionally **repairs** the folder by moving the corrupt
file into a `quarantine/` subdirectory (readers then yield positional
``None`` instead of re-tripping), and emits `scrub/reharvest.json` — the
worklist naming exactly which shard/chunk/rows a re-harvest must
regenerate.

Crash-only by construction (docs/ARCHITECTURE.md §11): every output is
idempotent and byte-deterministic (no timestamps, no absolute paths), the
ledger entry is durable BEFORE the repair move (crash barrier
``scrub.repair`` sits between them — the chaos matrix kills a real scrub
child there), and a re-run over a half-repaired store converges to the
same bytes. `scrub/scrub_report.json` is written LAST: its presence is
the step's completion marker.

**Backend-free by design** (enforced in tests): scrubbing is pure host
I/O — it never initializes a jax backend or touches a device (the
obs.report discipline), so it runs — and should be run — while the TPU
tunnel is wedged (docs/RUNBOOK_TUNNEL.md).

CLI::

    python -m sparse_coding_tpu.data.scrub <store_dir> [--repair] [--out DIR]
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Optional

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.data.ledger import (
    clear_quarantine,
    load_quarantine,
    record_quarantine,
)
from sparse_coding_tpu.data.shard_store import read_store_manifest
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.atomic import atomic_write_text, fsync_dir
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import array_sha256, bytes_sha256
from sparse_coding_tpu.resilience.retry import retry_io

QUARANTINE_DIR = "quarantine"
REPORT_NAME = "scrub_report.json"
WORKLIST_NAME = "reharvest.json"

register_fault_site("shard.scrub",
                    "scrub's per-chunk verify read (data/scrub.py — "
                    "transient errors get a bounded retry; structural "
                    "damage quarantines the chunk)")
register_crash_site("scrub.repair",
                    "scrub: quarantine ledger entry durable, the corrupt "
                    "chunk file not yet moved aside (data/scrub.py)")


def _chunk_rows(path: Path) -> Optional[int]:
    """Row count from the .npy header alone (no payload read); None when
    even the header is unreadable."""
    from sparse_coding_tpu.data.native_io import _npy_header

    try:
        _dtype, shape, _off = _npy_header(path)
        return int(shape[0]) if shape else None
    except (OSError, ValueError, EOFError):
        return None


def _verify_chunk(path: Path, expected: Optional[str],
                  io_retries: int = 3) -> Optional[str]:
    """Re-read one chunk and check its content digest; returns the
    failure reason, or None when the chunk is sound. Transient I/O gets
    the bounded retry; persistent I/O failure propagates (a flaky disk
    must not quarantine good data) — only structural damage and digest
    mismatches quarantine."""

    def _read():
        fault_point("shard.scrub")
        return np.load(path)

    try:
        arr = retry_io(_read, attempts=io_retries)
    except (ValueError, EOFError) as e:
        return f"unreadable npy: {e}"
    if expected is not None:
        got = array_sha256(arr)
        if got != expected:
            return (f"content digest mismatch ({got[:12]}… != "
                    f"{expected[:12]}…)")
    return None


def scrub_folder(folder: str | Path, repair: bool = False,
                 io_retries: int = 3) -> dict:
    """Scrub one finalized chunk folder (a shard, or a flat store).

    Returns ``{"checked", "ok", "quarantined": [i...], "worklist":
    [{"chunk", "rows"}...]}``. Chunks already repaired (file in
    ``quarantine/`` or missing with a ledger entry) are treated as
    quarantined without re-verification — the resume path after a kill
    anywhere in a previous scrub. A ledger-listed chunk whose live file
    verifies sound HEALED (re-harvested per the worklist): its stale
    ledger entry is cleared so readers deliver it again. With
    ``repair=True`` a corrupt chunk's
    file moves to ``quarantine/<i>.npy`` (rename — the original bytes are
    preserved for forensics) so later readers pay a positional ``None``
    instead of a read+digest of known garbage."""
    folder = Path(folder)
    if (any(folder.glob("*.pt"))
            and not any(folder.glob("*.npy"))
            and not any((folder / QUARANTINE_DIR).glob("*.npy"))):
        # reference pt stores (utils/ref_interop.py) carry no raw-chunk
        # digests and their chunks are not .npy files — scrubbing one
        # would land every healthy chunk in the missing-file branch and
        # durably quarantine the whole store. Refuse loudly instead.
        raise ValueError(
            f"{folder} is a pt-format reference store: scrub verifies raw "
            ".npy chunk digests only — convert via ref_interop, or skip")
    meta = json.loads((folder / "meta.json").read_text())
    digests = meta.get("chunk_digests") or {}
    n_chunks = int(meta.get("n_chunks", 0))
    qdir = folder / QUARANTINE_DIR
    ok = 0
    quarantined: list[int] = []
    worklist: list[dict] = []
    # the ledger is loaded ONCE and rewritten only for entries that
    # actually change: a re-scrub over Q already-quarantined chunks must
    # not pay Q ledger parses and Q durable fsync+rename cycles for zero
    # state change (idempotence stays — an unchanged entry's rewrite
    # would be byte-identical anyway)
    ledger = load_quarantine(folder)

    def _ledger_add(i: int, reason: str) -> None:
        entry = {"reason": str(reason), "file": f"{i}.npy"}
        if ledger.get(i) != entry:
            ledger.update(record_quarantine(folder, i, reason, f"{i}.npy"))

    for i in range(n_chunks):
        path = folder / f"{i}.npy"
        qpath = qdir / f"{i}.npy"
        if not path.exists():
            # missing from the live set: either a previous scrub already
            # repaired it (qpath/ledger) or the store lost a file —
            # both are quarantine-worklist outcomes, never a crash
            already = ledger.get(i)
            reason = (already or {}).get("reason") or "chunk file missing"
            _ledger_add(i, reason)
            quarantined.append(i)
            worklist.append({"chunk": i, "rows": _chunk_rows(qpath)})
            lease.beat()
            continue
        reason = _verify_chunk(path, digests.get(str(i)),
                               io_retries=io_retries)
        if reason is None:
            ok += 1
            if i in ledger:
                # the chunk HEALED: a re-harvest (scrub/reharvest.json
                # worklist) put a sound file back at this position — a
                # stale ledger entry would make readers skip it forever
                # while the report claims the store is clean. The
                # quarantine/ forensics copy (if any) stays: it records
                # what the rotted bytes were, and nothing consults it
                # while the live file exists.
                ledger = clear_quarantine(folder, i)
        else:
            rows = _chunk_rows(path)
            # ledger FIRST (durable knowledge), repair second: a kill
            # between them leaves a store that readers already skip
            # correctly and a re-run completes identically
            _ledger_add(i, reason)
            crash_barrier("scrub.repair")
            if repair:
                qdir.mkdir(parents=True, exist_ok=True)
                os.replace(path, qpath)
                fsync_dir(folder)
            quarantined.append(i)
            worklist.append({"chunk": i, "rows": rows})
        lease.beat()
    return {"checked": n_chunks, "ok": ok,
            "quarantined": sorted(quarantined), "worklist": worklist}


def scrub_store(root: str | Path, repair: bool = False,
                out_dir: Optional[str | Path] = None,
                io_retries: int = 3) -> dict:
    """Scrub a whole store — sharded (``manifest.json``) or flat — and
    write the two outputs under ``<root>/scrub/`` (or ``out_dir``):
    ``reharvest.json`` (the worklist) then ``scrub_report.json`` (the
    completion marker, LAST). Re-running over an unchanged store rewrites
    identical bytes. Returns the report dict."""
    root = Path(root)
    out = Path(out_dir) if out_dir is not None else root / "scrub"
    t0 = obs.monotime()
    manifest = read_store_manifest(root)
    shard_reports: dict[str, dict] = {}
    worklist: list[dict] = []
    if manifest is not None:
        for s in manifest["shards"]:
            d = root / s["name"]
            t_shard = obs.monotime()
            meta_path = d / "meta.json"
            sealed = str(s.get("meta_sha256", ""))
            if (not meta_path.exists()
                    or bytes_sha256(meta_path.read_bytes()) != sealed):
                # the shard's META itself is damaged: its digests can't
                # be trusted chunk-by-chunk — the whole shard goes on
                # the worklist
                rep = {"checked": 0, "ok": 0, "quarantined": [],
                       "worklist": [], "meta_damaged": True}
                worklist.append({"shard": s["name"], "chunk": None,
                                 "rows": None, "whole_shard": True})
            else:
                rep = scrub_folder(d, repair=repair, io_retries=io_retries)
                worklist.extend({"shard": s["name"], **w}
                                for w in rep["worklist"])
            shard_reports[s["name"]] = {k: v for k, v in rep.items()
                                        if k != "worklist"}
            obs.record_span("scrub.shard", obs.monotime() - t_shard,
                            shard=s["name"], checked=rep["checked"],
                            quarantined=len(rep["quarantined"]))
            obs.counter("scrub.chunks_checked").inc(rep["checked"])
            obs.counter("scrub.chunks_quarantined").inc(
                len(rep["quarantined"]))
    else:
        rep = scrub_folder(root, repair=repair, io_retries=io_retries)
        worklist = [{"shard": "", **w} for w in rep["worklist"]]
        shard_reports[""] = {k: v for k, v in rep.items() if k != "worklist"}
        obs.counter("scrub.chunks_checked").inc(rep["checked"])
        obs.counter("scrub.chunks_quarantined").inc(len(rep["quarantined"]))
    report = {"version": 1, "store": "sharded" if manifest else "flat",
              "repair": bool(repair),
              "checked": sum(r["checked"] for r in shard_reports.values()),
              "ok": sum(r["ok"] for r in shard_reports.values()),
              "quarantined": sum(len(r["quarantined"])
                                 for r in shard_reports.values()),
              "shards": shard_reports,
              "reharvest_entries": len(worklist)}
    out.mkdir(parents=True, exist_ok=True)
    atomic_write_text(out / WORKLIST_NAME,
                      json.dumps(worklist, indent=2, sort_keys=True))
    # report LAST: its presence is the supervisor step's done() marker
    atomic_write_text(out / REPORT_NAME,
                      json.dumps(report, indent=2, sort_keys=True))
    obs.record_span("scrub.store", obs.monotime() - t0,
                    checked=report["checked"],
                    quarantined=report["quarantined"])
    return report


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="re-verify a chunk store's digests; quarantine (and "
                    "with --repair, move aside) corrupt chunks; emit a "
                    "re-harvest worklist. Backend-free: safe to run while "
                    "the TPU tunnel is wedged (docs/RUNBOOK_TUNNEL.md).")
    parser.add_argument("store", help="store root (sharded or flat)")
    parser.add_argument("--repair", action="store_true",
                        help="move corrupt chunks into quarantine/ so "
                             "readers skip them without re-reading")
    parser.add_argument("--out", default=None,
                        help="output dir (default: <store>/scrub)")
    ns = parser.parse_args(argv)
    report = scrub_store(ns.store, repair=ns.repair, out_dir=ns.out)
    json.dump(report, sys.stdout, indent=2, sort_keys=True)
    print()


if __name__ == "__main__":
    main()
