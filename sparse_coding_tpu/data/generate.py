"""Activation-dataset generation CLI.

Re-design of the reference's `generate_test_data.py:30-67` (GenTestArgs
driving setup_data/setup_data_new): load a preset model + text dataset,
tokenize/pack, harvest all requested layers in one pass.

    python -m sparse_coding_tpu.data.generate --model_name gpt2 \
        --layers '[1,2]' --layer_loc residual --dataset_folder out/
"""

from __future__ import annotations

from sparse_coding_tpu.config import DataArgs


def main(argv=None) -> None:
    cfg = DataArgs.from_cli(argv)

    from transformers import AutoTokenizer

    from sparse_coding_tpu.data.harvest import setup_data
    from sparse_coding_tpu.data.tokenize import load_text_dataset
    from sparse_coding_tpu.lm.convert import load_model

    params, lm_cfg = load_model(cfg.model_name)
    tokenizer = AutoTokenizer.from_pretrained(cfg.model_name)
    texts = load_text_dataset(cfg.dataset_name, max_docs=cfg.max_docs)
    written = setup_data(cfg, params, lm_cfg, texts, tokenizer)
    for tap, n in written.items():
        print(f"{tap}: {n} chunks -> {cfg.dataset_folder}/{tap}/")


if __name__ == "__main__":
    main()
