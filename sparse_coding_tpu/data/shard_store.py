"""Self-healing sharded chunk store: shard dirs, sealed metas, one manifest.

A single harvest process writing one flat chunk folder is the data
plane's scaling ceiling (ROADMAP item 5): pod-scale sweeps and Group-SAE
multi-layer harvests need WRITERS that parallelize and a store that
localizes damage. The sharded layout is the smallest structure that buys
both:

```
store/
  manifest.json            # store-level truth, written LAST, atomically
  shard-000/
    0.npy 1.npy ...        # an ordinary ChunkStore folder
    meta.json              # per-shard chunk digests (ChunkWriter.finalize)
    shard.digest           # seal: sha256 of meta.json's bytes
    quarantine.json        # durable quarantine ledger (data/ledger.py)
  shard-001/ ...
```

- each shard is owned by ONE writer (a supervisor child —
  `pipeline.steps shard_harvest --shard i`): writers share nothing, so
  they parallelize across processes/hosts and a kill costs one shard's
  in-flight chunk, nothing else;
- a finished shard is **sealed**: `shard.digest` records the sha256 of
  its `meta.json` bytes (crash barrier ``shard.finalize`` sits between
  the two durable writes — the chaos matrix kills a real writer there);
- `manifest.json` aggregates the sealed shards (names, chunk counts,
  meta digests) and is written last and atomically behind fault site
  ``shard.write`` — its presence certifies a complete store, exactly as
  `meta.json` does for a flat folder.

:class:`ShardedChunkStore` reads the manifest and presents ONE
positional chunk index space (shard-major) with the full `ChunkStore`
reader contract: digest-verified loads, durable per-shard quarantine
ledgers, positional ``None`` for quarantined chunks, and multi-stream
reads via :func:`data.ingest.chunk_stream`.

Import discipline: module import stays jax-free (the scrub step and the
manifest-building supervisor child run against a wedged tunnel);
`ChunkStore` — whose module imports jax — loads lazily inside
:class:`ShardedChunkStore`.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

from sparse_coding_tpu.data.ledger import load_quarantine
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.errors import (
    ChunkCorruptionError,
    ResilienceError,
)
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import bytes_sha256
from sparse_coding_tpu.resilience.retry import retry_io

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
SHARD_PREFIX = "shard-"
SHARD_DIGEST_NAME = "shard.digest"

register_fault_site("shard.write",
                    "sharded-store durable writes: the per-shard "
                    "shard.digest seal and the store-level manifest "
                    "(data/shard_store.py, inside the bounded-retry scope)")
register_crash_site("shard.finalize",
                    "a shard's meta.json is durable, its shard.digest seal "
                    "not yet written (data/shard_store.py "
                    "write_shard_digest)")


class ShardLayoutError(ResilienceError):
    """A sharded store's on-disk structure contradicts itself: a shard
    missing its meta or seal, a seal that no longer matches the meta
    bytes, or shards disagreeing on activation width/dtype. Typed so the
    manifest step fails loudly instead of aggregating a damaged store."""


def shard_name(i: int) -> str:
    return f"{SHARD_PREFIX}{int(i):03d}"


def shard_dirs(root: str | Path) -> list[Path]:
    """Existing shard directories in shard INDEX order — numeric, not
    lexical: shard_name pads to 3 digits, so at >= 1000 shards a lexical
    sort would interleave ("shard-1000" < "shard-999") and silently break
    the bitwise shard-major concatenation contract."""
    root = Path(root)
    dirs = [p for p in root.glob(f"{SHARD_PREFIX}*") if p.is_dir()]
    return sorted(dirs, key=lambda p: (int(p.name[len(SHARD_PREFIX):])
                                       if p.name[len(SHARD_PREFIX):].isdigit()
                                       else -1, p.name))


def _durable_write(path: Path, text: str) -> None:
    def _once():
        fault_point("shard.write")
        atomic_write_text(path, text)

    retry_io(_once, attempts=3)


def write_shard_digest(shard_dir: str | Path) -> str:
    """Seal a completed shard: record sha256(meta.json bytes) in
    ``shard.digest``. Idempotent — resealing an unchanged shard rewrites
    identical bytes, which is what lets a killed writer's restart
    converge bitwise. The ``shard.finalize`` crash barrier sits at the
    worst instant: meta durable, seal not yet written."""
    shard_dir = Path(shard_dir)
    meta = shard_dir / "meta.json"
    if not meta.exists():
        raise ShardLayoutError(
            f"cannot seal {shard_dir}: no meta.json (unfinalized shard)")
    digest = bytes_sha256(meta.read_bytes())
    crash_barrier("shard.finalize")
    _durable_write(shard_dir / SHARD_DIGEST_NAME,
                   json.dumps({"meta_sha256": digest}, sort_keys=True) + "\n")
    return digest


def read_shard_digest(shard_dir: str | Path) -> Optional[str]:
    try:
        raw = json.loads((Path(shard_dir) / SHARD_DIGEST_NAME).read_text())
        return str(raw["meta_sha256"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def build_store_manifest(root: str | Path,
                         expect_shards: Optional[int] = None) -> dict:
    """Aggregate the sealed shards under ``root`` into ``manifest.json``
    (written LAST, atomically — its presence certifies a complete store).
    Every shard must be sealed and its seal must still match its meta
    bytes; shards must agree on activation width and dtype. Byte-
    deterministic: rebuilding over an unchanged store rewrites identical
    bytes (the chaos-matrix contract)."""
    root = Path(root)
    dirs = shard_dirs(root)
    if not dirs:
        raise ShardLayoutError(f"no {SHARD_PREFIX}* directories in {root}")
    if expect_shards is not None and len(dirs) != int(expect_shards):
        raise ShardLayoutError(
            f"{root}: expected {expect_shards} shard(s), found {len(dirs)}")
    shards = []
    dim: Optional[int] = None
    dtype: Optional[str] = None
    total = 0
    for d in dirs:
        meta_path = d / "meta.json"
        if not meta_path.exists():
            raise ShardLayoutError(f"{d} has no meta.json (unfinalized)")
        meta_bytes = meta_path.read_bytes()
        sealed = read_shard_digest(d)
        if sealed is None:
            raise ShardLayoutError(f"{d} is not sealed (no shard.digest)")
        got = bytes_sha256(meta_bytes)
        if got != sealed:
            raise ShardLayoutError(
                f"{d}: meta.json changed after sealing "
                f"({got[:12]}… != {sealed[:12]}…) — damaged or tampered "
                "shard; re-harvest or re-seal it deliberately")
        meta = json.loads(meta_bytes)
        d_dim = int(meta["activation_dim"])
        d_dtype = str(meta.get("dtype", ""))
        if dim is None:
            dim, dtype = d_dim, d_dtype
        elif (d_dim, d_dtype) != (dim, dtype):
            raise ShardLayoutError(
                f"{d}: activation_dim/dtype {(d_dim, d_dtype)} disagrees "
                f"with earlier shards {(dim, dtype)}")
        n = int(meta["n_chunks"])
        total += n
        shards.append({"name": d.name, "n_chunks": n, "meta_sha256": got})
    manifest = {"version": 1, "kind": "sharded_chunk_store",
                "n_shards": len(shards), "n_chunks": total,
                "activation_dim": dim, "dtype": dtype, "shards": shards}
    _durable_write(root / MANIFEST_NAME,
                   json.dumps(manifest, indent=2, sort_keys=True))
    return manifest


def read_store_manifest(root: str | Path) -> Optional[dict]:
    path = Path(root) / MANIFEST_NAME
    if not path.exists():
        return None
    return json.loads(path.read_text())


class ShardedChunkStore:
    """Reader over a sharded store: one positional chunk index space
    (shard-major, per the manifest's shard order) with the ChunkStore
    contract — so the sweep, eval, and streaming metrics run over a
    sharded store unchanged. Corruption stays shard-local: quarantine
    ledgers, digests, and scrub repairs all live in the owning shard."""

    def __init__(self, root: str | Path, quarantine_corrupt: bool = False,
                 verify_digests: bool = True, io_retries: int = 3):
        from sparse_coding_tpu.data.chunk_store import ChunkStore

        self.folder = Path(root)
        manifest = read_store_manifest(self.folder)
        if manifest is None:
            raise FileNotFoundError(
                f"no {MANIFEST_NAME} in {self.folder} — not a (complete) "
                "sharded store; build_store_manifest aggregates sealed "
                "shards")
        self.meta = manifest
        self.quarantine_corrupt = bool(quarantine_corrupt)
        self.format = "npy"
        self.shards: list = []
        self._offsets: list[int] = []
        off = 0
        for s in manifest["shards"]:
            store = ChunkStore(self.folder / s["name"],
                               quarantine_corrupt=quarantine_corrupt,
                               verify_digests=verify_digests,
                               io_retries=io_retries)
            if store.n_chunks != int(s["n_chunks"]):
                raise ShardLayoutError(
                    f"{store.folder}: meta says {store.n_chunks} chunk(s), "
                    f"manifest says {s['n_chunks']} — stale manifest?")
            self._offsets.append(off)
            off += int(s["n_chunks"])
            self.shards.append(store)
        self.n_total = off
        self.activation_dim = int(manifest["activation_dim"])

    @property
    def n_chunks(self) -> int:
        return self.n_total

    @property
    def quarantined(self) -> set[int]:
        """Global indices of quarantined chunks, unioned from every
        shard's (durable) ledger-backed set."""
        out: set[int] = set()
        for store, off in zip(self.shards, self._offsets):
            out.update(off + li for li in store.quarantined)
        return out

    def _locate(self, i: int):
        i = int(i)
        if not 0 <= i < self.n_total:
            raise IndexError(f"chunk {i} out of range [0, {self.n_total})")
        for store, off in zip(reversed(self.shards),
                              reversed(self._offsets)):
            if i >= off:
                return store, i - off, off
        raise IndexError(i)  # unreachable: offsets start at 0

    def _path(self, i: int) -> Path:
        store, local, _off = self._locate(i)
        return store._path(local)

    def load_chunk(self, i: int, dtype=np.float32) -> np.ndarray:
        store, local, _off = self._locate(i)
        try:
            return store.load_chunk(local, dtype)
        except ChunkCorruptionError as e:
            # re-type with the GLOBAL index (positional consumers and
            # operators see store coordinates; the path still names the
            # shard file)
            raise ChunkCorruptionError(int(i), e.path, e.reason) from e

    def _quarantine(self, err: ChunkCorruptionError) -> None:
        """Route a (global-index) quarantine into the owning shard's
        durable ledger, preserving the shard-local index on disk."""
        store, local, _off = self._locate(err.chunk_index)
        store._quarantine(ChunkCorruptionError(local, err.path, err.reason))

    def chunk_mean(self, i: int = 0) -> np.ndarray:
        return self.load_chunk(i).mean(axis=0)

    @property
    def center(self) -> Optional[np.ndarray]:
        # sharded harvests are written uncentered (each shard writer only
        # ever sees its own rows; a shared translation would need a
        # cross-shard reduction step — not provided yet)
        return None

    def batches(self, chunk: np.ndarray, batch_size: int,
                rng: np.random.Generator,
                drop_last: bool = True) -> Iterator[np.ndarray]:
        from sparse_coding_tpu.data.chunk_store import shuffled_batches

        return shuffled_batches(chunk, batch_size, rng, drop_last)

    # NOTE deliberately no serial_chunk_reader here: the foreground
    # single-stream path (the ingest degrade target) is ingest.py's
    # generic fallback loop — load_chunk + positional-None quarantine +
    # per-chunk beats — which this class satisfies by contract. The flat
    # ChunkStore DOES define one (aliasing its chunk_reader) to keep the
    # native 1-slab readahead; a sharded store has no equivalent slab.

    def chunk_reader(self, indices,
                     dtype=np.float32) -> Iterator[Optional[np.ndarray]]:
        """Multi-stream reader (data/ingest.py): decodes overlap across
        shards — which is exactly where sharding pays, since each
        stream's pread hits a different shard's files."""
        from sparse_coding_tpu.data.ingest import chunk_stream

        return chunk_stream(self, indices, dtype)

    def epoch(self, batch_size: int, rng: np.random.Generator,
              n_repetitions: int = 1,
              dtype=np.float32) -> Iterator[np.ndarray]:
        order = np.concatenate([rng.permutation(self.n_chunks)
                                for _ in range(n_repetitions)])
        for chunk in self.chunk_reader(order, dtype):
            if chunk is None:  # quarantined (quarantine_corrupt=True)
                continue
            yield from self.batches(chunk, batch_size, rng)

    def shard_quarantine_ledgers(self) -> dict[str, dict[int, dict]]:
        """{shard name: its ledger entries} — the operator's one-call view
        of everything the store has durably quarantined."""
        return {s.folder.name: load_quarantine(s.folder)
                for s in self.shards}


def first_sound_chunk(store) -> int:
    """Index of the first chunk the store can actually deliver — skips
    ledger-quarantined positions, so every one-chunk consumer (sweep
    centering, eval batch, baseline fits, centered-experiment PCA) rides
    a scrub-repaired store instead of crashing into the hole the scrub
    just healed. Raises when EVERY chunk is quarantined."""
    quarantined = getattr(store, "quarantined", None) or set()
    try:
        return next(i for i in range(store.n_chunks)
                    if i not in quarantined)
    except StopIteration:
        raise RuntimeError(
            f"{getattr(store, 'folder', store)}: every chunk is "
            "quarantined — nothing sound to read "
            "(see scrub/reharvest.json)") from None


def open_store(folder: str | Path, **kwargs):
    """The one store-opening entry point: a folder with a store-level
    ``manifest.json`` opens as a :class:`ShardedChunkStore`, anything
    else as a flat :class:`ChunkStore` — so sweep/eval/bench code is
    layout-agnostic."""
    folder = Path(folder)
    if (folder / MANIFEST_NAME).exists():
        return ShardedChunkStore(folder, **kwargs)
    from sparse_coding_tpu.data.chunk_store import ChunkStore

    return ChunkStore(folder, **kwargs)
