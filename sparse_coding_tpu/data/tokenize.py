"""Text → packed token rows.

Re-implements the reference's `chunk_and_tokenize` semantics
(reference: activation_dataset.py:136-235, itself adapted from tuned-lens):
documents are tokenized, joined with EOS separators, and packed into
fixed-length rows with no padding; returns the packed [n_rows, max_length]
array plus the nats/byte ratio used for bits-per-byte perplexity conversion.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


def pack_tokens(token_lists: Iterable[list[int]], max_length: int,
                eos_token_id: int) -> np.ndarray:
    """EOS-joined GPT-style packing into [n_rows, max_length] int32 rows.
    Trailing tokens that don't fill a row are dropped (matching the
    reference's drop-last behavior)."""
    stream: list[int] = []
    rows: list[list[int]] = []
    for toks in token_lists:
        stream.extend(toks)
        stream.append(eos_token_id)
        while len(stream) >= max_length:
            rows.append(stream[:max_length])
            stream = stream[max_length:]
    if not rows:
        return np.zeros((0, max_length), np.int32)
    return np.asarray(rows, np.int32)


def chunk_and_tokenize(texts: Iterable[str], tokenizer, max_length: int = 256,
                       eos_token_id: Optional[int] = None,
                       max_docs: Optional[int] = None) -> tuple[np.ndarray, float]:
    """Tokenize + pack a text iterable. Returns (rows, bits_per_byte_ratio)
    where ratio = (total_tokens/total_bytes)/ln(2): multiply a nats-per-token
    loss by it to get bits per byte (reference: activation_dataset.py:223-233)."""
    token_lists = []
    total_tokens = 0
    total_bytes = 0
    for i, text in enumerate(texts):
        if max_docs is not None and i >= max_docs:
            break
        toks = tokenizer.encode(text)
        token_lists.append(toks)
        total_tokens += len(toks)
        total_bytes += len(text.encode("utf-8"))
    import math

    eos = eos_token_id if eos_token_id is not None else tokenizer.eos_token_id
    rows = pack_tokens(token_lists, max_length, eos)
    ratio = total_tokens / max(total_bytes, 1) / math.log(2)
    return rows, ratio


def save_token_dataset(rows: np.ndarray, path: str | Path,
                       metadata: Optional[dict] = None) -> None:
    """Persist packed token rows for reuse across harvesting runs
    (reference: setup_token_data, activation_dataset.py:607)."""
    import json
    from pathlib import Path

    path = Path(path).with_suffix(".npy")  # np.save appends it anyway
    path.parent.mkdir(parents=True, exist_ok=True)
    np.save(path, rows)
    if metadata:
        path.with_suffix(".meta.json").write_text(json.dumps(metadata, indent=2))


def load_token_dataset(path: str | Path) -> np.ndarray:
    from pathlib import Path

    return np.load(Path(path).with_suffix(".npy"))


def load_text_dataset(dataset_name: str, split: str = "train",
                      text_key: str = "text",
                      max_docs: Optional[int] = None) -> list[str]:
    """HF-datasets loader (reference: make_sentence_dataset,
    activation_dataset.py:121-134). Requires a populated local HF cache in
    this zero-egress image."""
    from datasets import load_dataset

    ds = load_dataset(dataset_name, split=split)
    if max_docs is not None:
        ds = ds.select(range(min(max_docs, len(ds))))
    return ds[text_key]
