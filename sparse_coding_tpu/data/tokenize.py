"""Text → packed token rows.

Re-implements the reference's `chunk_and_tokenize` semantics
(reference: activation_dataset.py:136-235, itself adapted from tuned-lens):
documents are tokenized, joined with EOS separators, and packed into
fixed-length rows with no padding; returns the packed [n_rows, max_length]
array plus the nats/byte ratio used for bits-per-byte perplexity conversion.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Optional

import numpy as np


def pack_tokens(token_lists: Iterable[list[int]], max_length: int,
                eos_token_id: int) -> np.ndarray:
    """EOS-joined GPT-style packing into [n_rows, max_length] int32 rows.
    Trailing tokens that don't fill a row are dropped (matching the
    reference's drop-last behavior)."""
    stream: list[int] = []
    rows: list[list[int]] = []
    for toks in token_lists:
        stream.extend(toks)
        stream.append(eos_token_id)
        while len(stream) >= max_length:
            rows.append(stream[:max_length])
            stream = stream[max_length:]
    if not rows:
        return np.zeros((0, max_length), np.int32)
    return np.asarray(rows, np.int32)


def chunk_and_tokenize(texts: Iterable[str], tokenizer, max_length: int = 256,
                       eos_token_id: Optional[int] = None,
                       max_docs: Optional[int] = None) -> tuple[np.ndarray, float]:
    """Tokenize + pack a text iterable. Returns (rows, bits_per_byte_ratio)
    where ratio = (total_tokens/total_bytes)/ln(2): multiply a nats-per-token
    loss by it to get bits per byte (reference: activation_dataset.py:223-233)."""
    token_lists = []
    total_tokens = 0
    total_bytes = 0
    for i, text in enumerate(texts):
        if max_docs is not None and i >= max_docs:
            break
        toks = tokenizer.encode(text)
        token_lists.append(toks)
        total_tokens += len(toks)
        total_bytes += len(text.encode("utf-8"))
    import math

    eos = eos_token_id if eos_token_id is not None else tokenizer.eos_token_id
    rows = pack_tokens(token_lists, max_length, eos)
    ratio = total_tokens / max(total_bytes, 1) / math.log(2)
    return rows, ratio


def save_token_dataset(rows: np.ndarray, path: str | Path,
                       metadata: Optional[dict] = None) -> None:
    """Persist packed token rows for reuse across harvesting runs
    (reference: setup_token_data, activation_dataset.py:607)."""
    import json
    from pathlib import Path

    from sparse_coding_tpu.resilience.atomic import (
        atomic_save_npy,
        atomic_write_text,
    )

    path = Path(path).with_suffix(".npy")  # np.save appends it anyway
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_save_npy(path, rows)
    if metadata:
        atomic_write_text(path.with_suffix(".meta.json"),
                          json.dumps(metadata, indent=2))


def load_token_dataset(path: str | Path) -> np.ndarray:
    from pathlib import Path

    return np.load(Path(path).with_suffix(".npy"))


PILE_SHARD_URL = "https://the-eye.eu/public/AI/pile/train/{shard:02d}.jsonl.zst"
_PILE_NAMES = {"the_pile", "eleutherai/pile", "pile"}


def load_pile_shard(shard: Optional[int] = None,
                    cache_dir: str | Path = "~/.cache/sparse_coding_tpu/pile",
                    max_docs: Optional[int] = None,
                    allow_download: bool = False) -> list[str]:
    """Manual Pile-shard loader — the reference's curl+unzstd fallback when
    the HF pile dataset is unavailable (activation_dataset.py:124-129).
    Looks for `{NN}.jsonl(.zst)` under cache_dir (shard=None uses the lowest
    shard present); with allow_download=True (meaningless in a zero-egress
    image, but the capability exists) fetches shard 0 via curl first. .zst
    decompression streams through the zstandard module — no zstd binary
    needed. Shards are TRAIN-split jsonl with a fixed "text" field."""
    import json as _json

    cache_dir = Path(cache_dir).expanduser()
    if shard is None:
        found = sorted(cache_dir.glob("[0-9][0-9].jsonl*"))
        shard = int(found[0].name[:2]) if found else 0
    plain = cache_dir / f"{shard:02d}.jsonl"
    compressed = cache_dir / f"{shard:02d}.jsonl.zst"
    if not plain.exists() and not compressed.exists():
        if not allow_download:
            raise FileNotFoundError(
                f"no pile shard {shard:02d}.jsonl(.zst) under {cache_dir}; "
                "download one (PILE_SHARD_URL) or pass allow_download=True")
        import subprocess

        cache_dir.mkdir(parents=True, exist_ok=True)
        url = PILE_SHARD_URL.format(shard=shard)
        # download to a temp name: an interrupted transfer must never leave
        # a truncated file where the cache check would trust it
        tmp = compressed.with_suffix(".zst.part")
        subprocess.run(["curl", "-fL", "-o", str(tmp), url], check=True)
        tmp.rename(compressed)

    texts: list[str] = []

    def take(lines) -> list[str]:
        for line in lines:
            if not line.strip():
                continue
            texts.append(_json.loads(line)["text"])
            if max_docs is not None and len(texts) >= max_docs:
                break
        return texts

    if plain.exists():
        with open(plain, encoding="utf-8") as fh:
            return take(fh)
    import io

    import zstandard

    with open(compressed, "rb") as fh:
        stream = zstandard.ZstdDecompressor().stream_reader(fh)
        return take(io.TextIOWrapper(stream, encoding="utf-8"))


def load_text_dataset(dataset_name: str, split: str = "train",
                      text_key: str = "text",
                      max_docs: Optional[int] = None,
                      pile_shard_dir: Optional[str | Path] = None) -> list[str]:
    """HF-datasets loader (reference: make_sentence_dataset,
    activation_dataset.py:121-134). Requires a populated local HF cache in
    this zero-egress image. For pile datasets a manually-downloaded shard
    (load_pile_shard; reference's curl+unzstd path,
    activation_dataset.py:124-129) is the fallback when the HF load fails."""
    from datasets import load_dataset

    try:
        ds = load_dataset(dataset_name, split=split)
    except Exception as hf_err:
        # manual shards are train-split only: never silently substitute
        # train text for another requested split
        if dataset_name.lower() in _PILE_NAMES and split == "train":
            kwargs = {} if pile_shard_dir is None else {"cache_dir": pile_shard_dir}
            try:
                return load_pile_shard(max_docs=max_docs, **kwargs)
            except FileNotFoundError as shard_err:
                raise RuntimeError(
                    f"HF load of {dataset_name} failed ({hf_err}) and the "
                    f"manual-shard fallback found nothing ({shard_err})"
                ) from hf_err
        raise
    if max_docs is not None:
        ds = ds.select(range(min(max_docs, len(ds))))
    return ds[text_key]
