"""Fault-tolerant async device ingest: multi-stream decode → staging →
device transfer.

The sweep hot loop used to feed itself through a 1-slab lookahead
(`ChunkStore.chunk_reader` + `device_prefetch`): one chunk decoding while
one trains, 654 MB/s single-stream host decode (BENCH_SUITE_TPU.json).
Every open ROADMAP front — pod-scale sharded training, roofline kernels,
Group-SAE multi-layer harvests — multiplies chunk volume, so the data
plane must overlap MULTIPLE disk/decode streams with host staging and
``device_put`` and stay alive when any one stream dies. This module is
that pipeline:

- :func:`chunk_stream` — in-order chunk delivery with up to ``streams``
  concurrent decodes in flight (each decode rides the store's own
  hardened read path: native threaded pread, digest verify, bounded
  retry). Corrupt chunks quarantine through the store's durable ledger
  and yield ``None`` in position, so positional consumers stay aligned.
  A stream worker dying mid-epoch (native library failure, injected
  fault, OOM-killed thread) **degrades to the foreground single-stream
  path** for the rest of the sequence — the epoch completes with
  identical data, and the incident is counted (``ingest.degraded``).
- :func:`device_batches` — the host→device stage: double-buffered
  ``device_put`` against an optional sharding, with bounded retry behind
  fault site ``ingest.transfer`` and one ``ingest.transfer`` span per
  drained stream.

Progress contract (docs/ARCHITECTURE.md §11): ``lease.beat()`` fires on
the CONSUMER side at every delivered chunk and staged batch — main-thread
only, so a wedged decode or transfer stops the beats and the supervisor's
hang watchdog catches it (a side-thread heartbeat would beat straight
through the hang).

Fault sites (§10 scheme): ``ingest.decode`` (before each stream decode —
an injected error kills that stream and exercises the degrade path),
``ingest.transfer`` (inside the device-put retry scope). Deterministic
matrix entries live in tests/test_resilience.py.

Import discipline: jax is imported only inside :func:`device_batches`, so
:func:`chunk_stream` (and everything the scrub/shard layers need) stays
usable in jax-free processes.
"""

from __future__ import annotations

import logging
import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, Optional

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.errors import ChunkCorruptionError
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.retry import retry_io

logger = logging.getLogger(__name__)

register_fault_site("ingest.decode",
                    "async ingest stream decode — each background chunk "
                    "read (data/ingest.py chunk_stream), the DECODED chunk "
                    "as payload; an injected error kills the stream and "
                    "forces the degraded single-stream path, an injected "
                    "nan/corrupt payload must fail the finite gate and "
                    "quarantine positionally")
register_fault_site("ingest.transfer",
                    "host->device batch transfer — inside device_batches' "
                    "bounded-retry scope (data/ingest.py)")


def _available_ram_bytes() -> Optional[int]:
    try:
        return (os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):
        return None


def default_streams(chunk_nbytes: Optional[int] = None) -> int:
    """Decode streams that actually pay: bounded by real cores (threaded
    preads on a 1-CPU host just contend — native_io's measurement) AND,
    when the decoded chunk size is known, by free host RAM — the stream
    pipeline holds up to ``streams + 2`` decoded chunks resident
    (lookahead + the one being consumed), and auto mode must never turn
    a sweep that fit the serial reader's two-chunk bound into an OOM
    kill (which would bypass the in-thread degrade path entirely)."""
    from sparse_coding_tpu.data.native_io import _usable_cpus

    n = max(1, min(4, _usable_cpus()))
    if chunk_nbytes:
        avail = _available_ram_bytes()
        if avail is not None:
            # streams + 2 resident decoded chunks must fit in half of
            # currently-available RAM; below that, serial (streams=1,
            # the old two-chunk bound) is the only safe answer
            n = max(1, min(n, avail // (2 * int(chunk_nbytes)) - 2))
    return n


def _decoded_chunk_nbytes(store, indices, dtype) -> Optional[int]:
    """Size of one decoded (cast to ``dtype``) chunk, from the first
    SOUND index's .npy header alone — no payload read; skips ledger-
    quarantined positions so a scrub-repaired hole at the front of a
    shuffled order doesn't silently drop the RAM bound. None when it
    can't be determined cheaply (pt stores, no sound chunk)."""
    try:
        from sparse_coding_tpu.data.native_io import _npy_header

        quarantined = getattr(store, "quarantined", None) or set()
        ci = next(i for i in indices if i not in quarantined)
        _dt, shape, _off = _npy_header(store._path(ci))
        return int(np.prod(shape)) * np.dtype(dtype).itemsize
    except Exception:
        return None


def _serial_chunks(store, indices, dtype) -> Iterator[Optional[np.ndarray]]:
    """The foreground single-stream path over any store: the degrade
    target when a stream worker dies, and the generic fallback for stores
    without their own serial reader. Same contract as chunk_stream:
    positional Nones for quarantined chunks, a lease beat per delivery —
    and the same ``ingest.decode`` span per delivered chunk, so a
    decode-bound serial run (the streams=1 bench baseline, a degraded
    epoch) reports its decode wall instead of a misleading 0.0."""
    serial = getattr(store, "serial_chunk_reader", None)
    if serial is not None:
        it = serial(indices, dtype)
        for ci in indices:
            t0 = obs.monotime()
            try:
                chunk = next(it)
            except StopIteration:  # reader ended early (defensive)
                return
            if chunk is not None:
                obs.record_span("ingest.decode", obs.monotime() - t0,
                                chunk=int(ci), rows=int(chunk.shape[0]))
            yield chunk
        return
    for ci in indices:
        ci = int(ci)
        if store.quarantine_corrupt and ci in store.quarantined:
            # a skipped position is still reader progress: a long run of
            # ledger-known chunks must not starve the hang watchdog
            lease.beat()
            yield None
            continue
        t0 = obs.monotime()
        try:
            chunk = store.load_chunk(ci, dtype)
        except ChunkCorruptionError as e:
            if not store.quarantine_corrupt:
                raise
            store._quarantine(e)
            chunk = None
        if chunk is not None:
            obs.record_span("ingest.decode", obs.monotime() - t0,
                            chunk=ci, rows=int(chunk.shape[0]))
        lease.beat()
        yield chunk


def chunk_stream(store, indices, dtype=np.float32, streams: Optional[int] = None,
                 lookahead: Optional[int] = None) -> Iterator[Optional[np.ndarray]]:
    """Yield in-RAM chunks for ``indices`` in order, with up to ``streams``
    decodes concurrently in flight and at most ``lookahead`` decoded
    chunks resident beyond the one being consumed (the host-RAM bound).

    ``streams <= 1`` — and every ``pt``-format store, whose torch
    deserialization is not a thread-friendly raw read — delegates to the
    store's own single-stream reader, which keeps the native 1-slab
    readahead contract. Otherwise each in-flight decode is one
    ``store.load_chunk`` on a pool thread: digest verification, bounded
    retry, and the durable quarantine ledger all apply unchanged, so this
    pipeline changes WHEN chunks decode, never what arrives."""
    indices = [int(i) for i in indices]
    if streams is None:
        streams = default_streams(_decoded_chunk_nbytes(store, indices,
                                                        dtype))
    if (streams <= 1 or not indices
            or getattr(store, "format", "npy") == "pt"):
        yield from _serial_chunks(store, indices, dtype)
        return
    if lookahead is None:
        lookahead = streams + 1
    lookahead = max(1, int(lookahead))

    def decode(ci: int):
        t0 = obs.monotime()
        chunk = store.load_chunk(ci, dtype)
        out = fault_point("ingest.decode", chunk)
        if out is not chunk:
            # a fired corrupt/nan-mode fault returned a mutated COPY
            # (identity is the fired-vs-clean contract, resilience/
            # faults.py): the injected payload must re-pass the finite
            # gate the store applied to the real bytes — the drill for
            # post-digest in-memory rot reaching the step
            if not np.isfinite(out).all():
                raise ChunkCorruptionError(
                    int(ci), store._path(ci),
                    "non-finite values in decoded rows")
        return out, obs.monotime() - t0

    pool = ThreadPoolExecutor(max_workers=int(streams),
                              thread_name_prefix="ingest")
    pending: deque = deque()  # (chunk_index, future | None) in delivery order
    cursor = 0

    def submit_up_to_lookahead() -> None:
        nonlocal cursor
        while cursor < len(indices) and len(pending) < lookahead:
            ci = indices[cursor]
            if store.quarantine_corrupt and ci in store.quarantined:
                # ledger-known corrupt: never re-pay the read; the None
                # placeholder keeps delivery positional
                pending.append((ci, None))
            else:
                pending.append((ci, pool.submit(decode, ci)))
            cursor += 1

    try:
        submit_up_to_lookahead()
        while pending:
            ci, fut = pending.popleft()
            if fut is None:
                chunk = None
            else:
                try:
                    chunk, dur = fut.result()
                    obs.record_span("ingest.decode", dur, chunk=ci,
                                    rows=int(chunk.shape[0]))
                except ChunkCorruptionError as e:
                    if not store.quarantine_corrupt:
                        raise
                    store._quarantine(e)
                    chunk = None
                except Exception as e:
                    # a stream worker died (not data corruption): finish
                    # the epoch on the foreground single-stream path —
                    # same chunks, same order, the incident counted and
                    # visible in obs.report instead of a dead sweep
                    obs.counter("ingest.degraded").inc()
                    logger.warning(
                        "ingest stream failed on chunk %d (%r); degrading "
                        "to the foreground single-stream path for the "
                        "remaining %d chunk(s)", ci, e,
                        1 + len(pending) + len(indices) - cursor)
                    pool.shutdown(wait=False, cancel_futures=True)
                    # the failed chunk itself retries once, foreground
                    yield from _serial_chunks(store, [ci], dtype)
                    # decodes that already FINISHED in pending are not
                    # thrown away (each can be a multi-GB read): drain
                    # the done prefix in delivery order, then go serial
                    while pending:
                        ci2, fut2 = pending[0]
                        if fut2 is not None and (not fut2.done()
                                                 or fut2.cancelled()):
                            break
                        chunk2 = None
                        if fut2 is not None:
                            try:
                                chunk2, dur2 = fut2.result()
                                obs.record_span("ingest.decode", dur2,
                                                chunk=ci2,
                                                rows=int(chunk2.shape[0]))
                            except ChunkCorruptionError as e2:
                                if not store.quarantine_corrupt:
                                    raise
                                store._quarantine(e2)
                            except Exception:
                                break  # also died: re-reads serially
                        pending.popleft()
                        lease.beat()
                        yield chunk2
                        chunk2 = None
                    rest = [c for c, _ in pending] + indices[cursor:]
                    pending.clear()
                    yield from _serial_chunks(store, rest, dtype)
                    return
            # consumer-side progress beat (main thread — a wedged decode
            # stops these, by design)
            lease.beat()
            yield chunk
            chunk = None  # drop before refilling: the RAM bound
            submit_up_to_lookahead()
    finally:
        # early generator exit must not leave decode threads working for
        # nobody; in-flight loads finish their current pread and exit
        pool.shutdown(wait=False, cancel_futures=True)


def device_batches(batches: Iterable[np.ndarray], sharding=None,
                   buffer_size: int = 2) -> Iterator:
    """Double-buffered host→device stage: batch i+1 transfers while batch
    i computes (``jax.device_put`` is async, so a small lookahead queue
    suffices). THE host→device implementation —
    ``chunk_store.device_prefetch`` delegates here, so every training
    driver shares identical delivery order plus the hardening contract:
    transfers sit behind fault site ``ingest.transfer`` with bounded
    retry, every staged batch beats the lease, and one
    ``ingest.transfer`` span per drained stream records the host-side
    stage wall (dispatch wait, not on-wire time — device_put is async)."""
    import jax
    import jax.numpy as jnp

    queue: deque = deque()
    it = iter(batches)
    stage = {"batches": 0, "wait_s": 0.0}

    def put(x):
        t0 = obs.monotime()

        def _put_once():
            fault_point("ingest.transfer")
            return (jnp.asarray(x) if sharding is None
                    else jax.device_put(x, sharding))

        out = retry_io(_put_once, attempts=3)
        stage["wait_s"] += obs.monotime() - t0
        stage["batches"] += 1
        lease.beat()
        return out

    try:
        try:
            for _ in range(buffer_size):
                queue.append(put(next(it)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(put(next(it)))
            except StopIteration:
                pass
            yield out
    finally:
        if stage["batches"]:
            obs.record_span("ingest.transfer", stage["wait_s"],
                            batches=stage["batches"])
