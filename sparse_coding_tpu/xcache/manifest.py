"""Warmup manifest: the durable record of every program a process compiled.

`cached_compile(..., manifest_desc=...)` appends one descriptor per
distinct program — the serve engine records ``(model, op, bucket)``, the
sweep records its step program's ``(signature, members, batch shape,
dtype, fused path)`` — so a restarted process (and an operator reading
the cache dir) knows the FULL program set a deployment needs warm before
it admits traffic or touches the tunnel. The serve engine's ``warmup()``
walks exactly this set for its registry; the sweep's warm-start
precompiles its config's program before the first chunk is read
(docs/ARCHITECTURE.md §13).

Descriptors are data, not code: a descriptor cannot be compiled by
itself — the owning subsystem maps it back to a function — which is why
this file records *what must be warm* while the executable store holds
*the warm bytes*. Writes are read-modify-write through
``resilience.atomic`` and idempotent (a descriptor is its own key), so
concurrent children of one supervisor can record freely.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Optional


class WarmupManifest:
    """``<cache_dir>/warmup.json``: {descriptor-key: descriptor}."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._lock = threading.Lock()

    def _read(self) -> dict:
        try:
            data = json.loads(self.path.read_text())
            if isinstance(data, dict):
                return data
        except (OSError, ValueError):
            pass
        return {}

    def record(self, desc: dict) -> None:
        """Idempotently add one program descriptor (a plain JSON dict)."""
        key = json.dumps(desc, sort_keys=True, default=str)
        with self._lock:
            data = self._read()
            if data.get(key) == desc:
                return
            data[key] = desc
            from sparse_coding_tpu.resilience.atomic import atomic_write_text

            self.path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_text(self.path,
                              json.dumps(data, sort_keys=True, default=str))

    def descriptors(self, kind: Optional[str] = None) -> list[dict]:
        data = self._read()
        out = [v for v in data.values() if isinstance(v, dict)]
        if kind is not None:
            out = [d for d in out if d.get("kind") == kind]
        return out

    def __len__(self) -> int:
        return len(self._read())
