"""Persistent executable cache + warm start: zero-recompile restarts.

The crash-only architecture (docs/ARCHITECTURE.md §11) makes process
death the normal case — every supervisor step attempt, serving cold
start, and bench invocation is a fresh process — but XLA trace+compile
made every one of those restarts pay seconds-to-minutes of host work
before the first activation moved. This package converts "pays compile N
times" into "pays compile once per program version" (§13):

- :func:`enable` — process-wide bootstrap: turns on JAX's persistent
  compilation cache under ``<cache_dir>/jaxcache`` (min-compile-time and
  min-entry-size floors dropped so every program qualifies), which also
  wires the previously-dormant ``jax.cache_hits`` / ``jax.cache_misses``
  obs probes (obs/jaxprobes.py), and opens the explicit executable store
  + warmup manifest;
- :func:`cached_compile` — the explicit AOT store: serializes compiled
  executables (``jax.experimental.serialize_executable``) keyed on the
  lowered program text + shapes/dtypes + backend + device topology +
  jax/jaxlib versions, behind ``resilience/atomic`` writes, the
  ``xcache.load`` fault site, the ``xcache.store`` crash barrier, and a
  size-capped LRU manifest (xcache/store.py). Loading a stored
  executable performs NO backend compile — a fully warm process reports
  ``jax.compiles == 0`` for its warmed program set;
- the **warmup manifest** (xcache/manifest.py) — the record of every
  program the serve engine / sweep compiled, so a restarted process
  precompiles-or-loads the full set before admitting traffic or
  touching the tunnel.

Keying: two cache layers, one invalidation story. The jax persistent
cache keys on the XLA computation + compile options + platform version
(jax's own `cache_key`); the executable store keys on
:func:`program_key` = sha256(lowered StableHLO text ‖ backend ‖ device
kinds+count ‖ process count ‖ jax ‖ jaxlib ‖ XLA_FLAGS ‖ caller salt).
Shapes, dtypes, donation, and sharding are all part of the lowered text,
so any change to what would RUN yields a different key — the cache can
change only *when* a program compiles, never *what* executes
(tests/test_tpu_lowering.py proves the lowered HLO is bitwise identical
with the cache enabled). Backend is in both keys, so one shared cache
dir serves TPU runs and their degrade-to-CPU retries without collision.

Everything degrades: no cache dir → plain ``lowered.compile()``; a
runtime that cannot serialize → compile proceeds, entry skipped; a
corrupt entry → fresh compile. Caching is never on the failure path of
the workload it accelerates.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from pathlib import Path
from typing import Any, Optional, Sequence

from sparse_coding_tpu.obs import get_registry, monotime
from sparse_coding_tpu.xcache.manifest import WarmupManifest
from sparse_coding_tpu.xcache.store import ExecutableStore

logger = logging.getLogger(__name__)

ENV_DIR = "SPARSE_CODING_XCACHE_DIR"

# jax config knobs enable() flips; old values retained for disable()
_JAX_CACHE_OPTIONS = (
    ("jax_compilation_cache_dir", None),  # filled with <cache_dir>/jaxcache
    ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ("jax_persistent_cache_min_entry_size_bytes", -1),
)


class XCache:
    """One enabled cache: directory + executable store + warmup manifest."""

    def __init__(self, cache_dir: str | Path,
                 cap_bytes: Optional[int] = None):
        self.cache_dir = Path(cache_dir)
        self.cache_dir.mkdir(parents=True, exist_ok=True)
        self.store = ExecutableStore(self.cache_dir, cap_bytes=cap_bytes)
        self.warmup = WarmupManifest(self.cache_dir / "warmup.json")


_active: Optional[XCache] = None
_saved_config: list[tuple[str, Any]] = []
_lock = threading.Lock()


def default_cache_dir() -> Path:
    """``SPARSE_CODING_XCACHE_DIR``, else the user cache dir — shared
    across invocations on one machine, which is the point: a restarted
    bench/serve/sweep finds the previous process's executables."""
    env = os.environ.get(ENV_DIR, "").strip()
    if env:
        return Path(env)
    base = os.environ.get("XDG_CACHE_HOME", "").strip() or str(
        Path.home() / ".cache")
    return Path(base) / "sparse_coding_tpu" / "xcache"


def _reset_jax_cache() -> None:
    """Drop jax's in-memory handle on the persistent cache so a cache-dir
    change takes effect mid-process (tests switch dirs; production
    enables once). Best-effort across jax versions."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API, absence is fine
        pass


def enable(cache_dir: str | Path | None = None,
           cap_bytes: Optional[int] = None) -> XCache:
    """Turn on both cache layers for this process (idempotent per dir).

    Sets jax's persistent compilation cache to ``<cache_dir>/jaxcache``
    with the size/time floors dropped (our sweep/serve programs are many
    and individually small — exactly the shape the floors exclude),
    installs the obs jax probes so ``jax.cache_hits``/``jax.cache_misses``
    fire, and opens the executable store for :func:`cached_compile`."""
    global _active
    cache_dir = Path(cache_dir) if cache_dir is not None \
        else default_cache_dir()
    with _lock:
        if _active is not None and _active.cache_dir == cache_dir:
            return _active
        # build the store FIRST (its mkdir is the likely failure on a bad
        # cache dir): enable() must be all-or-nothing — a failed enable
        # must not leave jax's persistent cache pointed at an unusable
        # path while enabled() reports False
        cache = XCache(cache_dir, cap_bytes=cap_bytes)
        import jax

        for name, value in _JAX_CACHE_OPTIONS:
            if name == "jax_compilation_cache_dir":
                value = str(cache_dir / "jaxcache")
            try:
                if not any(n == name for n, _ in _saved_config):
                    _saved_config.append((name, getattr(jax.config, name)))
                jax.config.update(name, value)
            except (AttributeError, KeyError) as e:
                logger.warning("xcache: jax option %s unavailable (%s)",
                               name, e)
        _reset_jax_cache()
        from sparse_coding_tpu.obs import install_jax_probes

        install_jax_probes()  # wires /jax/compilation_cache/* -> registry
        _active = cache
        return _active


def enable_from_env() -> Optional[XCache]:
    """Enable iff ``SPARSE_CODING_XCACHE_DIR`` is set (how supervisor
    step children opt in — the supervisor propagates one shared dir per
    run); no-op returning None otherwise."""
    env = os.environ.get(ENV_DIR, "").strip()
    if not env:
        return None
    return enable(env)


def disable() -> None:
    """Restore the pre-:func:`enable` jax config and drop the active
    cache (tests; a production process enables once and exits)."""
    global _active
    with _lock:
        if _active is None and not _saved_config:
            return
        import jax

        while _saved_config:
            name, value = _saved_config.pop()
            try:
                jax.config.update(name, value)
            except (AttributeError, KeyError):
                pass
        _reset_jax_cache()
        _active = None


def enabled() -> bool:
    return _active is not None


def active_cache() -> Optional[XCache]:
    return _active


def _env_fingerprint() -> str:
    """Everything OUTSIDE the lowered program that can change the
    executable: backend, device topology, process count, jax/jaxlib
    versions, XLA flags. Per-backend keying is what lets one cache dir
    serve a TPU run and its degrade-to-CPU retry without collision."""
    import jax
    import jaxlib

    devs = jax.devices()
    return "|".join([
        jax.default_backend(),
        ",".join(sorted({d.device_kind for d in devs})),
        str(len(devs)), str(jax.process_count()),
        jax.__version__, jaxlib.__version__,
        os.environ.get("XLA_FLAGS", ""),
    ])


def program_key(lowered, extra: Any = None) -> str:
    """The executable-store key of one lowered program (§13 key schema):
    sha256 over the lowered StableHLO text (shapes, dtypes, donation and
    sharding included by construction), the environment fingerprint, and
    the caller's extra salt."""
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    h.update(_env_fingerprint().encode())
    if extra is not None:
        h.update(repr(extra).encode())
    return h.hexdigest()


def cached_compile(fn, args: Sequence[Any], *, key: Any = None,
                   label: str = "", manifest_desc: Optional[dict] = None,
                   jit_kwargs: Optional[dict] = None):
    """Compile-or-load the executable of ``fn`` for ``args``.

    ``fn`` is a function (jitted with ``jit_kwargs``) or an
    already-jitted callable; ``args`` are the lowering arguments —
    concrete arrays and/or ``jax.ShapeDtypeStruct`` specs. Always traces
    and lowers (cheap, and the lowered text IS the cache key); with a
    cache enabled, a stored entry is deserialized instead of compiled —
    no backend compile event fires on a hit — and a fresh compile is
    serialized back behind the ``xcache.store`` crash barrier. Without
    :func:`enable`, this is exactly ``jit(fn).lower(*args).compile()``.

    ``manifest_desc`` (a JSON dict) records the program in the warmup
    manifest so restarts know the full warm set (xcache/manifest.py)."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn,
                                                     **(jit_kwargs or {}))
    lowered = jitted.lower(*args)
    cache = _active
    if cache is None:
        return lowered.compile()
    if manifest_desc is not None:
        cache.warmup.record(manifest_desc)
    k = program_key(lowered, extra=key)
    compiled = cache.store.load(k, lowered.in_tree, lowered.out_tree)
    if compiled is not None:
        return compiled
    reg = get_registry()
    t0 = monotime()
    compiled = lowered.compile()
    dt = monotime() - t0
    reg.counter("xcache.misses").inc()
    reg.histogram("xcache.compile_s").observe(dt)
    cache.store.put(k, compiled, compile_s=dt, label=label)
    return compiled


__all__ = [
    "ENV_DIR",
    "ExecutableStore",
    "WarmupManifest",
    "XCache",
    "active_cache",
    "cached_compile",
    "default_cache_dir",
    "disable",
    "enable",
    "enable_from_env",
    "enabled",
    "program_key",
]
