"""Persistent executable store: crash-safe serialized XLA programs.

One directory of self-validating entry files plus an LRU manifest. An
entry is the `jax.experimental.serialize_executable` payload of one
compiled program wrapped in a small header (payload sha256, the compile
seconds it replaces, jax/jaxlib versions, a human label) — so a loaded
entry proves its own integrity before a byte of it reaches the runtime,
and the report can say how many compile-seconds a warm start skipped.

Durability rules (docs/ARCHITECTURE.md §13):

- entry writes go through :func:`resilience.atomic.atomic_write_bytes`
  (tmp + fsync + rename): a reader — possibly another supervisor child
  sharing the cache dir — can never observe a half-written entry;
- the worst instant is *entry durable, manifest not yet updated*: the
  named crash barrier ``xcache.store`` sits exactly there, and the chaos
  matrix SIGKILLs a real child at it (tests/test_pipeline_chaos.py). An
  orphaned entry is harmless — the manifest reconciles against the
  directory on its next write, and loads never consult the manifest;
- every load sits behind the named fault site ``xcache.load`` (error and
  corrupt modes, tests/test_resilience.py): a torn, bit-flipped, or
  version-stale entry is detected (header parse / digest / deserialize),
  counted in ``xcache.errors``, deleted, and the caller falls back to a
  fresh compile — a bad cache entry can never poison a run;
- eviction is size-capped LRU over the manifest's lamport clock (no wall
  clock: two processes sharing a cache dir must not fight over mtimes),
  rewritten atomically.

The manifest is bookkeeping, never ground truth: entry files are. A lost
manifest update (two processes racing the read-modify-write) costs at
most one stale LRU position, not correctness.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.obs import get_registry
from sparse_coding_tpu.resilience.atomic import atomic_write_bytes, atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site

logger = logging.getLogger(__name__)

register_fault_site("xcache.load",
                    "executable-cache entry load (xcache/store.py) — "
                    "corrupt/stale entries fall back to a fresh compile")
register_crash_site("xcache.store",
                    "executable-cache entry durable, LRU manifest not yet "
                    "updated (xcache/store.py)")

LOAD_FAULT_SITE = "xcache.load"
STORE_CRASH_SITE = "xcache.store"

ENV_CAP_BYTES = "SPARSE_CODING_XCACHE_CAP_BYTES"
DEFAULT_CAP_BYTES = 2 << 30  # 2 GiB of serialized executables

_HEADER_LEN = struct.Struct(">I")


class EntryCorruptError(Exception):
    """A cache entry failed its self-validation (header parse or payload
    digest). Internal to the store — callers see a fallback compile."""


def _pack_entry(payload: bytes, header: dict) -> bytes:
    header = dict(header)
    header["sha256"] = hashlib.sha256(payload).hexdigest()
    hj = json.dumps(header, sort_keys=True).encode()
    return _HEADER_LEN.pack(len(hj)) + hj + payload


def _unpack_entry(raw: bytes) -> tuple[dict, bytes]:
    if len(raw) < _HEADER_LEN.size:
        raise EntryCorruptError("entry shorter than its header-length field")
    (hlen,) = _HEADER_LEN.unpack(raw[:_HEADER_LEN.size])
    body = raw[_HEADER_LEN.size:]
    if hlen > len(body):
        raise EntryCorruptError("entry header length exceeds file size")
    try:
        header = json.loads(body[:hlen])
    except ValueError as e:
        raise EntryCorruptError(f"entry header is not JSON: {e}") from e
    payload = body[hlen:]
    want = header.get("sha256", "")
    if hashlib.sha256(payload).hexdigest() != want:
        raise EntryCorruptError("payload digest mismatch")
    return header, payload


class ExecutableStore:
    """The on-disk executable cache under ``<cache_dir>/exec``."""

    def __init__(self, cache_dir: str | Path,
                 cap_bytes: Optional[int] = None):
        self.cache_dir = Path(cache_dir)
        self.exec_dir = self.cache_dir / "exec"
        self.manifest_path = self.cache_dir / "manifest.json"
        self.exec_dir.mkdir(parents=True, exist_ok=True)
        if cap_bytes is None:
            cap_bytes = int(os.environ.get(ENV_CAP_BYTES,
                                           str(DEFAULT_CAP_BYTES)))
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()

    # -- entry I/O -----------------------------------------------------------

    def entry_path(self, key: str) -> Path:
        return self.exec_dir / f"{key}.bin"

    def load(self, key: str, in_tree, out_tree):
        """The deserialized executable for ``key``, or None when the entry
        is absent OR unusable (corrupt, stale, wrong runtime) — the caller
        then compiles fresh; a bad entry is counted, logged, and deleted."""
        path = self.entry_path(key)
        try:
            raw = path.read_bytes()
        except OSError:
            return None
        reg = get_registry()
        try:
            # the fault site covers the whole load; corrupt-mode flips a
            # payload byte, which the digest check below must catch
            raw = fault_point(LOAD_FAULT_SITE, raw)
            header, payload = _unpack_entry(raw)
            from jax.experimental import serialize_executable as se

            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — every failure means recompile
            reg.counter("xcache.errors").inc()
            logger.warning("xcache: entry %s unusable (%s: %s); falling "
                           "back to a fresh compile", key[:12],
                           type(e).__name__, e)
            path.unlink(missing_ok=True)
            self._forget(key)
            return None
        reg.counter("xcache.hits").inc()
        # the seconds this load replaced, as recorded at store time — the
        # report sums the histogram into "estimated compile seconds saved"
        reg.histogram("xcache.saved_s").observe(
            float(header.get("compile_s", 0.0)))
        self._touch(key)
        return compiled

    def put(self, key: str, compiled, compile_s: float,
            label: str = "") -> bool:
        """Serialize and persist one compiled executable. Returns False
        (counting ``xcache.errors``) when this runtime cannot serialize —
        the program still runs; only the NEXT process recompiles."""
        reg = get_registry()
        try:
            from jax.experimental import serialize_executable as se

            payload, _, _ = se.serialize(compiled)
        except Exception as e:  # noqa: BLE001 — caching is never fatal
            reg.counter("xcache.errors").inc()
            logger.warning("xcache: cannot serialize %s (%s: %s); entry "
                           "skipped", label or key[:12], type(e).__name__, e)
            return False
        import jax
        import jaxlib

        blob = _pack_entry(payload, {
            "compile_s": round(float(compile_s), 6), "label": label,
            "jax": jax.__version__, "jaxlib": jaxlib.__version__})
        atomic_write_bytes(self.entry_path(key), blob)
        # the worst instant: the entry is durable, the manifest is not — a
        # kill here leaves an orphan entry the next manifest write adopts
        # (chaos matrix case; tests/test_pipeline_chaos.py)
        crash_barrier(STORE_CRASH_SITE)
        self._record(key, size=len(blob), compile_s=float(compile_s),
                     label=label)
        return True

    def keys(self) -> list[str]:
        return sorted(p.stem for p in self.exec_dir.glob("*.bin"))

    def verify(self) -> dict[str, bool]:
        """Self-validate every entry on disk: {key: digest_ok}. Used by
        the chaos suite to prove a kill can never leave a torn entry."""
        out = {}
        for path in sorted(self.exec_dir.glob("*.bin")):
            try:
                _unpack_entry(path.read_bytes())
                out[path.stem] = True
            except EntryCorruptError:
                out[path.stem] = False
        return out

    # -- LRU manifest --------------------------------------------------------

    def _read_manifest(self) -> dict:
        try:
            data = json.loads(self.manifest_path.read_text())
            if isinstance(data, dict) and isinstance(data.get("entries"),
                                                     dict):
                return data
        except (OSError, ValueError):
            pass
        return {"clock": 0, "entries": {}}

    def _write_manifest(self, data: dict) -> None:
        # rename-atomic but fsync-free: the manifest is reconciled-from-
        # directory bookkeeping (LRU positions), so losing a write to a
        # power cut costs nothing — while a warm start performs one
        # manifest touch per loaded program, where per-write fsyncs
        # would eat the very latency the cache exists to remove
        atomic_write_text(self.manifest_path,
                          json.dumps(data, sort_keys=True), fsync=False)

    def _reconcile(self, data: dict) -> None:
        """Make the manifest agree with the directory: drop entries whose
        file vanished (another process evicted), adopt orphan files (a
        crash between entry write and manifest update — the
        ``xcache.store`` barrier instant)."""
        present = {p.stem: p for p in self.exec_dir.glob("*.bin")}
        entries = data["entries"]
        for key in [k for k in entries if k not in present]:
            del entries[key]
        for key, path in present.items():
            if key not in entries:
                try:
                    size = path.stat().st_size
                except OSError:
                    continue
                entries[key] = {"size": size, "compile_s": 0.0,
                                "label": "", "last_used": data["clock"]}

    def _mutate_manifest(self, fn) -> None:
        with self._lock:
            data = self._read_manifest()
            data["clock"] = int(data.get("clock", 0)) + 1
            self._reconcile(data)
            fn(data)
            self._write_manifest(data)

    def _record(self, key: str, size: int, compile_s: float,
                label: str) -> None:
        def update(data):
            data["entries"][key] = {"size": int(size),
                                    "compile_s": round(compile_s, 6),
                                    "label": label,
                                    "last_used": data["clock"]}
            self._evict(data, keep=key)

        self._mutate_manifest(update)

    def _touch(self, key: str) -> None:
        def update(data):
            if key in data["entries"]:
                data["entries"][key]["last_used"] = data["clock"]

        self._mutate_manifest(update)

    def _forget(self, key: str) -> None:
        def update(data):
            data["entries"].pop(key, None)

        self._mutate_manifest(update)

    def _evict(self, data: dict, keep: str) -> None:
        entries = data["entries"]
        total = sum(int(e.get("size", 0)) for e in entries.values())
        victims = sorted((k for k in entries if k != keep),
                         key=lambda k: entries[k].get("last_used", 0))
        reg = get_registry()
        for key in victims:
            if total <= self.cap_bytes:
                break
            total -= int(entries[key].get("size", 0))
            del entries[key]
            self.entry_path(key).unlink(missing_ok=True)
            reg.counter("xcache.evictions").inc()

    def manifest(self) -> dict:
        with self._lock:
            return self._read_manifest()
