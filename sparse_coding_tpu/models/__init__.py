from sparse_coding_tpu.models import learned_dict as learned_dict
from sparse_coding_tpu.models import signatures as signatures
from sparse_coding_tpu.models import sae as sae
from sparse_coding_tpu.models import topk as topk
# imported for their @register side effects so the string signature registry
# covers the full model zoo
from sparse_coding_tpu.models import combination as combination
from sparse_coding_tpu.models import direct_coef as direct_coef
from sparse_coding_tpu.models import ica as ica
from sparse_coding_tpu.models import lista as lista
from sparse_coding_tpu.models import nmf as nmf
from sparse_coding_tpu.models import pca as pca
from sparse_coding_tpu.models import positive as positive
from sparse_coding_tpu.models import rica as rica
from sparse_coding_tpu.models import semilinear as semilinear
from sparse_coding_tpu.models.learned_dict import (
    AddedNoise,
    Identity,
    IdentityPositive,
    IdentityReLU,
    LearnedDict,
    RandomDict,
    ReverseSAE,
    Rotation,
    TiedCenteredSAE,
    TiedSAE,
    TopKLearnedDict,
    UntiedSAE,
)
from sparse_coding_tpu.models.sae import (
    FunctionalMaskedSAE,
    FunctionalMaskedTiedSAE,
    FunctionalReverseSAE,
    FunctionalSAE,
    FunctionalThresholdingSAE,
    FunctionalTiedCenteredSAE,
    FunctionalTiedSAE,
    ThresholdingSAE,
)
from sparse_coding_tpu.models.topk import TopKEncoder
