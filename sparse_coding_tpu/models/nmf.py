"""NMF dictionary (reference: autoencoders/nmf.py).

Host-side sklearn fit with the reference's shift-to-nonnegative handling
(nmf.py:44-54); encode solves the NMF transform on host (sklearn), while the
fitted components live in a JAX pytree for device-side decode/eval.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.models.learned_dict import LearnedDict, TopKLearnedDict

Array = jax.Array


class NMFEncoder(LearnedDict):
    components: Array  # [n, d]
    shift: Array  # scalar
    _nmf: Any = struct.field(pytree_node=False, default=None)  # fitted sklearn model

    @classmethod
    def train(cls, dataset: Array, n_components: Optional[int] = None,
              max_iter: int = 400) -> "NMFEncoder":
        from sklearn.decomposition import NMF

        x = np.asarray(jax.device_get(dataset), np.float64)
        shift = min(float(x.min()), 0.0)  # shift data to nonneg (nmf.py:44-47)
        x = x - shift
        nmf = NMF(n_components=n_components, max_iter=max_iter, init="nndsvda")
        nmf.fit(x)
        return cls(components=jnp.asarray(nmf.components_, jnp.float32),
                   shift=jnp.asarray(shift, jnp.float32), _nmf=nmf)

    def encode(self, x: Array) -> Array:
        if self._nmf is None:
            raise RuntimeError("NMFEncoder needs its fitted sklearn model to encode")
        x_np = np.asarray(jax.device_get(x), np.float64)
        x_np = np.clip(x_np - float(self.shift), 0.0, None)
        c = self._nmf.transform(x_np)
        return jnp.asarray(c, jnp.float32)

    def get_learned_dict(self) -> Array:
        # NOTE (as the reference warns, nmf.py:60-62): H isn't recoverable by
        # multiplying with the dictionary; this is for geometry metrics only.
        return self.components

    def to_topk_dict(self, sparsity: int) -> TopKLearnedDict:
        return TopKLearnedDict(dictionary=self.components, k=sparsity)
