"""Direct coefficient optimization (ISTA/FISTA) over a fixed dictionary.

The reference *imports* `autoencoders.direct_coef_search.DirectCoefOptimizer`
(big_sweep_experiments.py:13) but the module does not exist in the repo —
SURVEY.md §2.1 flags it as a missing capability. This implements the implied
baseline: sparse codes obtained by directly minimizing
½‖x − cD‖² + α‖c‖₁ with FISTA, entirely on device via lax.scan (no learned
encoder). Useful as an upper bound on what any amortized encoder can achieve
with the same dictionary.
"""

from __future__ import annotations

import flax.struct as struct
import jax
import jax.numpy as jnp

from sparse_coding_tpu.models.learned_dict import LearnedDict, normalize_rows

Array = jax.Array


def _soft_threshold(x: Array, t: Array) -> Array:
    return jnp.sign(x) * jax.nn.relu(jnp.abs(x) - t)


def fista_codes(dictionary: Array, x: Array, l1_alpha: float,
                n_iters: int = 50, nonneg: bool = False) -> Array:
    """FISTA for c* = argmin ½‖x − cD‖² + α‖c‖₁, D row-normalized [n, d].

    Step size 1/L with L = ‖DDᵀ‖₂ estimated by power iteration (cheap, done
    in-trace)."""
    d = normalize_rows(dictionary)
    gram = d @ d.T  # [n, n]

    # power iteration for the Lipschitz constant
    def power_body(v, _):
        v = gram @ v
        return v / (jnp.linalg.norm(v) + 1e-8), None

    v0 = jnp.ones((gram.shape[0],)) / jnp.sqrt(gram.shape[0])
    v, _ = jax.lax.scan(power_body, v0, None, length=16)
    lipschitz = jnp.maximum(v @ gram @ v, 1e-6)
    step = 1.0 / lipschitz
    thresh = l1_alpha * step

    xd = x @ d.T  # [b, n]

    def prox(z):
        out = _soft_threshold(z, thresh)
        return jax.nn.relu(out) if nonneg else out

    def body(carry, _):
        c, y, t = carry
        grad = y @ gram - xd
        c_new = prox(y - step * grad)
        t_new = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        y_new = c_new + ((t - 1.0) / t_new) * (c_new - c)
        return (c_new, y_new, t_new), None

    c0 = jnp.zeros_like(xd)
    (c, _, _), _ = jax.lax.scan(body, (c0, c0, jnp.asarray(1.0)), None,
                                length=n_iters)
    return c


class DirectCoefOptimizer(LearnedDict):
    """Inference dict whose encode runs FISTA to convergence."""

    dictionary: Array
    l1_alpha: float = struct.field(pytree_node=False, default=1e-3)
    n_iters: int = struct.field(pytree_node=False, default=50)
    nonneg: bool = struct.field(pytree_node=False, default=True)

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        return fista_codes(self.dictionary, x, self.l1_alpha,
                           n_iters=self.n_iters, nonneg=self.nonneg)
