"""Reconstruction ICA (reference: autoencoders/rica.py, after Le et al.,
http://ai.stanford.edu/~quocle/LeKarpenkoNgiamNg.pdf): tied linear code with
smooth-L1 (or L1) sparsity — expressed as a DictSignature so it trains in the
same vmapped ensembles as the SAEs (the reference leaves it a torch nn.Module
with a separate train_batch loop)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.sae import _glorot, _mse
from sparse_coding_tpu.models.signatures import make_aux, register

Array = jax.Array


def _smooth_l1(c: Array, beta: float = 1.0) -> Array:
    """Huber/smooth-L1 against zero (reference: rica.py:36 uses
    F.smooth_l1_loss(c, 0), elementwise mean)."""
    absc = jnp.abs(c)
    return jnp.mean(jnp.where(absc < beta, 0.5 * c * c / beta, absc - 0.5 * beta))


@register("rica")
class RICA:
    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             sparsity_coef: float = 0.0, sparsity_loss: str = "smooth_l1",
             dtype=jnp.float32):
        params = {"weights": _glorot(key, (n_dict_components, activation_size), dtype)}
        buffers = {"sparsity_coef": jnp.asarray(sparsity_coef, dtype),
                   "sparsity_loss": sparsity_loss}
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        w = params["weights"]
        c = batch @ w.T
        x_hat = c @ w
        l_reconstruction = _mse(x_hat, batch)
        if buffers["sparsity_loss"] == "l1":
            l_sparsity = jnp.mean(jnp.abs(c))
        else:
            l_sparsity = _smooth_l1(c)
        total = l_reconstruction + buffers["sparsity_coef"] * l_sparsity
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_sparsity": l_sparsity}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> "RICADict":
        return RICADict(weights=params["weights"])


class RICADict(ld.LearnedDict):
    weights: Array

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.weights)

    def encode(self, x: Array) -> Array:
        return x @ self.weights.T
