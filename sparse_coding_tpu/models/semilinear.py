"""Semi-linear SAE: 2-layer ReLU MLP encoder, normalized linear decoder
(reference: autoencoders/semilinear_autoencoder.py:31-83)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.sae import _glorot, _l1, _mse, _normalize
from sparse_coding_tpu.models.signatures import make_aux, register

Array = jax.Array


@register("semilinear_sae")
class SemiLinearSAE:
    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, hidden_size: int | None = None, dtype=jnp.float32):
        hidden = hidden_size or n_dict_components
        k1, k2, k_dec = jax.random.split(key, 3)
        params = {
            "enc0_w": _glorot(k1, (hidden, activation_size), dtype),
            "enc0_b": jnp.zeros((hidden,), dtype),
            "enc1_w": _glorot(k2, (n_dict_components, hidden), dtype),
            "enc1_b": jnp.zeros((n_dict_components,), dtype),
            "decoder": _glorot(k_dec, (n_dict_components, activation_size), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype)}
        return params, buffers

    @staticmethod
    def encode(params, batch: Array) -> Array:
        h = jax.nn.relu(batch @ params["enc0_w"].T + params["enc0_b"])
        return jax.nn.relu(h @ params["enc1_w"].T + params["enc1_b"])

    @staticmethod
    def loss(params, buffers, batch: Array):
        c = SemiLinearSAE.encode(params, batch)
        dictionary = _normalize(params["decoder"])
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> "SemiLinearDict":
        return SemiLinearDict(enc0_w=params["enc0_w"], enc0_b=params["enc0_b"],
                              enc1_w=params["enc1_w"], enc1_b=params["enc1_b"],
                              dictionary=params["decoder"])


class SemiLinearDict(ld.LearnedDict):
    enc0_w: Array
    enc0_b: Array
    enc1_w: Array
    enc1_b: Array
    dictionary: Array

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        h = jax.nn.relu(x @ self.enc0_w.T + self.enc0_b)
        return jax.nn.relu(h @ self.enc1_w.T + self.enc1_b)
