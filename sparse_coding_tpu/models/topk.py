"""k-sparse (TopK) encoder.

Reference: autoencoders/topk_encoder.py — tied dictionary, codes are the
ReLU'd top-k projection scores, trained with MSE only (no L1 term). Because
`k` is a static shape parameter, members with different k cannot share one
vmapped ensemble; the engine buckets them per-k instead (the reference uses a
`no_stacking` Python loop, ensemble.py:100-116).

On TPU, `jax.lax.top_k` lowers to an efficient sort on the VPU and the scatter
is a one-hot matmul-free `.at[].set` — still dominated by the two MXU matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.sae import _glorot, _normalize
from sparse_coding_tpu.models.signatures import make_aux, register

Array = jax.Array


def topk_sparsify(scores: Array, k: int) -> Array:
    """Keep the top-k entries of each row (ReLU'd), zero the rest
    (reference: topk_encoder.py:20-27)."""
    topk_vals, topk_idx = jax.lax.top_k(scores, k)
    batch_idx = jnp.arange(scores.shape[0])[:, None]
    out = jnp.zeros_like(scores)
    return out.at[batch_idx, topk_idx].set(jax.nn.relu(topk_vals))


@register("topk")
class TopKEncoder:
    """Trainable top-k tied SAE (reference: topk_encoder.py:10-40)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             k: int, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
        }
        # k is static (shapes depend on it): kept in buffers as a plain int so
        # it partitions ensembles into same-k buckets rather than being traced.
        buffers = {"k": k}
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["encoder"])
        scores = batch @ dictionary.T
        c = topk_sparsify(scores, buffers["k"])
        x_hat = c @ dictionary
        l_reconstruction = jnp.mean(jnp.square(x_hat - batch))
        return l_reconstruction, make_aux(
            {"loss": l_reconstruction, "l_reconstruction": l_reconstruction}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.TopKLearnedDict:
        return ld.TopKLearnedDict(dictionary=params["encoder"], k=int(buffers["k"]))
