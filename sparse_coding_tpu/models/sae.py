"""Trainable SAE families.

TPU-native re-implementations of the reference's functional SAE zoo
(reference: autoencoders/sae_ensemble.py): pure init/loss/export functions over
explicit pytrees. Loss semantics match the reference exactly —
MSE(x̂, x) + l1_alpha·mean‖c‖₁ (+ bias_decay·‖b‖₂), decoder row-normalized
inside the loss — so training curves are comparable; the mechanics (jax.grad
through vmap, no in-place ops) are idiomatic JAX.

All matmuls are written on [batch, d] × [n, d] operands so XLA tiles them onto
the MXU; params default to float32 with bfloat16 activations handled upstream.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.signatures import AuxData, make_aux, register

Array = jax.Array

_EPS = 1e-8


def _glorot(key: Array, shape, dtype) -> Array:
    """Xavier-uniform init matching torch.nn.init.xavier_uniform_ on [n, d]
    (reference: sae_ensemble.py:27)."""
    fan_out, fan_in = shape
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-limit, maxval=limit)


def _normalize(d: Array) -> Array:
    return d / jnp.clip(jnp.linalg.norm(d, axis=-1, keepdims=True), _EPS)


def _mse(x_hat: Array, x: Array) -> Array:
    return jnp.mean(jnp.square(x_hat - x))


def _l1(c: Array) -> Array:
    return jnp.mean(jnp.sum(jnp.abs(c), axis=-1))


def _safe_norm(v: Array) -> Array:
    """L2 norm with a finite gradient at 0 (jnp.linalg.norm's grad at the
    zero vector is NaN, which would poison grads even when bias_decay=0)."""
    return jnp.sqrt(jnp.sum(jnp.square(v)) + _EPS * _EPS)


@register("sae")
class FunctionalSAE:
    """Untied ReLU SAE (reference: sae_ensemble.py:13-78)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, bias_decay: float = 0.0, dtype=jnp.float32):
        k_enc, k_dec = jax.random.split(key)
        params = {
            "encoder": _glorot(k_enc, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
            "decoder": _glorot(k_dec, (n_dict_components, activation_size), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
        }
        return params, buffers

    @staticmethod
    def encode(params, buffers, batch: Array) -> Array:
        return jax.nn.relu(batch @ params["encoder"].T + params["encoder_bias"])

    @staticmethod
    def loss(params, buffers, batch: Array):
        c = FunctionalSAE.encode(params, buffers, batch)
        dictionary = _normalize(params["decoder"])
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_norm(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_l1": l_l1, "l_bias_decay": l_bias_decay}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.UntiedSAE:
        return ld.UntiedSAE(encoder=params["encoder"],
                            encoder_bias=params["encoder_bias"],
                            dictionary=params["decoder"])


@register("tied_sae")
class FunctionalTiedSAE:
    """Tied SAE: encoder is the row-normalized dictionary; optional fixed
    whitening-centering transform (reference: sae_ensemble.py:81-162)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, bias_decay: float = 0.0,
             rotation: Optional[Array] = None, translation: Optional[Array] = None,
             scaling: Optional[Array] = None, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "center_rot": rotation if rotation is not None else jnp.eye(activation_size, dtype=dtype),
            "center_trans": translation if translation is not None else jnp.zeros((activation_size,), dtype),
            "center_scale": scaling if scaling is not None else jnp.ones((activation_size,), dtype),
        }
        return params, buffers

    @staticmethod
    def center(buffers, batch: Array) -> Array:
        return ((batch - buffers["center_trans"]) @ buffers["center_rot"].T) * buffers["center_scale"]

    @staticmethod
    def uncenter(buffers, batch: Array) -> Array:
        return (batch / buffers["center_scale"]) @ buffers["center_rot"] + buffers["center_trans"]

    @staticmethod
    def encode(params, buffers, batch: Array) -> Array:
        # centering applied exactly as in loss(), so public encode() is
        # consistent with training for non-identity transforms (ADVICE r1 #3)
        dictionary = _normalize(params["encoder"])
        batch = FunctionalTiedSAE.center(buffers, batch)
        return jax.nn.relu(batch @ dictionary.T + params["encoder_bias"])

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["encoder"])
        batch_centered = FunctionalTiedSAE.center(buffers, batch)
        c = jax.nn.relu(batch_centered @ dictionary.T + params["encoder_bias"])
        x_hat_centered = c @ dictionary
        # reconstruction measured in centered space (reference: sae_ensemble.py:148)
        l_reconstruction = _mse(x_hat_centered, batch_centered)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_norm(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.TiedSAE:
        return ld.TiedSAE(dictionary=params["encoder"],
                          encoder_bias=params["encoder_bias"],
                          centering_rot=buffers["center_rot"],
                          centering_trans=buffers["center_trans"],
                          centering_scale=buffers["center_scale"])


@register("tied_centered_sae")
class FunctionalTiedCenteredSAE:
    """Tied SAE with a *learnable* center translation
    (reference: sae_ensemble.py:164-230)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, center: Optional[Array] = None, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
            "center": center if center is not None else jnp.zeros((activation_size,), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype)}
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["encoder"])
        batch_centered = batch - params["center"]
        c = jax.nn.relu(batch_centered @ dictionary.T + params["encoder_bias"])
        x_hat_centered = c @ dictionary
        l_reconstruction = _mse(x_hat_centered, batch_centered)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.TiedCenteredSAE:
        return ld.TiedCenteredSAE(dictionary=params["encoder"],
                                  encoder_bias=params["encoder_bias"],
                                  centering_trans=params["center"])


def _threshold_gate(c: Array, scale: Array, gain: Array) -> Array:
    """Soft-threshold surrogate gate (reference: sae_ensemble.py:256-259):
    relu6(60·(u−0.9))/6 + relu(u−1) on the gain-shifted, scale²-normalized
    pre-activation u, rescaled back by scale²."""
    a_sq = jnp.clip(jnp.square(scale), _EPS)
    u = (c + gain) / a_sq
    gated = jnp.clip(60.0 * (u - 0.9), 0.0, 6.0) / 6.0 + jax.nn.relu(u - 1.0)
    return gated * a_sq


@register("thresholding_sae")
class FunctionalThresholdingSAE:
    """Soft-threshold gated tied SAE with learnable per-feature scale/gain
    (reference: sae_ensemble.py:232-289; its encode reads an uninitialized
    ``params["centering"]`` — a latent bug we do not replicate)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "activation_scale": jnp.ones((n_dict_components,), dtype),
            "activation_gain": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype)}
        return params, buffers

    @staticmethod
    def encode(params, buffers, batch: Array) -> Array:
        dictionary = _normalize(params["encoder"])
        scores = batch @ dictionary.T
        return _threshold_gate(scores, params["activation_scale"], params["activation_gain"])

    @staticmethod
    def loss(params, buffers, batch: Array):
        c = FunctionalThresholdingSAE.encode(params, buffers, batch)
        dictionary = _normalize(params["encoder"])
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> "ThresholdingSAE":
        return ThresholdingSAE(dictionary=params["encoder"],
                               activation_scale=params["activation_scale"],
                               activation_gain=params["activation_gain"])


class ThresholdingSAE(ld.LearnedDict):
    """Inference wrapper for the thresholding SAE
    (reference: sae_ensemble.py:292-305)."""

    dictionary: Array
    activation_scale: Array
    activation_gain: Array

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        scores = x @ self.get_learned_dict().T
        return _threshold_gate(scores, self.activation_scale, self.activation_gain)


@register("masked_tied_sae")
class FunctionalMaskedTiedSAE:
    """Tied SAE padded to `n_components_stack` with a coefficient mask, so
    members with *different dictionary sizes* share one vmapped ensemble
    (reference: sae_ensemble.py:309-373). `coef_mask` is True for ACTIVE
    coefficients (the reference uses the inverted convention, :332-333)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             n_components_stack: int, l1_alpha: float, bias_decay: float = 0.0,
             dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_components_stack, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_components_stack,), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "dict_size": jnp.asarray(n_dict_components, jnp.int32),
            "coef_mask": jnp.arange(n_components_stack) < n_dict_components,
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["encoder"])
        c = jax.nn.relu(batch @ dictionary.T + params["encoder_bias"])
        c = jnp.where(buffers["coef_mask"], c, 0.0)
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.TiedSAE:
        n = int(buffers["dict_size"])
        return ld.TiedSAE(dictionary=params["encoder"][:n],
                          encoder_bias=params["encoder_bias"][:n])


@register("masked_sae")
class FunctionalMaskedSAE:
    """Untied masked variant (reference: sae_ensemble.py:377-444)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             n_components_stack: int, l1_alpha: float, bias_decay: float = 0.0,
             dtype=jnp.float32):
        k_enc, k_dec = jax.random.split(key)
        params = {
            "encoder": _glorot(k_enc, (n_components_stack, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_components_stack,), dtype),
            "decoder": _glorot(k_dec, (n_components_stack, activation_size), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "dict_size": jnp.asarray(n_dict_components, jnp.int32),
            "coef_mask": jnp.arange(n_components_stack) < n_dict_components,
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["decoder"])
        c = jax.nn.relu(batch @ params["encoder"].T + params["encoder_bias"])
        c = jnp.where(buffers["coef_mask"], c, 0.0)
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.UntiedSAE:
        n = int(buffers["dict_size"])
        return ld.UntiedSAE(encoder=params["encoder"][:n],
                            encoder_bias=params["encoder_bias"][:n],
                            dictionary=params["decoder"][:n])


@register("reverse_sae")
class FunctionalReverseSAE:
    """Tied SAE subtracting the bias from active coefficients before decode
    (reference: sae_ensemble.py:447-503; implemented without the in-place
    masked writes)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, bias_decay: float = 0.0, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = _normalize(params["encoder"])
        c = jax.nn.relu(batch @ dictionary.T + params["encoder_bias"])
        c = jnp.where(c > 0.0, c - params["encoder_bias"], c)
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat, batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_norm(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_l1": l_l1, "l_bias_decay": l_bias_decay}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.ReverseSAE:
        return ld.ReverseSAE(dictionary=params["encoder"],
                             encoder_bias=params["encoder_bias"])
