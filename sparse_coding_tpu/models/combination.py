"""Ensemble-combination inference dictionary.

Since this framework trains many SAEs per sweep anyway, combining them at
inference is nearly free — the "ensembling SAEs" direction from the
retrieved literature (PAPERS.md: arXiv:2505.16077, bagging/concatenation of
independently-trained SAEs improves reconstruction and feature coverage;
technique reference only, no code taken).

`ConcatEnsembleDict` keeps the full `LearnedDict` contract
(decode(c) == c @ get_learned_dict(), learned_dict.py): the combined
dictionary is the members' normalized atoms stacked, and `encode` scales
each member's codes by 1/n_members — so the SUM reconstruction of the
combined codes equals the MEAN of member reconstructions (bagging), and
every downstream metric/intervention/erasure path that manipulates
individual features stays exactly consistent with predict().

Members must use identity centering (enforced at create): with per-member
affine centering the member atoms would live in different spaces and no
single combined dictionary could satisfy the contract.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.models.learned_dict import LearnedDict

Array = jax.Array


class ConcatEnsembleDict(LearnedDict):
    """Union-of-features combination: n_feats = Σ member n_feats; codes are
    member codes scaled by 1/n_members."""

    members: tuple  # of LearnedDict pytrees

    @classmethod
    def create(cls, members: Sequence[LearnedDict]) -> "ConcatEnsembleDict":
        if not members:
            raise ValueError("need at least one member dict")
        widths = {m.activation_size for m in members}
        if len(widths) != 1:
            raise ValueError(f"members disagree on activation size: {widths}")
        d = widths.pop()
        probe = jnp.asarray(np.random.default_rng(0).normal(size=(4, d)),
                            jnp.float32)
        for i, m in enumerate(members):
            if not bool(jnp.allclose(m.center(probe), probe, atol=1e-6)):
                raise ValueError(
                    f"member {i} has non-identity centering; the combined "
                    "dictionary contract requires all members in raw space")
        return cls(members=tuple(members))

    def get_learned_dict(self) -> Array:
        return jnp.concatenate([m.get_learned_dict() for m in self.members],
                               axis=0)

    def encode(self, x: Array) -> Array:
        scale = 1.0 / len(self.members)
        return jnp.concatenate([m.encode(x) * scale for m in self.members],
                               axis=-1)

    # decode/predict inherit from LearnedDict: decode(c) = c @ dict, and with
    # the 1/n_members code scaling that equals the mean member reconstruction
