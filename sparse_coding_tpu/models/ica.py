"""FastICA dictionary (reference: autoencoders/ica.py).

Host-side sklearn fit (the reference does the same and notes ~15 min/GB,
ica.py:43); encode/decode are device-side JAX using the fitted whitening +
unmixing matrices, so evals run on TPU. The reference's NNegICAEncoder is
broken (`np.clamp` doesn't exist, `self.scaler` unset — ica.py:71-75); this
version works.
"""

from __future__ import annotations

from typing import Optional

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.models.learned_dict import (
    LearnedDict,
    TopKLearnedDict,
    normalize_rows,
)

Array = jax.Array


class ICAEncoder(LearnedDict):
    """Linear ICA codes: c = (x − mean)/scale → ica_transform
    (reference: ica.py:18-58). Fitted parameters baked into arrays."""

    components: Array  # [n, d] unmixing rows (in standardized space)
    scaler_mean: Array  # [d]
    scaler_scale: Array  # [d]
    ica_mean: Array  # [d] FastICA's internal mean

    @classmethod
    def train(cls, dataset: Array, n_components: Optional[int] = None,
              max_iter: int = 500,
              random_state: Optional[int] = None) -> "ICAEncoder":
        from sklearn.decomposition import FastICA
        from sklearn.preprocessing import StandardScaler

        x = np.asarray(jax.device_get(dataset), np.float64)
        scaler = StandardScaler()
        x_std = scaler.fit_transform(x)
        ica = FastICA(n_components=n_components, max_iter=max_iter,
                      random_state=random_state)
        ica.fit(x_std)
        return cls(
            components=jnp.asarray(ica.components_, jnp.float32),
            scaler_mean=jnp.asarray(scaler.mean_, jnp.float32),
            scaler_scale=jnp.asarray(scaler.scale_, jnp.float32),
            ica_mean=jnp.asarray(ica.mean_, jnp.float32),
        )

    def encode(self, x: Array) -> Array:
        x_std = (x - self.scaler_mean) / self.scaler_scale
        return (x_std - self.ica_mean) @ self.components.T

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.components)

    def to_topk_dict(self, sparsity: int) -> TopKLearnedDict:
        """± components TopK export (reference: ica.py:53-58)."""
        comps = jnp.concatenate([self.components, -self.components], axis=0)
        return TopKLearnedDict(dictionary=comps, k=sparsity)

    def to_nneg_dict(self) -> "NNegICAEncoder":
        return NNegICAEncoder(components=self.components,
                              scaler_mean=self.scaler_mean,
                              scaler_scale=self.scaler_scale,
                              ica_mean=self.ica_mean)


class NNegICAEncoder(ICAEncoder):
    """Rectified ± ICA codes (reference: ica.py:61-81, fixed)."""

    def encode(self, x: Array) -> Array:
        c = super().encode(x)
        return jnp.concatenate([jax.nn.relu(c), jax.nn.relu(-c)], axis=-1)

    def get_learned_dict(self) -> Array:
        comps = jnp.concatenate([self.components, -self.components], axis=0)
        return normalize_rows(comps)
