"""Unrolled iterative-shrinkage (LISTA) and residual-denoising encoders.

Re-implements the reference's residual_denoising_autoencoder.py in pure JAX:
- `FunctionalLISTADenoisingSAE`: unrolled LISTA (arXiv:2008.02683 per the
  reference's citation) with soft-threshold shrinkage and momentum mixing;
- `FunctionalResidualDenoisingSAE`: residual stack of
  relu-shift → orthogonal mix layers.

The unrolled encoder layers are stacked [L, ...] pytrees scanned with
lax.scan (the reference holds a Python list of per-layer dicts,
residual_denoising_autoencoder.py:53). The reference's inference wrapper also
reads `params["dict"]` that init never creates
(residual_denoising_autoencoder.py:188 vs :142) — fixed here by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.signatures import make_aux, register

Array = jax.Array


def _orthogonal(key: Array, shape, dtype=jnp.float32) -> Array:
    return jax.nn.initializers.orthogonal()(key, shape, dtype)


def shrinkage(r: Array, theta: Array) -> Array:
    """Soft threshold: sign(r)·relu(|r| − θ)
    (reference: residual_denoising_autoencoder.py:9-11)."""
    return jnp.sign(r) * jax.nn.relu(jnp.abs(r) - theta)


def _lista_layer_init(key: Array, d_activation: int, n_features: int, dtype):
    k_w, k_theta = jax.random.split(key)
    return {
        "W": _orthogonal(k_w, (n_features, d_activation), dtype),
        "theta": 0.02 * jax.random.normal(k_theta, (n_features,), dtype),
        "rho": jnp.asarray(0.1, dtype),
    }


def _lista_step(layer: dict, y: Array, b: Array, x: Array, A: Array):
    """One LISTA iteration solving Ay=b
    (reference: residual_denoising_autoencoder.py:24-36)."""
    m = jnp.clip(layer["rho"], 0.0, 1.0)
    Ay = y @ A  # [batch, d]
    r = y + (b - Ay) @ layer["W"].T
    x_new = shrinkage(r, layer["theta"])
    y_new = x_new + m * (x_new - x)
    return y_new, x_new


@register("lista_denoising_sae")
class FunctionalLISTADenoisingSAE:
    """(reference: residual_denoising_autoencoder.py:39-103)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, n_hidden_layers: int = 2, dtype=jnp.float32):
        k_dec, *k_layers = jax.random.split(key, n_hidden_layers + 1)
        layers = [_lista_layer_init(k, activation_size, n_dict_components, dtype)
                  for k in k_layers]
        params = {
            "decoder": _orthogonal(k_dec, (n_dict_components, activation_size), dtype),
            # stacked [L, ...] for lax.scan
            "encoder_layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype),
                   "n_hidden_layers": n_hidden_layers}
        return params, buffers

    @staticmethod
    def encode(params, batch: Array, dictionary: Array) -> Array:
        y0 = batch @ dictionary.T
        def body(carry, layer):
            y, x = carry
            y_new, x_new = _lista_step(layer, y, batch, x, dictionary)
            return (y_new, x_new), None
        (y, _), _ = jax.lax.scan(body, (y0, y0), params["encoder_layers"])
        return y

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = ld.normalize_rows(params["decoder"])
        c = FunctionalLISTADenoisingSAE.encode(params, batch, dictionary)
        x_hat = c @ dictionary
        l_reconstruction = jnp.mean(jnp.square(x_hat - batch))
        l_sparsity = buffers["l1_alpha"] * jnp.mean(jnp.sum(jnp.abs(c), axis=-1))
        total = l_reconstruction + l_sparsity
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_l1": l_sparsity}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> "LISTADenoisingSAE":
        return LISTADenoisingSAE(decoder=params["decoder"],
                                 encoder_layers=params["encoder_layers"])


class LISTADenoisingSAE(ld.LearnedDict):
    """(reference: residual_denoising_autoencoder.py:106-131)."""

    decoder: Array
    encoder_layers: dict  # stacked [L, ...]

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.decoder)

    def encode(self, x: Array) -> Array:
        return FunctionalLISTADenoisingSAE.encode(
            {"encoder_layers": self.encoder_layers}, x, self.get_learned_dict())


def _resid_layer_init(key: Array, n_features: int, dtype):
    k_w, k_theta = jax.random.split(key)
    return {
        "W": _orthogonal(k_w, (n_features, n_features), dtype),
        "theta": 0.02 * jax.random.normal(k_theta, (n_features,), dtype),
    }


@register("residual_denoising_sae")
class FunctionalResidualDenoisingSAE:
    """(reference: residual_denoising_autoencoder.py:134-182)."""

    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, n_hidden_layers: int = 2, dtype=jnp.float32):
        k_dec, k_bias, *k_layers = jax.random.split(key, n_hidden_layers + 2)
        layers = [_resid_layer_init(k, n_dict_components, dtype) for k in k_layers]
        params = {
            "decoder": _orthogonal(k_dec, (n_dict_components, activation_size), dtype),
            "encoder_layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
            "encoder_bias": 0.02 * jax.random.normal(k_bias, (n_dict_components,), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype),
                   "n_hidden_layers": n_hidden_layers}
        return params, buffers

    @staticmethod
    def encode(params, batch: Array, dictionary: Array) -> Array:
        x = batch @ dictionary.T
        def body(x, layer):
            x_ = jax.nn.relu(x + layer["theta"])
            return x_ @ layer["W"].T + x, None
        x, _ = jax.lax.scan(body, x, params["encoder_layers"])
        return jax.nn.relu(x + params["encoder_bias"])

    @staticmethod
    def loss(params, buffers, batch: Array):
        dictionary = ld.normalize_rows(params["decoder"])
        c = FunctionalResidualDenoisingSAE.encode(params, batch, dictionary)
        x_hat = c @ dictionary
        l_reconstruction = jnp.mean(jnp.square(x_hat - batch))
        l_sparsity = buffers["l1_alpha"] * jnp.mean(jnp.sum(jnp.abs(c), axis=-1))
        total = l_reconstruction + l_sparsity
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_l1": l_sparsity}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> "ResidualDenoisingSAE":
        return ResidualDenoisingSAE(decoder=params["decoder"],
                                    encoder_layers=params["encoder_layers"],
                                    encoder_bias=params["encoder_bias"])


class ResidualDenoisingSAE(ld.LearnedDict):
    """(reference: residual_denoising_autoencoder.py:185-201, minus its
    params["dict"] init bug)."""

    decoder: Array
    encoder_layers: dict
    encoder_bias: Array

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.decoder)

    def encode(self, x: Array) -> Array:
        return FunctionalResidualDenoisingSAE.encode(
            {"encoder_layers": self.encoder_layers,
             "encoder_bias": self.encoder_bias}, x, self.get_learned_dict())
