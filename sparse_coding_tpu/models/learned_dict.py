"""Inference-side dictionary interface.

TPU-native re-design of the reference's `LearnedDict` ABC
(reference: autoencoders/learned_dict.py:16-53): every dictionary is an
immutable flax-struct pytree with pure `encode`/`decode`/`predict` methods, so
any dict can be passed straight into jitted eval/intervention functions (and
vmapped over for batched-dict evals — something the torch ABC cannot do).

Conventions (matching the reference):
- activations x: [batch, d_activation]
- codes c: [batch, n_feats]
- dictionary D: [n_feats, d_activation]; `decode(c) = c @ normalize(D)`
  (the reference's einsum "nd,bn->bd", learned_dict.py:32)
- `predict = uncenter ∘ decode ∘ encode ∘ center` (learned_dict.py:45)
"""

from __future__ import annotations

from typing import ClassVar, Optional

import flax.struct as struct
import jax
import jax.numpy as jnp

Array = jax.Array

_NORM_EPS = 1e-8


def normalize_rows(d: Array, eps: float = _NORM_EPS) -> Array:
    """Row-normalize a dictionary to unit L2 norm. clip (not +eps) matches
    the training-side _normalize (models/sae.py) and the reference's
    torch.clamp, so exported inference dictionaries equal the ones the loss
    saw even for degenerate near-zero rows (ADVICE r1 #1)."""
    return d / jnp.clip(jnp.linalg.norm(d, axis=-1, keepdims=True), eps)


# Every LearnedDict subclass auto-registers here (by class name) so artifact
# files can be reconstructed without hand-maintained registries
# (utils/artifacts.py reads this).
LEARNED_DICT_REGISTRY: dict[str, type] = {}


class LearnedDict(struct.PyTreeNode):
    """Base class: subclasses provide `encode` and `get_learned_dict`.

    Uniform inference signature (audited at the serving-registry boundary,
    serve/registry.py::audit_signature): ``encode(x: [b, d]) -> [b, n]``,
    ``decode(c: [b, n]) -> [b, d]``, ``predict(x: [b, d]) -> [b, d]`` —
    all pure, all row-independent unless ``batch_coupled`` says otherwise.
    """

    # True when encode/predict depend on the WHOLE batch (not row-wise) —
    # e.g. AddedNoise salts its RNG with the batch sum. Such dicts cannot
    # be served through the coalescing micro-batcher: mixing rows from
    # different requests would change each request's answer.
    batch_coupled: ClassVar[bool] = False

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        LEARNED_DICT_REGISTRY[cls.__name__] = cls

    @property
    def n_feats(self) -> int:
        return self.get_learned_dict().shape[0]

    @property
    def activation_size(self) -> int:
        return self.get_learned_dict().shape[-1]

    def n_dict_components(self) -> int:
        return self.n_feats

    def get_learned_dict(self) -> Array:
        raise NotImplementedError

    def encode(self, x: Array) -> Array:
        raise NotImplementedError

    def decode(self, c: Array) -> Array:
        return c @ self.get_learned_dict()

    def center(self, x: Array) -> Array:
        return x

    def uncenter(self, x: Array) -> Array:
        return x

    def predict(self, x: Array) -> Array:
        return self.uncenter(self.decode(self.encode(self.center(x))))


class Identity(LearnedDict):
    """Identity dictionary: features are the neuron basis
    (reference: learned_dict.py:56-69)."""

    eye: Array

    @classmethod
    def create(cls, activation_size: int, dtype=jnp.float32) -> "Identity":
        return cls(eye=jnp.eye(activation_size, dtype=dtype))

    def get_learned_dict(self) -> Array:
        return self.eye

    def encode(self, x: Array) -> Array:
        return x


class IdentityReLU(Identity):
    """Identity with ReLU codes (reference: learned_dict.py:86-103)."""

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x)


class IdentityPositive(LearnedDict):
    """±identity: stacks +I and −I so both signs get nonnegative codes
    (reference: learned_dict.py:71-84)."""

    pm_eye: Array

    @classmethod
    def create(cls, activation_size: int, dtype=jnp.float32) -> "IdentityPositive":
        eye = jnp.eye(activation_size, dtype=dtype)
        return cls(pm_eye=jnp.concatenate([eye, -eye], axis=0))

    def get_learned_dict(self) -> Array:
        return self.pm_eye

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.pm_eye.T)


class RandomDict(LearnedDict):
    """Random unit-norm dictionary with ReLU projection codes
    (reference: learned_dict.py:106-126)."""

    dictionary: Array

    @classmethod
    def create(cls, key: Array, activation_size: int, n_feats: Optional[int] = None,
               dtype=jnp.float32) -> "RandomDict":
        n = n_feats or activation_size
        d = jax.random.normal(key, (n, activation_size), dtype=dtype)
        return cls(dictionary=normalize_rows(d))

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.get_learned_dict().T)


class Rotation(LearnedDict):
    """Orthonormal rotation dictionary (reference: learned_dict.py:277-293)."""

    rotation: Array  # [n, d], orthonormal rows

    @classmethod
    def create(cls, key: Array, activation_size: int, dtype=jnp.float32) -> "Rotation":
        g = jax.random.normal(key, (activation_size, activation_size), dtype=dtype)
        q, _ = jnp.linalg.qr(g)
        return cls(rotation=q.T)

    def get_learned_dict(self) -> Array:
        return self.rotation

    def encode(self, x: Array) -> Array:
        return x @ self.rotation.T


class AddedNoise(LearnedDict):
    """Identity encode with additive-noise predict, a null-model baseline
    (reference: learned_dict.py:260-275)."""

    noise_mag: Array
    eye: Array
    key: Array

    batch_coupled: ClassVar[bool] = True  # RNG salt = f(whole batch)

    @classmethod
    def create(cls, key: Array, activation_size: int, noise_mag: float,
               dtype=jnp.float32) -> "AddedNoise":
        return cls(noise_mag=jnp.asarray(noise_mag, dtype),
                   eye=jnp.eye(activation_size, dtype=dtype), key=key)

    def get_learned_dict(self) -> Array:
        return self.eye

    def _noised(self, x: Array) -> Array:
        # the reference draws FRESH noise every encode() call; a frozen
        # pytree has no mutable key, so the key is folded with a
        # batch-content salt instead: different batches get independent
        # noise, repeated calls on the same batch are deterministic
        # (deviation noted in PARITY.md; ADVICE r1 #2)
        # bitcast (not clip/round) keeps distinct sums distinct at any scale
        salt = jax.lax.bitcast_convert_type(
            jnp.sum(x).astype(jnp.float32), jnp.int32)
        k = jax.random.fold_in(self.key, salt.astype(jnp.uint32))
        return x + self.noise_mag * jax.random.normal(k, x.shape,
                                                      dtype=x.dtype)

    def encode(self, x: Array) -> Array:
        return self._noised(x)

    def predict(self, x: Array) -> Array:
        return self._noised(x)


class UntiedSAE(LearnedDict):
    """Separately-learned encoder and decoder
    (reference: learned_dict.py:129-150)."""

    encoder: Array  # [n, d]
    encoder_bias: Array  # [n]
    dictionary: Array  # [n, d]

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.encoder.T + self.encoder_bias)


class TiedSAE(LearnedDict):
    """Tied encoder = normalized dictionary, with an optional affine centering
    transform (rotation R, translation t, per-dim scale s), matching the
    reference's TiedSAE (learned_dict.py:152-215): center(x) = ((x − t) @ Rᵀ)/s.
    """

    dictionary: Array  # [n, d]
    encoder_bias: Array  # [n]
    centering_rot: Optional[Array] = None  # [d, d]
    centering_trans: Optional[Array] = None  # [d]
    centering_scale: Optional[Array] = None  # [d]

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.get_learned_dict().T + self.encoder_bias)

    def center(self, x: Array) -> Array:
        """center(x) = (R·(x − t))·s — matches the reference's whitening
        transform orientation (sae_ensemble.py:127-128)."""
        if self.centering_trans is not None:
            x = x - self.centering_trans
        if self.centering_rot is not None:
            x = x @ self.centering_rot.T
        if self.centering_scale is not None:
            x = x * self.centering_scale
        return x

    def uncenter(self, x: Array) -> Array:
        if self.centering_scale is not None:
            x = x / self.centering_scale
        if self.centering_rot is not None:
            x = x @ self.centering_rot
        if self.centering_trans is not None:
            x = x + self.centering_trans
        return x


class TiedCenteredSAE(TiedSAE):
    """Tied SAE with a learnable center translation
    (reference: sae_ensemble.py:164-230 inference side)."""


class ReverseSAE(LearnedDict):
    """Tied SAE whose decode subtracts the bias from *active* coefficients
    before projecting (reference: learned_dict.py:218-257 — whose torch decode
    mutates its input in place, learned_dict.py:253-255; this version is pure).
    """

    dictionary: Array
    encoder_bias: Array

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.get_learned_dict().T + self.encoder_bias)

    def decode(self, c: Array) -> Array:
        active = c > 0
        adjusted = jnp.where(active, c - self.encoder_bias, c)
        return adjusted @ self.get_learned_dict()


class TopKLearnedDict(LearnedDict):
    """k-sparse inference dict: keep the top-k scores, ReLU the rest away
    (reference: topk_encoder.py:43-63)."""

    dictionary: Array
    k: int = struct.field(pytree_node=False, default=8)

    def get_learned_dict(self) -> Array:
        return normalize_rows(self.dictionary)

    def encode(self, x: Array) -> Array:
        scores = x @ self.get_learned_dict().T
        topk_vals, topk_idx = jax.lax.top_k(scores, self.k)
        batch_idx = jnp.arange(scores.shape[0])[:, None]
        out = jnp.zeros_like(scores)
        return out.at[batch_idx, topk_idx].set(jax.nn.relu(topk_vals))
