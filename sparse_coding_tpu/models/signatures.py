"""The trainable-dictionary protocol.

TPU-native analogue of the reference's `DictSignature`
(reference: autoencoders/ensemble.py:15-22): a signature is a namespace of
*pure functions* over explicit params/buffers pytrees, so the ensemble engine
can `jax.vmap(jax.grad(sig.loss))` over a stacked ensemble axis.

Contract:
- ``init(key, ...) -> (params, buffers)``: params are trained, buffers are
  per-member constants (hyperparameters like l1_alpha live here as 0-d arrays
  so they can vary across vmapped ensemble members).
- ``loss(params, buffers, batch) -> (loss, aux)`` where ``aux`` is an
  `AuxData` of scalar loss components and activity statistics (the reference
  returns the full code tensor as aux, sae_ensemble.py:74-76 — we return
  reduced statistics instead to keep the jitted step memory-light, plus a
  per-feature activity count used for dead-feature tracking).
- ``to_learned_dict(params, buffers) -> LearnedDict``: inference export.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Protocol, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp

Array = jax.Array
Params = Any
Buffers = Any


class AuxData(struct.PyTreeNode):
    """Reduced per-step statistics returned by every signature's loss.

    The three sentinel fields (docs/ARCHITECTURE.md §16) are filled in by
    the ensemble step functions — device-side, folded into the aux the
    step already returns, so detection costs no extra host sync — and
    stay ``None`` when a signature's bare ``loss`` builds the aux or the
    sentinel is disabled (``Ensemble(sentinel=False)``):

    - ``finite``: per-member bool — this step's loss, grads, and update
      were all finite (on the whole-step fused paths, where grads never
      leave the kernel, the update delta stands in for the grads);
    - ``grad_norm``: per-member global grad L2 norm (update-delta norm on
      the whole-step fused paths — finiteness is what the guardian keys
      on, and the scale is still a divergence trend signal);
    - ``inputs_finite``: scalar bool — the batch itself was finite
      (splits the data-corruption incident class from hyperparameter
      divergence, train/guardian.py).
    """

    losses: dict[str, Array]  # scalar loss components, incl. "loss"
    l0: Array  # mean number of nonzero coefficients per sample
    feat_activity: Array  # [n_feats] count of samples activating each feature
    finite: Optional[Array] = None  # [N] bool per-member step-finite flag
    grad_norm: Optional[Array] = None  # [N] member global grad/update norm
    inputs_finite: Optional[Array] = None  # scalar bool: batch was finite


def make_aux(losses: dict[str, Array], c: Array) -> AuxData:
    active = c > 0.0
    return AuxData(
        losses=losses,
        l0=jnp.mean(jnp.sum(active, axis=-1).astype(jnp.float32)),
        feat_activity=jnp.sum(active, axis=0).astype(jnp.int32),
    )


class DictSignature(Protocol):
    init: Callable[..., Tuple[Params, Buffers]]
    loss: Callable[[Params, Buffers, Array], Tuple[Array, AuxData]]
    to_learned_dict: Callable[[Params, Buffers], Any]


# Registry so sweep configs can name signatures by string.
_REGISTRY: dict[str, type] = {}


def register(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.signature_name = name
        return cls
    return deco


def get_signature(name: str) -> type:
    return _REGISTRY[name]


def signature_names() -> list[str]:
    return sorted(_REGISTRY)
