"""Nonnegativity-constrained SAE variants
(reference: autoencoders/mlp_tests.py).

The reference's FunctionalPositiveTiedSAE clamps the encoder to ≥0 inside the
loss by *mutating params* (mlp_tests.py:100 `params["encoder"] =
torch.clamp(...)`) and applies a fixed +0.18 input shift (:104,110). Here the
clamp is a projection inside the pure loss (gradients flow through the clamp,
matching the torch autograd behavior) and the shift is an explicit buffer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.models.sae import _glorot, _l1, _mse, _safe_norm
from sparse_coding_tpu.models.signatures import make_aux, register

Array = jax.Array

INPUT_SHIFT = 0.18  # reference: mlp_tests.py:104,110


@register("positive_tied_sae")
class FunctionalPositiveTiedSAE:
    @staticmethod
    def init(key: Array, activation_size: int, n_dict_components: int,
             l1_alpha: float, bias_decay: float = 0.0, dtype=jnp.float32):
        params = {
            "encoder": jnp.abs(_glorot(key, (n_dict_components, activation_size), dtype)),
            # bias init at -1 (reference: mlp_tests.py:89)
            "encoder_bias": -jnp.ones((n_dict_components,), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "input_shift": jnp.asarray(INPUT_SHIFT, dtype),
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch: Array):
        encoder = jax.nn.relu(params["encoder"])  # nonneg projection
        norms = jnp.clip(jnp.linalg.norm(encoder, axis=-1, keepdims=True), 1e-8)
        dictionary = encoder / norms
        shifted = batch + buffers["input_shift"]
        c = jax.nn.relu(shifted @ dictionary.T + params["encoder_bias"])
        x_hat = c @ dictionary
        l_reconstruction = _mse(x_hat - buffers["input_shift"], batch)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_norm(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        return total, make_aux(
            {"loss": total, "l_reconstruction": l_reconstruction,
             "l_l1": l_l1, "l_bias_decay": l_bias_decay}, c)

    @staticmethod
    def to_learned_dict(params, buffers) -> ld.TiedSAE:
        return ld.TiedSAE(dictionary=jax.nn.relu(params["encoder"]),
                          encoder_bias=params["encoder_bias"])
