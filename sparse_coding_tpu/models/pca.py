"""Streaming PCA + PCA-based dictionaries.

TPU-native re-design of the reference's `BatchedPCA`/`BatchedMean`/`PCAEncoder`
(reference: autoencoders/pca.py): the streaming covariance/mean accumulation
is a single jitted `lax.scan` over fixed-size batches (the reference drives a
Python loop per batch, pca.py:10-17), eigh runs on device, and the exported
dictionaries are the same family: top-k PCA codes, rotation, ±rotation tied
SAE, and the whitening centering transform used for centered SAE training.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.struct as struct
import jax
import jax.numpy as jnp

from sparse_coding_tpu.models.learned_dict import (
    LearnedDict,
    Rotation,
    TiedSAE,
    TopKLearnedDict,
    normalize_rows,
)

Array = jax.Array


class PCAState(struct.PyTreeNode):
    """Streaming moment state (reference: BatchedPCA, pca.py:41-64)."""

    cov: Array  # [d, d]
    mean: Array  # [d]
    n_samples: Array  # scalar

    @classmethod
    def create(cls, n_dims: int, dtype=jnp.float32) -> "PCAState":
        return cls(cov=jnp.zeros((n_dims, n_dims), dtype),
                   mean=jnp.zeros((n_dims,), dtype),
                   n_samples=jnp.zeros((), dtype))


@jax.jit
def pca_update(state: PCAState, batch: Array) -> PCAState:
    """Numerically-stable streaming covariance update (same recurrence as
    reference pca.py:54-64)."""
    b = batch.shape[0]
    corrected = batch - state.mean
    new_mean = state.mean + jnp.mean(corrected, axis=0) * b / (state.n_samples + b)
    cov_update = (corrected.T @ (batch - new_mean)) / b
    w_old = state.n_samples / (state.n_samples + b)
    w_new = b / (state.n_samples + b)
    return PCAState(cov=state.cov * w_old + cov_update * w_new,
                    mean=new_mean, n_samples=state.n_samples + b)


def fit_pca(activations: Array, batch_size: int = 512) -> PCAState:
    """Fit over a dataset in one jitted scan (reference: calc_pca,
    pca.py:6-13)."""
    d = activations.shape[-1]
    n = (activations.shape[0] // batch_size) * batch_size
    batches = activations[:n].reshape(-1, batch_size, d)

    def body(state, batch):
        return pca_update(state, batch), None

    state, _ = jax.lax.scan(body, PCAState.create(d), batches)
    tail = activations[n:]
    if tail.shape[0]:
        state = pca_update(state, tail)
    return state


def fit_mean(activations: Array, batch_size: int = 512) -> Array:
    """(reference: BatchedMean/calc_mean, pca.py:15-38)."""
    return fit_pca(activations, batch_size).mean


class BatchedPCA:
    """Stateful convenience wrapper matching the reference's interface
    (train_batch / get_pca / exports, pca.py:41-110)."""

    def __init__(self, n_dims: int):
        self.state = PCAState.create(n_dims)
        self.n_dims = n_dims

    def train_batch(self, activations: Array) -> None:
        self.state = pca_update(self.state, jnp.asarray(activations))

    def get_mean(self) -> Array:
        return self.state.mean

    def get_pca(self) -> tuple[Array, Array]:
        cov_symm = (self.state.cov + self.state.cov.T) / 2
        return jnp.linalg.eigh(cov_symm)

    def get_centering_transform(self) -> tuple[Array, Array, Array]:
        """(mean, eigvecs, 1/√eigvals) whitening transform for centered SAE
        training (reference: pca.py:71-82)."""
        eigvals, eigvecs = self.get_pca()
        eigvals = jnp.clip(eigvals, 1e-6)
        return self.get_mean(), eigvecs, 1.0 / jnp.sqrt(eigvals)

    def get_dict(self) -> Array:
        """Eigenvectors as rows, descending eigenvalue order
        (reference: pca.py:90-93)."""
        eigvals, eigvecs = self.get_pca()
        order = jnp.argsort(-eigvals)
        return eigvecs[:, order].T

    def to_learned_dict(self, sparsity: int) -> "PCAEncoder":
        return PCAEncoder(pca_dict=normalize_rows(self.get_dict()), k=sparsity)

    def to_topk_dict(self, sparsity: int) -> TopKLearnedDict:
        """± eigenvector TopK dict (reference: pca.py:96-100)."""
        d = self.get_dict()
        return TopKLearnedDict(dictionary=jnp.concatenate([d, -d], axis=0),
                               k=sparsity)

    def to_rotation_dict(self, n_components: Optional[int] = None) -> Rotation:
        n = n_components or self.n_dims
        return Rotation(rotation=self.get_dict()[:n])

    def to_pve_rotation_dict(self, n_components: Optional[int] = None) -> TiedSAE:
        """±rotation tied SAE with mean-centering (reference: pca.py:102-107)."""
        n = n_components or self.n_dims
        dirs = self.get_dict()[:n]
        return TiedSAE(dictionary=jnp.concatenate([dirs, -dirs], axis=0),
                       encoder_bias=jnp.zeros(2 * n),
                       centering_trans=self.get_mean())


class PCAEncoder(LearnedDict):
    """Top-k-|score| sparse PCA codes (reference: pca.py:113-135). Keeps the
    top-k components by |score| with their *signed* values."""

    pca_dict: Array  # [n, d] already normalized
    k: int = struct.field(pytree_node=False, default=8)

    def get_learned_dict(self) -> Array:
        return self.pca_dict

    def encode(self, x: Array) -> Array:
        scores = x @ self.pca_dict.T
        _, idx = jax.lax.top_k(jnp.abs(scores), self.k)
        batch_idx = jnp.arange(scores.shape[0])[:, None]
        vals = jnp.take_along_axis(scores, idx, axis=-1)
        out = jnp.zeros_like(scores)
        return out.at[batch_idx, idx].set(vals)
