"""Dynamic micro-batching queue for the serving engine.

Requests (a few activation rows each) are coalesced per (model, op) stream
into one padded device program per batch — the serving-side instance of the
repo's dispatch-amortization doctrine (docs/ARCHITECTURE.md §7): through
the axon tunnel a dispatch costs ~54 ms, so per-request dispatch would cap
throughput at ~18 req/s regardless of batch math. The whole hot loop here
is host Python over numpy buffers and threading primitives — ``lax``-free
by construction; the only jax entry point is the engine's dispatch callback
invoking an AOT-compiled executable.

Flush policy (per (model, op) stream, oldest stream first):

- **capacity flush**: pending rows reach the largest bucket → dispatch now;
- **deadline flush**: the oldest request has waited ``max_wait_s`` →
  dispatch whatever is pending into the smallest covering bucket;
- **backpressure**: queued rows would exceed ``max_queue_rows`` → the
  submit call fails fast with :class:`QueueFullError` (typed, carries the
  depth) instead of adding unbounded latency.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from sparse_coding_tpu.obs import monotime
from sparse_coding_tpu.serve.metrics import ServingMetrics


class ServeError(RuntimeError):
    """Base class for typed serving failures."""


class QueueFullError(ServeError):
    """Backpressure rejection: admitting the request would push the queue
    past ``max_queue_rows`` (or, at the gateway, past the SLO admission
    ladder). The request was NOT enqueued. ``retry_after_s`` mirrors
    :class:`CircuitOpenError`'s contract — the predicted time for the
    current queue to drain (depth x recent per-row service rate) — so
    shed clients back off intelligently instead of hot-retrying; ``None``
    when no service rate has been observed yet."""

    def __init__(self, queued_rows: int, max_queue_rows: int,
                 retry_after_s: float | None = None):
        hint = ("" if retry_after_s is None
                else f"; retry in ~{retry_after_s:.2f}s")
        super().__init__(
            f"serving queue full: {queued_rows} rows queued "
            f"(max {max_queue_rows}); request rejected{hint}")
        self.queued_rows = queued_rows
        self.max_queue_rows = max_queue_rows
        self.retry_after_s = retry_after_s


class RequestTooLargeError(ServeError):
    """The request exceeds the largest shape bucket; route it through
    :func:`sparse_coding_tpu.serve.offline.score_offline` instead."""

    def __init__(self, rows: int, max_rows: int):
        super().__init__(
            f"request of {rows} rows exceeds the largest bucket "
            f"({max_rows}); use serve.offline.score_offline for bulk "
            f"scoring")
        self.rows = rows
        self.max_rows = max_rows


class DispatchError(ServeError):
    """One flush's dispatch failed after exhausting its retry budget; only
    THAT flush's requests carry this error — the worker thread and every
    other queued request are unaffected. ``cause`` is the underlying
    exception; ``key`` names the (model, op) stream."""

    def __init__(self, key: tuple, cause: BaseException):
        model, op = key
        super().__init__(
            f"dispatch failed for {model!r}/{op}: {cause!r}")
        self.key = key
        self.cause = cause


class CircuitOpenError(ServeError):
    """The dispatch circuit breaker is open: the backend failed repeatedly
    and new work is being shed instead of queued behind a sick device.
    Retry after ``retry_after_s`` (the breaker's remaining cooldown)."""

    def __init__(self, key: tuple, retry_after_s: float):
        model, op = key
        super().__init__(
            f"circuit open for {model!r}/{op}: backend failing; retry in "
            f"~{retry_after_s:.2f}s")
        self.key = key
        self.retry_after_s = retry_after_s


class ServeFuture:
    """Synchronization handle for one in-flight request."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result: Any = None
        self._error: BaseException | None = None

    def _set_result(self, result: Any) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("serving request did not complete in time")
        if self._error is not None:
            raise self._error
        return self._result


@dataclass
class Request:
    """One submitted unit of work: ``x`` is always [rows, width] float;
    ``squeeze`` remembers a 1-D submission so the result matches.
    ``trace_id`` is the critical-path correlation id minted at admission
    (obs.mint_trace_id, §12); ``queue_s`` is stamped by the dispatcher
    when the request leaves the queue, so the completion event can
    decompose latency into queue wait vs dispatch."""

    key: tuple  # (model_name, op)
    x: np.ndarray
    rows: int
    squeeze: bool
    t_submit: float
    future: ServeFuture = field(default_factory=ServeFuture)
    trace_id: str = ""
    queue_s: float = 0.0


class MicroBatcher:
    """Single worker thread draining per-(model, op) request streams into
    the dispatch callback. ``dispatch(key, requests, deadline_flush)`` owns
    bucket selection, padding, the compiled call, and result fan-out; it
    returns the number of rows actually served (None/0 for a shed or
    failed flush — those must not feed the service-rate estimate)."""

    def __init__(self, dispatch: Callable[[tuple, list[Request], bool], None],
                 max_rows_per_batch: int, max_wait_s: float,
                 max_queue_rows: int, metrics: ServingMetrics):
        self._dispatch = dispatch
        self._max_rows = max_rows_per_batch
        self._max_wait_s = max_wait_s
        self._max_queue_rows = max_queue_rows
        self._metrics = metrics
        self._queues: dict[tuple, deque[Request]] = {}
        self._queued_rows = 0
        # recent per-row service rate (rows/s EWMA over dispatch walls):
        # feeds QueueFullError.retry_after_s and the gateway's predicted
        # admission wait; None until the first dispatch completes
        self._rate_rows_s: float | None = None
        self._rate_alpha = 0.2
        self._cond = threading.Condition()
        self._stop = False
        self._paused = False
        self._worker = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._worker.start()

    # -- producer side -------------------------------------------------------

    def submit(self, request: Request) -> ServeFuture:
        with self._cond:
            if self._stop:
                raise ServeError("serving engine is shut down")
            if self._queued_rows + request.rows > self._max_queue_rows:
                self._metrics.record_reject()
                raise QueueFullError(self._queued_rows, self._max_queue_rows,
                                     self._predicted_wait_locked())
            self._queues.setdefault(request.key, deque()).append(request)
            self._queued_rows += request.rows
            self._metrics.record_enqueue(request.rows)
            self._cond.notify_all()
        return request.future

    def _predicted_wait_locked(self, extra_rows: int = 0) -> float | None:
        # _cond held by caller
        if self._rate_rows_s is None or self._rate_rows_s <= 0:
            return None
        return (self._queued_rows + extra_rows) / self._rate_rows_s

    def predicted_wait_s(self, extra_rows: int = 0) -> float | None:
        """Predicted time for the current queue (plus ``extra_rows``) to
        drain at the recent service rate; None before any dispatch has
        been timed. The gateway's SLO admission compares this against a
        request's deadline."""
        with self._cond:
            return self._predicted_wait_locked(extra_rows)

    @property
    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    @property
    def max_rows(self) -> int:
        with self._cond:
            return self._max_rows

    def set_max_rows(self, max_rows: int) -> None:
        """Hot-swap the capacity-flush threshold to a new ladder's
        largest bucket (gateway ladder swap, serve/ladder.py §24).
        Queued requests are untouched — an already-admitted request
        larger than the new ladder still dispatches (the engine falls
        back to a previously-warmed rung), so a shrink-swap can never
        strand admitted work."""
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        with self._cond:
            self._max_rows = int(max_rows)
            self._cond.notify_all()

    def take_joiners(self, key: tuple,
                     remaining_rows: int) -> list[Request]:
        """Continuous rebatching (§24): pop queued requests of ``key``'s
        stream — strictly FIFO, never skipping the head (skipping would
        reorder results against submission order and break dispatch
        determinism) — while they fit ``remaining_rows``, so requests
        that arrived after the flush was popped ride the already-chosen
        bucket's pad rows instead of waiting a full cycle. Joining only
        ever ACCELERATES a request, so deadlines and priority ordering
        are respected by construction. A present head that does not fit
        is counted rejected (``serve.rebatch.rejected``)."""
        joined: list[Request] = []
        rows = 0
        with self._cond:
            q = self._queues.get(key)
            while q and remaining_rows - rows >= q[0].rows:
                r = q.popleft()
                joined.append(r)
                rows += r.rows
            rejected = 1 if (q and remaining_rows - rows > 0) else 0
            if rows:
                self._queued_rows -= rows
        if rows:
            self._metrics.record_dequeue(rows)
        self._metrics.record_rebatch(len(joined), rows, rejected)
        return joined

    @property
    def service_rate_rows_s(self) -> float | None:
        """Recent rows/s service-rate EWMA (None before the first timed
        dispatch) — the typed ``LoadSignals`` feed (serve/slo.py): the
        elastic plane reads load through this, never the raw field."""
        with self._cond:
            return self._rate_rows_s

    def _observe_service(self, rows: int, dur_s: float) -> None:
        if rows <= 0 or dur_s <= 0:
            return
        inst = rows / dur_s
        with self._cond:
            if self._rate_rows_s is None:
                self._rate_rows_s = inst
            else:
                a = self._rate_alpha
                self._rate_rows_s = (1 - a) * self._rate_rows_s + a * inst

    def pause(self) -> None:
        """Hold dispatch (drain-style maintenance and deterministic tests);
        submissions still enqueue — and still backpressure."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def shutdown(self, wait: bool = True) -> None:
        with self._cond:
            self._stop = True
            self._paused = False
            self._cond.notify_all()
        if wait:
            self._worker.join(timeout=30)

    # -- worker side ---------------------------------------------------------

    def _pick_stream(self, now: float) -> tuple[tuple | None, float | None]:
        """(key of the stream to flush NOW, or None; earliest deadline among
        pending streams when nothing is flushable). A stream is flushable
        when it reaches bucket capacity or its oldest request's deadline —
        choosing the oldest FLUSHABLE stream (not the globally oldest one)
        avoids head-of-line blocking: a capacity-full stream must not wait
        behind an older sparse stream that is still accumulating."""
        flush_key, flush_t = None, None
        next_deadline = None
        for key, q in self._queues.items():
            if not q:
                continue
            deadline = q[0].t_submit + self._max_wait_s
            if (sum(r.rows for r in q) >= self._max_rows
                    or now >= deadline or self._stop):
                if flush_t is None or q[0].t_submit < flush_t:
                    flush_key, flush_t = key, q[0].t_submit
            elif next_deadline is None or deadline < next_deadline:
                next_deadline = deadline
        return flush_key, next_deadline

    def _pop_batch(self) -> tuple[tuple, list[Request], bool] | None:
        """Block until a stream is flushable (capacity or deadline), then
        pop greedily up to the largest bucket. Returns None on shutdown."""
        with self._cond:
            while True:
                if self._stop and (self._paused
                                   or not any(self._queues.values())):
                    return None
                if self._paused:
                    self._cond.wait(timeout=0.1)
                    continue
                now = monotime()
                key, next_deadline = self._pick_stream(now)
                if key is None:
                    self._cond.wait(
                        timeout=0.1 if next_deadline is None
                        else max(1e-4, next_deadline - now))
                    continue
                q = self._queues[key]
                deadline_hit = now >= q[0].t_submit + self._max_wait_s
                reqs: list[Request] = [q.popleft()]
                rows = reqs[0].rows
                while q and rows + q[0].rows <= self._max_rows:
                    r = q.popleft()
                    reqs.append(r)
                    rows += r.rows
                self._queued_rows -= rows
                self._metrics.record_dequeue(rows)
                return key, reqs, deadline_hit and rows < self._max_rows

    def _loop(self) -> None:
        # worker-survival contract: NO exception from the dispatch callback
        # may escape this loop — it would kill the only drain thread and
        # strand every queued result() waiter until timeout. A failed flush
        # marks exactly its own requests failed (typed) and the worker
        # moves on to the next batch.
        while True:
            popped = self._pop_batch()
            if popped is None:
                return
            key, reqs, deadline_flush = popped
            t0 = monotime()
            try:
                served = self._dispatch(key, reqs, deadline_flush)
                # only rows the backend actually SERVED feed the rate:
                # a shed/failed flush "completes" in microseconds and
                # would inflate the EWMA by orders of magnitude, turning
                # retry_after_s into a hot-retry hint during the exact
                # incidents it exists for (dispatchers return None for
                # flushes that did no device work)
                if isinstance(served, int) and served > 0:
                    self._observe_service(served, monotime() - t0)
            except BaseException as e:  # noqa: BLE001 — fan the error out
                err = e if isinstance(e, ServeError) else DispatchError(key, e)
                n = 0
                for r in reqs:
                    if not r.future.done():
                        r.future._set_error(err)
                        n += 1
                if n:
                    self._metrics.record_request_errors(n, type(err).__name__)
