"""SLO-driven admission control for the serving gateway.

At front-door scale, overload is a scheduling decision, not an accident:
when demand exceeds capacity SOMETHING will not be served, and the only
question is whether the victim is chosen (scavenger work, with a typed
retry hint) or random (every caller times out together). This module
makes the choice explicit:

- **priority classes** — ``interactive`` (a human is waiting), ``batch``
  (a job is waiting), ``scavenger`` (nobody is waiting). Requests carry
  one; admission sheds scavenger-first.
- **brownout ladder** — admission level 0 admits everything, level 1
  sheds scavenger, level 2 sheds scavenger+batch. Interactive traffic is
  never shed by the ladder — only by hard queue backpressure — which is
  what lets the gateway promise "zero interactive requests lost" through
  a replica failure (ISSUE 6 acceptance).
- **closed-loop controller** — the gateway feeds its observed p99 after
  every flush; sustained p99 above ``target_p99_ms`` climbs the ladder
  one rung, sustained p99 below ``narrow_frac * target`` descends.
  Adjustment is count-gated (``adjust_every`` observations between
  moves), so the loop is deterministic under a deterministic load and
  cannot flap on a single slow dispatch.
- **deadline + queue-pressure sheds** — a request whose predicted wait
  (queue depth x recent per-row service rate, from the micro-batcher)
  already exceeds its deadline is refused NOW, not after it times out;
  lower priorities are refused earlier on the queue-depth ramp
  (``scavenger_depth_frac`` / ``batch_depth_frac`` of the hard cap).

Sheds reuse the typed contracts callers already handle:
:class:`~sparse_coding_tpu.serve.batching.QueueFullError` carrying
``retry_after_s`` (the predicted drain time). Everything here is plain
host Python with no clock reads — state advances only on ``observe_p99``
/ ``admit`` calls, so tests drive it exactly.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass

from sparse_coding_tpu.serve.batching import QueueFullError

INTERACTIVE = "interactive"
BATCH = "batch"
SCAVENGER = "scavenger"
PRIORITIES = (INTERACTIVE, BATCH, SCAVENGER)


def priority_rank(priority: str) -> int:
    """Scheduling rank (0 = most urgent). The ONE ordering shared by the
    gateway's admission ladder and the fleet scheduler's bin-packing
    (pipeline/placement.py): a tenant's sweep and a serving request mean
    the same thing by "interactive". Unknown priorities raise — both
    callers validate at their front door."""
    if priority not in PRIORITIES:
        raise ValueError(f"unknown priority {priority!r} "
                         f"(supported: {PRIORITIES})")
    return PRIORITIES.index(priority)


def windowed_quantile(samples, q: float):
    """Nearest-rank quantile over a RECENT-sample window (the gateway's
    rolling latency deque). The closed loop must read this, never a
    cumulative histogram: all-time quantiles hold an incident's slow
    tail in the p99 for tens of thousands of requests after recovery,
    pinning the brownout ladder up. Returns None on an empty window."""
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1,
              max(0, math.ceil(q * len(ordered)) - 1))
    return ordered[idx]

# admission level -> priorities the ladder sheds at that level
_LADDER: dict[int, frozenset] = {
    0: frozenset(),
    1: frozenset({SCAVENGER}),
    2: frozenset({SCAVENGER, BATCH}),
}
MAX_LEVEL = max(_LADDER)


@dataclass(frozen=True)
class LoadSignals:
    """One typed load observation — the AUDITED struct the elastic plane
    (pipeline/plane.py) scales the pod's serve/train split from. The
    gateway assembles it from the controllers that already compute each
    number (micro-batcher queue + service-rate EWMA, admission ladder);
    the plane never reaches into controller internals, so the seam
    between "what serving knows" and "what the arbiter acts on" is this
    one immutable record."""

    queued_rows: int                        # rows waiting right now
    queue_depth_ewma: float                 # LoadTracker's smoothed depth
    service_rate_rows_s: float | None       # batcher EWMA; None pre-traffic
    predicted_wait_s: float | None          # drain estimate for new work
    admission_level: int                    # brownout rung (0 = open)
    ticks: int = 0                          # observations folded so far
    # largest rung of the ACTIVE bucket ladder (0 = unreported): ladder
    # swaps (serve/ladder.py §24) surface through the same audited
    # struct the arbiter already reads, so plane breadcrumbs and tests
    # see capacity-shape changes without reaching into the gateway
    active_max_rows: int = 0


class LoadTracker:
    """Deterministic EWMA fold over load observations.

    Like everything in this module, NO clock reads — state advances only
    on :meth:`observe` calls, so a scripted observation sequence always
    produces the exact same :class:`LoadSignals` stream and the plane's
    scale decisions replay bit-for-bit in tests."""

    def __init__(self, alpha: float = 0.3):
        if not (0.0 < alpha <= 1.0):
            raise ValueError("alpha must be in (0, 1]")
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._depth_ewma: float | None = None
        self._ticks = 0
        self._last: LoadSignals | None = None

    def observe(self, queued_rows: int,
                service_rate_rows_s: float | None = None,
                predicted_wait_s: float | None = None,
                admission_level: int = 0,
                active_max_rows: int = 0) -> LoadSignals:
        """Fold one observation; returns the updated snapshot."""
        rows = max(0, int(queued_rows))
        with self._lock:
            if self._depth_ewma is None:
                self._depth_ewma = float(rows)
            else:
                self._depth_ewma += self._alpha * (rows - self._depth_ewma)
            self._ticks += 1
            self._last = LoadSignals(
                queued_rows=rows,
                queue_depth_ewma=self._depth_ewma,
                service_rate_rows_s=service_rate_rows_s,
                predicted_wait_s=predicted_wait_s,
                admission_level=int(admission_level),
                ticks=self._ticks,
                active_max_rows=int(active_max_rows))
            return self._last

    def snapshot(self) -> LoadSignals:
        """Latest signals without advancing state (all-zero pre-traffic)."""
        with self._lock:
            if self._last is None:
                return LoadSignals(queued_rows=0, queue_depth_ewma=0.0,
                                   service_rate_rows_s=None,
                                   predicted_wait_s=None,
                                   admission_level=0, ticks=0)
            return self._last


class AdmissionController:
    """Brownout ladder + closed-loop p99 controller (gateway-owned)."""

    def __init__(self, target_p99_ms: float = 100.0,
                 narrow_frac: float = 0.5,
                 adjust_every: int = 32,
                 scavenger_depth_frac: float = 0.5,
                 batch_depth_frac: float = 0.85):
        if target_p99_ms <= 0:
            raise ValueError("target_p99_ms must be > 0")
        if not (0.0 < narrow_frac < 1.0):
            raise ValueError("narrow_frac must be in (0, 1)")
        if not (0.0 < scavenger_depth_frac <= batch_depth_frac <= 1.0):
            raise ValueError("need 0 < scavenger_depth_frac <= "
                             "batch_depth_frac <= 1")
        self.target_p99_ms = float(target_p99_ms)
        self._narrow_frac = float(narrow_frac)
        self._adjust_every = max(1, int(adjust_every))
        self._depth_frac = {SCAVENGER: float(scavenger_depth_frac),
                            BATCH: float(batch_depth_frac),
                            INTERACTIVE: 1.0}
        self._lock = threading.Lock()
        self._level = 0
        self._since_change = 0
        self._n_widened = 0
        self._n_narrowed = 0

    # -- closed loop ----------------------------------------------------------

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def set_level(self, level: int) -> None:
        """Operator override (drills, tests): pin the ladder rung."""
        if level not in _LADDER:
            raise ValueError(f"admission level must be in "
                             f"{sorted(_LADDER)}, got {level}")
        with self._lock:
            self._level = level
            self._since_change = 0

    def observe_p99(self, p99_ms: float | None) -> int:
        """Feed one p99 observation (the gateway calls this after every
        flush with its latency histogram's current p99); returns the
        possibly-adjusted level. Count-gated: at most one rung move per
        ``adjust_every`` observations."""
        with self._lock:
            if p99_ms is None:
                return self._level
            self._since_change += 1
            if self._since_change < self._adjust_every:
                return self._level
            if p99_ms > self.target_p99_ms and self._level < MAX_LEVEL:
                self._level += 1
                self._n_widened += 1
                self._since_change = 0
            elif (p99_ms < self.target_p99_ms * self._narrow_frac
                    and self._level > 0):
                self._level -= 1
                self._n_narrowed += 1
                self._since_change = 0
            return self._level

    # -- per-request admission ------------------------------------------------

    def admit(self, priority: str, deadline_s: float | None,
              queued_rows: int, max_queue_rows: int,
              predicted_wait_s: float | None) -> None:
        """Admit or raise a typed shed for one request. Shed reasons, in
        check order: brownout ladder (priority shed at the current
        level), queue-depth ramp (lower priorities refused earlier), and
        deadline (predicted wait already exceeds it)."""
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(supported: {PRIORITIES})")
        with self._lock:
            shed_priorities = _LADDER[self._level]
        if priority in shed_priorities:
            raise QueueFullError(queued_rows, max_queue_rows,
                                 predicted_wait_s)
        if queued_rows > self._depth_frac[priority] * max_queue_rows:
            raise QueueFullError(queued_rows, max_queue_rows,
                                 predicted_wait_s)
        if (deadline_s is not None and predicted_wait_s is not None
                and predicted_wait_s > deadline_s):
            raise QueueFullError(queued_rows, max_queue_rows,
                                 predicted_wait_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self._level,
                    "target_p99_ms": self.target_p99_ms,
                    "sheds_priorities": sorted(_LADDER[self._level]),
                    "widened": self._n_widened,
                    "narrowed": self._n_narrowed}
