"""Multi-dict model registry for the serving engine.

Loads trained dictionaries from both artifact families the repo produces —
native ``learned_dicts.pkl`` (utils/artifacts.py) and reference torch
``learned_dicts.pt`` (utils/ref_interop.py) — into a name → entry table the
engine compiles bucket programs against. Registration is the trust and
shape boundary: every dict passes a signature audit (uniform
encode/decode/predict shapes, models/learned_dict.py contract) before it
becomes servable, and batch-coupled dicts (AddedNoise) are rejected because
the micro-batcher coalesces rows across requests.

``register_stack`` builds the vmapped multi-dict path from the ensembling
direction in PAPERS.md ("Ensembling Sparse Autoencoders"): N structurally
identical dicts stack into one pytree with a leading member axis, and the
engine scores a single activation batch against all N in ONE device program
(`vmap(op, in_axes=(0, None))`) instead of N dispatches.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from sparse_coding_tpu.models.learned_dict import LearnedDict
from sparse_coding_tpu.utils.trees import stack_trees


@dataclasses.dataclass(frozen=True)
class RegistryEntry:
    name: str
    tree: Any  # LearnedDict pytree; stacked (leading member axis) if n_stack
    cls_name: str
    n_stack: int | None  # None = single dict, int = vmapped member count
    d_activation: int
    n_feats: int
    hyperparams: Any  # dict (single) or list[dict] (stack)

    @property
    def is_stack(self) -> bool:
        return self.n_stack is not None


def audit_signature(ld: LearnedDict) -> tuple[int, int]:
    """Enforce the uniform inference contract on a candidate dict: encode
    maps [b, d] → [b, n_feats], decode maps codes back to [b, d], predict
    preserves [b, d]. Runs on a 2-row zero batch (a startup-time trace, not
    a hot-path cost) and returns (d_activation, n_feats)."""
    d = int(ld.activation_size)
    n = int(ld.n_feats)
    x = jnp.zeros((2, d), jnp.float32)
    c = ld.encode(x)
    if tuple(c.shape) != (2, n):
        raise TypeError(
            f"{type(ld).__name__}.encode([2, {d}]) returned shape "
            f"{tuple(c.shape)}, expected (2, {n}) — violates the uniform "
            "LearnedDict signature (models/learned_dict.py)")
    xr = ld.decode(c)
    if tuple(xr.shape) != (2, d):
        raise TypeError(
            f"{type(ld).__name__}.decode([2, {n}]) returned shape "
            f"{tuple(xr.shape)}, expected (2, {d})")
    p = ld.predict(x)
    if tuple(p.shape) != (2, d):
        raise TypeError(
            f"{type(ld).__name__}.predict([2, {d}]) returned shape "
            f"{tuple(p.shape)}, expected (2, {d})")
    return d, n


class ModelRegistry:
    """Name → :class:`RegistryEntry` table. Mutations before
    ``ServingEngine.warmup()`` are free; dicts registered after warmup are
    served but their first query pays an on-the-fly compile (counted by the
    engine's recompile metric)."""

    def __init__(self, audit: bool = True):
        self._audit = audit
        self._entries: dict[str, RegistryEntry] = {}

    # -- registration --------------------------------------------------------

    def register(self, name: str, ld: LearnedDict,
                 hyperparams: dict | None = None) -> RegistryEntry:
        if name in self._entries:
            raise ValueError(f"model {name!r} already registered")
        if not isinstance(ld, LearnedDict):
            raise TypeError(f"{name!r}: expected a LearnedDict, got "
                            f"{type(ld).__name__}")
        if type(ld).batch_coupled:
            raise TypeError(
                f"{name!r}: {type(ld).__name__} is batch_coupled (encode "
                "depends on the whole batch) — coalesced serving would "
                "change per-request results; serve it out-of-band instead")
        if self._audit:
            d, n = audit_signature(ld)
        else:
            d, n = int(ld.activation_size), int(ld.n_feats)
        entry = RegistryEntry(name=name, tree=ld,
                              cls_name=type(ld).__name__, n_stack=None,
                              d_activation=d, n_feats=n,
                              hyperparams=dict(hyperparams or {}))
        self._entries[name] = entry
        return entry

    def register_stack(self, name: str, dicts: Sequence[LearnedDict],
                       hyperparams: Sequence[dict] | None = None
                       ) -> RegistryEntry:
        """Register N structurally identical dicts as ONE vmapped entry.
        Homogeneity is required exactly as vmap requires it: same class,
        same static fields, same leaf structure and shapes."""
        if not dicts:
            raise ValueError("register_stack needs at least one dict")
        if name in self._entries:
            raise ValueError(f"model {name!r} already registered")
        head = dicts[0]
        for ld in dicts:
            if type(ld) is not type(head):
                raise TypeError(
                    f"{name!r}: mixed classes in stack "
                    f"({type(head).__name__} vs {type(ld).__name__})")
            if type(ld).batch_coupled:
                raise TypeError(f"{name!r}: {type(ld).__name__} is "
                                "batch_coupled and cannot be served")
            if (jax.tree.structure(ld) != jax.tree.structure(head)
                    or [tuple(l.shape) for l in jax.tree.leaves(ld)]
                    != [tuple(l.shape) for l in jax.tree.leaves(head)]):
                raise TypeError(f"{name!r}: stack members differ in "
                                "structure or leaf shapes")
        if self._audit:
            d, n = audit_signature(head)
        else:
            d, n = int(head.activation_size), int(head.n_feats)
        entry = RegistryEntry(
            name=name, tree=stack_trees(list(dicts)),
            cls_name=type(head).__name__, n_stack=len(dicts),
            d_activation=d, n_feats=n,
            hyperparams=[dict(h) for h in hyperparams] if hyperparams
            else [{} for _ in dicts])
        self._entries[name] = entry
        return entry

    # -- artifact loading ----------------------------------------------------

    def load_native(self, path: str | Path, prefix: str | None = None,
                    select: Callable[[dict], bool] | None = None
                    ) -> list[str]:
        """Load a native ``learned_dicts.pkl`` sweep artifact; each record
        registers as ``{prefix}/{i}``. ``select`` filters by hyperparams
        before reconstruction (utils/artifacts.py::load_learned_dicts)."""
        from sparse_coding_tpu.utils.artifacts import load_learned_dicts

        pairs = load_learned_dicts(path, select=select)
        return self._register_pairs(pairs, prefix or Path(path).stem)

    def load_reference(self, path: str | Path,
                       prefix: str | None = None) -> list[str]:
        """Load a reference torch ``learned_dicts.pt`` through the
        allowlisted unpickler (utils/ref_interop.py) and register each
        converted dict as ``{prefix}/{i}``."""
        from sparse_coding_tpu.utils.ref_interop import (
            load_reference_learned_dicts,
        )

        pairs = load_reference_learned_dicts(path)
        return self._register_pairs(pairs, prefix or Path(path).stem)

    def _register_pairs(self, pairs, prefix: str) -> list[str]:
        names = []
        for i, (ld, hyper) in enumerate(pairs):
            name = f"{prefix}/{i}"
            self.register(name, ld, hyper)
            names.append(name)
        return names

    # -- lookup --------------------------------------------------------------

    def get(self, name: str) -> RegistryEntry:
        try:
            return self._entries[name]
        except KeyError:
            raise KeyError(f"model {name!r} not registered "
                           f"(have: {sorted(self._entries)})") from None

    def names(self) -> list[str]:
        return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __len__(self) -> int:
        return len(self._entries)
