"""Replica health scoring for the serving gateway.

Routing a front door needs one number per replica that answers "how
likely is the NEXT dispatch here to come back fast and correct?". The
circuit breaker is a binary answer (sick / not sick) with hysteresis;
this module adds the continuous one: an **EWMA health score** fed by
every dispatch outcome — success/failure and latency — so the gateway
can prefer the fastest healthy replica long before anything trips, and
hedges route to the *next-healthiest* rather than a random peer
("Ensembling Sparse Autoencoders", PAPERS.md, motivates replica pools as
the unit of redundancy; health-weighting is what makes a pool better
than round-robin).

Score formula (deterministic, host-side Python only — the serving
metrics doctrine):

    ok_ewma  <- (1-a) * ok_ewma  + a * (1 if ok else 0)     (starts 1.0)
    lat_ewma <- (1-a) * lat_ewma + a * dur_s                (starts 0.0)
    score = ok_ewma / (1 + lat_ewma / latency_scale_s)

A perfect replica scores 1.0; errors decay the numerator, latency grows
the denominator, and both heal with fresh good outcomes at the same EWMA
rate. ``latency_scale_s`` sets how much latency it takes to halve the
score (default 50 ms — the order of one tunnel dispatch).
"""

from __future__ import annotations

import threading


class EwmaHealth:
    """Thread-safe EWMA health score over dispatch outcomes."""

    def __init__(self, alpha: float = 0.2, latency_scale_s: float = 0.05):
        if not (0.0 < alpha <= 1.0):
            raise ValueError("alpha must be in (0, 1]")
        if latency_scale_s <= 0:
            raise ValueError("latency_scale_s must be > 0")
        self._alpha = float(alpha)
        self._latency_scale_s = float(latency_scale_s)
        self._lock = threading.Lock()
        # optimistic start: a fresh (warm) replica must be routable —
        # a pessimistic 0.0 start would starve it of the traffic that
        # would prove it healthy
        self._ok = 1.0
        self._lat = 0.0
        self._n = 0

    def record(self, dur_s: float, ok: bool) -> None:
        """Fold one dispatch outcome in. Failures count their wall too:
        a replica that fails slowly is worse than one that fails fast."""
        a = self._alpha
        with self._lock:
            self._ok = (1 - a) * self._ok + (a if ok else 0.0)
            self._lat = (1 - a) * self._lat + a * max(0.0, float(dur_s))
            self._n += 1

    @property
    def score(self) -> float:
        """Health in (0, 1]: 1.0 = always succeeding instantly."""
        with self._lock:
            return self._ok / (1.0 + self._lat / self._latency_scale_s)

    @property
    def observations(self) -> int:
        with self._lock:
            return self._n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "score": self._ok / (1.0 + self._lat
                                     / self._latency_scale_s),
                "ok_ewma": self._ok,
                "latency_ewma_s": self._lat,
                "observations": self._n,
            }
