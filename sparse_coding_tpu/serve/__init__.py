"""Feature-extraction serving engine.

Turns trained LearnedDict artifacts into a low-latency online service plus
a high-throughput offline scorer, built from four pieces:

- :mod:`registry`  — named model store; loads native ``learned_dicts.pkl``
  and reference ``learned_dicts.pt`` artifacts, audits signatures, stacks
  homogeneous dicts for the vmapped multi-dict path.
- :mod:`engine`    — AOT-compiled padded shape-bucket programs
  (compile-or-load through ``xcache.cached_compile`` at warmup — a
  restarted engine deserializes instead of recompiling, docs/
  ARCHITECTURE.md §13; steady state never traces).
- :mod:`batching`  — dynamic micro-batching queue: coalesce, deadline
  flush, backpressure; the Python hot loop is ``lax``-free.
- :mod:`metrics`   — per-bucket counters, fill ratios, latency quantiles,
  recompile counter (must stay 0 after warmup).
- :mod:`offline`   — batch scorer reusing the same compiled buckets.

Dispatch is hardened (docs/ARCHITECTURE.md §10): typed per-request
errors, a per-stream retry budget for transient failures, and a circuit
breaker (``resilience.CircuitBreaker``) that sheds load while the backend
is sick — all driven deterministically in CI via the ``serve.dispatch``
fault site.

Above the single engine sits the **self-healing gateway**
(docs/ARCHITECTURE.md §14):

- :mod:`gateway`   — replica pools with per-replica breakers, health-
  weighted routing + failover, p95-triggered request hedging, warm-spare
  activation at zero compiles via the xcache warmup manifest.
- :mod:`health`    — EWMA replica health scores.
- :mod:`slo`       — priority classes, brownout admission ladder, and
  the closed-loop p99 controller.

See docs/ARCHITECTURE.md §8 for the engine design rationale.
"""

from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.serve.batching import (
    CircuitOpenError,
    DispatchError,
    QueueFullError,
    RequestTooLargeError,
    ServeError,
    ServeFuture,
)
from sparse_coding_tpu.serve.engine import (
    ServingEngine,
    bucket_op_fn,
    build_bucket_program,
)
from sparse_coding_tpu.serve.gateway import Replica, ServingGateway
from sparse_coding_tpu.serve.health import EwmaHealth
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.offline import score_offline
from sparse_coding_tpu.serve.registry import ModelRegistry, RegistryEntry
from sparse_coding_tpu.serve.slo import (
    BATCH,
    INTERACTIVE,
    PRIORITIES,
    SCAVENGER,
    AdmissionController,
)

__all__ = [
    "AdmissionController",
    "BATCH",
    "CircuitBreaker",
    "CircuitOpenError",
    "DispatchError",
    "EwmaHealth",
    "INTERACTIVE",
    "ModelRegistry",
    "PRIORITIES",
    "RegistryEntry",
    "Replica",
    "SCAVENGER",
    "ServingEngine",
    "ServingGateway",
    "ServingMetrics",
    "ServeError",
    "ServeFuture",
    "QueueFullError",
    "RequestTooLargeError",
    "bucket_op_fn",
    "build_bucket_program",
    "score_offline",
]
