"""Feature-extraction serving engine.

Turns trained LearnedDict artifacts into a low-latency online service plus
a high-throughput offline scorer, built from four pieces:

- :mod:`registry`  — named model store; loads native ``learned_dicts.pkl``
  and reference ``learned_dicts.pt`` artifacts, audits signatures, stacks
  homogeneous dicts for the vmapped multi-dict path.
- :mod:`engine`    — AOT-compiled padded shape-bucket programs
  (compile-or-load through ``xcache.cached_compile`` at warmup — a
  restarted engine deserializes instead of recompiling, docs/
  ARCHITECTURE.md §13; steady state never traces).
- :mod:`batching`  — dynamic micro-batching queue: coalesce, deadline
  flush, backpressure; the Python hot loop is ``lax``-free.
- :mod:`metrics`   — per-bucket counters, fill ratios, latency quantiles,
  recompile counter (must stay 0 after warmup).
- :mod:`offline`   — batch scorer reusing the same compiled buckets.

Dispatch is hardened (docs/ARCHITECTURE.md §10): typed per-request
errors, a per-stream retry budget for transient failures, and a circuit
breaker (``resilience.CircuitBreaker``) that sheds load while the backend
is sick — all driven deterministically in CI via the ``serve.dispatch``
fault site.

Above the single engine sits the **self-healing gateway**
(docs/ARCHITECTURE.md §14):

- :mod:`gateway`   — replica pools with per-replica breakers, health-
  weighted routing + failover, p95-triggered request hedging, warm-spare
  activation at zero compiles via the xcache warmup manifest.
- :mod:`health`    — EWMA replica health scores.
- :mod:`slo`       — priority classes, brownout admission ladder, and
  the closed-loop p99 controller.

See docs/ARCHITECTURE.md §8 for the engine design rationale.
"""

import importlib

# Attributes resolve LAZILY (PEP 562, mirroring the package root): the
# fleet scheduler (pipeline/fleet.py) shares slo.py's priority classes,
# and its import chain — like every scheduler-side pipeline module — must
# stay jax-free so the scheduler process never becomes a second
# tunnel-touching jax process while its worker children own the tunnel
# (CLAUDE.md). Importing the engine/gateway submodules still pulls jax;
# importing `sparse_coding_tpu.serve` (or slo/batching/metrics) does not.
_LAZY_ATTRS = {
    "CircuitBreaker": ("sparse_coding_tpu.resilience.breaker",
                       "CircuitBreaker"),
    "CircuitOpenError": ("sparse_coding_tpu.serve.batching",
                         "CircuitOpenError"),
    "DispatchError": ("sparse_coding_tpu.serve.batching", "DispatchError"),
    "QueueFullError": ("sparse_coding_tpu.serve.batching", "QueueFullError"),
    "RequestTooLargeError": ("sparse_coding_tpu.serve.batching",
                             "RequestTooLargeError"),
    "ServeError": ("sparse_coding_tpu.serve.batching", "ServeError"),
    "ServeFuture": ("sparse_coding_tpu.serve.batching", "ServeFuture"),
    "ServingEngine": ("sparse_coding_tpu.serve.engine", "ServingEngine"),
    "CATALOG_OPS": ("sparse_coding_tpu.serve.engine", "CATALOG_OPS"),
    "DEFAULT_OPS": ("sparse_coding_tpu.serve.engine", "DEFAULT_OPS"),
    "bucket_op_fn": ("sparse_coding_tpu.serve.engine", "bucket_op_fn"),
    "build_bucket_program": ("sparse_coding_tpu.serve.engine",
                             "build_bucket_program"),
    "op_rows_axis": ("sparse_coding_tpu.serve.engine", "op_rows_axis"),
    "Replica": ("sparse_coding_tpu.serve.gateway", "Replica"),
    "ServingGateway": ("sparse_coding_tpu.serve.gateway", "ServingGateway"),
    "EwmaHealth": ("sparse_coding_tpu.serve.health", "EwmaHealth"),
    # ladder derivation is jax-free by design (§24): importing these
    # never pulls the engine/gateway modules
    "STATIC_LADDER": ("sparse_coding_tpu.serve.ladder", "STATIC_LADDER"),
    "LadderError": ("sparse_coding_tpu.serve.ladder", "LadderError"),
    "derive_ladder": ("sparse_coding_tpu.serve.ladder", "derive_ladder"),
    "ladder_pad_rows": ("sparse_coding_tpu.serve.ladder",
                        "ladder_pad_rows"),
    "ladder_to_json": ("sparse_coding_tpu.serve.ladder", "ladder_to_json"),
    "parse_snapshot": ("sparse_coding_tpu.serve.ladder", "parse_snapshot"),
    "pinned_ladder": ("sparse_coding_tpu.serve.ladder", "pinned_ladder"),
    "snapshot_bytes": ("sparse_coding_tpu.serve.ladder", "snapshot_bytes"),
    "traffic_snapshot": ("sparse_coding_tpu.serve.ladder",
                         "traffic_snapshot"),
    "ServingMetrics": ("sparse_coding_tpu.serve.metrics", "ServingMetrics"),
    "score_offline": ("sparse_coding_tpu.serve.offline", "score_offline"),
    "ModelRegistry": ("sparse_coding_tpu.serve.registry", "ModelRegistry"),
    "RegistryEntry": ("sparse_coding_tpu.serve.registry", "RegistryEntry"),
    "BATCH": ("sparse_coding_tpu.serve.slo", "BATCH"),
    "INTERACTIVE": ("sparse_coding_tpu.serve.slo", "INTERACTIVE"),
    "PRIORITIES": ("sparse_coding_tpu.serve.slo", "PRIORITIES"),
    "SCAVENGER": ("sparse_coding_tpu.serve.slo", "SCAVENGER"),
    "AdmissionController": ("sparse_coding_tpu.serve.slo",
                            "AdmissionController"),
}


def __getattr__(name):
    if name in _LAZY_ATTRS:
        module, attr = _LAZY_ATTRS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'sparse_coding_tpu.serve' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_ATTRS))

__all__ = [
    "AdmissionController",
    "BATCH",
    "CATALOG_OPS",
    "CircuitBreaker",
    "DEFAULT_OPS",
    "CircuitOpenError",
    "DispatchError",
    "EwmaHealth",
    "INTERACTIVE",
    "LadderError",
    "ModelRegistry",
    "PRIORITIES",
    "RegistryEntry",
    "Replica",
    "SCAVENGER",
    "STATIC_LADDER",
    "ServingEngine",
    "ServingGateway",
    "ServingMetrics",
    "ServeError",
    "ServeFuture",
    "QueueFullError",
    "RequestTooLargeError",
    "bucket_op_fn",
    "build_bucket_program",
    "derive_ladder",
    "ladder_pad_rows",
    "ladder_to_json",
    "op_rows_axis",
    "parse_snapshot",
    "pinned_ladder",
    "score_offline",
    "snapshot_bytes",
    "traffic_snapshot",
]
