"""Feature-extraction serving engine.

Turns trained LearnedDict artifacts into a low-latency online service plus
a high-throughput offline scorer, built from four pieces:

- :mod:`registry`  — named model store; loads native ``learned_dicts.pkl``
  and reference ``learned_dicts.pt`` artifacts, audits signatures, stacks
  homogeneous dicts for the vmapped multi-dict path.
- :mod:`engine`    — AOT-compiled padded shape-bucket programs
  (compile-or-load through ``xcache.cached_compile`` at warmup — a
  restarted engine deserializes instead of recompiling, docs/
  ARCHITECTURE.md §13; steady state never traces).
- :mod:`batching`  — dynamic micro-batching queue: coalesce, deadline
  flush, backpressure; the Python hot loop is ``lax``-free.
- :mod:`metrics`   — per-bucket counters, fill ratios, latency quantiles,
  recompile counter (must stay 0 after warmup).
- :mod:`offline`   — batch scorer reusing the same compiled buckets.

Dispatch is hardened (docs/ARCHITECTURE.md §10): typed per-request
errors, a per-stream retry budget for transient failures, and a circuit
breaker (``resilience.CircuitBreaker``) that sheds load while the backend
is sick — all driven deterministically in CI via the ``serve.dispatch``
fault site.

See docs/ARCHITECTURE.md §8 for design rationale.
"""

from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.serve.batching import (
    CircuitOpenError,
    DispatchError,
    QueueFullError,
    RequestTooLargeError,
    ServeError,
    ServeFuture,
)
from sparse_coding_tpu.serve.engine import (
    ServingEngine,
    bucket_op_fn,
    build_bucket_program,
)
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.offline import score_offline
from sparse_coding_tpu.serve.registry import ModelRegistry, RegistryEntry

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DispatchError",
    "ModelRegistry",
    "RegistryEntry",
    "ServingEngine",
    "ServingMetrics",
    "ServeError",
    "ServeFuture",
    "QueueFullError",
    "RequestTooLargeError",
    "bucket_op_fn",
    "build_bucket_program",
    "score_offline",
]
