"""Feature-extraction serving engine.

Turns trained LearnedDict artifacts into a low-latency online service plus
a high-throughput offline scorer, built from four pieces:

- :mod:`registry`  — named model store; loads native ``learned_dicts.pkl``
  and reference ``learned_dicts.pt`` artifacts, audits signatures, stacks
  homogeneous dicts for the vmapped multi-dict path.
- :mod:`engine`    — AOT-compiled padded shape-bucket programs
  (``jit(...).lower(...).compile()`` at warmup; steady state never traces).
- :mod:`batching`  — dynamic micro-batching queue: coalesce, deadline
  flush, backpressure; the Python hot loop is ``lax``-free.
- :mod:`metrics`   — per-bucket counters, fill ratios, latency quantiles,
  recompile counter (must stay 0 after warmup).
- :mod:`offline`   — batch scorer reusing the same compiled buckets.

See docs/ARCHITECTURE.md §8 for design rationale.
"""

from sparse_coding_tpu.serve.batching import (
    QueueFullError,
    RequestTooLargeError,
    ServeError,
    ServeFuture,
)
from sparse_coding_tpu.serve.engine import ServingEngine, bucket_op_fn
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.offline import score_offline
from sparse_coding_tpu.serve.registry import ModelRegistry, RegistryEntry

__all__ = [
    "ModelRegistry",
    "RegistryEntry",
    "ServingEngine",
    "ServingMetrics",
    "ServeError",
    "ServeFuture",
    "QueueFullError",
    "RequestTooLargeError",
    "bucket_op_fn",
    "score_offline",
]
