"""Offline high-throughput batch scoring over the serving engine.

Bulk jobs (score a whole activation dump against a registry model) reuse
the SAME AOT-compiled bucket executables the online path serves from — no
separate compile cache, no queue: the driver slices the input into
largest-bucket slabs and calls :meth:`ServingEngine.run_padded` directly
from the caller thread, so a nightly re-scoring job keeps the recompile
counter at 0 and exercises exactly the programs production traffic uses.

Accepts an in-RAM array or a ChunkStore-like object with ``n_chunks`` /
``load_chunk`` (the data-layer streaming contract), processing one chunk at
a time with bounded memory.
"""

from __future__ import annotations

from typing import Any, Iterator

import jax
import numpy as np

from sparse_coding_tpu.serve.engine import ServingEngine


def _iter_arrays(activations: Any) -> Iterator[np.ndarray]:
    if hasattr(activations, "n_chunks") and hasattr(activations,
                                                    "load_chunk"):
        for i in range(activations.n_chunks):
            yield np.asarray(activations.load_chunk(i))
    else:
        yield np.asarray(activations)


def score_offline(engine: ServingEngine, model: str, activations: Any,
                  op: str = "encode") -> Any:
    """Score ``activations`` ([rows, width] array or chunk store) through
    ``model``'s compiled bucket programs. Returns the concatenated result
    with the same leading row count (a (values, indices) pair for
    ``op="topk"``); the tail slab pads into the smallest covering bucket
    exactly like an online partial flush."""
    slab_rows = engine._buckets[-1]
    width = engine._op_width(engine._registry.get(model), op)
    pieces: list[Any] = []
    total = 0
    for arr in _iter_arrays(activations):
        if arr.ndim != 2 or arr.shape[1] != width:
            raise ValueError(f"offline input must be [rows, {width}], got "
                             f"{arr.shape}")
        for start in range(0, arr.shape[0], slab_rows):
            slab = np.ascontiguousarray(
                arr[start:start + slab_rows]).astype(engine._np_dtype,
                                                     copy=False)
            _, host = engine.run_padded(model, op, slab)
            pieces.append(host)
            total += slab.shape[0]
    if not pieces:
        raise ValueError("no rows to score")
    rows_axis = 1 if engine._registry.get(model).is_stack else 0
    return jax.tree.map(
        lambda *leaves: np.concatenate(leaves, axis=rows_axis), *pieces)
