"""Derived bucket ladders: traffic-shaped serving shapes (§24).

The engine's shape-bucket ladder (serve/engine.py, default 8/64/512) was
a constant picked before any traffic existed. This module makes it a
DERIVED artifact: a pure, byte-deterministic solver that reads one
metrics-registry snapshot — the rolling request-size histogram
(``serve.request_rows``) plus the per-bucket fill counters — and returns
the K-rung ladder minimizing expected pad-rows over that traffic,
subject to a max-rungs compile budget and a row-alignment constraint
(mesh data-axis divisibility rides on the alignment).

Doctrine, mirroring groups/similarity.py:

- **snapshot in, ladder out** — derivation never reads live mutable
  state. ``snapshot_bytes`` freezes the registry's instruments into
  canonical JSON wrapped with a self-digest; ``parse_snapshot`` verifies
  the digest, so a corrupted snapshot (fault site
  ``gateway.ladder.derive`` in mode=corrupt) fails loudly and
  deterministically instead of deriving a garbage ladder.
- **byte-determinism** — integer sizes, integer weights, a DP with
  first-strict-improvement tie-breaks: the same snapshot bytes produce
  the same ``ladder_to_json`` bytes, build-twice bitwise
  (tests/test_ladder.py).
- **jax-free** — the solver runs on the gateway's maintenance path and
  in the arbiter's tick; it must never become a tunnel-touching import
  (the serve/ lazy-import contract).

The swap itself (warm the candidate's programs through xcache in a
spare, then atomically replace the active ladder behind crash barrier
``gateway.ladder.swap``) lives in serve/gateway.py.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional, Sequence

STATIC_LADDER = (8, 64, 512)

# manual override: a comma-separated rung list, e.g. "8,24,96" — the
# operator's pin wins over derivation and bypasses the flap guard
# (docs/RUNBOOK_TUNNEL.md, "A flapping or stuck ladder swap")
PIN_ENV = "SPARSE_CODING_LADDER_PIN"

SNAPSHOT_VERSION = 1

# request-size histogram bounds (rows): denser than the geometric
# latency default and carrying non-power-of-two edges (6/12/24/48/96/
# 192/384/768) so the solver can see — and pick — rungs the static
# ladder never offered. Upper edges are the candidate rung vocabulary.
REQUEST_ROW_BOUNDS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96,
                      128, 192, 256, 384, 512, 768, 1024, 1536, 2048)


class LadderError(ValueError):
    """Typed failure of snapshot parsing or ladder derivation."""


class SnapshotIntegrityError(LadderError):
    """The snapshot bytes do not match their embedded digest (torn or
    corrupted payload) — derivation must be skipped, never guessed."""


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def _digest(obj) -> str:
    return hashlib.sha256(_canonical(obj)).hexdigest()


def _split_instrument(key: str) -> tuple[str, dict]:
    """``"serve.rows{bucket=8}"`` → ``("serve.rows", {"bucket": "8"})``."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


def traffic_snapshot(registry) -> dict:
    """Freeze one registry's serving-traffic instruments into a plain
    JSON-able dict: the rolling request-size histogram plus per-bucket
    batch/row fill counters and latency histograms. This dict — not the
    live registry — is what derivation consumes."""
    raw = registry.snapshot()
    request_rows = None
    latency: dict[str, dict] = {}
    for key, h in raw.get("histograms", {}).items():
        name, labels = _split_instrument(key)
        if name == "serve.request_rows":
            request_rows = h
        elif name == "serve.latency_s" and "bucket" in labels:
            latency[labels["bucket"]] = {
                "count": int(h.get("count", 0)),
                "sum": float(h.get("sum", 0.0))}
    buckets: dict[str, dict] = {}
    for key, v in raw.get("counters", {}).items():
        name, labels = _split_instrument(key)
        if name in ("serve.batches", "serve.rows") and "bucket" in labels:
            b = buckets.setdefault(labels["bucket"],
                                   {"batches": 0, "rows": 0})
            b["batches" if name == "serve.batches" else "rows"] = int(v)
    if request_rows is None:
        request_rows = {"bounds": list(REQUEST_ROW_BOUNDS),
                        "counts": [0] * (len(REQUEST_ROW_BOUNDS) + 1),
                        "sum": 0.0, "count": 0, "min": None, "max": None}
    return {"version": SNAPSHOT_VERSION,
            "request_rows": request_rows,
            "buckets": buckets,
            "latency": latency}


def snapshot_bytes(registry) -> bytes:
    """Canonical self-digested snapshot bytes — the corruptible payload
    the ``gateway.ladder.derive`` fault site carries. Any bit flip is
    caught by :func:`parse_snapshot` (digest mismatch or JSON decode
    error), never silently derived from."""
    snap = traffic_snapshot(registry)
    return _canonical({"digest": _digest(snap), "snapshot": snap})


def parse_snapshot(raw: bytes) -> dict:
    """Decode + integrity-check snapshot bytes; returns the snapshot
    dict. Raises :class:`SnapshotIntegrityError` on any mismatch."""
    if isinstance(raw, (bytes, bytearray)):
        raw = bytes(raw).decode("utf-8", errors="strict")
    try:
        env = json.loads(raw)
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise SnapshotIntegrityError(
            f"ladder snapshot is not valid JSON: {e}") from e
    if not isinstance(env, dict) or "snapshot" not in env:
        raise SnapshotIntegrityError(
            "ladder snapshot envelope missing 'snapshot'")
    snap = env["snapshot"]
    want = env.get("digest")
    got = _digest(snap)
    if want != got:
        raise SnapshotIntegrityError(
            f"ladder snapshot digest mismatch: recorded {want!r}, "
            f"recomputed {got!r}")
    return snap


def _ceil_align(n: int, align: int) -> int:
    return ((int(n) + align - 1) // align) * align


def _weighted_sizes(snapshot: dict, align: int) -> list[tuple[int, int]]:
    """(size, weight) pairs from the request-size histogram: each bin
    contributes its UPPER edge (conservative — derivation never under-
    provisions a bin) weighted by its count; the overflow bin uses the
    observed max rounded up to alignment."""
    hist = snapshot.get("request_rows") or {}
    bounds = [int(b) for b in hist.get("bounds", [])]
    counts = [int(c) for c in hist.get("counts", [])]
    out: list[tuple[int, int]] = []
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if i < len(bounds):
            size = bounds[i]
        else:
            mx = hist.get("max")
            if mx is None:
                continue
            size = _ceil_align(int(mx), align)
        out.append((max(size, 1), c))
    return sorted(out)


def derive_ladder(snapshot: dict, *, max_rungs: int = 4, align: int = 8,
                  min_rung: int = 8,
                  fallback: Sequence[int] = STATIC_LADDER) -> dict:
    """Solve for the ≤``max_rungs`` ladder minimizing expected pad-rows
    over the snapshot's request-size distribution.

    Exact DP over the candidate rung vocabulary (the align-rounded
    distinct observed sizes): ``cost(prev, rung)`` is the pad paid by
    every observed size in ``(prev, rung]`` served at ``rung``; the
    largest candidate is mandatory (the ladder must cover the observed
    max). All-integer arithmetic and first-strict-improvement
    tie-breaks make the result a pure function of the snapshot bytes.
    With no traffic the ``fallback`` ladder is returned verbatim
    (reason ``"no-traffic"``) so a cold gateway never swaps."""
    if max_rungs < 1:
        raise LadderError("max_rungs must be >= 1")
    if align < 1 or min_rung < 1:
        raise LadderError("align and min_rung must be >= 1")
    sizes = _weighted_sizes(snapshot, align)
    base = {"align": int(align), "max_rungs": int(max_rungs),
            "version": SNAPSHOT_VERSION}
    if "digest" in snapshot:
        base["source_digest"] = snapshot["digest"]
    if not sizes:
        return dict(base, rungs=[int(b) for b in fallback],
                    expected_pad_rows=0, request_count=0,
                    reason="no-traffic")
    total_requests = sum(w for _, w in sizes)
    # candidate vocabulary: align-rounded observed sizes, floored at
    # min_rung; ascending and distinct by construction of the set
    cands = sorted({max(_ceil_align(s, align), _ceil_align(min_rung, align))
                    for s, _ in sizes})
    m = len(cands)
    INF = float("inf")

    def seg_cost(prev_c: int, c: int) -> int:
        return sum(w * (c - s) for s, w in sizes if prev_c < s <= c)

    # dp[k][j]: min pad covering every size <= cands[j] with exactly k
    # rungs, rung cands[j] chosen; parent pointers rebuild the ladder
    k_max = min(max_rungs, m)
    dp = [[INF] * m for _ in range(k_max + 1)]
    parent = [[-1] * m for _ in range(k_max + 1)]
    for j in range(m):
        dp[1][j] = seg_cost(0, cands[j])
    for k in range(2, k_max + 1):
        for j in range(k - 1, m):
            best, arg = INF, -1
            for i in range(j):
                prev = dp[k - 1][i]
                if prev == INF:
                    continue
                cost = prev + seg_cost(cands[i], cands[j])
                if cost < best:
                    best, arg = cost, i
            dp[k][j], parent[k][j] = best, arg
    best_k, best_cost = 1, dp[1][m - 1]
    for k in range(2, k_max + 1):
        if dp[k][m - 1] < best_cost:  # strict: prefer FEWER rungs on tie
            best_k, best_cost = k, dp[k][m - 1]
    rungs: list[int] = []
    k, j = best_k, m - 1
    while j >= 0 and k >= 1:
        rungs.append(cands[j])
        j = parent[k][j]
        k -= 1
    rungs.reverse()
    return dict(base, rungs=rungs, expected_pad_rows=int(best_cost),
                request_count=int(total_requests), reason="derived")


def ladder_pad_rows(snapshot: dict, rungs: Sequence[int]) -> int:
    """Expected pad-rows of serving the snapshot's request sizes on a
    GIVEN ladder (the comparison the bench's wasted-pad headline and
    the swap decision read); sizes above the top rung are uncoverable
    and cost the full top-rung pad each (they would be rejected)."""
    rungs = sorted(int(r) for r in rungs)
    sizes = _weighted_sizes(snapshot, align=1)
    pad = 0
    for s, w in sizes:
        cover = next((r for r in rungs if r >= s), None)
        pad += w * ((cover - s) if cover is not None else rungs[-1])
    return int(pad)


def ladder_to_json(ladder: dict) -> str:
    """Canonical JSON of one derived ladder — the byte-determinism
    surface tests assert on (same snapshot ⇒ identical bytes)."""
    return _canonical(ladder).decode("utf-8")


def pinned_ladder(env: Optional[dict] = None) -> tuple[int, ...] | None:
    """The operator's manual ladder pin (``SPARSE_CODING_LADDER_PIN``,
    comma-separated rungs), or None when unset/empty. Raises
    :class:`LadderError` on a malformed pin — a misconfigured override
    must fail loudly, not silently serve the old ladder."""
    raw = (env if env is not None else os.environ).get(PIN_ENV, "").strip()
    if not raw:
        return None
    try:
        rungs = tuple(int(p) for p in raw.split(",") if p.strip())
    except ValueError as e:
        raise LadderError(f"malformed {PIN_ENV}={raw!r}: {e}") from e
    if not rungs or list(rungs) != sorted(set(rungs)) or rungs[0] < 1:
        raise LadderError(
            f"{PIN_ENV}={raw!r} must be unique ascending positive rungs")
    return rungs
