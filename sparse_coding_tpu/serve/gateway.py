"""Self-healing serving gateway: replica pools, failover, hedging, SLO.

One :class:`~sparse_coding_tpu.serve.engine.ServingEngine` is a solid
single replica — AOT bucket programs, a breaker, typed backpressure —
but a single replica is not a front door: one sick backend takes the
whole service down, and there is no notion of request priority,
per-request deadline, or failover (ROADMAP item 2). The gateway makes
every failure mode a handled, observable path:

- **replica pools with health scoring** — the gateway owns N engine
  replicas over one shared :class:`ModelRegistry`. Each replica gets its
  own :class:`~sparse_coding_tpu.resilience.breaker.CircuitBreaker`
  (probe-token API: a raced stale outcome can never fake-heal it) plus
  an EWMA health score (serve/health.py) fed by every dispatch outcome.
  Routing is health-ordered; a failed dispatch **fails over** to the
  next-healthiest replica inside the same flush, so one replica dying
  loses zero admitted requests.
- **warm spares** — a replica whose breaker opens is drained and
  replaced by a spare activated at ZERO backend compiles: the xcache
  warmup manifest (``warmup.json``, docs/ARCHITECTURE.md §13) tells the
  spare the full warm set, and every program loads from the executable
  store before the spare admits traffic. Activation is fault-injectable
  (``gateway.spare.activate``) and crash-barriered at the worst instant
  (warm set loaded, traffic not yet admitted).
- **request hedging** — when a dispatched flush exceeds the bucket's
  observed p95 (the gateway's own dispatch histograms), the same padded
  batch fires at the next-healthiest replica and the first result wins.
  Losers are not cancelled (XLA executions cannot be) but their cost is
  counted: ``gateway.hedges_fired`` / ``hedges_won`` (hedge returned
  first) / ``hedges_wasted`` (primary won after all).
- **SLO admission** — requests carry a priority class
  (interactive / batch / scavenger) and an optional deadline; admission
  sheds scavenger-first via the brownout ladder (serve/slo.py), with a
  closed-loop controller widening/narrowing from the observed p99.
  Sheds reuse the typed ``QueueFullError`` (now with ``retry_after_s``)
  / ``CircuitOpenError`` contracts.
- **traffic-shaped bucket ladders** (serve/ladder.py, §24) — the bucket
  ladder is a derived, hot-swappable artifact: ``maybe_swap_ladder``
  (riding the elastic plane's arbiter tick) derives a pad-minimizing
  candidate from a self-digested traffic snapshot (fault site
  ``gateway.ladder.derive``), holds it through the plane's
  ``Hysteresis`` flap guard, warms its programs through xcache in a
  spare, and flips atomically behind crash barrier
  ``gateway.ladder.swap`` — zero backend compiles on the swap. The
  dispatch path continuously REBATCHES: late-arriving queued requests
  that fit the chosen bucket's remaining rows join the in-flight
  assembly in strict FIFO order (``serve.rebatch.joined/rejected``).
  Every admission check reads the ACTIVE ladder, so a post-swap
  largest-bucket change can't strand admitted work (engines fall back
  to known warm rungs) and oversize errors always cite the live max.

Every routing/hedge/activation decision point is a named fault site
(``gateway.route``, ``gateway.hedge``, ``gateway.spare.activate`` —
docs/ARCHITECTURE.md §10/§14) with deterministic fault-matrix entries in
tests/test_resilience.py; the kill-a-replica drill and the
SIGKILL-mid-activation chaos case live in tests/test_serve_gateway.py;
the SIGKILL-at-ladder-swap chaos case lives in
tests/test_pipeline_chaos.py.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as futures_wait
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.obs import monotime
from sparse_coding_tpu.parallel import partition
from sparse_coding_tpu.pipeline.plane import Hysteresis
from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.resilience.crash import (
    crash_barrier,
    register_crash_site,
)
from sparse_coding_tpu.resilience.faults import (
    fault_point,
    register_fault_site,
)
from sparse_coding_tpu.serve.batching import (
    CircuitOpenError,
    DispatchError,
    MicroBatcher,
    QueueFullError,
    Request,
    ServeFuture,
)
from sparse_coding_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    DEFAULT_OPS,
    ProgramCache,
    ServingEngine,
    fanout_results,
    op_rows_axis,
    prepare_request,
)
from sparse_coding_tpu.serve.health import EwmaHealth
from sparse_coding_tpu.serve.ladder import (
    derive_ladder,
    ladder_pad_rows,
    parse_snapshot,
    pinned_ladder,
    snapshot_bytes,
)
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.registry import ModelRegistry
from sparse_coding_tpu.serve.slo import (
    BATCH,
    PRIORITIES,
    AdmissionController,
    LoadSignals,
    LoadTracker,
    windowed_quantile,
)

register_fault_site("gateway.route",
                    "gateway dispatch — transport/decision point "
                    "immediately before one replica attempt")
register_fault_site("gateway.hedge",
                    "gateway hedging — immediately before firing the "
                    "hedge dispatch at the next-healthiest replica")
register_fault_site("gateway.spare.activate",
                    "warm-spare activation — before the manifest-driven "
                    "warm set loads")
register_crash_site("gateway.spare.activate",  # lint: allow-unmatrixed-crash SIGKILL chaos case lives in tests/test_serve_gateway.py (real gateway at the barrier)
                    "warm spare fully loaded from the executable store, "
                    "not yet admitted to the routing set")
register_fault_site("gateway.ladder.derive",
                    "ladder derivation — the self-digested traffic "
                    "snapshot bytes feeding derive_ladder (corruptible "
                    "payload); an injected error/corruption is a counted "
                    "skip (gateway.ladder.derive_errors) and the active "
                    "ladder is retained")
register_crash_site("gateway.ladder.swap",
                    "candidate ladder's programs fully warm in the "
                    "shared table and durable in the xcache store, the "
                    "active ladder NOT yet replaced — a restart serves "
                    "on the old ladder at zero compiles")

ACTIVE = "active"
DRAINING = "draining"
SPARE = "spare"


@dataclass
class GatewayRequest(Request):
    """One admitted front-door request: a :class:`Request` carrying its
    SLO contract (priority class + optional deadline)."""

    priority: str = BATCH
    deadline_s: Optional[float] = None


class Replica:
    """One pool member: an engine plus ITS OWN breaker + health score.

    The engine's internal breaker/batcher are idle here — the gateway
    owns coalescing and dispatches through ``run_padded`` directly, so
    per-replica failure accounting lives at the gateway layer where the
    routing decision is made."""

    def __init__(self, name: str, engine: ServingEngine, state: str,
                 breaker_threshold: int, breaker_reset_s: float,
                 health_alpha: float, health_latency_scale_s: float,
                 clock=None):
        self.name = name
        self.engine = engine
        self.state = state
        self._breaker_kwargs = dict(
            failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s)
        if clock is not None:
            self._breaker_kwargs["clock"] = clock
        self._health_kwargs = dict(
            alpha=health_alpha, latency_scale_s=health_latency_scale_s)
        self.breaker = CircuitBreaker(**self._breaker_kwargs)
        self.health = EwmaHealth(**self._health_kwargs)

    def reset(self) -> None:
        """Fresh breaker + health (reinstating a drained replica): the
        old instance's history describes the FAILED incarnation."""
        self.breaker = CircuitBreaker(**self._breaker_kwargs)
        self.health = EwmaHealth(**self._health_kwargs)

    def snapshot(self) -> dict:
        return {"state": self.state,
                "breaker": self.breaker.snapshot(),
                "health": self.health.snapshot(),
                "recompiles": self.engine.metrics.recompiles}


class _Attempt:
    """One replica dispatch attempt: the breaker admission token plus
    the ``abandoned`` flag a charged timeout sets — once an attempt has
    been charged as its replica's failure, its eventual late resolution
    must not touch the breaker (a late success would reset the failure
    streak and keep a consistently-past-deadline replica permanently
    routable)."""

    __slots__ = ("rep", "token", "abandoned")

    def __init__(self, rep: Replica, token):
        self.rep = rep
        self.token = token
        self.abandoned = False


class ServingGateway:
    """Front door over a pool of :class:`ServingEngine` replicas.

    ``submit(model, x, op, priority, deadline_s)`` admits through the
    SLO ladder into ONE gateway-owned micro-batching queue; the dispatch
    worker routes each coalesced flush to the healthiest admitting
    replica with failover + hedging. ``warmup()`` warms every ACTIVE
    replica (spares stay cold in memory — their executables are already
    durable in the xcache store, which is exactly what makes activation
    free). ``maintain()`` runs the self-healing pass (drain opened
    replicas, activate spares); it also runs automatically after every
    flush."""

    def __init__(self, registry: ModelRegistry,
                 n_replicas: int = 2,
                 n_spares: int = 1,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 ops: Sequence[str] = DEFAULT_OPS,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 8192,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 5.0,
                 health_alpha: float = 0.2,
                 health_latency_scale_s: float = 0.05,
                 hedge_after_s: Optional[float] = None,
                 hedge_min_samples: int = 20,
                 dispatch_timeout_s: float = 60.0,
                 admission: Optional[AdmissionController] = None,
                 admission_window: int = 512,
                 metrics_registry=None,
                 breaker_clock=None,
                 engine_kwargs: Optional[dict] = None,
                 rebatch: bool = True,
                 ladder_max_rungs: int = 4,
                 ladder_hold_ticks: int = 2,
                 ladder_align: int = 8):
        if n_replicas < 1:
            raise ValueError("need at least one active replica")
        if n_spares < 0:
            raise ValueError("n_spares must be >= 0")
        self._registry = registry
        # the ACTIVE bucket ladder: starts at the construction ladder,
        # atomically replaced by swap_ladder (serve/ladder.py §24) — every
        # admission-time check (prepare_request's oversize rejection, the
        # hedge trigger's bucket lookup) reads THIS, never the
        # construction constant
        self._buckets = tuple(int(b) for b in buckets)
        self._ops = tuple(ops)
        self._max_queue_rows = int(max_queue_rows)
        self._hedge_after_s = hedge_after_s
        self._hedge_min_samples = int(hedge_min_samples)
        if dispatch_timeout_s <= 0:
            raise ValueError("dispatch_timeout_s must be > 0")
        self._dispatch_timeout_s = float(dispatch_timeout_s)
        self._admission = admission if admission is not None \
            else AdmissionController()
        # typed load snapshot for the elastic plane (serve/slo.py):
        # advanced only by load_signals() calls, so the plane's scale
        # decisions are deterministic under a scripted observation stream
        self._load = LoadTracker()
        # the closed loop must see RECENT latency, not all-time history:
        # a cumulative histogram's p99 would hold the brownout ladder up
        # for tens of thousands of requests after an incident ends.
        # Appended only on the dispatch worker thread.
        self._recent_lat: deque = deque(maxlen=max(16,
                                                   int(admission_window)))
        self.metrics = ServingMetrics(registry=metrics_registry)
        self._reg = self.metrics.registry
        ekw = dict(engine_kwargs or {})
        ekw.setdefault("buckets", self._buckets)
        ekw.setdefault("ops", self._ops)
        # one executable table for the whole pool: replicas of one
        # registry compile identical programs, so N replicas (and the
        # warm spare) share ONE executable instance per (model, op,
        # bucket) — a spare activation is a table lookup in-process, and
        # a restarted process still loads from the xcache store
        ekw.setdefault("program_cache", ProgramCache())
        self._np_dtype = None  # set from the first replica below
        self._replicas: dict[str, Replica] = {}
        self._order: list[str] = []  # construction order (stable tiebreak)
        for i in range(n_replicas + n_spares):
            name = (f"replica-{i}" if i < n_replicas
                    else f"spare-{i - n_replicas}")
            engine = ServingEngine(registry, **ekw)
            if self._np_dtype is None:
                self._np_dtype = engine._np_dtype
            self._replicas[name] = Replica(
                name, engine,
                ACTIVE if i < n_replicas else SPARE,
                breaker_threshold, breaker_reset_s,
                health_alpha, health_latency_scale_s,
                clock=breaker_clock)
            self._order.append(name)
        self._pool_lock = threading.Lock()
        # per-flush critical-path scratch (winner replica, hedged flag):
        # written only on the single batcher worker thread (and by
        # _hedged_run, which runs on that same thread)
        self._last_flush: dict = {}
        # sized past 2 because a HUNG dispatch (wedged tunnel: blocks,
        # never raises) cannot be cancelled and holds its worker until
        # the backend answers. The dispatch timeout below records such a
        # replica as failing, so its breaker opens and routing stops
        # feeding it — hung workers stay bounded by the failure
        # threshold plus stray hedges, well under this cap.
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * (n_replicas + n_spares)),
            thread_name_prefix="gateway-dispatch")
        self._batcher = MicroBatcher(
            dispatch=self._dispatch,
            max_rows_per_batch=self._buckets[-1],
            max_wait_s=max_wait_ms / 1e3,
            max_queue_rows=self._max_queue_rows,
            metrics=self.metrics)
        # traffic-shaped ladder state (§24): continuous rebatching on the
        # dispatch path, plus the derive→hold→swap loop. The swap's flap
        # guard is the plane's Hysteresis — a candidate must survive
        # ``ladder_hold_ticks`` consecutive derivations before it swaps
        # in; derivation alignment folds in the mesh's data-axis
        # divisibility so a derived rung is always shardable.
        self._rebatch = bool(rebatch)
        self._ladder_max_rungs = max(1, int(ladder_max_rungs))
        self._ladder_align = max(
            int(ladder_align),
            partition.batch_alignment(ekw.get("mesh")))
        self._ladder_hyst = Hysteresis(ladder_hold_ticks)
        self._candidate_rungs: Optional[tuple] = None
        self._publish_ladder_gauges()

    # -- lifecycle -----------------------------------------------------------

    def warmup(self, max_workers: int | None = None) -> int:
        """AOT compile-or-load every active replica's full program set
        (spares warm on activation from the manifest). Returns the total
        number of programs prepared across replicas."""
        total = 0
        with obs.span("gateway.warmup",
                      replicas=len(self._active_replicas())):
            for rep in self._active_replicas():
                total += rep.engine.warmup(max_workers=max_workers)
        return total

    def shutdown(self, wait: bool = True) -> None:
        self._batcher.shutdown(wait=wait)
        self._hedge_pool.shutdown(wait=wait)
        for rep in self._replicas.values():
            rep.engine.shutdown(wait=wait)

    def pause(self) -> None:
        """Hold gateway dispatch (deterministic tests / maintenance);
        submissions still admit, enqueue, and backpressure."""
        self._batcher.pause()

    def resume(self) -> None:
        self._batcher.resume()

    def __enter__(self) -> "ServingGateway":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- pool views ----------------------------------------------------------

    def _active_replicas(self) -> list[Replica]:
        return [self._replicas[n] for n in self._order
                if self._replicas[n].state == ACTIVE]

    def _spare_replicas(self) -> list[Replica]:
        return [self._replicas[n] for n in self._order
                if self._replicas[n].state == SPARE]

    def _routing_order(self) -> list[Replica]:
        """Health-weighted routing: active replicas, healthiest first
        (construction order breaks exact ties, so routing is
        deterministic under deterministic traffic)."""
        actives = self._active_replicas()
        idx = {n: i for i, n in enumerate(self._order)}
        return sorted(actives,
                      key=lambda r: (-r.health.score, idx[r.name]))

    def replica(self, name: str) -> Replica:
        return self._replicas[name]

    def replica_names(self) -> list[str]:
        return list(self._order)

    def active_replica_names(self) -> list[str]:
        """Names currently in the routing set (construction order) —
        the elastic plane's view of how wide the pool actually is."""
        return [r.name for r in self._active_replicas()]

    # -- request path --------------------------------------------------------

    def submit(self, model: str, x, op: str = "encode",
               priority: str = BATCH,
               deadline_s: Optional[float] = None) -> ServeFuture:
        """Admit one request through the SLO ladder and enqueue it.
        Raises typed sheds: :class:`QueueFullError` (brownout ladder,
        deadline, queue pressure — with ``retry_after_s``) or
        :class:`CircuitOpenError` (no replica currently admits)."""
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(supported: {PRIORITIES})")
        entry = self._registry.get(model)
        actives = self._active_replicas()
        admitting = [r for r in actives if r.breaker.admission_allowed()]
        if not admitting:
            self._record_shed(priority)
            cooldown = min((r.breaker.seconds_until_probe()
                            for r in actives), default=0.0)
            raise CircuitOpenError((model, op), cooldown)
        arr, rows, squeeze = prepare_request(entry, op, self._ops,
                                             self._buckets, self._np_dtype,
                                             x)
        try:
            self._admission.admit(
                priority, deadline_s,
                queued_rows=self._batcher.queued_rows,
                max_queue_rows=self._max_queue_rows,
                predicted_wait_s=self._batcher.predicted_wait_s(rows))
        except QueueFullError:
            self._record_shed(priority)
            raise
        # critical-path identity (§12): minted at admission, carried
        # through queue wait → flush assembly → replica dispatch → hedge,
        # and emitted with the per-stage walls on completion so
        # obs.report decomposes p50/p95/p99 request latency by stage
        req = GatewayRequest(key=(model, op), x=arr, rows=rows,
                             squeeze=squeeze, t_submit=monotime(),
                             priority=priority, deadline_s=deadline_s,
                             trace_id=obs.mint_trace_id())
        try:
            return self._batcher.submit(req)
        except QueueFullError:
            # hard backpressure is also a shed, just the last-resort rung
            self._reg.counter("gateway.shed", priority=priority).inc()
            raise

    def query(self, model: str, x, op: str = "encode",
              priority: str = BATCH, deadline_s: Optional[float] = None,
              timeout: float | None = 60.0):
        """Blocking submit+result."""
        return self.submit(model, x, op=op, priority=priority,
                           deadline_s=deadline_s).result(timeout=timeout)

    def _record_shed(self, priority: str) -> None:
        self.metrics.record_shed()
        self._reg.counter("gateway.shed", priority=priority).inc()

    # -- dispatch (gateway batcher worker thread) ----------------------------

    def _run_one(self, attempt: "_Attempt", model: str, op: str, x):
        """One replica attempt: timed, breaker- and health-accounted.
        Success/failure is recorded HERE so hedge losers that finish
        after the winner still update their replica's score — UNLESS the
        attempt was abandoned by a charged timeout: a late success must
        not reset the breaker's failure streak (a replica consistently
        finishing just past the deadline would otherwise never open,
        never drain, and slowly park every pool worker)."""
        rep = attempt.rep
        t0 = monotime()
        try:
            bucket, host = rep.engine.run_padded(model, op, x)
        except BaseException:
            dur = monotime() - t0
            rep.health.record(dur, ok=False)
            if attempt.abandoned:
                self._reg.counter("gateway.late_results",
                                  replica=rep.name).inc()
            else:
                rep.breaker.record_failure(attempt.token)
                self._reg.counter("gateway.replica_errors",
                                  replica=rep.name).inc()
            raise
        dur = monotime() - t0
        # health always learns the TRUE latency (late = slow = low score)
        rep.health.record(dur, ok=True)
        if attempt.abandoned:
            self._reg.counter("gateway.late_results",
                              replica=rep.name).inc()
            return bucket, host
        rep.breaker.record_success(attempt.token)
        self._reg.counter("gateway.routes", replica=rep.name).inc()
        self._reg.histogram("gateway.dispatch_s", bucket=bucket).observe(dur)
        return bucket, host

    def configure_hedging(self, hedge_after_s: Optional[float]) -> None:
        """Operator knob: explicit hedge trigger override in seconds
        (0.0 hedges every flush, a large value effectively disables);
        ``None`` restores the observed-p95 default."""
        self._hedge_after_s = hedge_after_s

    def _hedge_deadline_s(self, rows: int) -> Optional[float]:
        """When to hedge a flush of ``rows`` rows: the explicit override
        if configured, else the observed p95 of its bucket's dispatch
        wall (None — no hedging — until enough samples exist)."""
        if self._hedge_after_s is not None:
            return self._hedge_after_s
        i = bisect.bisect_left(self._buckets, rows)
        if i == len(self._buckets):
            return None
        h = self._reg.histogram("gateway.dispatch_s",
                                bucket=self._buckets[i])
        if h.count < self._hedge_min_samples:
            return None
        return h.quantile(0.95)

    def _timeout_failure(self, attempt: "_Attempt") -> TimeoutError:
        """A dispatch that neither returned nor raised within the budget
        is a failure of ITS replica: a hung backend (wedged tunnel)
        blocks forever instead of erroring, and without this its breaker
        would never open and routing would keep feeding it. The call
        itself cannot be cancelled — its worker is abandoned (pool is
        sized for that) and the attempt is MARKED abandoned so its
        eventual resolution cannot touch the breaker."""
        attempt.abandoned = True
        attempt.rep.breaker.record_failure(attempt.token)
        attempt.rep.health.record(self._dispatch_timeout_s, ok=False)
        self._reg.counter("gateway.dispatch_timeouts",
                          replica=attempt.rep.name).inc()
        return TimeoutError(
            f"replica {attempt.rep.name} dispatch exceeded "
            f"{self._dispatch_timeout_s}s (hung backend?)")

    def _bounded_result(self, fut, attempt: "_Attempt", t_end: float):
        try:
            return fut.result(timeout=max(0.0, t_end - monotime()))
        except FutureTimeoutError:
            raise self._timeout_failure(attempt) from None

    def _hedged_run(self, attempt: "_Attempt", backups: list[Replica],
                    model: str, op: str, x, rows: int):
        """Primary dispatch with p95-triggered hedging; first success
        wins. Every wait is bounded by ``dispatch_timeout_s``: a hung
        participant is recorded as that replica's failure and the caller
        fails over — a wedged backend degrades the pool, never wedges
        the gateway. Raises only when every participant failed or timed
        out."""
        t_end = monotime() + self._dispatch_timeout_s
        fut = self._hedge_pool.submit(self._run_one, attempt, model, op, x)
        deadline = self._hedge_deadline_s(rows)
        if deadline is None or not backups:
            return self._bounded_result(fut, attempt, t_end)
        try:
            return fut.result(
                timeout=min(deadline, max(0.0, t_end - monotime())))
        except FutureTimeoutError:
            if monotime() >= t_end:
                raise self._timeout_failure(attempt) from None
            # primary is slow, not failed (nor timed out yet): hedge it
        hedge = None
        for rep in backups:
            tok = rep.breaker.allow()
            if tok:
                hedge = _Attempt(rep, tok)
                break
        if hedge is None:
            return self._bounded_result(fut, attempt, t_end)
        try:
            fault_point("gateway.hedge")
            hfut = self._hedge_pool.submit(self._run_one, hedge,
                                           model, op, x)
        except BaseException:  # noqa: BLE001 — hedging is best-effort
            # a failed hedge FIRING must never fail the request: the
            # primary is still running and remains the answer
            self._reg.counter("gateway.hedges_abandoned").inc()
            return self._bounded_result(fut, attempt, t_end)
        self._reg.counter("gateway.hedges_fired").inc()
        self._last_flush["hedged"] = True
        owners = {fut: attempt, hfut: hedge}
        pending = {fut, hfut}
        first_err: Optional[BaseException] = None
        while pending:
            done, pending = futures_wait(pending,
                                         timeout=max(0.0,
                                                     t_end - monotime()),
                                         return_when=FIRST_COMPLETED)
            if not done:
                # overall budget exhausted with participant(s) hung:
                # charge each hung replica, fail over
                err: Optional[BaseException] = first_err
                for f in pending:
                    err = self._timeout_failure(owners[f])
                raise err
            for f in done:
                if f.exception() is None:
                    if f is hfut:
                        self._reg.counter("gateway.hedges_won").inc()
                    else:
                        self._reg.counter("gateway.hedges_wasted").inc()
                    self._last_flush["replica"] = owners[f].rep.name
                    # first-wins cancel semantics: the loser cannot be
                    # cancelled mid-execution; its outcome is recorded
                    # by _run_one when it finishes and then discarded
                    return f.result()
                if first_err is None:
                    first_err = f.exception()
        raise first_err  # both participants failed

    def _dispatch(self, key: tuple, requests: list[Request],
                  deadline_flush: bool) -> int | None:
        """Returns rows served (the batcher's service-rate input), None
        for a shed or failed flush."""
        model, op = key
        # critical-path stage 1, queue wait: stamped per request the
        # moment the flush leaves the queue (§12)
        t_flush = monotime()
        queue_hist = self._reg.histogram("serve.stage_s", stage="queue")
        rows = sum(r.rows for r in requests)
        # continuous rebatching (§24): membership is no longer frozen at
        # pop time — queued requests that arrived before dispatch and fit
        # the chosen bucket's remaining rows join the assembly in strict
        # FIFO order, converting pad rows into served rows for free
        if self._rebatch:
            target = self._covering_bucket(rows)
            if target is not None and target > rows:
                joiners = self._batcher.take_joiners(key, target - rows)
                if joiners:
                    requests = requests + joiners
                    rows += sum(r.rows for r in joiners)
        for r in requests:
            # clamp: a joiner can be submitted a hair after t_flush
            r.queue_s = max(0.0, t_flush - r.t_submit)
            queue_hist.observe(r.queue_s)
        if len(requests) == 1:
            x = requests[0].x
        else:
            x = np.concatenate([r.x for r in requests], axis=0)
        self._reg.histogram("serve.stage_s", stage="assemble").observe(
            monotime() - t_flush)
        candidates = self._routing_order()
        last_err: Optional[BaseException] = None
        t_disp = monotime()
        try:
            for i, rep in enumerate(candidates):
                token = rep.breaker.allow()
                if not token:
                    continue
                try:
                    fault_point("gateway.route")
                except BaseException as e:  # noqa: BLE001 — typed below
                    # a routing/transport failure counts against the
                    # replica it was destined for
                    rep.breaker.record_failure(token)
                    rep.health.record(0.0, ok=False)
                    self._reg.counter("gateway.route_errors").inc()
                    last_err = e
                    if i + 1 < len(candidates):
                        self._reg.counter("gateway.failovers").inc()
                    continue
                try:
                    self._last_flush = {"replica": rep.name,
                                        "hedged": False}
                    bucket, host = self._hedged_run(
                        _Attempt(rep, token), candidates[i + 1:], model,
                        op, x, rows)
                except BaseException as e:  # noqa: BLE001 — typed below
                    last_err = e
                    if i + 1 < len(candidates):
                        self._reg.counter("gateway.failovers").inc()
                    continue
                # stage 3, replica dispatch (failovers + hedge included:
                # this is the request's actual critical path)
                self._reg.histogram("serve.stage_s",
                                    stage="dispatch").observe(
                    monotime() - t_disp)
                self._finish_flush(key, requests, rows, bucket, host,
                                   deadline_flush)
                return rows
            # every candidate refused or failed
            self.metrics.record_dispatch_failure()
            if last_err is None:
                self.metrics.record_shed(len(requests))
                err: Exception = CircuitOpenError(
                    key, min((r.breaker.seconds_until_probe()
                              for r in candidates), default=0.0))
            else:
                err = (last_err if isinstance(last_err, DispatchError)
                       else DispatchError(key, last_err))
            self.metrics.record_request_errors(len(requests),
                                               type(err).__name__)
            for r in requests:
                if not r.future.done():
                    r.future._set_error(err)
            return None
        finally:
            self.maintain()

    def _finish_flush(self, key, requests, rows, bucket, host,
                      deadline_flush) -> None:
        model, op = key
        self.metrics.record_batch(bucket, len(requests), rows,
                                  deadline_flush)
        rows_axis = op_rows_axis(self._registry.get(model), op)
        flush = getattr(self, "_last_flush", {})
        t_fan = monotime()

        def on_latency(r, lat):
            self.metrics.record_latency(bucket, lat)
            self._reg.counter("gateway.served",
                              priority=getattr(r, "priority", BATCH)).inc()
            self._lat_hist().observe(lat)
            self._recent_lat.append(lat)
            # the request's whole critical path in ONE correlated event,
            # keyed by the trace id minted at admission — obs.report's
            # request-stage decomposition reads the stage histograms;
            # this event is the per-request drill-down
            obs.emit_event(
                "serve.request", trace=getattr(r, "trace_id", ""),
                model=model, op=op,
                priority=getattr(r, "priority", BATCH), rows=r.rows,
                bucket=bucket, replica=flush.get("replica", ""),
                hedged=flush.get("hedged", False),
                queue_s=round(getattr(r, "queue_s", 0.0), 6),
                total_s=round(lat, 6))

        fanout_results(requests, host, rows_axis, on_latency=on_latency)
        # stage 4, result fan-out back to the waiters
        self._reg.histogram("serve.stage_s", stage="fanout").observe(
            monotime() - t_fan)
        # closed loop: feed the controller the RECENT pool-wide p99 (the
        # all-time histogram would pin the ladder up long after an
        # incident ends) and expose the resulting rung as a gauge
        p99 = windowed_quantile(list(self._recent_lat), 0.99)
        level = self._admission.observe_p99(
            None if p99 is None else p99 * 1e3)
        self._reg.gauge("gateway.admission_level").set(level)

    def _lat_hist(self):
        return self._reg.histogram("gateway.latency_s")

    # -- traffic-shaped bucket ladder (serve/ladder.py, §24) -----------------

    @property
    def active_buckets(self) -> tuple:
        """The ladder currently admitting and shaping traffic."""
        return self._buckets

    def _covering_bucket(self, rows: int) -> Optional[int]:
        """Smallest ACTIVE rung covering ``rows`` (None when a
        shrink-swap left admitted work above the active max — the engine
        then covers from its known-rung fallback and rebatching simply
        skips the flush)."""
        buckets = self._buckets
        i = bisect.bisect_left(buckets, rows)
        return buckets[i] if i < len(buckets) else None

    def _publish_ladder_gauges(self, old_n_rungs: int = 0) -> None:
        """Active rungs as gauges (``gateway.ladder.rung{idx=..}``) —
        the obs.report "ladder" section reads these; stale indices from
        a longer previous ladder are zeroed so the report never shows a
        ghost rung."""
        buckets = self._buckets
        for i, b in enumerate(buckets):
            self._reg.gauge("gateway.ladder.rung", idx=i).set(b)
        for i in range(len(buckets), max(old_n_rungs, len(buckets))):
            self._reg.gauge("gateway.ladder.rung", idx=i).set(0)
        self._reg.gauge("gateway.ladder.n_rungs").set(len(buckets))
        self._reg.gauge("gateway.ladder.max_rung").set(buckets[-1])

    def maybe_swap_ladder(self) -> Optional[dict]:
        """One derive→hold→swap pass; rides the elastic plane's arbiter
        tick (pipeline/plane.py) and is safe to call from any
        maintenance loop. Never raises: a failed derivation (fault site
        ``gateway.ladder.derive``, including corrupt snapshot bytes —
        the self-digest catches any flip) or a failed swap is a counted
        skip and the ACTIVE ladder is retained. The operator pin
        (``SPARSE_CODING_LADDER_PIN``) overrides derivation AND the flap
        guard. Returns the swap breadcrumb dict, or None when nothing
        swapped."""
        try:
            pin = pinned_ladder()
        except Exception:  # noqa: BLE001 — malformed pin: counted skip
            self._reg.counter("gateway.ladder.derive_errors").inc()
            return None
        if pin is not None:
            if pin == self._buckets:
                return None
            return self._guarded_swap(pin, source="pin")
        try:
            # derivation is seeded from a SNAPSHOT, never live mutable
            # state: the bytes are the corruptible fault payload, and
            # parse_snapshot's digest check turns any corruption into a
            # typed, counted skip
            raw = snapshot_bytes(self._reg)
            raw = fault_point("gateway.ladder.derive", raw)
            snap = parse_snapshot(raw)
            cand = derive_ladder(snap, max_rungs=self._ladder_max_rungs,
                                 align=self._ladder_align,
                                 fallback=self._buckets)
        except Exception:  # noqa: BLE001 — derive failure: counted skip
            self._reg.counter("gateway.ladder.derive_errors").inc()
            return None
        rungs = tuple(int(b) for b in cand["rungs"])
        if rungs == self._buckets:
            self._ladder_hyst.vote(0)
            self._candidate_rungs = None
            return None
        # only swap when the candidate actually saves pad on the
        # snapshot's own traffic (the derived optimum always does unless
        # rounding/fallback interfered — this guards the degenerate
        # cases deterministically)
        if (ladder_pad_rows(snap, rungs)
                >= ladder_pad_rows(snap, self._buckets)):
            self._ladder_hyst.vote(0)
            self._candidate_rungs = None
            return None
        if rungs != self._candidate_rungs:
            # a NEW candidate restarts the hold window: hysteresis
            # confirms persistence of one specific ladder, not churn
            self._ladder_hyst.vote(0)
            self._candidate_rungs = rungs
        if not self._ladder_hyst.vote(1):
            self._reg.counter("gateway.ladder.held").inc()
            return None
        self._candidate_rungs = None
        return self._guarded_swap(
            rungs, source="derived",
            expected_pad_rows=cand.get("expected_pad_rows"))

    def _guarded_swap(self, rungs: tuple, source: str,
                      **detail) -> Optional[dict]:
        try:
            return self.swap_ladder(rungs, source=source, **detail)
        except Exception:  # noqa: BLE001 — swap failure: counted skip,
            # active ladder retained; warm progress (if any) is durable
            # in the xcache store so the retry is cheaper
            self._reg.counter("gateway.ladder.swap_errors").inc()
            return None

    def swap_ladder(self, rungs, source: str = "manual",
                    **detail) -> dict:
        """Zero-compile atomic ladder swap. Order is the whole contract:
        (1) warm every (model, op, new-rung) program through
        ``xcache.cached_compile`` in a warm spare (or the healthiest
        active when the pool has no spare) — the pool's SHARED program
        table plus the durable executable store make the flip free for
        every replica; (2) crash barrier ``gateway.ladder.swap`` at the
        worst instant (candidate fully warm + durable, active ladder
        untouched — a SIGKILL here restarts onto the OLD ladder at zero
        compiles, bitwise); (3) under the pool lock, atomically replace
        the active ladder on the gateway, every replica engine, and the
        batcher's capacity threshold."""
        rungs = tuple(int(b) for b in rungs)
        if not rungs or list(rungs) != sorted(set(rungs)):
            raise ValueError(f"rungs must be unique ascending: {rungs}")
        with self._pool_lock:
            warmer = next(iter(self._spare_replicas()), None)
            if warmer is None:
                warmer = self._routing_order()[0]
        with obs.span("gateway.ladder.swap", source=source,
                      rungs=",".join(str(b) for b in rungs)):
            programs = warmer.engine.warm_buckets(rungs)
            # THE swap instant: every candidate program is in the shared
            # table and durable in the xcache store; nothing has been
            # replaced. SIGKILL here must cost nothing (chaos matrix:
            # restart serves the old ladder, 0 compiles, bitwise).
            crash_barrier("gateway.ladder.swap")
            with self._pool_lock:
                old = self._buckets
                self._buckets = rungs
                for name in self._order:
                    self._replicas[name].engine.set_buckets(rungs)
                self._batcher.set_max_rows(rungs[-1])
                self._publish_ladder_gauges(old_n_rungs=len(old))
        self._reg.counter("gateway.ladder.swaps").inc()
        obs.emit_event("gateway.ladder.swap", rungs=list(rungs),
                       old=list(old), source=source,
                       programs_warmed=programs, **detail)
        return {"rungs": rungs, "old": old, "source": source,
                "programs_warmed": programs, **detail}

    # -- self-healing --------------------------------------------------------

    def maintain(self) -> list[str]:
        """One self-healing pass: every ACTIVE replica whose breaker is
        OPEN is drained and (when a spare exists) replaced by a warm
        spare activated from the manifest. Runs after every flush and on
        demand; returns the names of replicas drained this pass."""
        drained: list[str] = []
        with self._pool_lock:
            for rep in self._active_replicas():
                if rep.breaker.state != "open":
                    continue
                spare = next(iter(self._spare_replicas()), None)
                if spare is None:
                    self._reg.counter("gateway.spare_exhausted").inc()
                    continue
                if self._activate_spare(spare, replacing=rep):
                    drained.append(rep.name)
        return drained

    def _activate_spare(self, spare: Replica,
                        replacing: Optional[Replica] = None) -> bool:
        """Warm the spare from the xcache warmup manifest, then swap it
        into the routing set — in place of ``replacing`` (self-healing
        drain) or as an EXTRA active when ``replacing`` is None (elastic
        scale-up: nothing drains, the pool widens). On failure the spare
        stays a spare (retried next maintain pass) and the pool keeps
        serving on the surviving replicas — activation is never on the
        failure path of in-flight traffic."""
        try:
            with obs.span("gateway.spare.activate", spare=spare.name,
                          replacing=replacing.name if replacing else ""):
                fault_point("gateway.spare.activate")
                programs = spare.engine.warmup_from_manifest()
                # worst instant: the spare's full warm set is loaded (and
                # any fresh compiles are durable in the store), but the
                # routing swap below has not happened — a SIGKILL here
                # must leave a restart that heals identically
                crash_barrier("gateway.spare.activate")
                spare.state = ACTIVE
                if replacing is not None:
                    replacing.state = DRAINING
        except BaseException:  # noqa: BLE001 — activation is off-path
            self._reg.counter("gateway.spare_activation_errors").inc()
            return False
        self._reg.counter("gateway.spare_activations").inc()
        self._reg.counter("gateway.spare_programs_warmed").inc(programs)
        return True

    # -- elastic pool (pipeline/plane.py drives these) -----------------------

    def scale_up(self, n: int = 1) -> list[str]:
        """Elastic scale-up: activate up to ``n`` warm spares as EXTRA
        actives (no replica drained). Zero compiles by construction —
        the spare warms from the xcache manifest through the pool's
        shared program table, exactly the self-healing activation path.
        Returns the names activated (may be shorter when spares ran out
        or an activation failed; the plane retries next tick)."""
        activated: list[str] = []
        with self._pool_lock:
            for spare in self._spare_replicas()[:max(0, int(n))]:
                if self._activate_spare(spare, replacing=None):
                    activated.append(spare.name)
        return activated

    def scale_down(self, n: int = 1) -> list[str]:
        """Elastic scale-down: drain the ``n`` least-healthy actives
        (never below one). A DRAINING replica leaves the routing order
        immediately — in-flight dispatches finish on it, new flushes
        don't start — and ``reinstate()`` returns it to the spare set
        once the plane's drain window passes. Returns the names
        drained."""
        drained: list[str] = []
        with self._pool_lock:
            for rep in reversed(self._routing_order()):
                if len(drained) >= max(0, int(n)):
                    break
                if len(self._active_replicas()) <= 1:
                    break  # the front door never scales to zero
                rep.state = DRAINING
                drained.append(rep.name)
        return drained

    def load_signals(self) -> LoadSignals:
        """Fold one load observation and return the typed snapshot the
        elastic plane scales from (serve/slo.py ``LoadSignals``): queue
        depth + service-rate EWMA from the micro-batcher, brownout rung
        from the admission controller — one audited struct, no
        controller internals."""
        return self._load.observe(
            queued_rows=self._batcher.queued_rows,
            service_rate_rows_s=self._batcher.service_rate_rows_s,
            predicted_wait_s=self._batcher.predicted_wait_s(),
            admission_level=self._admission.level,
            active_max_rows=self._buckets[-1])

    def reinstate(self, name: str) -> None:
        """Ops hook: return a drained (repaired) replica to the pool as
        a warm-spare candidate with a fresh breaker + health score."""
        rep = self._replicas[name]
        if rep.state != DRAINING:
            raise ValueError(f"{name!r} is {rep.state}, not draining")
        rep.reset()
        rep.state = SPARE

    # -- read side -----------------------------------------------------------

    def stats(self) -> dict:
        """One coherent snapshot: the serving-metrics schema (buckets,
        latency quantiles, queue, sheds) plus the gateway section —
        per-replica breaker/health/state, hedge and failover counters,
        admission ladder state."""
        snap = self.metrics.snapshot()
        c = self._reg.counter
        snap["replicas"] = {n: self._replicas[n].snapshot()
                            for n in self._order}
        snap["admission"] = self._admission.snapshot()
        snap["gateway"] = {
            "hedges_fired": c("gateway.hedges_fired").value,
            "hedges_won": c("gateway.hedges_won").value,
            "hedges_wasted": c("gateway.hedges_wasted").value,
            "hedges_abandoned": c("gateway.hedges_abandoned").value,
            "failovers": c("gateway.failovers").value,
            "route_errors": c("gateway.route_errors").value,
            "dispatch_timeouts": {
                n: c("gateway.dispatch_timeouts", replica=n).value
                for n in self._order},
            "replica_errors": {
                n: c("gateway.replica_errors", replica=n).value
                for n in self._order},
            "routes": {n: c("gateway.routes", replica=n).value
                       for n in self._order},
            "spare_activations": c("gateway.spare_activations").value,
            "spare_activation_errors":
                c("gateway.spare_activation_errors").value,
            "spare_exhausted": c("gateway.spare_exhausted").value,
            "shed": {p: c("gateway.shed", priority=p).value
                     for p in PRIORITIES},
            "served": {p: c("gateway.served", priority=p).value
                       for p in PRIORITIES},
            "late_results": {
                n: c("gateway.late_results", replica=n).value
                for n in self._order},
            # the controller is the source of truth (the gauge only
            # refreshes per flush and would lag a set_level override)
            "admission_level": self._admission.level,
            "ladder": {
                "rungs": list(self._buckets),
                "swaps": c("gateway.ladder.swaps").value,
                "held": c("gateway.ladder.held").value,
                "derive_errors": c("gateway.ladder.derive_errors").value,
                "swap_errors": c("gateway.ladder.swap_errors").value,
            },
        }
        return snap
