"""AOT shape-bucket serving engine.

Online inference is request-driven: shapes arrive one ragged handful of
rows at a time, and jit's trace-on-first-shape model would turn every new
row count into a compile in the latency path. The engine removes tracing
from steady state entirely:

- requests coalesce (serve/batching.py) into a small ladder of padded row
  buckets (default 8/64/512 — geometric, so padding waste is bounded at
  ~8x worst case on the smallest bucket and amortizes with load);
- each (model, op, bucket) program is AOT-compiled at startup through
  ``xcache.cached_compile`` — ``warmup()`` walks the full product (on a
  bounded thread pool: XLA compiles release the GIL) so the first real
  request already hits a compiled executable, and with the executable
  cache enabled (``xcache.enable``) a RESTARTED engine loads serialized
  executables instead of recompiling: the second cold start performs
  zero backend compiles (docs/ARCHITECTURE.md §13). Every program is
  recorded in the warmup manifest, the durable statement of what must be
  warm before the engine admits traffic;
- the model pytree is an ARGUMENT of the compiled program (not a closed-
  over constant), so weights live in ordinary device buffers shared across
  buckets rather than being baked into N executables;
- the padded input buffer is donated on TPU (it is fresh per batch, so
  XLA may write outputs in place; donation is skipped on CPU where it is
  unimplemented and only warns);
- a registry stack entry compiles the vmapped multi-dict program
  ``vmap(op, in_axes=(0, None))`` — one activation batch scored against N
  dictionaries in a single dispatch;
- every compiled-cache miss after warmup increments the recompile counter
  (serve/metrics.py) — the invariant a healthy deployment asserts on.

The dispatch path (host loop → numpy concat/pad → one device call → numpy
fan-out) is ``lax``-free Python per docs/ARCHITECTURE.md §7: exactly one
device program and one bulk transfer each way per coalesced batch.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu import obs, xcache
from sparse_coding_tpu.obs import monotime
from sparse_coding_tpu.parallel import partition
from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.serve.batching import (
    CircuitOpenError,
    DispatchError,
    MicroBatcher,
    Request,
    RequestTooLargeError,
    ServeError,
    ServeFuture,
)
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.registry import ModelRegistry, RegistryEntry

DEFAULT_BUCKETS = (8, 64, 512)
DEFAULT_OPS = ("encode", "decode", "topk")
# catalog query ops (docs/ARCHITECTURE.md §20): compiled/bucketed/warmed
# exactly like DEFAULT_OPS but opt-in per engine — the catalog serving
# surface constructs its pool with ops=DEFAULT_OPS + CATALOG_OPS, and
# plain feature-extraction engines keep their warm set unchanged.
CATALOG_OPS = ("neighbors", "vote")

register_fault_site("serve.dispatch",
                    "ServingEngine.run_padded — immediately before the "
                    "compiled device call")

# transient dispatch failures (worth a retry / distinct from a poisoned
# request): the I/O family — the tunnel path surfaces flaky transport as
# OSError subclasses. Everything else fails the flush immediately.
TRANSIENT_DISPATCH_ERRORS = (OSError, TimeoutError, ConnectionError)


def bucket_op_fn(op: str, k: int | None = None):
    """The pure per-bucket program for one op. Module-level (not an engine
    closure) so tests/test_tpu_lowering.py can AOT-lower the exact
    functions the engine compiles. ``x`` is [bucket_rows, d] for
    encode/predict/topk and [bucket_rows, n_feats] for decode."""
    if op == "encode":
        return lambda ld, x: ld.encode(x)
    if op == "decode":
        return lambda ld, x: ld.decode(x)
    if op == "predict":
        return lambda ld, x: ld.predict(x)
    if op == "topk":
        if k is None or k < 1:
            raise ValueError("topk op needs k >= 1")

        def topk(ld, x):
            vals, idx = jax.lax.top_k(ld.encode(x), k)
            return vals, idx

        return topk
    if op == "neighbors":
        # catalog top-k decoder-row similarity (catalog/query.py —
        # module-level there so the lowering tests exercise the real
        # kernel; §20)
        if k is None or k < 1:
            raise ValueError("neighbors op needs k >= 1")
        from sparse_coding_tpu.catalog.query import neighbor_topk

        return lambda ld, x: neighbor_topk(ld, x, k)
    if op == "vote":
        # 2505.16077 union/vote aggregation: consumes the STACKED tree
        # itself (vmaps internally, reduces the member axis) — see the
        # vote special case in build_bucket_program
        from sparse_coding_tpu.catalog.query import union_vote

        return union_vote
    raise ValueError(f"unknown serving op {op!r} (supported: encode, "
                     f"decode, predict, topk, neighbors, vote)")


def op_width(entry: RegistryEntry, op: str) -> int:
    """Input width of one op's program: the SINGLE home of the width rule,
    shared by submit-time validation and program compilation so the two
    can never drift."""
    return entry.n_feats if op == "decode" else entry.d_activation


def op_rows_axis(entry: RegistryEntry, op: str) -> int:
    """Rows axis of one op's host result tree: stack entries carry a
    leading member axis — EXCEPT the catalog ``vote`` op, which reduces
    it (catalog/query.py::union_vote). The SINGLE home of the fan-out
    axis rule, shared by the engine and gateway dispatch paths."""
    return 1 if (entry.is_stack and op != "vote") else 0


def prepare_request(entry: RegistryEntry, op: str, ops: Sequence[str],
                    buckets: Sequence[int], np_dtype,
                    x) -> tuple[np.ndarray, int, bool]:
    """Validate and canonicalize one request payload — the SINGLE home of
    the submit-time contract, shared by the engine and the gateway front
    door so the two can never drift. Returns ``(arr, rows, squeeze)``
    with ``arr`` always [rows, width]."""
    if op not in ops:
        raise ValueError(f"op {op!r} not served (engine ops: {tuple(ops)})")
    if op == "vote" and not entry.is_stack:
        raise ValueError(f"op 'vote' aggregates a multi-dict stack; "
                         f"{entry.name!r} is a single-dict entry")
    arr = np.asarray(x, dtype=np_dtype)
    squeeze = arr.ndim == 1
    if squeeze:
        arr = arr[None, :]
    if arr.ndim != 2:
        raise ValueError(f"request must be 1-D or 2-D, got shape "
                         f"{arr.shape}")
    width = op_width(entry, op)
    if arr.shape[1] != width:
        raise ValueError(
            f"{entry.name!r}/{op}: expected width {width}, got "
            f"{arr.shape[1]}")
    rows = arr.shape[0]
    if rows == 0:
        raise ValueError("empty request")
    if rows > buckets[-1]:
        raise RequestTooLargeError(rows, buckets[-1])
    return arr, rows, squeeze


def fanout_results(requests: list[Request], host, rows_axis: int,
                   on_latency=None) -> None:
    """Slice one dispatched batch's host result tree back to its
    requests (in queue order) and resolve their futures; shared by the
    engine dispatch and the gateway dispatch. ``on_latency(request,
    seconds)`` fires per request before its future resolves."""
    now = monotime()
    ofs = 0
    for r in requests:
        sl = ((slice(None),) * rows_axis
              + (slice(ofs, ofs + r.rows),))
        res = jax.tree.map(lambda a: a[sl], host)
        if r.squeeze:
            sq = (slice(None),) * rows_axis + (0,)
            res = jax.tree.map(lambda a: a[sq], res)
        ofs += r.rows
        if on_latency is not None:
            on_latency(r, now - r.t_submit)
        r.future._set_result(res)


def build_bucket_program(entry: RegistryEntry, op: str, bucket: int,
                         dtype, topk_k: int):
    """(fn, input spec) for one (entry, op, bucket) program — the exact
    function+shape the engine AOT-compiles. Module-level so
    tests/test_tpu_lowering.py lowers the hardened dispatch path's real
    programs rather than a reconstruction."""
    fn = bucket_op_fn(op, k=min(topk_k, entry.n_feats))
    if op == "vote":
        # union_vote consumes the stacked tree whole (vmaps internally
        # over the member axis, then reduces it) — re-vmapping would
        # split the stack before the vote can count across members
        if not entry.is_stack:
            raise ValueError(
                f"op 'vote' aggregates a multi-dict stack; register "
                f"{entry.name!r} via register_stack")
    elif entry.is_stack:
        fn = jax.vmap(fn, in_axes=(0, None))
    spec = jax.ShapeDtypeStruct((bucket, op_width(entry, op)),
                                jnp.dtype(dtype))
    return fn, spec


class ProgramCache:
    """Compiled-executable table, shareable between engines.

    Engines serving the SAME registry (a gateway's replica pool) compile
    IDENTICAL (model, op, bucket) programs — same lowered text, same
    xcache key. Sharing one table means N in-process replicas hold one
    executable instance instead of N deserialized clones: less memory,
    and a warm spare activates by table lookup with zero loads and zero
    compiles (cross-process restarts still load from the xcache store).
    Executables are immutable and thread-safe to share; per-key locks
    keep parallel warmup compiles from duplicating work."""

    def __init__(self):
        self.lock = threading.Lock()
        self.compiled: dict[tuple, Any] = {}
        self.key_locks: dict[tuple, threading.Lock] = {}


class ServingEngine:
    """Request-driven feature extraction over a :class:`ModelRegistry`.

    ``submit`` enqueues and returns a :class:`ServeFuture`; ``query`` is
    the blocking convenience. ``warmup()`` AOT-compiles every
    (model, op, bucket) program; after it returns, ``stats()["recompiles"]``
    staying 0 proves steady-state serving never traces.
    """

    def __init__(self, registry: ModelRegistry,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 ops: Sequence[str] = DEFAULT_OPS,
                 topk_k: int = 16,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 8192,
                 donate: bool | None = None,
                 dtype=jnp.float32,
                 latency_window: int = 4096,
                 breaker: CircuitBreaker | None = None,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 5.0,
                 dispatch_retries: int = 2,
                 stream_retry_budget: int = 16,
                 retry_backoff_s: float = 0.002,
                 warmup_workers: int | None = None,
                 program_cache: ProgramCache | None = None,
                 perf_probe_every: int = obs.perf.DEFAULT_PROBE_EVERY,
                 mesh=None):
        # mesh-sharded serving (docs/ARCHITECTURE.md §19, ISSUE 15): with a
        # ("model", "data") mesh, entry pytrees place once through the
        # partition rule layer (dict stacks member-sharded over "model",
        # single dicts replicated), padded inputs row-shard over "data",
        # and every bucket program compiles WITH those shardings — the
        # sharding fingerprint is folded into the xcache key and warmup
        # manifest so a warm mesh restart loads the mesh executables at
        # zero backend compiles.
        self._mesh = mesh
        self._placed_trees: dict[str, Any] = {}
        self._registry = registry
        self._buckets = self._validate_buckets(buckets)
        # every ladder this engine has EVER served (construction + swaps):
        # their programs are warm in the shared ProgramCache, so an
        # admitted request a shrink-swap left above the active max falls
        # back to a known larger rung instead of being stranded (§24)
        self._known_buckets = self._buckets
        self._ops = tuple(ops)
        self._topk_k = int(topk_k)
        self._dtype = jnp.dtype(dtype)
        self._np_dtype = np.dtype(dtype)
        # donation lets XLA alias the padded input for outputs; CPU's
        # runtime doesn't implement it and would warn every compile
        self._donate = (jax.default_backend() == "tpu"
                        if donate is None else bool(donate))
        self.metrics = ServingMetrics(latency_window=latency_window)
        # dispatch resilience (docs/ARCHITECTURE.md §10): transient
        # failures retry against a per-stream budget (refilled on
        # success); consecutive failures trip the breaker, which sheds
        # load at BOTH ends — submit refuses new work, the worker fails
        # queued flushes fast — until a half-open probe heals it
        self._dispatch_retries = int(dispatch_retries)
        self._stream_retry_budget = int(stream_retry_budget)
        self._retry_backoff_s = float(retry_backoff_s)
        self._retry_tokens: dict[tuple, int] = {}
        self._retry_lock = threading.Lock()
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s)
        # mirror every breaker transition into the metrics snapshot
        self._breaker.set_on_transition(self.metrics.record_breaker_transition)
        # per-key locks (allocated under the cache lock) rather than one
        # global compile lock: warmup fans compiles out over a thread
        # pool, and XLA releases the GIL while compiling — serializing on
        # one lock would quietly undo the parallelism. The table itself
        # may be SHARED across a replica pool (see ProgramCache).
        self._programs = (program_cache if program_cache is not None
                          else ProgramCache())
        self._warmup_workers = (max(1, int(warmup_workers))
                                if warmup_workers is not None
                                else min(8, os.cpu_count() or 2))
        # device-time perf evidence (obs/perf.py, §12): every Nth flush's
        # dispatch wall (already host-synced by the numpy readback) lands
        # as serve.mfu + serve.device_step_s + the roofline-gap ratio.
        # Deliberately on the PROCESS registry (not the engine-private
        # one): a replica pool's device-time samples merge into one
        # distribution, and flush_metrics() carries them into the run's
        # report without per-engine plumbing.
        self._perf_probe = obs.DeviceStepProbe(
            "serve", every=max(0, int(perf_probe_every)))
        self._warmed = False
        self._batcher = MicroBatcher(
            dispatch=self._dispatch,
            max_rows_per_batch=self._buckets[-1],
            max_wait_s=max_wait_ms / 1e3,
            max_queue_rows=max_queue_rows,
            metrics=self.metrics)

    # -- bucket ladder -------------------------------------------------------

    def _validate_buckets(self, buckets: Sequence[int]) -> tuple[int, ...]:
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"buckets must be unique ascending: {buckets}")
        align = partition.batch_alignment(self._mesh)
        if align > 1:
            bad = [b for b in buckets if int(b) % align != 0]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by mesh data axis "
                    f"{align}; pick a divisible bucket ladder")
        return tuple(int(b) for b in buckets)

    @property
    def buckets(self) -> tuple[int, ...]:
        """The ACTIVE bucket ladder (may differ from construction after
        a gateway ladder swap, serve/ladder.py §24)."""
        return self._buckets

    def set_buckets(self, buckets: Sequence[int]) -> None:
        """Atomically replace the active ladder (gateway ladder swap,
        §24). The old rungs stay in the known set so already-admitted
        oversize work still finds a warm program; warm the NEW rungs
        first (:meth:`warm_buckets`) or steady state pays recompiles."""
        new = self._validate_buckets(buckets)
        self._known_buckets = tuple(sorted(set(self._known_buckets)
                                           | set(new)))
        self._buckets = new
        self._batcher.set_max_rows(new[-1])

    def warm_buckets(self, buckets: Sequence[int],
                     max_workers: int | None = None) -> int:
        """AOT compile-or-load every (model, op) program for the GIVEN
        rungs — the candidate-ladder warm pass of a zero-compile swap:
        run against a spare's engine (or any pool member — the program
        table is shared), the executables land durably in the xcache
        store and in the warmup manifest, so the subsequent
        :meth:`set_buckets` is a pure table flip. Returns the number of
        programs prepared; does not change the active ladder."""
        rungs = self._validate_buckets(buckets)
        todo = [(name, op, bucket)
                for name in self._registry.names()
                for op in self._ops
                for bucket in rungs
                if (name, op, bucket) not in self._programs.compiled
                and (op != "vote" or self._registry.get(name).is_stack)]
        workers = (max(1, int(max_workers)) if max_workers is not None
                   else self._warmup_workers)
        workers = min(workers, len(todo)) if todo else 1
        with obs.span("serve.warmup", programs=len(todo), workers=workers,
                      source="ladder"):
            if workers <= 1:
                for key in todo:
                    self._get_compiled(*key, count_miss=False)
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    futures = [pool.submit(self._get_compiled, *key,
                                           count_miss=False)
                               for key in todo]
                    for f in futures:
                        f.result()
        return len(todo)

    # -- lifecycle -----------------------------------------------------------

    def warmup(self, max_workers: int | None = None) -> int:
        """AOT compile-or-load every (model, op, bucket) program for the
        CURRENT registry contents — the full set is warm BEFORE the
        engine admits traffic. Returns the number of executables
        prepared. Idempotent; re-run after registering more models.

        Compilation fans out over a bounded thread pool (XLA compiles
        release the GIL; ``max_workers`` overrides the engine default,
        1 forces the serial order) and is timed under the
        ``serve.warmup`` span. With the executable cache enabled
        (``xcache.enable``), programs stored by a previous process load
        instead of compiling, and every program is recorded in the
        warmup manifest (docs/ARCHITECTURE.md §13)."""
        todo = [(name, op, bucket)
                for name in self._registry.names()
                for op in self._ops
                for bucket in self._buckets
                if (name, op, bucket) not in self._programs.compiled
                # vote is stack-only: a mixed pool (single-dict catalog
                # entries + one stack) warms each entry's valid ops
                and (op != "vote" or self._registry.get(name).is_stack)]
        workers = (max(1, int(max_workers)) if max_workers is not None
                   else self._warmup_workers)
        workers = min(workers, len(todo)) if todo else 1
        with obs.span("serve.warmup", programs=len(todo), workers=workers):
            if workers <= 1:
                for key in todo:
                    self._get_compiled(*key, count_miss=False)
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    futures = [pool.submit(self._get_compiled, *key,
                                           count_miss=False)
                               for key in todo]
                    for f in futures:
                        f.result()  # propagate the first compile failure
        self._warmed = True
        return len(todo)

    def warmup_from_manifest(self, manifest=None,
                             max_workers: int | None = None) -> int:
        """Warm exactly the program set the xcache warmup manifest
        records (docs/ARCHITECTURE.md §13) — how a SPARE engine activates
        at zero compiles: ``warmup.json`` is the durable statement of the
        full warm set a deployment needs, and with the executable cache
        enabled every listed program loads instead of compiling.

        ``manifest`` defaults to the active cache's; descriptors naming
        models/ops/buckets this engine does not serve are skipped. With
        no manifest (or an empty one) this falls back to the full
        registry-product :meth:`warmup` — a spare must never admit
        traffic cold just because the manifest is missing. Returns the
        number of programs prepared."""
        from sparse_coding_tpu import xcache as _xcache

        if manifest is None:
            cache = _xcache.active_cache()
            manifest = cache.warmup if cache is not None else None
        descs = manifest.descriptors(kind="serve") if manifest else []
        names = set(self._registry.names())
        matched = sorted({
            (d["model"], d["op"], int(d["bucket"]))
            for d in descs
            if (d.get("model") in names and d.get("op") in self._ops
                # known (not just active) rungs: after a shrink-swap a
                # spare may still be routed admitted old-ladder work
                and int(d.get("bucket", -1)) in self._known_buckets
                and (d.get("op") != "vote"
                     or self._registry.get(d["model"]).is_stack))})
        if not matched:
            # no manifest, or none of its descriptors name programs THIS
            # engine serves (foreign deployment sharing the cache dir,
            # renamed models): warm the full registry product — a spare
            # must never admit traffic cold because the manifest had
            # nothing useful to say about it
            return self.warmup(max_workers=max_workers)
        todo = [key for key in matched
                if key not in self._programs.compiled]
        workers = (max(1, int(max_workers)) if max_workers is not None
                   else self._warmup_workers)
        workers = min(workers, len(todo)) if todo else 1
        with obs.span("serve.warmup", programs=len(todo), workers=workers,
                      source="manifest"):
            if workers <= 1:
                for key in todo:
                    self._get_compiled(*key, count_miss=False)
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    futures = [pool.submit(self._get_compiled, *key,
                                           count_miss=False)
                               for key in todo]
                    for f in futures:
                        f.result()
        self._warmed = True
        return len(todo)

    def shutdown(self, wait: bool = True) -> None:
        self._batcher.shutdown(wait=wait)

    def pause(self) -> None:
        self._batcher.pause()

    def resume(self) -> None:
        self._batcher.resume()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- request path --------------------------------------------------------

    def submit(self, model: str, x, op: str = "encode") -> ServeFuture:
        """Enqueue one request. ``x`` is [rows, width] (or a single [width]
        row, returned un-batched); width is d_activation for
        encode/predict/topk and n_feats for decode. Raises
        :class:`QueueFullError` under backpressure and
        :class:`RequestTooLargeError` past the largest bucket."""
        entry = self._registry.get(model)
        if not self._breaker.admission_allowed():
            # graceful load shedding: while the circuit is open there is
            # no point queueing work behind a sick backend — refuse at
            # admission with the cooldown as a retry hint
            self.metrics.record_shed()
            raise CircuitOpenError((model, op),
                                   self._breaker.seconds_until_probe())
        arr, rows, squeeze = prepare_request(entry, op, self._ops,
                                             self._buckets, self._np_dtype,
                                             x)
        # no trace id here: the critical-path correlation id is minted
        # at GATEWAY admission (the front door owns the request story);
        # a bare engine emits no per-request events
        req = Request(key=(model, op), x=arr, rows=rows, squeeze=squeeze,
                      t_submit=monotime())
        return self._batcher.submit(req)

    def query(self, model: str, x, op: str = "encode",
              timeout: float | None = 60.0):
        """Blocking submit+result."""
        return self.submit(model, x, op=op).result(timeout=timeout)

    def topk(self, model: str, x, timeout: float | None = 60.0):
        """Top-k feature query: (values, indices) of the k strongest
        features per row (k fixed per engine at construction — it is a
        static shape in the compiled programs)."""
        return self.query(model, x, op="topk", timeout=timeout)

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["warmed"] = self._warmed
        snap["compiled_programs"] = len(self._programs.compiled)
        snap["breaker"] = self._breaker.snapshot()
        return snap

    # -- compiled-program cache ----------------------------------------------

    def _op_width(self, entry: RegistryEntry, op: str) -> int:
        return op_width(entry, op)

    def _bucket_for(self, rows: int) -> int:
        buckets = self._buckets
        i = bisect.bisect_left(buckets, rows)
        if i < len(buckets):
            return buckets[i]
        # §24: a shrink-swap may land while work admitted against the
        # OLD ladder is still queued — its old rungs stay warm in the
        # shared program table, so cover from the known set rather than
        # stranding admitted requests. Fresh oversize submissions are
        # still rejected against the ACTIVE ladder (prepare_request).
        known = self._known_buckets
        j = bisect.bisect_left(known, rows)
        if j < len(known):
            return known[j]
        raise RequestTooLargeError(rows, buckets[-1])

    def _entry_tree(self, model: str):
        """The served pytree of one entry: mesh-placed (once, through the
        partition rule layer — dict stacks member-sharded over "model",
        single dicts replicated) or the registry tree verbatim."""
        entry = self._registry.get(model)
        if self._mesh is None:
            return entry.tree
        tree = self._placed_trees.get(model)
        if tree is None:
            tree = partition.place_tree(
                entry.tree, self._mesh, partition.serve_rules(entry.is_stack))
            self._placed_trees[model] = tree
        return tree

    def _compile(self, entry: RegistryEntry, op: str, bucket: int,
                 model: str):
        fn, spec = build_bucket_program(entry, op, bucket, self._dtype,
                                        self._topk_k)
        donate = (1,) if self._donate else ()
        # compile-or-load through the executable store (§13): the model
        # pytree is an ARGUMENT, so the lowered text — and therefore the
        # cache key — depends only on shapes, and same-shape models share
        # one stored executable per (op, bucket). The manifest descriptor
        # records the program so a restarted process knows the warm set.
        # On a mesh (§19) the program is lowered WITH the partition-rule
        # shardings — entry tree per serve_rules, input rows over "data" —
        # and the sharding fingerprint salts the key so mesh and
        # single-device twins never collide in one shared cache dir.
        jit_kwargs: dict[str, Any] = {"donate_argnums": donate}
        fingerprint = None
        if self._mesh is not None:
            rules = partition.serve_rules(entry.is_stack)
            fingerprint = partition.sharding_fingerprint(
                self._mesh, entry.tree, rules)
            jit_kwargs["in_shardings"] = (
                partition.tree_shardings(self._mesh, entry.tree, rules),
                partition.batch_sharding(self._mesh))
        desc = {"kind": "serve", "model": model, "op": op,
                "bucket": int(bucket), "dtype": str(self._dtype),
                "stack": bool(entry.is_stack)}
        if fingerprint is not None:
            desc["sharding"] = fingerprint
        return xcache.cached_compile(
            jax.jit(fn, **jit_kwargs), (entry.tree, spec),
            key=fingerprint,
            label=f"serve/{model}/{op}/{bucket}",
            manifest_desc=desc)

    def _get_compiled(self, model: str, op: str, bucket: int,
                      count_miss: bool = True):
        key = (model, op, bucket)
        programs = self._programs
        compiled = programs.compiled.get(key)
        if compiled is None:
            with programs.lock:
                compiled = programs.compiled.get(key)
                if compiled is not None:
                    return compiled
                lock = programs.key_locks.setdefault(key, threading.Lock())
            with lock:
                compiled = programs.compiled.get(key)
                if compiled is None:
                    if self._warmed and count_miss:
                        self.metrics.record_recompile(key)
                    compiled = self._compile(self._registry.get(model), op,
                                             bucket, model)
                    programs.compiled[key] = compiled
        return compiled

    # -- dispatch (runs on the batcher worker thread) ------------------------

    def run_padded(self, model: str, op: str, x: np.ndarray):
        """One coalesced batch through one compiled program: pad [rows, w]
        up to its bucket, single device call, results sliced back to
        ``rows`` on host. Shared by the online dispatch and the offline
        scorer; returns (bucket, numpy result tree)."""
        rows = x.shape[0]
        bucket = self._bucket_for(rows)
        if rows < bucket:
            pad = np.zeros((bucket, x.shape[1]), self._np_dtype)
            pad[:rows] = x
            x = pad
        compiled = self._get_compiled(model, op, bucket)
        # perf sample (obs/perf.py): the flush is host-synced by the
        # numpy readback below, so the dispatch wall IS the device wall —
        # no extra barrier needed, just the cadence check
        sample_perf = self._perf_probe.should_sample()
        if sample_perf:
            t_perf = monotime()
        fault_point("serve.dispatch")
        # §13 donation rule: a DONATED input must be a runtime-owned
        # buffer. On non-TPU backends jnp.asarray wraps host numpy
        # zero-copy — safe for a fresh compile (which drops donation
        # there) but an executable loaded from the cache retains its
        # input-output aliasing, and x may even be the caller's own
        # request array. jnp.array materializes an owned copy; TPU
        # transfers copy by construction, so the hot path stays asarray.
        if self._mesh is not None:
            # mesh path: row-shard the padded batch over "data";
            # device_put of host numpy always materializes runtime-owned
            # buffers, so the donation rule holds by construction
            dev_x = partition.place_batch(x, self._mesh)
        elif self._donate and jax.default_backend() != "tpu":
            dev_x = jnp.array(x)
        else:
            dev_x = jnp.asarray(x)
        out = compiled(self._entry_tree(model), dev_x)
        entry = self._registry.get(model)
        rows_axis = op_rows_axis(entry, op)
        sl = (slice(None),) * rows_axis + (slice(0, rows),)
        host = jax.tree.map(lambda a: np.asarray(a)[sl], out)
        if sample_perf:
            from sparse_coding_tpu.ops.roofline import serve_flush_plan

            plan = serve_flush_plan(op, bucket, entry.n_feats,
                                    entry.d_activation,
                                    n_stack=entry.n_stack or 1,
                                    itemsize=self._np_dtype.itemsize)
            # MFU numerator policy (StepCost): model-REQUIRED flops — the
            # real `rows`, not the padded bucket, so an underfilled flush
            # reads as LOW utilization (exactly the pad waste the bucket
            # ladder must see). The roofline prediction stays at the
            # padded cost: the device really executes the full bucket.
            self._perf_probe.record(
                monotime() - t_perf,
                cost=obs.StepCost(flops=plan.mxu_flops * (rows / bucket),
                                  path=f"serve.{op}",
                                  predicted_s=plan.est_s,
                                  hbm_bytes=plan.hbm_bytes,
                                  tile=str(bucket), activations=rows))
        return bucket, host

    def _take_retry_token(self, key: tuple) -> bool:
        with self._retry_lock:
            left = self._retry_tokens.get(key, self._stream_retry_budget)
            if left <= 0:
                return False
            self._retry_tokens[key] = left - 1
            return True

    def _refill_retry_budget(self, key: tuple) -> None:
        with self._retry_lock:
            self._retry_tokens[key] = self._stream_retry_budget

    def _fail_requests(self, requests: list[Request],
                       err: ServeError) -> None:
        self.metrics.record_request_errors(len(requests), type(err).__name__)
        for r in requests:
            if not r.future.done():
                r.future._set_error(err)

    def _dispatch(self, key: tuple, requests: list[Request],
                  deadline_flush: bool) -> int | None:
        """Returns rows served (the batcher's service-rate input), None
        for a shed or failed flush."""
        model, op = key
        # the admission token identifies THIS dispatch to the breaker: a
        # half-open probe's outcome is honored only when reported with
        # its own token, so a raced stale dispatch can't fake-heal it
        token = self._breaker.allow()
        if not token:
            # fail-fast drain while the circuit is open: the queue keeps
            # moving (no wedge) and nothing touches the sick backend
            self.metrics.record_shed(len(requests))
            self._fail_requests(requests, CircuitOpenError(
                key, self._breaker.seconds_until_probe()))
            return None
        rows = sum(r.rows for r in requests)
        if len(requests) == 1:
            x = requests[0].x
        else:
            x = np.concatenate([r.x for r in requests], axis=0)
        attempt = 0
        while True:
            try:
                bucket, host = self.run_padded(model, op, x)
                break
            except BaseException as e:  # noqa: BLE001 — typed fan-out
                transient = (isinstance(e, TRANSIENT_DISPATCH_ERRORS)
                             and not isinstance(e, ServeError))
                if (transient and attempt < self._dispatch_retries
                        and self._take_retry_token(key)):
                    attempt += 1
                    self.metrics.record_dispatch_retry()
                    time.sleep(self._retry_backoff_s * attempt)
                    continue
                self._breaker.record_failure(token)
                self.metrics.record_dispatch_failure()
                err = e if isinstance(e, ServeError) else DispatchError(key, e)
                self._fail_requests(requests, err)
                return None
        self._breaker.record_success(token)
        self._refill_retry_budget(key)
        self.metrics.record_batch(bucket, len(requests), rows,
                                  deadline_flush)
        rows_axis = op_rows_axis(self._registry.get(model), op)
        fanout_results(
            requests, host, rows_axis,
            on_latency=lambda r, lat: self.metrics.record_latency(bucket,
                                                                  lat))
        return rows
