"""AOT shape-bucket serving engine.

Online inference is request-driven: shapes arrive one ragged handful of
rows at a time, and jit's trace-on-first-shape model would turn every new
row count into a compile in the latency path. The engine removes tracing
from steady state entirely:

- requests coalesce (serve/batching.py) into a small ladder of padded row
  buckets (default 8/64/512 — geometric, so padding waste is bounded at
  ~8x worst case on the smallest bucket and amortizes with load);
- each (model, op, bucket) program is AOT-compiled at startup via
  ``jit(f).lower(model, spec).compile()`` — ``warmup()`` walks the full
  product so the first real request already hits a compiled executable;
- the model pytree is an ARGUMENT of the compiled program (not a closed-
  over constant), so weights live in ordinary device buffers shared across
  buckets rather than being baked into N executables;
- the padded input buffer is donated on TPU (it is fresh per batch, so
  XLA may write outputs in place; donation is skipped on CPU where it is
  unimplemented and only warns);
- a registry stack entry compiles the vmapped multi-dict program
  ``vmap(op, in_axes=(0, None))`` — one activation batch scored against N
  dictionaries in a single dispatch;
- every compiled-cache miss after warmup increments the recompile counter
  (serve/metrics.py) — the invariant a healthy deployment asserts on.

The dispatch path (host loop → numpy concat/pad → one device call → numpy
fan-out) is ``lax``-free Python per docs/ARCHITECTURE.md §7: exactly one
device program and one bulk transfer each way per coalesced batch.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.obs import monotime
from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.serve.batching import (
    CircuitOpenError,
    DispatchError,
    MicroBatcher,
    Request,
    RequestTooLargeError,
    ServeError,
    ServeFuture,
)
from sparse_coding_tpu.serve.metrics import ServingMetrics
from sparse_coding_tpu.serve.registry import ModelRegistry, RegistryEntry

DEFAULT_BUCKETS = (8, 64, 512)
DEFAULT_OPS = ("encode", "decode", "topk")

register_fault_site("serve.dispatch",
                    "ServingEngine.run_padded — immediately before the "
                    "compiled device call")

# transient dispatch failures (worth a retry / distinct from a poisoned
# request): the I/O family — the tunnel path surfaces flaky transport as
# OSError subclasses. Everything else fails the flush immediately.
TRANSIENT_DISPATCH_ERRORS = (OSError, TimeoutError, ConnectionError)


def bucket_op_fn(op: str, k: int | None = None):
    """The pure per-bucket program for one op. Module-level (not an engine
    closure) so tests/test_tpu_lowering.py can AOT-lower the exact
    functions the engine compiles. ``x`` is [bucket_rows, d] for
    encode/predict/topk and [bucket_rows, n_feats] for decode."""
    if op == "encode":
        return lambda ld, x: ld.encode(x)
    if op == "decode":
        return lambda ld, x: ld.decode(x)
    if op == "predict":
        return lambda ld, x: ld.predict(x)
    if op == "topk":
        if k is None or k < 1:
            raise ValueError("topk op needs k >= 1")

        def topk(ld, x):
            vals, idx = jax.lax.top_k(ld.encode(x), k)
            return vals, idx

        return topk
    raise ValueError(f"unknown serving op {op!r} "
                     f"(supported: encode, decode, predict, topk)")


def op_width(entry: RegistryEntry, op: str) -> int:
    """Input width of one op's program: the SINGLE home of the width rule,
    shared by submit-time validation and program compilation so the two
    can never drift."""
    return entry.n_feats if op == "decode" else entry.d_activation


def build_bucket_program(entry: RegistryEntry, op: str, bucket: int,
                         dtype, topk_k: int):
    """(fn, input spec) for one (entry, op, bucket) program — the exact
    function+shape the engine AOT-compiles. Module-level so
    tests/test_tpu_lowering.py lowers the hardened dispatch path's real
    programs rather than a reconstruction."""
    fn = bucket_op_fn(op, k=min(topk_k, entry.n_feats))
    if entry.is_stack:
        fn = jax.vmap(fn, in_axes=(0, None))
    spec = jax.ShapeDtypeStruct((bucket, op_width(entry, op)),
                                jnp.dtype(dtype))
    return fn, spec


class ServingEngine:
    """Request-driven feature extraction over a :class:`ModelRegistry`.

    ``submit`` enqueues and returns a :class:`ServeFuture`; ``query`` is
    the blocking convenience. ``warmup()`` AOT-compiles every
    (model, op, bucket) program; after it returns, ``stats()["recompiles"]``
    staying 0 proves steady-state serving never traces.
    """

    def __init__(self, registry: ModelRegistry,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 ops: Sequence[str] = DEFAULT_OPS,
                 topk_k: int = 16,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 8192,
                 donate: bool | None = None,
                 dtype=jnp.float32,
                 latency_window: int = 4096,
                 breaker: CircuitBreaker | None = None,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 5.0,
                 dispatch_retries: int = 2,
                 stream_retry_budget: int = 16,
                 retry_backoff_s: float = 0.002):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"buckets must be unique ascending: {buckets}")
        self._registry = registry
        self._buckets = tuple(int(b) for b in buckets)
        self._ops = tuple(ops)
        self._topk_k = int(topk_k)
        self._dtype = jnp.dtype(dtype)
        self._np_dtype = np.dtype(dtype)
        # donation lets XLA alias the padded input for outputs; CPU's
        # runtime doesn't implement it and would warn every compile
        self._donate = (jax.default_backend() == "tpu"
                        if donate is None else bool(donate))
        self.metrics = ServingMetrics(latency_window=latency_window)
        # dispatch resilience (docs/ARCHITECTURE.md §10): transient
        # failures retry against a per-stream budget (refilled on
        # success); consecutive failures trip the breaker, which sheds
        # load at BOTH ends — submit refuses new work, the worker fails
        # queued flushes fast — until a half-open probe heals it
        self._dispatch_retries = int(dispatch_retries)
        self._stream_retry_budget = int(stream_retry_budget)
        self._retry_backoff_s = float(retry_backoff_s)
        self._retry_tokens: dict[tuple, int] = {}
        self._retry_lock = threading.Lock()
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s)
        # mirror every breaker transition into the metrics snapshot
        self._breaker.set_on_transition(self.metrics.record_breaker_transition)
        self._compiled: dict[tuple, Any] = {}
        self._compile_lock = threading.Lock()
        self._warmed = False
        self._batcher = MicroBatcher(
            dispatch=self._dispatch,
            max_rows_per_batch=self._buckets[-1],
            max_wait_s=max_wait_ms / 1e3,
            max_queue_rows=max_queue_rows,
            metrics=self.metrics)

    # -- lifecycle -----------------------------------------------------------

    def warmup(self) -> int:
        """AOT-compile every (model, op, bucket) program for the CURRENT
        registry contents. Returns the number of executables compiled.
        Idempotent; re-run after registering more models."""
        n = 0
        for name in self._registry.names():
            for op in self._ops:
                for bucket in self._buckets:
                    if (name, op, bucket) not in self._compiled:
                        self._get_compiled(name, op, bucket,
                                           count_miss=False)
                        n += 1
        self._warmed = True
        return n

    def shutdown(self, wait: bool = True) -> None:
        self._batcher.shutdown(wait=wait)

    def pause(self) -> None:
        self._batcher.pause()

    def resume(self) -> None:
        self._batcher.resume()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- request path --------------------------------------------------------

    def submit(self, model: str, x, op: str = "encode") -> ServeFuture:
        """Enqueue one request. ``x`` is [rows, width] (or a single [width]
        row, returned un-batched); width is d_activation for
        encode/predict/topk and n_feats for decode. Raises
        :class:`QueueFullError` under backpressure and
        :class:`RequestTooLargeError` past the largest bucket."""
        entry = self._registry.get(model)
        if op not in self._ops:
            raise ValueError(f"op {op!r} not served (engine ops: "
                             f"{self._ops})")
        if not self._breaker.admission_allowed():
            # graceful load shedding: while the circuit is open there is
            # no point queueing work behind a sick backend — refuse at
            # admission with the cooldown as a retry hint
            self.metrics.record_shed()
            raise CircuitOpenError((model, op),
                                   self._breaker.seconds_until_probe())
        arr = np.asarray(x, dtype=self._np_dtype)
        squeeze = arr.ndim == 1
        if squeeze:
            arr = arr[None, :]
        if arr.ndim != 2:
            raise ValueError(f"request must be 1-D or 2-D, got shape "
                             f"{arr.shape}")
        width = self._op_width(entry, op)
        if arr.shape[1] != width:
            raise ValueError(
                f"{model!r}/{op}: expected width {width}, got "
                f"{arr.shape[1]}")
        rows = arr.shape[0]
        if rows == 0:
            raise ValueError("empty request")
        if rows > self._buckets[-1]:
            raise RequestTooLargeError(rows, self._buckets[-1])
        req = Request(key=(model, op), x=arr, rows=rows, squeeze=squeeze,
                      t_submit=monotime())
        return self._batcher.submit(req)

    def query(self, model: str, x, op: str = "encode",
              timeout: float | None = 60.0):
        """Blocking submit+result."""
        return self.submit(model, x, op=op).result(timeout=timeout)

    def topk(self, model: str, x, timeout: float | None = 60.0):
        """Top-k feature query: (values, indices) of the k strongest
        features per row (k fixed per engine at construction — it is a
        static shape in the compiled programs)."""
        return self.query(model, x, op="topk", timeout=timeout)

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["warmed"] = self._warmed
        snap["compiled_programs"] = len(self._compiled)
        snap["breaker"] = self._breaker.snapshot()
        return snap

    # -- compiled-program cache ----------------------------------------------

    def _op_width(self, entry: RegistryEntry, op: str) -> int:
        return op_width(entry, op)

    def _bucket_for(self, rows: int) -> int:
        i = bisect.bisect_left(self._buckets, rows)
        if i == len(self._buckets):
            raise RequestTooLargeError(rows, self._buckets[-1])
        return self._buckets[i]

    def _compile(self, entry: RegistryEntry, op: str, bucket: int):
        fn, spec = build_bucket_program(entry, op, bucket, self._dtype,
                                        self._topk_k)
        donate = (1,) if self._donate else ()
        return (jax.jit(fn, donate_argnums=donate)
                .lower(entry.tree, spec).compile())

    def _get_compiled(self, model: str, op: str, bucket: int,
                      count_miss: bool = True):
        key = (model, op, bucket)
        compiled = self._compiled.get(key)
        if compiled is None:
            with self._compile_lock:
                compiled = self._compiled.get(key)
                if compiled is None:
                    if self._warmed and count_miss:
                        self.metrics.record_recompile(key)
                    compiled = self._compile(self._registry.get(model), op,
                                             bucket)
                    self._compiled[key] = compiled
        return compiled

    # -- dispatch (runs on the batcher worker thread) ------------------------

    def run_padded(self, model: str, op: str, x: np.ndarray):
        """One coalesced batch through one compiled program: pad [rows, w]
        up to its bucket, single device call, results sliced back to
        ``rows`` on host. Shared by the online dispatch and the offline
        scorer; returns (bucket, numpy result tree)."""
        rows = x.shape[0]
        bucket = self._bucket_for(rows)
        if rows < bucket:
            pad = np.zeros((bucket, x.shape[1]), self._np_dtype)
            pad[:rows] = x
            x = pad
        compiled = self._get_compiled(model, op, bucket)
        fault_point("serve.dispatch")
        out = compiled(self._registry.get(model).tree, jnp.asarray(x))
        rows_axis = 1 if self._registry.get(model).is_stack else 0
        sl = (slice(None),) * rows_axis + (slice(0, rows),)
        host = jax.tree.map(lambda a: np.asarray(a)[sl], out)
        return bucket, host

    def _take_retry_token(self, key: tuple) -> bool:
        with self._retry_lock:
            left = self._retry_tokens.get(key, self._stream_retry_budget)
            if left <= 0:
                return False
            self._retry_tokens[key] = left - 1
            return True

    def _refill_retry_budget(self, key: tuple) -> None:
        with self._retry_lock:
            self._retry_tokens[key] = self._stream_retry_budget

    def _fail_requests(self, requests: list[Request],
                       err: ServeError) -> None:
        self.metrics.record_request_errors(len(requests), type(err).__name__)
        for r in requests:
            if not r.future.done():
                r.future._set_error(err)

    def _dispatch(self, key: tuple, requests: list[Request],
                  deadline_flush: bool) -> None:
        model, op = key
        if not self._breaker.allow():
            # fail-fast drain while the circuit is open: the queue keeps
            # moving (no wedge) and nothing touches the sick backend
            self.metrics.record_shed(len(requests))
            self._fail_requests(requests, CircuitOpenError(
                key, self._breaker.seconds_until_probe()))
            return
        rows = sum(r.rows for r in requests)
        if len(requests) == 1:
            x = requests[0].x
        else:
            x = np.concatenate([r.x for r in requests], axis=0)
        attempt = 0
        while True:
            try:
                bucket, host = self.run_padded(model, op, x)
                break
            except BaseException as e:  # noqa: BLE001 — typed fan-out
                transient = (isinstance(e, TRANSIENT_DISPATCH_ERRORS)
                             and not isinstance(e, ServeError))
                if (transient and attempt < self._dispatch_retries
                        and self._take_retry_token(key)):
                    attempt += 1
                    self.metrics.record_dispatch_retry()
                    time.sleep(self._retry_backoff_s * attempt)
                    continue
                self._breaker.record_failure()
                self.metrics.record_dispatch_failure()
                err = e if isinstance(e, ServeError) else DispatchError(key, e)
                self._fail_requests(requests, err)
                return
        self._breaker.record_success()
        self._refill_retry_budget(key)
        self.metrics.record_batch(bucket, len(requests), rows,
                                  deadline_flush)
        rows_axis = 1 if self._registry.get(model).is_stack else 0
        now = monotime()
        ofs = 0
        for r in requests:
            sl = ((slice(None),) * rows_axis
                  + (slice(ofs, ofs + r.rows),))
            res = jax.tree.map(lambda a: a[sl], host)
            if r.squeeze:
                sq = (slice(None),) * rows_axis + (0,)
                res = jax.tree.map(lambda a: a[sq], res)
            ofs += r.rows
            self.metrics.record_latency(bucket, now - r.t_submit)
            r.future._set_result(res)
