"""Serving observability: the obs registry behind the serving snapshot.

Everything here is plain host-side Python — the metrics path must never
touch jax, or instrumentation itself would add device dispatches to the
hot loop (the obs core keeps the same discipline). Since the obs
subsystem (docs/ARCHITECTURE.md §12) the counters/gauges/histograms live
in a :class:`sparse_coding_tpu.obs.Registry` — so `obs.report` and
`flush_metrics` see serving traffic through the same instrument taxonomy
as every other subsystem — while ``snapshot()`` keeps its original schema
(tests and the bench suite read it) and its exact ring-buffer latency
quantiles.

The one invariant the snapshot exists to prove is ``recompiles == 0``
after warmup: every compiled-program cache miss in steady state means a
shape escaped the bucket ladder and the engine silently paid a
trace+compile in a latency-sensitive path.

Instrument names (labels carry the bucket): ``serve.requests``,
``serve.rejected``, ``serve.shed``, ``serve.dispatch_retries``,
``serve.dispatch_failures``, ``serve.recompiles``,
``serve.request_errors{type=..}``, ``serve.breaker_transitions``,
``serve.queue_rows`` (gauge; its high-water mark is the max),
``serve.batches{bucket=..}`` / ``serve.batch_requests`` / ``serve.rows``
/ ``serve.deadline_flushes``, ``serve.latency_s{bucket=..}`` (histogram),
``serve.request_rows`` (row-valued histogram — the rolling request-size
distribution ladder derivation snapshots, serve/ladder.py §24), and the
continuous-rebatching counters ``serve.rebatch.joined`` /
``serve.rebatch.joined_rows`` / ``serve.rebatch.rejected``.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Optional

from sparse_coding_tpu.obs.registry import Registry
from sparse_coding_tpu.serve.ladder import REQUEST_ROW_BOUNDS


def _quantile_ms(samples: list[float], q: float) -> float | None:
    """Nearest-rank quantile of a list of second-valued latencies, in ms."""
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
    return ordered[idx] * 1e3


class ServingMetrics:
    """Thread-safe counters shared by the engine, the batcher, and the
    offline driver. ``snapshot()`` is the only read surface; ``registry``
    exposes the same numbers as obs instruments.

    Each engine owns a PRIVATE registry by default (two engines in one
    process must not sum their queues); pass ``registry=`` — e.g.
    ``obs.get_registry()`` — to publish into a shared one."""

    def __init__(self, latency_window: int = 4096,
                 registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        self._lock = threading.Lock()
        self._latency_window = latency_window
        self._buckets: set[int] = set()
        self._latencies: dict[int, deque[float]] = {}
        self._recompile_keys: list[tuple] = []
        self._queued_rows = 0
        self._error_types: set[str] = set()
        self._breaker_state = "closed"
        # bounded mirror of the breaker's history: a flapping backend
        # cycling open/half_open for days must not grow the snapshot
        self._breaker_transitions: deque[str] = deque(maxlen=256)
        r = self.registry
        self._submitted = r.counter("serve.requests")
        self._rejected = r.counter("serve.rejected")
        self._shed = r.counter("serve.shed")
        self._retries = r.counter("serve.dispatch_retries")
        self._failures = r.counter("serve.dispatch_failures")
        self._recompiles = r.counter("serve.recompiles")
        self._n_transitions = r.counter("serve.breaker_transitions")
        self._queue_gauge = r.gauge("serve.queue_rows")
        # the rolling request-size distribution (row-valued bounds, not
        # the latency default): ladder derivation's primary input
        self._request_rows = r.histogram("serve.request_rows",
                                         bounds=REQUEST_ROW_BOUNDS)
        self._rebatch_joined = r.counter("serve.rebatch.joined")
        self._rebatch_joined_rows = r.counter("serve.rebatch.joined_rows")
        self._rebatch_rejected = r.counter("serve.rebatch.rejected")

    # -- write side (engine / batcher) --------------------------------------

    def record_enqueue(self, rows: int) -> None:
        self._submitted.inc()
        self._request_rows.observe(rows)
        with self._lock:
            self._queued_rows += rows
            self._queue_gauge.set(self._queued_rows)

    def record_dequeue(self, rows: int) -> None:
        with self._lock:
            self._queued_rows = max(0, self._queued_rows - rows)
            self._queue_gauge.set(self._queued_rows)

    def record_reject(self) -> None:
        self._rejected.inc()

    def record_batch(self, bucket: int, n_requests: int, rows: int,
                     deadline_flush: bool) -> None:
        with self._lock:
            self._buckets.add(bucket)
        r = self.registry
        r.counter("serve.batches", bucket=bucket).inc()
        r.counter("serve.batch_requests", bucket=bucket).inc(n_requests)
        r.counter("serve.rows", bucket=bucket).inc(rows)
        if deadline_flush:
            r.counter("serve.deadline_flushes", bucket=bucket).inc()

    def record_rebatch(self, joined: int, joined_rows: int,
                       rejected: int = 0) -> None:
        """One flush's continuous-rebatching outcome: ``joined``
        late-arriving requests (``joined_rows`` rows of pad they filled)
        merged into the in-flight assembly; ``rejected`` counts a stream
        head that was present but did not fit the remaining rows."""
        if joined:
            self._rebatch_joined.inc(joined)
            self._rebatch_joined_rows.inc(joined_rows)
        if rejected:
            self._rebatch_rejected.inc(rejected)

    def record_latency(self, bucket: int, seconds: float) -> None:
        with self._lock:
            self._buckets.add(bucket)
            q = self._latencies.get(bucket)
            if q is None:
                q = self._latencies[bucket] = deque(
                    maxlen=self._latency_window)
            q.append(seconds)
        self.registry.histogram("serve.latency_s", bucket=bucket).observe(
            seconds)

    def record_recompile(self, key: tuple) -> None:
        self._recompiles.inc()
        with self._lock:
            self._recompile_keys.append(key)

    def record_request_errors(self, n: int, error_type: str) -> None:
        """n requests in one flush failed with the given error type."""
        with self._lock:
            self._error_types.add(error_type)
        self.registry.counter("serve.request_errors", type=error_type).inc(n)

    def record_dispatch_retry(self) -> None:
        self._retries.inc()

    def record_dispatch_failure(self) -> None:
        self._failures.inc()

    def record_shed(self, n: int = 1) -> None:
        """n requests refused without device work (open breaker)."""
        self._shed.inc(n)

    def record_breaker_transition(self, old: str, new: str) -> None:
        self._n_transitions.inc()
        with self._lock:
            self._breaker_state = new
            self._breaker_transitions.append(f"{old}->{new}")

    # -- read side -----------------------------------------------------------

    @property
    def recompiles(self) -> int:
        return self._recompiles.value

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def snapshot(self) -> dict:
        """One coherent dict of everything: per-bucket request counts, fill
        ratios (rows served / bucket capacity dispatched), latency p50/p99,
        queue-depth high-water mark, rejections, and the recompile counter
        (with the offending (model, op, bucket) keys when nonzero)."""
        r = self.registry
        with self._lock:
            bucket_sizes = sorted(self._buckets)
            latencies = {b: list(q) for b, q in self._latencies.items()}
            recompile_keys = list(self._recompile_keys)
            error_types = set(self._error_types)
            breaker_state = self._breaker_state
            breaker_transitions = list(self._breaker_transitions)
            queued = self._queued_rows
        buckets = {}
        all_lat: list[float] = []
        for size in bucket_sizes:
            lat = latencies.get(size, [])
            all_lat.extend(lat)
            batches = r.counter("serve.batches", bucket=size).value
            rows = r.counter("serve.rows", bucket=size).value
            capacity = batches * size
            buckets[size] = {
                "batches": batches,
                "requests": r.counter("serve.batch_requests",
                                      bucket=size).value,
                "rows": rows,
                "fill_ratio": (rows / capacity) if capacity else 0.0,
                "deadline_flushes": r.counter("serve.deadline_flushes",
                                              bucket=size).value,
                "p50_ms": _quantile_ms(lat, 0.50),
                "p99_ms": _quantile_ms(lat, 0.99),
            }
        return {
            "buckets": buckets,
            "p50_ms": _quantile_ms(all_lat, 0.50),
            "p99_ms": _quantile_ms(all_lat, 0.99),
            "requests": self._submitted.value,
            "rejected": self._rejected.value,
            "queue_depth_rows": queued,
            "max_queue_depth_rows": int(self._queue_gauge.max),
            "recompiles": self._recompiles.value,
            "recompile_keys": recompile_keys,
            "request_errors": {
                t: r.counter("serve.request_errors", type=t).value
                for t in sorted(error_types)},
            "rebatch": {
                "joined": self._rebatch_joined.value,
                "joined_rows": self._rebatch_joined_rows.value,
                "rejected": self._rebatch_rejected.value},
            "dispatch_retries": self._retries.value,
            "dispatch_failures": self._failures.value,
            "shed_requests": self._shed.value,
            "breaker_state": breaker_state,
            "breaker_transitions": breaker_transitions,
            "breaker_n_transitions": self._n_transitions.value,
        }
