"""Serving observability: per-bucket counters and latency quantiles.

Everything here is plain host-side Python (a lock, dicts, deques) — the
metrics path must never touch jax, or instrumentation itself would add
device dispatches to the hot loop. The one invariant the snapshot exists to
prove is ``recompiles == 0`` after warmup: every compiled-program cache miss
in steady state means a shape escaped the bucket ladder and the engine
silently paid a trace+compile in a latency-sensitive path.
"""

from __future__ import annotations

import math
import threading
from collections import deque


def _quantile_ms(samples: list[float], q: float) -> float | None:
    """Nearest-rank quantile of a list of second-valued latencies, in ms."""
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
    return ordered[idx] * 1e3


class _BucketStats:
    __slots__ = ("batches", "requests", "rows", "deadline_flushes",
                 "latencies")

    def __init__(self, latency_window: int):
        self.batches = 0
        self.requests = 0
        self.rows = 0
        self.deadline_flushes = 0
        self.latencies: deque[float] = deque(maxlen=latency_window)


class ServingMetrics:
    """Thread-safe counters shared by the engine, the batcher, and the
    offline driver. ``snapshot()`` is the only read surface."""

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._latency_window = latency_window
        self._buckets: dict[int, _BucketStats] = {}
        self._recompiles = 0
        self._recompile_keys: list[tuple] = []
        self._rejected = 0
        self._queued_rows = 0
        self._max_queued_rows = 0
        self._submitted = 0
        # resilience counters (docs/ARCHITECTURE.md §10): per-request error
        # counts by type, dispatch retries/failures, shed requests, and the
        # circuit breaker's current state + transition history — the
        # snapshot is how an operator sees the breaker at all
        self._request_errors: dict[str, int] = {}
        self._dispatch_retries = 0
        self._dispatch_failures = 0
        self._shed_requests = 0
        self._breaker_state = "closed"
        # bounded mirror of the breaker's history: a flapping backend
        # cycling open/half_open for days must not grow the snapshot
        self._breaker_transitions: deque[str] = deque(maxlen=256)
        self._breaker_n_transitions = 0

    # -- write side (engine / batcher) --------------------------------------

    def _bucket(self, bucket: int) -> _BucketStats:
        b = self._buckets.get(bucket)
        if b is None:
            b = self._buckets[bucket] = _BucketStats(self._latency_window)
        return b

    def record_enqueue(self, rows: int) -> None:
        with self._lock:
            self._submitted += 1
            self._queued_rows += rows
            self._max_queued_rows = max(self._max_queued_rows,
                                        self._queued_rows)

    def record_dequeue(self, rows: int) -> None:
        with self._lock:
            self._queued_rows = max(0, self._queued_rows - rows)

    def record_reject(self) -> None:
        with self._lock:
            self._rejected += 1

    def record_batch(self, bucket: int, n_requests: int, rows: int,
                     deadline_flush: bool) -> None:
        with self._lock:
            b = self._bucket(bucket)
            b.batches += 1
            b.requests += n_requests
            b.rows += rows
            if deadline_flush:
                b.deadline_flushes += 1

    def record_latency(self, bucket: int, seconds: float) -> None:
        with self._lock:
            self._bucket(bucket).latencies.append(seconds)

    def record_recompile(self, key: tuple) -> None:
        with self._lock:
            self._recompiles += 1
            self._recompile_keys.append(key)

    def record_request_errors(self, n: int, error_type: str) -> None:
        """n requests in one flush failed with the given error type."""
        with self._lock:
            self._request_errors[error_type] = (
                self._request_errors.get(error_type, 0) + n)

    def record_dispatch_retry(self) -> None:
        with self._lock:
            self._dispatch_retries += 1

    def record_dispatch_failure(self) -> None:
        with self._lock:
            self._dispatch_failures += 1

    def record_shed(self, n: int = 1) -> None:
        """n requests refused without device work (open breaker)."""
        with self._lock:
            self._shed_requests += n

    def record_breaker_transition(self, old: str, new: str) -> None:
        with self._lock:
            self._breaker_state = new
            self._breaker_transitions.append(f"{old}->{new}")
            self._breaker_n_transitions += 1

    # -- read side -----------------------------------------------------------

    @property
    def recompiles(self) -> int:
        with self._lock:
            return self._recompiles

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def snapshot(self) -> dict:
        """One coherent dict of everything: per-bucket request counts, fill
        ratios (rows served / bucket capacity dispatched), latency p50/p99,
        queue-depth high-water mark, rejections, and the recompile counter
        (with the offending (model, op, bucket) keys when nonzero)."""
        with self._lock:
            buckets = {}
            all_lat: list[float] = []
            for size in sorted(self._buckets):
                b = self._buckets[size]
                lat = list(b.latencies)
                all_lat.extend(lat)
                capacity = b.batches * size
                buckets[size] = {
                    "batches": b.batches,
                    "requests": b.requests,
                    "rows": b.rows,
                    "fill_ratio": (b.rows / capacity) if capacity else 0.0,
                    "deadline_flushes": b.deadline_flushes,
                    "p50_ms": _quantile_ms(lat, 0.50),
                    "p99_ms": _quantile_ms(lat, 0.99),
                }
            return {
                "buckets": buckets,
                "p50_ms": _quantile_ms(all_lat, 0.50),
                "p99_ms": _quantile_ms(all_lat, 0.99),
                "requests": self._submitted,
                "rejected": self._rejected,
                "queue_depth_rows": self._queued_rows,
                "max_queue_depth_rows": self._max_queued_rows,
                "recompiles": self._recompiles,
                "recompile_keys": list(self._recompile_keys),
                "request_errors": dict(self._request_errors),
                "dispatch_retries": self._dispatch_retries,
                "dispatch_failures": self._dispatch_failures,
                "shed_requests": self._shed_requests,
                "breaker_state": self._breaker_state,
                "breaker_transitions": list(self._breaker_transitions),
                "breaker_n_transitions": self._breaker_n_transitions,
            }
