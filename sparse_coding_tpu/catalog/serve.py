"""Catalog serving: feature-intelligence request classes over the gateway.

:class:`CatalogService` is the front door for the catalog's query
surface (docs/ARCHITECTURE.md §20). It composes a built
:class:`~sparse_coding_tpu.catalog.build.CatalogIndex` (the durable stat
arrays) with a :class:`~sparse_coding_tpu.serve.gateway.ServingGateway`
whose engines serve the catalog ops (``CATALOG_OPS`` — the
``neighbors`` top-k similarity kernel and the 2505.16077 ``vote``
aggregation, serve/engine.py), and maps each request class onto its SLO
priority (serve/slo.py):

====================  ==========  =================================
request class         priority    backend op
====================  ==========  =================================
``feature.stats``     interactive (none — host index lookup)
``feature.neighbors`` interactive ``neighbors`` (seeded by feature)
``feature.search``    batch       ``neighbors`` (caller's vector)
``feature.union``     batch       ``vote`` (multi-dict stack)
====================  ==========  =================================

Dead features never appear in neighbor results: the engine's top-k runs
over the full feature axis (a static shape — compiled once per bucket),
and the service filters hits through the index's dead mask (plus the
self-match) before returning. Diverged dicts never reach this layer at
all — the build drops them (``skip_diverged``), and serving stacks must
be loaded with the same filter.

Every query passes the ``catalog.query`` fault site before touching the
gateway, so the query path is drillable like any dispatch edge (§10,
tests/test_resilience.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from sparse_coding_tpu.catalog.build import CatalogIndex
from sparse_coding_tpu.catalog.query import unpack_neighbors
from sparse_coding_tpu.resilience.faults import (
    fault_point,
    register_fault_site,
)
from sparse_coding_tpu.serve.slo import BATCH, INTERACTIVE, PRIORITIES

register_fault_site("catalog.query",
                    "catalog query path — immediately before the index "
                    "lookup / gateway submit of one feature.* request "
                    "(catalog/serve.py)")

# request class -> (backend op or None for host-side, SLO priority)
REQUEST_CLASSES: dict[str, tuple[Optional[str], str]] = {
    "feature.stats": (None, INTERACTIVE),
    "feature.neighbors": ("neighbors", INTERACTIVE),
    "feature.search": ("neighbors", BATCH),
    "feature.union": ("vote", BATCH),
}


def request_priority(request_class: str) -> str:
    """SLO priority of one catalog request class (typed on unknowns so a
    misrouted class can never silently serve at the wrong priority)."""
    try:
        priority = REQUEST_CLASSES[request_class][1]
    except KeyError:
        raise ValueError(
            f"unknown catalog request class {request_class!r} "
            f"(supported: {sorted(REQUEST_CLASSES)})") from None
    assert priority in PRIORITIES
    return priority


class CatalogService:
    """Feature-intelligence queries over a built index + gateway pool.

    ``models[i]`` names the gateway registry entry serving catalog dict
    ``i`` — registered by the caller from the SAME artifact set the index
    was built from, with the SAME diverged filter (e.g.
    ``registry.load_native(pkl, select=lambda h: not h.get("diverged"))``),
    so index positions and serving entries line up. ``stack_model``
    optionally names a homogeneous stack entry for ``feature.union``.
    """

    def __init__(self, index: CatalogIndex, gateway,
                 models: Sequence[str], stack_model: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        if len(models) != index.n_dicts:
            raise ValueError(
                f"{len(models)} serving models for {index.n_dicts} "
                "catalog dicts — the index and the registry must be "
                "loaded from the same artifact set with the same "
                "diverged filter")
        self.index = index
        self._gateway = gateway
        self._models = list(models)
        self._stack_model = stack_model
        self._deadline_s = deadline_s

    # -- host-side request class ---------------------------------------------

    def stats(self, dict_i: int, feature_id: int) -> dict:
        """``feature.stats``: one feature's durable stat row. Pure index
        lookup — no device work, interactive by construction."""
        fault_point("catalog.query")
        return self.index.feature_stats(dict_i, feature_id)

    # -- device-backed request classes ---------------------------------------

    def _submit_neighbors(self, dict_i: int, q: np.ndarray,
                          request_class: str):
        op, priority = REQUEST_CLASSES[request_class]
        fault_point("catalog.query")
        return self._gateway.query(
            self._models[dict_i], q, op=op, priority=priority,
            deadline_s=self._deadline_s)

    def _filter_hits(self, dict_i: int, vals: np.ndarray,
                     idx: np.ndarray, k: int,
                     exclude_feat: Optional[int]) -> list[dict]:
        dead = self.index.dead(dict_i)
        out = []
        for cos, f in zip(vals.tolist(), idx.tolist()):
            if f == exclude_feat or dead[f]:
                continue  # dead features are never neighbors (§20)
            out.append({"feature": int(f), "cos": float(cos)})
            if len(out) >= k:
                break
        return out

    def neighbors(self, dict_i: int, feature_id: int,
                  k: Optional[int] = None) -> list[dict]:
        """``feature.neighbors``: the nearest live decoder rows to one
        feature's own decoder row, served interactive. Returns up to
        ``k`` (default: the engine's compiled top-k minus the self-match)
        ``{"feature", "cos"}`` hits, dead features filtered out."""
        q = self.index.rows(dict_i)[int(feature_id)]
        packed = self._submit_neighbors(dict_i, q, "feature.neighbors")
        vals, idx = unpack_neighbors(packed)
        want = int(k) if k is not None else max(1, idx.shape[-1] - 1)
        return self._filter_hits(dict_i, vals, idx, want,
                                 exclude_feat=int(feature_id))

    def search(self, dict_i: int, x, k: Optional[int] = None) -> list[dict]:
        """``feature.search``: nearest live decoder rows to a CALLER
        activation/direction vector, served at batch priority (offline
        interp sweeps — latency-tolerant, throughput-bound)."""
        q = np.asarray(x, dtype=np.float32)
        packed = self._submit_neighbors(dict_i, q, "feature.search")
        vals, idx = unpack_neighbors(packed)
        want = int(k) if k is not None else idx.shape[-1]
        if q.ndim == 1:
            return self._filter_hits(dict_i, vals, idx, want,
                                     exclude_feat=None)
        return [self._filter_hits(dict_i, v, i, want, exclude_feat=None)
                for v, i in zip(vals, idx)]

    def union(self, x, quorum: int = 1) -> np.ndarray:
        """``feature.union``: the 2505.16077 union/vote aggregation — one
        batch encoded by every member of the serving stack, features kept
        when at least ``quorum`` members fire. Returns a bool mask
        [rows?, n_feats] (squeezed like the gateway contract)."""
        if self._stack_model is None:
            raise ValueError("no stack_model configured for feature.union")
        op, priority = REQUEST_CLASSES["feature.union"]
        fault_point("catalog.query")
        votes = self._gateway.query(
            self._stack_model, np.asarray(x, dtype=np.float32), op=op,
            priority=priority, deadline_s=self._deadline_s)
        return np.asarray(votes) >= quorum
