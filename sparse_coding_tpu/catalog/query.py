"""Catalog query kernels: the exact pure functions the engine compiles.

Module-level (not closures) for the same reason as
``serve/engine.py::bucket_op_fn``: tests/test_tpu_lowering.py must
AOT-lower the REAL programs the serving path dispatches, not a
reconstruction. Both kernels ride the ordinary shape-bucket machinery —
compiled through ``xcache.cached_compile``, mesh-placed through
``parallel/partition.py`` (a dict stack's member axis is already the
sharded axis; a big single dict's feature rows shard via
``CATALOG_FEATURE_RULES``).

The top-k result is PACKED into one array ``[rows, 2k]`` (similarity
values, then neighbor indices cast to the value dtype) so the result
stays a single-leaf tree through the padded fan-out slicing
(``fanout_results``); :func:`unpack_neighbors` splits it back on host.
Index precision is exact for any real dictionary (n_feats < 2**24).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def neighbor_topk(ld, x, k: int):
    """Batched top-k decoder-row similarity for one dictionary: cosine of
    each query row against every (already normalized) decoder row,
    ``jax.lax.top_k`` over the feature axis. ``x`` is [rows, d] query
    vectors (unit-normalize on host for true cosines); returns the packed
    [rows, 2k] (values ++ indices) array."""
    sims = x @ ld.get_learned_dict().T
    vals, idx = jax.lax.top_k(sims, k)
    return jnp.concatenate([vals, idx.astype(vals.dtype)], axis=-1)


def union_vote(ld_stack, x):
    """The 2505.16077 union/vote aggregation op over a vmapped multi-dict
    stack ("Ensembling Sparse Autoencoders", PAPERS.md): every member
    encodes the same batch, and each feature's vote count is the number
    of members whose code fires. Consumes the stacked tree directly —
    ``build_bucket_program`` must NOT re-vmap it — and reduces the member
    axis, so the result rows axis is 0 even for a stack
    (``op_rows_axis``). Returns [rows, n_feats] vote counts."""
    codes = jax.vmap(lambda ld, b: ld.encode(b), in_axes=(0, None))(
        ld_stack, x)
    return jnp.sum((codes > 0).astype(x.dtype), axis=0)


def unpack_neighbors(packed) -> tuple[np.ndarray, np.ndarray]:
    """Host-side split of the packed neighbor result: [..., 2k] ->
    (values [..., k] float, indices [..., k] int32)."""
    packed = np.asarray(packed)
    k = packed.shape[-1] // 2
    return (packed[..., :k],
            packed[..., k:].astype(np.int32))


def place_catalog_rows(rows, mesh):
    """Shard one big dictionary's normalized decoder rows over the mesh
    feature axis (``partition.CATALOG_FEATURE_RULES`` — [n, d] rows over
    "model", docs/ARCHITECTURE.md §19/§20) through the placement seam."""
    from sparse_coding_tpu.parallel import partition

    return partition.place_tree(rows, mesh,
                                partition.CATALOG_FEATURE_RULES)
