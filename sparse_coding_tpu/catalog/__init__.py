"""Feature catalog: a queryable, serveable feature-intelligence index
over sweep artifacts (docs/ARCHITECTURE.md §20).

Two halves:

- :mod:`build` — the **backend-free** catalog build (jax is never
  imported, like ``data/scrub.py``): streams per-feature activation
  frequency + mean magnitude from the chunk store through
  ``data/ingest.chunk_stream``, drops guardian-quarantined
  (``diverged=True``) members, flags dead features, and compiles
  cross-dict feature matching (the ``metrics/core.py`` MMCS machinery,
  mirrored in numpy) into a byte-deterministic on-disk index
  (``index.json`` + per-dict ``.npy`` arrays, all written through
  ``resilience/atomic.py``). It rides the supervisor DAG as the
  ``catalog`` step after ``eval`` (pipeline/steps.py) behind the
  ``catalog.finalize`` crash barrier.
- :mod:`query` / :mod:`serve` — the serving half: batched top-k
  decoder-row similarity and the 2505.16077 union/vote aggregation op
  compiled as ordinary shape-bucket programs (``xcache.cached_compile``,
  mesh placement through ``parallel/partition.py``), fronted by
  :class:`~sparse_coding_tpu.catalog.serve.CatalogService`'s request
  classes (``feature.neighbors`` / ``feature.stats`` /
  ``feature.search``) with their own SLO priorities.

Attributes resolve LAZILY (PEP 562, mirroring the package root):
importing ``sparse_coding_tpu.catalog`` (or :mod:`build`) must stay
jax-free so the build step is schedulable against a wedged TPU tunnel;
only :mod:`query` / :mod:`serve` pull jax.
"""

import importlib

_LAZY_ATTRS = {
    "CatalogIndex": ("sparse_coding_tpu.catalog.build", "CatalogIndex"),
    "build_catalog": ("sparse_coding_tpu.catalog.build", "build_catalog"),
    "load_catalog_records": ("sparse_coding_tpu.catalog.build",
                             "load_catalog_records"),
    "neighbor_topk": ("sparse_coding_tpu.catalog.query", "neighbor_topk"),
    "union_vote": ("sparse_coding_tpu.catalog.query", "union_vote"),
    "unpack_neighbors": ("sparse_coding_tpu.catalog.query",
                         "unpack_neighbors"),
    "CatalogService": ("sparse_coding_tpu.catalog.serve", "CatalogService"),
    "REQUEST_CLASSES": ("sparse_coding_tpu.catalog.serve",
                        "REQUEST_CLASSES"),
}


def __getattr__(name):
    if name in _LAZY_ATTRS:
        module, attr = _LAZY_ATTRS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'sparse_coding_tpu.catalog' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_ATTRS))


__all__ = sorted(_LAZY_ATTRS)
