"""Catalog build: sweep artifacts -> a byte-deterministic on-disk index.

**Backend-free by design** (CLAUDE.md / docs/ARCHITECTURE.md §20): this
module never imports jax, so a catalog rebuild is schedulable while the
TPU tunnel is wedged — exactly like ``data/scrub.py``. Everything a jax
module would provide is mirrored in numpy against the exact reference
formulas:

- encode mirrors cite the flax classes they shadow
  (models/learned_dict.py); parity is asserted in tests/test_catalog.py;
- the cross-dict matching mirrors ``metrics/core.py:225-255``
  (``mcs_duplicates`` / ``mmcs`` / ``mmcs_from_list`` — reference
  standard_metrics.py:270-297), gated by the same parity test.

Determinism contract: records are processed in artifact order, chunks in
ascending index order (quarantined positions skipped — the quarantine
set is durable store state, so two builds over the same store agree),
accumulators are float64 cast once to float32, every array is written
as a raw ``.npy`` via :func:`resilience.atomic.atomic_save_npy` (never
npz — zip headers embed timestamps), and ``index.json`` is
``json.dumps(..., sort_keys=True)``. Two builds from the same artifact
set + store are byte-identical (tests/test_catalog.py, and the chaos
matrix proves it across a SIGKILL at ``catalog.finalize``).

Diverged members (``hyperparams["diverged"]=True`` — the training
guardian's quarantine tag) are dropped before any stats are computed,
mirroring ``load_learned_dicts(skip_diverged=True)``
(utils/artifacts.py:70-96) without the jax reconstruction.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from pathlib import Path
from typing import Optional

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.resilience.atomic import (
    atomic_save_npy,
    atomic_write_text,
)
from sparse_coding_tpu.resilience.crash import (
    crash_barrier,
    register_crash_site,
)
from sparse_coding_tpu.resilience.faults import (
    fault_point,
    register_fault_site,
)

register_fault_site("catalog.build",
                    "catalog build I/O — the artifact-set read and every "
                    "chunk-stats accumulation step (catalog/build.py)")
register_crash_site("catalog.finalize",
                    "catalog build — every per-dict/cross-dict .npy array "
                    "durable, index.json (the completion marker and "
                    "serving manifest) not yet written")

INDEX_NAME = "index.json"
INDEX_VERSION = 1
_NORM_EPS = 1e-8  # models/learned_dict.py _NORM_EPS


class CatalogBuildError(ValueError):
    """Typed build failure: unsupported dictionary class or empty input."""


def _relu(x: np.ndarray) -> np.ndarray:
    return np.maximum(x, 0.0)


def normalize_rows_np(d: np.ndarray) -> np.ndarray:
    """numpy mirror of models/learned_dict.py:30 ``normalize_rows``:
    clip (not +eps), so catalog decoder rows equal the served ones."""
    n = np.linalg.norm(d, axis=-1, keepdims=True)
    return d / np.clip(n, _NORM_EPS, None)


def load_catalog_records(path: str | Path,
                         skip_diverged: bool = True) -> list[dict]:
    """Read a ``learned_dicts.pkl`` artifact as raw records without jax
    reconstruction — the backend-free twin of
    ``load_learned_dicts(skip_diverged=True)`` (utils/artifacts.py:70-96;
    same record schema, same diverged filter, no device transfers)."""
    fault_point("catalog.build")
    with Path(path).open("rb") as fh:
        records = pickle.load(fh)
    if skip_diverged:
        records = [r for r in records
                   if not r["hyperparams"].get("diverged")]
    return records


def decoder_rows_np(rec: dict) -> np.ndarray:
    """Normalized decoder rows [n_feats, d] of one artifact record —
    numpy mirror of ``get_learned_dict()`` for the dictionary-bearing
    classes (models/learned_dict.py)."""
    fields = rec["fields"]
    for name in ("dictionary", "encoder", "eye", "pm_eye", "rotation"):
        if name in fields:
            d = np.asarray(fields[name], dtype=np.float32)
            # Identity/Rotation classes return their matrix verbatim;
            # every *SAE/RandomDict normalizes (learned_dict.py)
            if name in ("eye", "pm_eye", "rotation"):
                return d
            if name == "encoder" and "dictionary" in fields:
                continue  # UntiedSAE: the decoder is `dictionary`
            return normalize_rows_np(d)
    raise CatalogBuildError(
        f"record class {rec['cls']!r} carries no decoder matrix "
        f"(fields: {sorted(fields)})")


def encode_np(rec: dict, x: np.ndarray) -> np.ndarray:
    """numpy mirror of ``encode`` for the artifact classes the sweep
    produces. Formulas cite models/learned_dict.py; parity with the flax
    classes is asserted in tests/test_catalog.py."""
    cls = rec["cls"]
    fields = rec["fields"]
    if cls in ("TiedSAE", "TiedCenteredSAE", "ReverseSAE"):
        # learned_dict.py:242-243 / :283-284:
        # relu(x @ normalize_rows(D).T + encoder_bias)
        dn = normalize_rows_np(np.asarray(fields["dictionary"], np.float32))
        bias = np.asarray(fields["encoder_bias"], np.float32)
        return _relu(x @ dn.T + bias)
    if cls == "UntiedSAE":
        # learned_dict.py:223-224: relu(x @ encoder.T + encoder_bias)
        enc = np.asarray(fields["encoder"], np.float32)
        bias = np.asarray(fields["encoder_bias"], np.float32)
        return _relu(x @ enc.T + bias)
    if cls == "RandomDict":
        # learned_dict.py:151-152
        dn = normalize_rows_np(np.asarray(fields["dictionary"], np.float32))
        return _relu(x @ dn.T)
    if cls == "TopKLearnedDict":
        # learned_dict.py:302-307: keep top-k scores, relu them into a
        # scatter (argpartition — ties are measure-zero for real sweeps)
        dn = normalize_rows_np(np.asarray(fields["dictionary"], np.float32))
        k = int(rec["static"].get("k", 8))
        scores = x @ dn.T
        idx = np.argpartition(scores, -k, axis=1)[:, -k:]
        out = np.zeros_like(scores)
        rows = np.arange(scores.shape[0])[:, None]
        out[rows, idx] = _relu(np.take_along_axis(scores, idx, axis=1))
        return out
    raise CatalogBuildError(
        f"no backend-free encode mirror for class {cls!r}; supported: "
        "TiedSAE, TiedCenteredSAE, ReverseSAE, UntiedSAE, RandomDict, "
        "TopKLearnedDict")


def mmcs_np(rows_a: np.ndarray, rows_b: np.ndarray) -> float:
    """numpy mirror of ``metrics/core.py:232`` ``mmcs(a, b)`` =
    mean over a's atoms of max cosine to any b atom
    (``mcs_duplicates(ground=b, model=a)``, core.py:225-229; reference
    standard_metrics.py:270-277). Inputs are already row-normalized."""
    return float(np.mean(np.max(rows_a @ rows_b.T, axis=-1)))


def _sanitize_hyperparams(hyper: dict) -> dict:
    return {k: v for k, v in sorted(hyper.items())
            if isinstance(v, (bool, int, float, str))}


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with path.open("rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _dict_tag(i: int) -> str:
    return f"d{i:03d}"


def _normalize_artifacts(artifact_path,
                         group: Optional[str]) -> list[tuple[Path, object]]:
    """Accept one path, a list of paths, or a list of ``(path, group)``
    pairs; return ``[(Path, group_label), ...]`` in input order. The
    bare forms inherit the build-level ``group`` label."""
    if isinstance(artifact_path, (str, Path)):
        return [(Path(artifact_path), group)]
    out = []
    for item in artifact_path:
        if isinstance(item, (tuple, list)):
            path, label = item
            out.append((Path(path), label))
        else:
            out.append((Path(item), group))
    if not out:
        raise CatalogBuildError("empty artifact list")
    return out


def build_catalog(artifact_path, store_dir: str | Path,
                  out_dir: str | Path, *, dead_threshold: float = 0.0,
                  experiment: Optional[str] = None,
                  group: Optional[str] = None) -> dict:
    """Build the feature-intelligence index for one or more sweep
    artifact sets.

    ``artifact_path`` is one ``learned_dicts.pkl`` path, a list of them,
    or a list of ``(path, group_label)`` pairs — the Group-SAE case
    (§23): a group's dictionaries indexed TOGETHER with its per-layer
    baseline dictionaries, so the cross-dict MMCS/matching arrays pair a
    group feature directly against its baselines. Every index row
    carries a ``group`` label (the pair's, else the build-level
    ``group=`` kwarg, else None); records concatenate in artifact order
    so the determinism contract is unchanged.

    Streams every sound chunk of ``store_dir`` once through
    ``data/ingest.chunk_stream`` (lease beats per delivered chunk ride
    along), accumulating per-feature activation counts and magnitude
    sums for every non-diverged record, then computes the cross-dict
    matching arrays and writes:

    - per dict ``i`` (tag ``d{i:03d}``): ``<tag>_rows.npy`` (normalized
      decoder rows), ``<tag>_freq.npy`` (activation frequency),
      ``<tag>_mag.npy`` (mean magnitude over firing events),
      ``<tag>_dead.npy`` (bool: frequency <= ``dead_threshold``),
      ``<tag>_match_dict.npy`` / ``<tag>_match_feat.npy`` /
      ``<tag>_match_cos.npy`` (nearest live partner feature across the
      other dicts; -1/-1/0 with a single dict);
    - ``mmcs.npy``: the pairwise MMCS matrix
      (mirrors ``metrics/core.py:248`` ``mmcs_from_list``);
    - ``index.json`` — written LAST, behind the ``catalog.finalize``
      crash barrier: the completion marker AND the serving manifest
      (schema + per-file sha256 digests).

    Returns the index metadata dict. Byte-deterministic: rebuilding over
    the same inputs reproduces every file bit for bit.
    """
    from sparse_coding_tpu.data.ingest import chunk_stream
    from sparse_coding_tpu.data.shard_store import open_store

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    artifacts = _normalize_artifacts(artifact_path, group)
    with obs.span("catalog.build"):
        records, labels = [], []
        n_dropped = 0
        for path, label in artifacts:
            recs = load_catalog_records(path, skip_diverged=True)
            n_dropped += _count_diverged(path, len(recs))
            records.extend(recs)
            labels.extend([label] * len(recs))
        if not records:
            raise CatalogBuildError(
                "no non-diverged records in "
                f"{[str(p) for p, _ in artifacts]}")
        rows_norm = [decoder_rows_np(rec) for rec in records]
        store = open_store(store_dir, quarantine_corrupt=True)
        indices = list(range(store.n_chunks))
        counts = [np.zeros(r.shape[0], dtype=np.int64) for r in rows_norm]
        mags = [np.zeros(r.shape[0], dtype=np.float64) for r in rows_norm]
        rows_total = 0
        chunks_read = 0
        for chunk in chunk_stream(store, indices):
            if chunk is None:  # quarantined position (durable store state)
                continue
            fault_point("catalog.build")
            x = np.asarray(chunk, dtype=np.float32)
            for i, rec in enumerate(records):
                codes = encode_np(rec, x)
                counts[i] += (codes > 0).sum(axis=0)
                mags[i] += codes.sum(axis=0, dtype=np.float64)
            rows_total += x.shape[0]
            chunks_read += 1
        if rows_total == 0:
            raise CatalogBuildError(
                f"store {store_dir} delivered zero rows (all chunks "
                "quarantined?)")

        meta_dicts = []
        files: dict[str, Path] = {}
        freqs, deads = [], []
        for i, rec in enumerate(records):
            tag = _dict_tag(i)
            freq = (counts[i] / rows_total).astype(np.float32)
            mag = (mags[i] / np.maximum(counts[i], 1)).astype(np.float32)
            dead = freq <= np.float32(dead_threshold)
            freqs.append(freq)
            deads.append(dead)
            for suffix, arr in (("rows", rows_norm[i]), ("freq", freq),
                                ("mag", mag), ("dead", dead)):
                files[f"{tag}_{suffix}.npy"] = arr
            meta_dicts.append({
                "tag": tag, "cls": rec["cls"],
                "group": (None if labels[i] is None else str(labels[i])),
                "n_feats": int(rows_norm[i].shape[0]),
                "d_activation": int(rows_norm[i].shape[1]),
                "n_dead": int(dead.sum()),
                "hyperparams": _sanitize_hyperparams(rec["hyperparams"])})

        # cross-dict matching (metrics/core.py MMCS machinery, §20):
        # mmcs.npy mirrors mmcs_from_list exactly (upper triangle computed,
        # mirrored — core.py:248-255); the per-feature nearest-partner
        # arrays exclude DEAD partner atoms so a dead feature can never be
        # offered as a neighbor
        m = len(records)
        mmcs_mat = np.eye(m, dtype=np.float32)
        for i in range(m):
            for j in range(i):
                v = np.float32(mmcs_np(rows_norm[i], rows_norm[j]))
                mmcs_mat[i, j] = mmcs_mat[j, i] = v
        files["mmcs.npy"] = mmcs_mat
        for i in range(m):
            n_i = rows_norm[i].shape[0]
            best_cos = np.full(n_i, -np.inf, dtype=np.float32)
            best_dict = np.full(n_i, -1, dtype=np.int32)
            best_feat = np.full(n_i, -1, dtype=np.int32)
            for j in range(m):
                if j == i or rows_norm[j].shape[1] != rows_norm[i].shape[1]:
                    continue
                sims = (rows_norm[i] @ rows_norm[j].T).astype(np.float32)
                sims[:, deads[j]] = -np.inf
                feat_j = np.argmax(sims, axis=1).astype(np.int32)
                cos_j = sims[np.arange(n_i), feat_j]
                better = cos_j > best_cos
                best_cos = np.where(better, cos_j, best_cos)
                best_dict = np.where(better, np.int32(j), best_dict)
                best_feat = np.where(better, feat_j, best_feat)
            tag = _dict_tag(i)
            files[f"{tag}_match_dict.npy"] = best_dict
            files[f"{tag}_match_feat.npy"] = best_feat
            files[f"{tag}_match_cos.npy"] = np.where(
                np.isfinite(best_cos), best_cos, np.float32(0.0))

        for name, arr in files.items():
            atomic_save_npy(out / name, arr)
        index = {
            "version": INDEX_VERSION,
            "experiment": experiment,
            "dead_threshold": float(dead_threshold),
            "n_rows": int(rows_total),
            "n_chunks_read": int(chunks_read),
            "quarantined_chunks": sorted(int(c) for c in store.quarantined),
            "dropped_diverged": int(n_dropped),
            "dicts": meta_dicts,
            "files": {name: _sha256(out / name) for name in sorted(files)},
        }
        # worst instant: every array durable, the completion marker not
        # yet written — a SIGKILL here must leave a restart that rebuilds
        # to the bitwise-identical index (chaos matrix, §20)
        crash_barrier("catalog.finalize")
        atomic_write_text(out / INDEX_NAME,
                          json.dumps(index, indent=2, sort_keys=True))
    return index


def _count_diverged(artifact_path: str | Path, n_kept: int) -> int:
    with Path(artifact_path).open("rb") as fh:
        return len(pickle.load(fh)) - n_kept


class CatalogIndex:
    """Read-side handle on a built catalog directory (jax-free).

    Loads ``index.json`` plus every array eagerly (catalog arrays are
    per-feature vectors — tiny next to the chunk store). ``verify=True``
    re-hashes each array file against the manifest digests, turning a
    torn/stale directory into a typed error instead of silent garbage.
    """

    def __init__(self, root: Path, meta: dict,
                 arrays: dict[str, np.ndarray]):
        self.root = root
        self.meta = meta
        self._arrays = arrays

    @classmethod
    def load(cls, root: str | Path, verify: bool = False) -> "CatalogIndex":
        root = Path(root)
        marker = root / INDEX_NAME
        if not marker.exists():
            raise FileNotFoundError(
                f"no catalog index at {marker} (incomplete build?)")
        meta = json.loads(marker.read_text())
        arrays = {}
        for name, digest in meta["files"].items():
            path = root / name
            if verify and _sha256(path) != digest:
                raise CatalogBuildError(
                    f"catalog array {name} does not match its index.json "
                    "digest (torn or stale build directory)")
            arrays[name] = np.load(path)
        return cls(root, meta, arrays)

    @property
    def n_dicts(self) -> int:
        return len(self.meta["dicts"])

    def _arr(self, i: int, suffix: str) -> np.ndarray:
        return self._arrays[f"{_dict_tag(i)}_{suffix}.npy"]

    def rows(self, i: int) -> np.ndarray:
        return self._arr(i, "rows")

    def freq(self, i: int) -> np.ndarray:
        return self._arr(i, "freq")

    def mag(self, i: int) -> np.ndarray:
        return self._arr(i, "mag")

    def dead(self, i: int) -> np.ndarray:
        return self._arr(i, "dead")

    def mmcs_matrix(self) -> np.ndarray:
        return self._arrays["mmcs.npy"]

    def feature_stats(self, dict_i: int, feature_id: int) -> dict:
        """One feature's full stat row — the payload ``feature.stats``
        serves (catalog/serve.py)."""
        f = int(feature_id)
        return {
            "dict": int(dict_i),
            "feature": f,
            "freq": float(self.freq(dict_i)[f]),
            "mag": float(self.mag(dict_i)[f]),
            "dead": bool(self.dead(dict_i)[f]),
            "match_dict": int(self._arr(dict_i, "match_dict")[f]),
            "match_feat": int(self._arr(dict_i, "match_feat")[f]),
            "match_cos": float(self._arr(dict_i, "match_cos")[f]),
        }
