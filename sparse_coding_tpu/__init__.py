"""sparse_coding_tpu — a TPU-native (JAX/XLA/pjit) sparse-coding framework.

A ground-up re-design of the capabilities of HoagyC/sparse_coding (see
/root/reference) for TPU hardware:

- ensembles of sparse autoencoders trained with a single vmapped+jitted step
  (reference: autoencoders/ensemble.py uses torch.vmap imitating JAX),
- data/model sharding over a `jax.sharding.Mesh` replacing the reference's
  process-per-GPU scheduler (cluster_runs.py) and gloo DDP
  (experiments/huge_batch_size.py),
- a pure-JAX LM forward with activation taps replacing transformer_lens
  `run_with_cache` (activation_dataset.py), incl. a sequence-parallel
  ring-attention path for long contexts,
- metrics, interpretation, and plotting layers mirroring standard_metrics.py,
  interpret.py and plotting/,
- a request-driven serving engine (serve/) — micro-batched, AOT-compiled
  shape-bucket feature extraction over a multi-dict registry — a workload
  the reference has no counterpart for.
"""

__version__ = "0.1.0"

from sparse_coding_tpu import config as config
from sparse_coding_tpu import ensemble as ensemble
from sparse_coding_tpu import models as models
from sparse_coding_tpu import serve as serve
from sparse_coding_tpu.ensemble import Ensemble, EnsembleGroup
from sparse_coding_tpu.parallel.mesh import make_mesh
