"""sparse_coding_tpu — a TPU-native (JAX/XLA/pjit) sparse-coding framework.

A ground-up re-design of the capabilities of HoagyC/sparse_coding (see
/root/reference) for TPU hardware:

- ensembles of sparse autoencoders trained with a single vmapped+jitted step
  (reference: autoencoders/ensemble.py uses torch.vmap imitating JAX),
- data/model sharding over a `jax.sharding.Mesh` replacing the reference's
  process-per-GPU scheduler (cluster_runs.py) and gloo DDP
  (experiments/huge_batch_size.py),
- a pure-JAX LM forward with activation taps replacing transformer_lens
  `run_with_cache` (activation_dataset.py), incl. a sequence-parallel
  ring-attention path for long contexts,
- metrics, interpretation, and plotting layers mirroring standard_metrics.py,
  interpret.py and plotting/,
- a request-driven serving engine (serve/) — micro-batched, AOT-compiled
  shape-bucket feature extraction over a multi-dict registry — a workload
  the reference has no counterpart for.

Submodules and the convenience re-exports (``Ensemble``,
``EnsembleGroup``, ``make_mesh``) resolve LAZILY (PEP 562): importing
``sparse_coding_tpu`` alone must not import jax, so the jax-free tooling
under ``sparse_coding_tpu.analysis`` (the static-analysis CLI,
``scripts/lint.sh``) can run while another process owns the TPU tunnel —
the axon plugin initializes the tunnel in every jax-importing process
(see CLAUDE.md), and a lint must never be that second process.
"""

import importlib

__version__ = "0.1.0"

_SUBMODULES = (
    "analysis", "config", "data", "ensemble", "interp", "lm", "metrics",
    "models", "obs", "ops", "parallel", "pipeline", "plotting",
    "resilience", "serve", "tasks", "train", "utils", "xcache",
)

_LAZY_ATTRS = {
    "Ensemble": ("sparse_coding_tpu.ensemble", "Ensemble"),
    "EnsembleGroup": ("sparse_coding_tpu.ensemble", "EnsembleGroup"),
    "make_mesh": ("sparse_coding_tpu.parallel.mesh", "make_mesh"),
}


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"sparse_coding_tpu.{name}")
    if name in _LAZY_ATTRS:
        module, attr = _LAZY_ATTRS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'sparse_coding_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES) | set(_LAZY_ATTRS))
