"""Streaming pairwise angular similarity between harvested layers.

The Group-SAE grouping signal (arXiv 2410.21508 §3: layers whose
residual streams point the same way can share one SAE) is the mean
angular similarity ``1 - arccos(cos θ)/π`` between ROW-ALIGNED
activations of two layers: every ``harvest-<i>`` writer replays the
same producer stream (same tokens / same seeded generator rows), so row
``r`` of shard ``i`` and row ``r`` of shard ``j`` are the same input
observed at two depths, and the cosine between them is meaningful.

Jax-free at import (the ``group`` step must be schedulable against a
wedged tunnel up to the point real chunk bytes are read); chunk reads go
through the flat :class:`~sparse_coding_tpu.data.chunk_store.ChunkStore`
per shard — lazily imported — so every sampled chunk is digest-verified
exactly as the sweep would verify it. Every read sits behind fault site
``groups.similarity`` (tests/test_resilience.py injects here).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.retry import retry_io

register_fault_site("groups.similarity",
                    "group-SAE similarity pass — every digest-verified "
                    "sampled-chunk read feeding the pairwise "
                    "layer-similarity accumulation (groups/similarity.py)")

_NORM_EPS = 1e-8  # models/learned_dict.py _NORM_EPS


class GroupStoreError(ValueError):
    """The multi-tap store cannot support a grouping pass: missing
    manifest, shards disagreeing on chunk count (row alignment would be
    meaningless), or fewer than two layers."""


def layer_taps(store_dir: str | Path) -> list[dict]:
    """Per-layer tap records for a multi-tap sharded store, in shard
    (= layer) order: ``{"shard", "tap", "layer", "layer_loc",
    "n_chunks"}``. Taps come from each shard's ``meta.json`` (the group
    harvest stamps them at finalize); a digest-less legacy shard falls
    back to its positional index so similarity still runs."""
    from sparse_coding_tpu.data.shard_store import read_store_manifest

    store_dir = Path(store_dir)
    manifest = read_store_manifest(store_dir)
    if manifest is None or manifest.get("kind") != "sharded_chunk_store":
        raise GroupStoreError(
            f"{store_dir}: no sharded-store manifest — the group pass "
            "needs the multi-tap store's completion marker "
            "(build_store_manifest)")
    out = []
    for i, s in enumerate(manifest["shards"]):
        meta = json.loads((store_dir / s["name"] / "meta.json").read_text())
        out.append({
            "shard": str(s["name"]),
            "tap": str(meta.get("tap", f"layer.{i}")),
            "layer": int(meta.get("layer", i)),
            "layer_loc": str(meta.get("layer_loc", "residual")),
            "n_chunks": int(s["n_chunks"]),
        })
    return out


def _sample_rows(rng: np.random.Generator, n_rows: int,
                 n_sample_rows: int) -> np.ndarray:
    take = min(int(n_sample_rows), int(n_rows))
    return np.sort(rng.permutation(n_rows)[:take])


def layer_similarity(store_dir: str | Path, *, n_sample_chunks: int = 1,
                     n_sample_rows: int = 2048, seed: int = 0,
                     taps: Optional[list[dict]] = None) -> dict:
    """Mean pairwise angular similarity between every layer pair.

    Returns ``{"matrix": [L, L] float64 (diag exactly 1), "taps",
    "layers", "layer_loc", "n_rows", "chunk_indices"}``. Deterministic:
    the sampled chunk indices and the per-chunk row subset derive only
    from ``seed`` — two passes over the same store agree bitwise."""
    from sparse_coding_tpu.data.chunk_store import ChunkStore

    store_dir = Path(store_dir)
    taps = layer_taps(store_dir) if taps is None else taps
    n_layers = len(taps)
    if n_layers < 2:
        raise GroupStoreError(
            f"{store_dir}: {n_layers} layer shard(s) — grouping needs at "
            "least two harvested layers")
    n_chunks = {t["n_chunks"] for t in taps}
    if len(n_chunks) != 1:
        raise GroupStoreError(
            f"{store_dir}: shards disagree on chunk count ({sorted(n_chunks)})"
            " — rows are not aligned across layers; re-harvest")
    n_chunks = n_chunks.pop()
    rng = np.random.default_rng(int(seed))
    take_chunks = min(int(n_sample_chunks), n_chunks)
    chunk_indices = sorted(int(c) for c in
                           rng.permutation(n_chunks)[:take_chunks])
    stores = [ChunkStore(store_dir / t["shard"]) for t in taps]

    acc = np.zeros((n_layers, n_layers), dtype=np.float64)
    rows_total = 0
    with obs.span("groups.similarity", layers=n_layers,
                  chunks=len(chunk_indices)):
        for ci in chunk_indices:
            row_rng = np.random.default_rng([int(seed), int(ci)])
            rows: Optional[np.ndarray] = None
            units = []
            for li, store in enumerate(stores):
                def _read(store=store):
                    fault_point("groups.similarity")
                    return store.load_chunk(ci, np.float32)

                chunk = retry_io(_read, attempts=3)
                if rows is None:
                    rows = _sample_rows(row_rng, chunk.shape[0],
                                        n_sample_rows)
                elif chunk.shape[0] < (int(rows[-1]) + 1 if len(rows) else 0):
                    raise GroupStoreError(
                        f"{store_dir}: chunk {ci} row counts disagree "
                        f"across layers — rows are not aligned")
                x = chunk[rows]
                norm = np.linalg.norm(x, axis=1, keepdims=True)
                units.append(x / np.clip(norm, _NORM_EPS, None))
                lease.beat()  # one digest-verified layer-chunk delivered
            n = units[0].shape[0]
            for i in range(n_layers):
                for j in range(i + 1, n_layers):
                    cos = np.clip(np.sum(units[i] * units[j], axis=1),
                                  -1.0, 1.0)
                    ang = 1.0 - np.arccos(cos) / np.pi
                    acc[i, j] += float(np.sum(ang, dtype=np.float64))
            rows_total += n
    if rows_total == 0:
        raise GroupStoreError(f"{store_dir}: sampled zero rows")
    matrix = acc / rows_total
    matrix = matrix + matrix.T
    np.fill_diagonal(matrix, 1.0)
    return {
        "matrix": matrix,
        "taps": [t["tap"] for t in taps],
        "layers": [t["layer"] for t in taps],
        "layer_loc": taps[0]["layer_loc"],
        "n_rows": int(rows_total),
        "chunk_indices": chunk_indices,
    }
