"""One fleet tenant per group: pooled-store training configs + enqueue.

The Group-SAE training plane is DELIBERATELY not a new scheduler: after
the ``group`` step finalizes ``groups.json``, each group becomes an
ordinary fleet tenant (``pipeline/fleet.py``, docs/ARCHITECTURE.md §18)
whose pipeline is ``sweep → eval (→ catalog)`` over the group's pooled
store view ``<store>/group-<g>/`` (``kind="group"`` — no harvest edge:
the pooled chunks are the multi-tap harvest's, referenced relatively).
Guardian halts stay contained per group (one diverging group's tenant
exits ``STEP_EXIT_HALTED`` inside its own run dir while the others
complete), all tenants share the fleet's ONE xcache, and the scheduler's
bin-packing/preemption applies unchanged.

Jax-free; the fleet modules import lazily (a grouping CLI must stay
usable against a wedged tunnel).
"""

from __future__ import annotations

import copy
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.groups.assign import load_groups


def group_tenant_config(base_config: dict, group: dict,
                        store_dir: str | Path,
                        out_root: str | Path) -> dict:
    """Derive one group tenant's pipeline config from a base config
    (sweep/eval/catalog sections supply hyperparameters): the tenant
    trains on ``<store>/<group name>/`` (the pooled view) and writes all
    artifacts under ``<out_root>/<group name>/``. The group name is
    stamped into the sweep/eval/catalog sections so every downstream
    artifact — catalog index rows included — carries its group label."""
    cfg = copy.deepcopy(base_config)
    gname = str(group["name"])
    gdir = Path(store_dir) / gname
    out = Path(out_root) / gname
    # eval/catalog read the store through config["harvest"]; the pooled
    # view is already durable, so the tenant pipeline has no harvest step
    cfg["harvest"] = {"dataset_folder": str(gdir)}
    ens = cfg["sweep"]["ensemble"]
    ens["dataset_folder"] = str(gdir)
    ens["output_folder"] = str(out / "sweep")
    # the pooled store concatenates the member layers' chunks
    ens["n_chunks"] = int(group["n_chunks"])
    cfg["sweep"]["group"] = gname
    cfg["eval"] = {**cfg.get("eval", {}), "output_folder": str(out / "eval")}
    if "catalog" in cfg:
        cfg["catalog"] = {**cfg["catalog"],
                          "output_folder": str(out / "catalog"),
                          "group": gname}
    return cfg


def enqueue_group_tenants(sched, store_dir: str | Path, base_config: dict,
                          out_root: str | Path, *,
                          priority: str = "batch",
                          env: Optional[dict] = None,
                          max_attempts: int = 2,
                          heartbeat_stale_s: Optional[float] = None,
                          env_overrides: Optional[dict] = None) -> list[str]:
    """Enqueue one ``kind="group"`` tenant per group of the finalized
    assignment (idempotent per name — the queue dedupes). Returns the
    tenant names in group order. ``env_overrides`` maps a group name to
    extra per-tenant env (the containment drill poisons exactly one)."""
    payload = load_groups(store_dir)
    names: list[str] = []
    for group in payload["groups"]:
        cfg = group_tenant_config(base_config, group, store_dir, out_root)
        tenant_env = dict(env or {})
        tenant_env.update((env_overrides or {}).get(group["name"], {}))
        sched.enqueue(group["name"], cfg, kind="group", priority=priority,
                      env=tenant_env, max_attempts=max_attempts,
                      heartbeat_stale_s=heartbeat_stale_s)
        names.append(group["name"])
    return names
