"""Group-SAE subsystem (docs/ARCHITECTURE.md §23).

Adjacent layers' residual streams are similar enough to share one SAE
trained on their pooled activations (Group-SAE, arXiv 2410.21508 —
PAPERS.md), cutting sweep cost roughly by the group ratio G/L. The
subsystem is three small, jax-free-at-import pieces over the sharded
store layout the data plane already has (taps ARE shards):

- :mod:`groups.similarity` — streaming pairwise angular-similarity
  matrix between harvested layers, from digest-verified sampled chunks
  (fault site ``groups.similarity``);
- :mod:`groups.assign` — deterministic adjacent-layer greedy clustering
  to a target G, emitting per-group pooled-store manifests plus the
  sha256-digested ``groups.json`` completion marker (written LAST,
  behind crash barrier ``groups.finalize``);
- :mod:`groups.tenants` — one fleet tenant per group (sweep → eval →
  catalog over the group's pooled view, ``kind="group"``).
"""

from sparse_coding_tpu.groups.assign import (
    GROUPS_NAME,
    GroupBuildError,
    build_groups,
    greedy_adjacent_groups,
    group_name,
    load_groups,
)
from sparse_coding_tpu.groups.similarity import layer_similarity, layer_taps
from sparse_coding_tpu.groups.tenants import (
    enqueue_group_tenants,
    group_tenant_config,
)

__all__ = [
    "GROUPS_NAME", "GroupBuildError", "build_groups",
    "greedy_adjacent_groups", "group_name", "load_groups",
    "layer_similarity", "layer_taps",
    "enqueue_group_tenants", "group_tenant_config",
]
