"""Deterministic adjacent-layer greedy grouping → durable ``groups.json``.

The paper's assignment (arXiv 2410.21508 §3.1): start with every layer
its own group, repeatedly merge the ADJACENT pair with the highest
average-linkage angular similarity until G groups remain. Adjacency is
layer order — a group is always a contiguous layer range — and ties
break to the lowest index, so the assignment is a pure function of the
similarity matrix.

Durable layout (mirrors catalog/build.py's finalize discipline):

```
store/                       # the multi-tap sharded store (taps ARE shards)
  manifest.json              # store-level truth (data/shard_store.py)
  shard-<i>/                 # layer i's chunk folder, sealed
  similarity.npy             # the [L, L] float64 matrix, durable FIRST
  group-<g>/manifest.json    # pooled view: a sharded_chunk_store manifest
                             # whose shard names are RELATIVE ("../shard-000")
                             # so open_store() trains on the pool unchanged
  groups.json                # completion marker: written LAST, sort_keys,
                             # self-digested (payload_sha256), behind crash
                             # barrier ``groups.finalize``
```

Every durable write before the marker sits behind fault site
``groups.build`` (bounded retry); the build is byte-deterministic —
rebuilding over the same store rewrites identical bytes, which is what
the chaos matrix's SIGKILL-at-``groups.finalize`` case proves.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.groups.similarity import layer_similarity, layer_taps
from sparse_coding_tpu.resilience.atomic import (
    atomic_save_npy,
    atomic_write_text,
)
from sparse_coding_tpu.resilience.crash import (
    crash_barrier,
    register_crash_site,
)
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import (
    bytes_sha256,
    check_payload_digest,
    embed_payload_digest,
)
from sparse_coding_tpu.resilience.retry import retry_io

register_fault_site("groups.build",
                    "group-SAE assignment build I/O — the durable writes "
                    "of similarity.npy and the per-group pooled-store "
                    "manifests, before groups.json (groups/assign.py)")
register_crash_site("groups.finalize",
                    "group assignment build — similarity.npy and every "
                    "per-group pooled-store manifest durable, groups.json "
                    "(the completion marker) not yet written "
                    "(groups/assign.py)")

GROUPS_NAME = "groups.json"
GROUPS_VERSION = 1
SIMILARITY_NAME = "similarity.npy"


class GroupBuildError(ValueError):
    """Typed grouping failure: an impossible target G, or a
    ``groups.json`` whose embedded digest no longer matches its payload
    (the assignment cannot be trusted)."""


def group_name(g: int) -> str:
    return f"group-{int(g):03d}"


def greedy_adjacent_groups(matrix: np.ndarray,
                           n_groups: int) -> list[list[int]]:
    """Merge adjacent groups by highest average linkage until
    ``n_groups`` remain. Returns contiguous layer-index lists in layer
    order. Deterministic: strict ``>`` comparison breaks score ties to
    the lowest adjacent-pair index."""
    matrix = np.asarray(matrix, dtype=np.float64)
    n_layers = int(matrix.shape[0])
    if not 1 <= int(n_groups) <= n_layers:
        raise GroupBuildError(
            f"n_groups={n_groups} out of range [1, {n_layers}]")
    groups: list[list[int]] = [[i] for i in range(n_layers)]
    while len(groups) > int(n_groups):
        best_k, best_score = 0, -np.inf
        for k in range(len(groups) - 1):
            pair = matrix[np.ix_(groups[k], groups[k + 1])]
            score = float(pair.mean())
            if score > best_score:
                best_k, best_score = k, score
        groups[best_k:best_k + 2] = [groups[best_k] + groups[best_k + 1]]
    return groups


def _durable_write_text(path: Path, text: str) -> None:
    def _once():
        fault_point("groups.build")
        atomic_write_text(path, text)

    retry_io(_once, attempts=3)


def _durable_save_npy(path: Path, arr: np.ndarray) -> None:
    def _once():
        fault_point("groups.build")
        atomic_save_npy(path, arr)

    retry_io(_once, attempts=3)


def build_groups(store_dir: str | Path, *, n_groups: int,
                 n_sample_chunks: int = 1, n_sample_rows: int = 2048,
                 seed: int = 0) -> dict:
    """Similarity pass + greedy assignment + durable artifacts; returns
    the ``groups.json`` payload. Byte-deterministic and re-runnable from
    scratch at any instant (the crash-only step contract): a rebuild
    over the same store rewrites every artifact bit for bit."""
    from sparse_coding_tpu.data.shard_store import read_store_manifest

    store_dir = Path(store_dir)
    taps = layer_taps(store_dir)
    manifest = read_store_manifest(store_dir)
    shards_by_name = {s["name"]: s for s in manifest["shards"]}
    with obs.span("groups.build", layers=len(taps), n_groups=int(n_groups)):
        sim = layer_similarity(store_dir, n_sample_chunks=n_sample_chunks,
                               n_sample_rows=n_sample_rows, seed=seed,
                               taps=taps)
        assignment = greedy_adjacent_groups(sim["matrix"], n_groups)

        _durable_save_npy(store_dir / SIMILARITY_NAME,
                          np.asarray(sim["matrix"], dtype=np.float64))
        files = {SIMILARITY_NAME:
                 bytes_sha256((store_dir / SIMILARITY_NAME).read_bytes())}

        group_rows = []
        for g, members in enumerate(assignment):
            gname = group_name(g)
            gdir = store_dir / gname
            gdir.mkdir(parents=True, exist_ok=True)
            # the pooled view: shard names are RELATIVE into the parent
            # store (ShardedChunkStore resolves `folder / name`), so ONE
            # set of chunk bytes backs both the per-layer and the pooled
            # readers — no copies, digests verified where they live
            shard_entries = []
            for li in members:
                src = shards_by_name[taps[li]["shard"]]
                shard_entries.append({"name": f"../{src['name']}",
                                      "n_chunks": int(src["n_chunks"]),
                                      "meta_sha256": str(src["meta_sha256"])})
            g_manifest = {
                "version": 1, "kind": "sharded_chunk_store",
                "n_shards": len(shard_entries),
                "n_chunks": sum(e["n_chunks"] for e in shard_entries),
                "activation_dim": int(manifest["activation_dim"]),
                "dtype": str(manifest["dtype"]),
                "shards": shard_entries,
                "group": {"id": g, "name": gname,
                          "layers": [taps[li]["layer"] for li in members],
                          "taps": [taps[li]["tap"] for li in members]},
            }
            text = json.dumps(g_manifest, indent=2, sort_keys=True)
            _durable_write_text(gdir / "manifest.json", text)
            files[f"{gname}/manifest.json"] = bytes_sha256(text.encode())
            group_rows.append({
                "id": g, "name": gname,
                "layers": [taps[li]["layer"] for li in members],
                "taps": [taps[li]["tap"] for li in members],
                "shards": [taps[li]["shard"] for li in members],
                "n_chunks": g_manifest["n_chunks"],
            })

        payload = embed_payload_digest({
            "version": GROUPS_VERSION,
            "kind": "group_assignment",
            "layer_loc": sim["layer_loc"],
            "layers": sim["layers"],
            "taps": sim["taps"],
            "n_layers": len(taps),
            "n_groups": len(group_rows),
            "groups": group_rows,
            "params": {"seed": int(seed),
                       "n_sample_chunks": int(n_sample_chunks),
                       "n_sample_rows": int(n_sample_rows),
                       "n_rows_sampled": int(sim["n_rows"]),
                       "chunk_indices": list(sim["chunk_indices"])},
            "files": files,
        })
        # worst instant: every pooled manifest + similarity.npy durable,
        # the completion marker not yet written — a SIGKILL here must
        # leave a restart that rebuilds to the bitwise-identical marker
        crash_barrier("groups.finalize")
        atomic_write_text(store_dir / GROUPS_NAME,
                          json.dumps(payload, indent=2, sort_keys=True))
    return payload


def load_groups(store_dir: str | Path, verify: bool = True) -> dict:
    """Read ``groups.json``; with ``verify`` the embedded payload digest
    must match (a tampered/rotted assignment raises typed instead of
    silently steering tenants at the wrong shards)."""
    path = Path(store_dir) / GROUPS_NAME
    if not path.exists():
        raise FileNotFoundError(
            f"no {GROUPS_NAME} at {path} (incomplete group build?)")
    payload = json.loads(path.read_text())
    if verify and check_payload_digest(payload) == "mismatch":
        raise GroupBuildError(
            f"{path}: embedded payload digest mismatch — the group "
            "assignment cannot be trusted; rebuild it (delete the file "
            "and re-run the group step)")
    return payload
