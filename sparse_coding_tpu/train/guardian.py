"""Training health guardian: divergence quarantine + last-good rollback.

The in-graph anomaly sentinel (ensemble.py, docs/ARCHITECTURE.md §16)
detects and CONTAINS numerical failure device-side: a member whose step
went non-finite keeps its params bit-identically unchanged, and the
per-member finite flags / grad norms ride the aux the step already
returns. This module is the host half of the ladder — it decides what a
detection MEANS and makes the outcome durable:

1. **Per-member quarantine.** A member whose steps go non-finite while
   the batch itself was finite has diverged (hyperparameter corner, the
   paper's deliberately aggressive l1/lr grids): its live-mask bit is
   cleared (``Ensemble.freeze_members``), the incident is recorded in a
   durable ``guardian.json`` ledger next to the sweep's checkpoints
   (atomic rewrite, mirroring data/ledger.py), and its artifact is
   tagged ``diverged=True`` so evals/serving can skip it.
2. **Escalation + auto-rollback.** Non-finite *inputs* (data corruption —
   a distinct incident class, flagged by the sentinel's batch-finite
   scalar) or a quarantined-member fraction crossing the threshold
   trigger a rollback: incident + chunk quarantine become durable FIRST
   (the PR-8 ledger makes the offending chunk a positional hole), the
   ``guardian.rollback`` crash barrier sits between that durability and
   the restore, and then the sweep restores the retained last-good
   checkpoint set (``resume_sweep_state``) and replays — bitwise the run
   that never saw the poisoned chunk.
3. **Typed halt.** A rollback demanded again at a site that already
   rolled back — or past the run's rollback budget — is structural:
   :class:`~sparse_coding_tpu.resilience.errors.DivergenceHaltError`
   carries the diagnosis (``poisoned-data`` vs ``hyperparameter``,
   triage recipe in docs/RUNBOOK_TUNNEL.md).

Multi-host: every rollback/halt decision passes through
:func:`sparse_coding_tpu.parallel.agree_any` — the branch contains
collective barriers, so any host's anomaly must move all hosts together
(the ``_agree_preempted`` rule, generalized).

Determinism: detection is in-graph; accumulation across a chunk is one
tiny device-side combine per training window (no host sync until the
chunk boundary); the drill fault site ``sweep.anomaly`` injects NaN into
a chosen batch (mode=nan) or a chosen member's loss-scale buffer
(mode=error, message ``member=<i>``) so every ladder rung replays
identically in CI (tests/test_resilience.py, tests/test_pipeline_chaos.py).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Callable, Optional, Sequence

import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.parallel import agree_any
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.errors import (
    ChunkCorruptionError,
    DivergenceHaltError,
    LedgerCorruptionError,
)
from sparse_coding_tpu.resilience.manifest import (
    check_payload_digest,
    embed_payload_digest,
)
from sparse_coding_tpu.resilience.faults import (
    InjectedFault,
    fault_point,
    register_fault_site,
)

LEDGER_NAME = "guardian.json"

register_fault_site("sweep.anomaly",
                    "training-batch anomaly injection — every host batch "
                    "passes through this site in the sweep hot loop "
                    "(train/guardian.py inject_anomaly); mode=nan poisons "
                    "the batch (non-finite-input incident), mode=error "
                    "with message member=<i> poisons that member's "
                    "loss-scale buffer (per-member divergence drill)")
register_crash_site("guardian.rollback",
                    "guardian incident ledger + chunk quarantine durable, "
                    "the last-good checkpoint restore not yet performed "
                    "(train/guardian.py rollback_restore)")

_MEMBER_RE = re.compile(r"member=(\d+)")


class GuardianRollback(Exception):
    """Internal control-flow signal: the guardian decided to roll back.
    ``train/sweep.py`` catches it at the chunk loop, restores the
    last-good checkpoint set through :meth:`Guardian.rollback_restore`,
    and replays. Never escapes ``sweep()``."""

    def __init__(self, site: str, incident: str, chunk_pos: int,
                 chunk_index: int):
        super().__init__(
            f"guardian rollback at {site}: {incident} "
            f"(chunk {chunk_index} quarantined)")
        self.site = site
        self.incident = incident
        self.chunk_pos = int(chunk_pos)
        self.chunk_index = int(chunk_index)


def _subensembles(e) -> list:
    """Buckets of an EnsembleGroup in insertion order, or [e] for a plain
    Ensemble (duck-typed twin of train/sweep.py::_ensembles_of, local so
    guardian never imports the sweep module)."""
    sub = getattr(e, "ensembles", None)
    return list(sub.values()) if isinstance(sub, dict) else [e]


def _bucket_items(e) -> list:
    """[(bucket_name, Ensemble)] — for a plain Ensemble the bucket name
    is empty (raw_items in the sweep use the ENTRY name there)."""
    sub = getattr(e, "ensembles", None)
    if isinstance(sub, dict):
        return list(sub.items())
    return [("", e)]


def _reduce_leading(x, op):
    """Reduce any leading (scan-window) axes down to the trailing member
    axis — aux under ``run_steps`` arrives stacked [K, N]."""
    import jax.numpy as jnp

    ops = {"all": jnp.all, "max": jnp.max}
    while x.ndim > 1:
        x = ops[op](x, axis=0)
    return x


def _combine_acc(acc, finite, grad_norm, inputs_finite):
    """One training window folded into the per-bucket device accumulator
    (finite_all [N], inputs_all scalar, grad_norm_max [N]) — an async
    [N]-sized device op per window, never a host sync; the boundary check
    pulls the accumulator once per chunk. Dispatched jitted (one program
    per aux shape): per-op eager dispatch through the axon tunnel costs
    ~ms each, which would tax the hot loop this sentinel must not."""
    import jax.numpy as jnp

    f = _reduce_leading(finite, "all")
    g = _reduce_leading(grad_norm, "max")
    i = (jnp.all(inputs_finite) if inputs_finite is not None
         else jnp.asarray(True))
    if acc is None:
        return f, i, g
    return acc[0] & f, acc[1] & i, jnp.maximum(acc[2], g)


_COMBINE_JIT = None


def _combine(acc, finite, grad_norm, inputs_finite):
    global _COMBINE_JIT
    if _COMBINE_JIT is None:
        import jax

        _COMBINE_JIT = jax.jit(_combine_acc)
    return _COMBINE_JIT(acc, finite, grad_norm, inputs_finite)


class Guardian:
    """Host-side divergence bookkeeping for one sweep run.

    ``ensembles`` is the sweep's ``[(Ensemble|EnsembleGroup, hypers,
    name)]`` list; ``member_names`` the per-entry stream names (for the
    ledger's human-readable ``member`` field). State lives in
    ``<out_dir>/guardian.json`` — written atomically with sorted keys and
    no wall-clock fields, so an interrupted-and-resumed incident leaves a
    ledger byte-identical to an uninterrupted one (the chaos-matrix
    contract).
    """

    def __init__(self, out_dir: str | Path, ensembles: Sequence,
                 member_names: Sequence[Sequence[str]],
                 member_fraction: float = 0.5,
                 rollback_budget: int = 4,
                 fresh: bool = False):
        self.path = Path(out_dir) / LEDGER_NAME
        self.ensembles = list(ensembles)
        self.member_names = [list(n) for n in member_names]
        self.member_fraction = float(member_fraction)
        self.rollback_budget = int(rollback_budget)
        self._acc: dict = {}  # (ens_idx, sub_name) -> device accumulator
        if fresh:
            # a NON-resume run into a reused out_dir starts over (like its
            # checkpoints): inheriting a previous run's quarantines and
            # spent rollback budget would tag healthy members diverged and
            # could halt the new run on its first incident. Resumes
            # (fresh=False) keep the ledger — that persistence is the
            # whole point.
            self._drop_stale_ledger()
            self._state = {"version": 1, "members": {}, "rollbacks": {}}
        else:
            self._state = self._load()

    # -- ledger ---------------------------------------------------------------

    def _drop_stale_ledger(self) -> None:
        import jax

        if jax.process_index() != 0:
            return
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass

    def _load(self) -> dict:
        try:
            raw = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return {"version": 1, "members": {}, "rollbacks": {}}
        if isinstance(raw, dict) and raw.get("version") == 1:
            # a parse-able ledger failing its embedded digest is bit rot
            # or a hand-edit: resuming on fabricated quarantines/rollback
            # counts could halt a healthy run (or trust a diverged
            # member), so the mismatch is typed, never silent. Legacy
            # digest-less ledgers load unverified (fsck flags them STALE).
            if check_payload_digest(raw) == "mismatch":
                raise LedgerCorruptionError(self.path,
                                            "payload digest mismatch")
            raw.pop("payload_sha256", None)
            raw.setdefault("members", {})
            raw.setdefault("rollbacks", {})
            return raw
        return {"version": 1, "members": {}, "rollbacks": {}}

    def _write(self) -> None:
        # atomic + deterministic bytes (sorted keys, no timestamps):
        # rewriting the same incident twice — a resumed rollback — is
        # byte-idempotent, which the chaos matrix compares on. Multi-host:
        # decisions are replicated (replicated flags in, replicated ledger
        # state), so process 0 alone owns the file, like checkpoint swaps.
        import jax

        if jax.process_index() != 0:
            return
        atomic_write_text(
            self.path,
            json.dumps(embed_payload_digest(self._state), indent=2,
                       sort_keys=True))

    @property
    def quarantined_members(self) -> dict[str, dict]:
        return dict(self._state["members"])

    def total_rollbacks(self) -> int:
        return sum(rb["count"] for rb in self._state["rollbacks"].values())

    # -- injection drill ------------------------------------------------------

    def inject_anomaly(self, batch: np.ndarray) -> np.ndarray:
        """Fault site ``sweep.anomaly``: every host batch passes through.
        mode=nan returns a NaN-poisoned copy of the batch (the
        data-corruption drill); mode=error whose message names
        ``member=<i>`` poisons that member's loss-scale buffer instead
        (the hyperparameter-divergence drill: the member's loss and grads
        go NaN while its params stay finite). Any other error-mode
        injection propagates — this site hosts drills, not I/O faults."""
        try:
            return fault_point("sweep.anomaly", batch)
        except InjectedFault as e:
            m = _MEMBER_RE.search(str(e))
            if m is None:
                raise
            self._poison_member(int(m.group(1)))
            return batch

    def _poison_member(self, index: int) -> None:
        """Drill target: member ``index`` of the FIRST bucket of the
        FIRST sweep entry (the drill grammar names one index; multi-entry
        grids drill their first entry by design — documented in §16). An
        out-of-range index is a plan bug and fails loudly: jax's
        ``.at[oob].set`` would silently drop the write and the drill
        would report success while poisoning nothing."""
        import jax.numpy as jnp

        ens = _subensembles(self.ensembles[0][0])[0]
        if not 0 <= int(index) < ens.n_members:
            raise ValueError(
                f"sweep.anomaly drill names member={index} but the first "
                f"bucket has {ens.n_members} member(s)")
        buffers = dict(ens.state.buffers) if ens.state.buffers else {}
        if "l1_alpha" in buffers:
            arr = buffers["l1_alpha"]
            buffers["l1_alpha"] = arr.at[index].set(jnp.nan)
            ens.state = ens.state.replace(buffers=buffers)
        else:
            # signatures without a loss-scale buffer: a NaN lr makes the
            # member's UPDATE non-finite, which the sentinel catches the
            # same way (params still frozen at their last finite values)
            ens.state = ens.state.replace(
                lrs=ens.state.lrs.at[index].set(jnp.nan))

    # -- per-window observation (device-side, async) --------------------------

    def observe(self, ens_idx: int, sub_name: str, aux) -> None:
        """Fold one training window's aux into the (ens, bucket)
        accumulator. No-op when the sentinel is off (aux carries no
        finite field). Dispatches a tiny device combine; never syncs."""
        if getattr(aux, "finite", None) is None:
            return
        key = (int(ens_idx), str(sub_name))
        self._acc[key] = _combine(self._acc.get(key), aux.finite,
                                  aux.grad_norm, aux.inputs_finite)

    # -- the chunk-boundary decision ladder -----------------------------------

    def check_boundary(self, chunk_pos: int, chunk_index: int,
                       store=None) -> None:
        """One host sync per chunk: pull the window accumulators, then run
        the ladder — input incident (rollback), new member quarantines
        (freeze + ledger), fraction escalation (rollback). Raises
        :class:`GuardianRollback` or (ladder exhausted)
        :class:`DivergenceHaltError`. The consensus calls run in a fixed
        order on every host so the collective branches stay aligned."""
        if not self._acc:
            # nothing trained this chunk (quarantined hole) — but a prior
            # fraction breach must still escalate at this site, or a
            # rolled-back run would sail past the very state it rolled
            # back for (the halt that ends the hyperparameter ladder).
            # agree_any runs UNCONDITIONALLY: every host must make the
            # same sequence of consensus calls (the ledger is replicated,
            # but the call pattern must not depend on it)
            if agree_any(self._dead_fraction() >= self.member_fraction,
                         "guardian-fraction"):
                self._escalate(chunk_pos, chunk_index, "hyperparameter",
                               store)
            return
        t0 = obs.monotime()
        import jax

        pulled = {k: jax.device_get(v) for k, v in self._acc.items()}
        self._acc.clear()

        inputs_bad = agree_any(
            any(not bool(np.all(inputs)) for _, inputs, _ in pulled.values()),
            "guardian-input")
        if inputs_bad:
            self._escalate(chunk_pos, chunk_index, "poisoned-data", store)

        # member incidents on sound inputs: freeze + durable ledger
        newly: list[tuple[int, str, int, Optional[float]]] = []
        for (ens_idx, sub), (finite, _inputs, gn) in sorted(pulled.items()):
            finite = np.asarray(finite).reshape(-1)
            gn = np.asarray(gn).reshape(-1)
            for i in np.flatnonzero(~finite):
                key = self._member_key(ens_idx, sub, int(i))
                if key in self._state["members"]:
                    continue  # already quarantined (stays non-finite)
                norm = float(gn[i]) if np.isfinite(gn[i]) else None
                newly.append((ens_idx, sub, int(i), norm))
        if newly:
            self._quarantine_members(newly, chunk_pos, chunk_index)

        if agree_any(self._dead_fraction() >= self.member_fraction,
                     "guardian-fraction"):
            self._escalate(chunk_pos, chunk_index, "hyperparameter", store)
        obs.record_span("guardian.check", obs.monotime() - t0,
                        chunk=chunk_index, pos=chunk_pos,
                        quarantined=len(newly))

    def _member_key(self, ens_idx: int, sub: str, i: int) -> str:
        name = self.ensembles[ens_idx][2]
        return f"{name}/{sub or name}/{i}"

    def dead_indices(self, ens_idx: int, sub_name: str) -> list[int]:
        """Quarantined member indices of one (entry, bucket) — the
        sweep's logging path masks these out of its loss-mean/max streams
        instead of letting their NaN losses poison the aggregates."""
        entry_name = self.ensembles[ens_idx][2]
        bucket = sub_name or entry_name
        return sorted(info["index"]
                      for info in self._state["members"].values()
                      if info["entry"] == entry_name
                      and info["bucket"] == bucket)

    def _quarantine_members(self, newly, chunk_pos: int,
                            chunk_index: int) -> None:
        frozen = []
        for ens_idx, sub, i, norm in newly:
            entry_name = self.ensembles[ens_idx][2]
            names = self.member_names[ens_idx] if ens_idx < len(
                self.member_names) else []
            self._state["members"][self._member_key(ens_idx, sub, i)] = {
                "entry": entry_name, "bucket": sub or entry_name,
                "index": i,
                "member": names[i] if i < len(names) else f"member{i}",
                "reason": "non-finite loss/grads on finite inputs",
                "grad_norm": norm,
                "chunk_pos": chunk_pos, "chunk": chunk_index,
            }
            frozen.append(self._member_key(ens_idx, sub, i))
        # freeze BEFORE the durable write: even a ledger-write failure
        # (read-only dir, full disk) leaves this process protected
        by_bucket: dict[tuple[int, str], list[int]] = {}
        for ens_idx, sub, i, _ in newly:
            by_bucket.setdefault((ens_idx, sub), []).append(i)
        for (ens_idx, sub), idxs in by_bucket.items():
            entry, _, entry_name = self.ensembles[ens_idx]
            for bucket_name, ens in _bucket_items(entry):
                if (bucket_name or entry_name) == (sub or entry_name):
                    ens.freeze_members(idxs)
        self._write()
        obs.counter("guardian.members_quarantined").inc(len(newly))
        obs.emit_event("guardian.incident", incident="member-divergence",
                       members=frozen, chunk=chunk_index, pos=chunk_pos)

    def _dead_fraction(self) -> float:
        total = sum(ens.n_members for e, _, _ in self.ensembles
                    for ens in _subensembles(e))
        return len(self._state["members"]) / max(1, total)

    def _escalate(self, chunk_pos: int, chunk_index: int, incident: str,
                  store) -> None:
        """Record the rollback durably (or halt typed if this site already
        rolled back / the budget is spent), quarantine the chunk through
        the PR-8 ledger, and raise the rollback signal."""
        site = f"chunk[{chunk_pos}]"
        rb = self._state["rollbacks"].get(site)
        exhausted = (rb is not None and rb["count"] >= 1) or \
            self.total_rollbacks() >= self.rollback_budget
        if exhausted:
            self._state["halt"] = {"site": site, "diagnosis": incident,
                                   "chunk": chunk_index}
            self._write()
            obs.counter("guardian.halts").inc()
            obs.emit_event("guardian.halt", site=site, diagnosis=incident,
                           chunk=chunk_index)
            raise DivergenceHaltError(
                site, incident,
                detail=f"chunk {chunk_index}; "
                       f"{len(self._state['members'])} member(s) "
                       f"quarantined, {self.total_rollbacks()} rollback(s)")
        self._state["rollbacks"][site] = {
            "count": (rb["count"] + 1 if rb else 1),
            "incident": incident, "chunk": chunk_index}
        self._write()
        self._quarantine_chunk(store, chunk_index)
        obs.counter("guardian.rollbacks").inc()
        obs.emit_event("guardian.incident", incident=incident,
                       chunk=chunk_index, pos=chunk_pos, rollback=True)
        raise GuardianRollback(site, incident, chunk_pos, chunk_index)

    def _quarantine_chunk(self, store, chunk_index: int) -> None:
        if store is None or not hasattr(store, "_quarantine"):
            return
        try:
            path = store._path(chunk_index)
        except ChunkCorruptionError:
            return  # already a hole
        store._quarantine(ChunkCorruptionError(
            chunk_index, path,
            "guardian: non-finite activations reached the training step"))
        obs.counter("guardian.chunks_quarantined").inc()

    # -- rollback + resume plumbing -------------------------------------------

    def rollback_restore(self, restore_fn: Callable[[], tuple]) -> tuple:
        """The restore half of a rollback: the crash barrier sits exactly
        between the durable ledger writes (_escalate, already done) and
        the checkpoint restore — the chaos matrix kills here and proves a
        restarted run resumes bitwise. ``restore_fn`` is the sweep's
        closure over ``resume_sweep_state`` (or re-init for a pre-first-
        checkpoint incident); returns its (chunks_done, rng_state)."""
        crash_barrier("guardian.rollback")
        t0 = obs.monotime()
        done, rng_state = restore_fn()
        self.refreeze()
        obs.record_span("guardian.rollback", obs.monotime() - t0,
                        chunks_done=int(done))
        return done, rng_state

    def refreeze(self) -> None:
        """Re-apply every ledgered member quarantine to the live ensembles
        — a restored (or re-initialized) checkpoint predates the freeze,
        and a quarantined member must stay dead across rollbacks and
        resumes."""
        for info in self._state["members"].values():
            for e, _, name in self.ensembles:
                if name != info["entry"]:
                    continue
                for bucket_name, ens in _bucket_items(e):
                    if (bucket_name or name) == info["bucket"]:
                        ens.freeze_members([info["index"]])

    # -- artifact hygiene -----------------------------------------------------

    def diverged_flat(self, entry_name: str) -> dict[int, dict]:
        """Flat member index → ledger info for one entry, in the same
        bucket-insertion-order flattening ``_flat_dicts`` uses — the map
        artifact tagging keys on."""
        out: dict[int, dict] = {}
        for e, _, name in self.ensembles:
            if name != entry_name:
                continue
            offset = 0
            for bucket_name, ens in _bucket_items(e):
                bucket = bucket_name or name
                for info in self._state["members"].values():
                    if info["entry"] == name and info["bucket"] == bucket:
                        out[offset + info["index"]] = info
                offset += ens.n_members
        return out

    def tag_hypers(self, entry_name: str,
                   tagged: Sequence[tuple]) -> list[tuple]:
        """[(dict, hyper)] → same list with quarantined members' hypers
        carrying ``diverged=True`` (+ the ledger reason), so every
        artifact save and the sweep's return value agree on which members
        are poisoned."""
        diverged = self.diverged_flat(entry_name)
        out = []
        for i, (ld, hyper) in enumerate(tagged):
            if i in diverged:
                hyper = {**hyper, "diverged": True,
                         "diverged_reason": diverged[i]["reason"]}
            out.append((ld, hyper))
        return out
