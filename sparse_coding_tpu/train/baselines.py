"""Baseline dictionary suite runner.

Re-design of the reference's `sweep_baselines.py:27-174`: per (layer,
layer_loc) chunk folder, fit BatchedPCA (on-device scan) and ICA (host
sklearn, as the reference does), export top-k dicts matched to a trained
SAE's measured sparsity, and save RandomDict / IdentityReLU nulls. The
reference parallelizes layers with an mp.Pool over GPUs (:171); here PCA is
a single jitted scan per layer and the host-bound ICA dominates, so layers
run serially by default (the ICA fit is the reference's own ~15 min/GB
bottleneck, ica.py:43).

Artifacts: one `learned_dicts.pkl`-style file per baseline in
`{output_folder}/l{layer}_{layer_loc}/` with the same skip-if-exists
idempotence (:56,75,99,106).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.data.shard_store import first_sound_chunk, open_store
from sparse_coding_tpu.metrics.core import mean_nonzero_activations
from sparse_coding_tpu.models import IdentityReLU, RandomDict
from sparse_coding_tpu.models.ica import ICAEncoder
from sparse_coding_tpu.models.pca import BatchedPCA, fit_pca
from sparse_coding_tpu.utils.artifacts import load_learned_dicts, save_learned_dicts


def measure_sae_sparsity(learned_dict, chunk: np.ndarray,
                         batch_size: int = 8192) -> float:
    """Total firing frequency of a trained SAE — the sparsity budget given to
    the top-k baseline exports (reference: sweep_baselines.py:48-54)."""
    n = min(chunk.shape[0], 65536)
    acts = jnp.asarray(chunk[:n])
    return float(jnp.sum(mean_nonzero_activations(learned_dict, acts)))


def run_layer_baselines(
    chunk_folder: str | Path,
    output_folder: str | Path,
    sparsity: int = 128,
    reference_dict=None,
    max_ica_samples: int = 200_000,
    remake: bool = False,
    seed: int = 0,
) -> dict[str, object]:
    """Fit/export all baselines for one chunk folder. Returns
    {name: LearnedDict}."""
    out = Path(output_folder)
    out.mkdir(parents=True, exist_ok=True)
    store = open_store(chunk_folder)
    chunk = store.load_chunk(first_sound_chunk(store))
    d = store.activation_dim

    if reference_dict is not None:
        sparsity = max(1, int(round(measure_sae_sparsity(reference_dict, chunk))))

    results: dict[str, object] = {}

    def artifact(name):
        return out / f"{name}.pkl"

    def save(name, ld):
        save_learned_dicts([(ld, {"baseline": name, "sparsity": sparsity})],
                           artifact(name))
        results[name] = ld

    def cached(name) -> bool:
        """Per-artifact skip, so partial crashes refit only what's missing and
        re-runs return the FULL results dict."""
        if artifact(name).exists() and not remake:
            results[name] = load_learned_dicts(artifact(name))[0][0]
            return True
        return False

    pca_names = ("pca", "pca_topk", "pca_rotation")
    if not all(cached(n) for n in pca_names):
        pca = BatchedPCA(d)
        pca.state = fit_pca(jnp.asarray(chunk), batch_size=512)
        save("pca", pca.to_learned_dict(sparsity=d))  # full-rank; topk below
        save("pca_topk", pca.to_topk_dict(sparsity))
        save("pca_rotation", pca.to_rotation_dict())

    ica_names = ("ica", "ica_topk")
    if not all(cached(n) for n in ica_names):
        ica = ICAEncoder.train(jnp.asarray(chunk[:max_ica_samples]))
        save("ica", ica)
        save("ica_topk", ica.to_topk_dict(sparsity))

    if not cached("random"):
        save("random", RandomDict.create(jax.random.PRNGKey(seed), d))
    if not cached("identity_relu"):
        save("identity_relu", IdentityReLU.create(d))

    return results


def run_all_baselines(
    chunks_root: str | Path,
    output_root: str | Path,
    layers: Sequence[int],
    layer_locs: Sequence[str] = ("residual",),
    sparsity: int = 128,
    reference_dicts: Optional[dict] = None,
    **kwargs,
) -> None:
    """Reference: sweep_baselines.py main loop over layers × layer_locs."""
    for layer in layers:
        for loc in layer_locs:
            name = f"l{layer}_{loc}"
            ref = (reference_dicts or {}).get((layer, loc))
            run_layer_baselines(Path(chunks_root) / f"{loc}.{layer}",
                                Path(output_root) / name,
                                sparsity=sparsity, reference_dict=ref, **kwargs)
