"""Chunk dispatch: drive many ensembles through one in-RAM chunk.

API-parity layer for the reference's scheduler (reference:
cluster_runs.py:100-157 `dispatch_job_on_chunk`, :50-98
`dispatch_lite`/`collect_lite`). The reference pins the chunk into POSIX
shared memory and forks one OS process per ensemble/GPU; on TPU the same
concurrency comes from XLA's async dispatch — each ensemble's jitted step is
enqueued without blocking the host, so interleaving step calls pipelines all
ensembles on the device with zero processes. These helpers keep the
reference's call shape (incl. the non-blocking lite variant) for users
porting scripts.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import numpy as np

from sparse_coding_tpu.data.chunk_store import device_prefetch, shuffled_batches
from sparse_coding_tpu.ensemble import Ensemble, EnsembleGroup


def dispatch_job_on_chunk(ensembles: Sequence[Ensemble | EnsembleGroup],
                          chunk: np.ndarray, batch_size: int = 1024,
                          seed: int = 0, sharding=None,
                          progress: Optional[Callable[[int, int], None]] = None
                          ) -> dict[str, Any]:
    """Train every ensemble over one shuffled pass of the chunk; blocks until
    all device work is done (the reference's join, cluster_runs.py:145-157).
    Returns the last aux per ensemble index."""
    rng = np.random.default_rng(seed)
    total = (chunk.shape[0] // batch_size)
    last_aux: dict[str, Any] = {}
    for i, batch in enumerate(device_prefetch(
            shuffled_batches(chunk, batch_size, rng), sharding)):
        for j, ens in enumerate(ensembles):
            last_aux[str(j)] = ens.step_batch(batch)  # async dispatch
        if progress is not None:
            progress(i + 1, total)
    # barrier: materialize the final losses (join-equivalent)
    return LiteJob(ensembles, last_aux).collect()


class LiteJob:
    """Non-blocking handle (reference: dispatch_lite/collect_lite,
    cluster_runs.py:50-98): work is enqueued asynchronously; `collect()` is
    the barrier."""

    def __init__(self, ensembles, last_aux):
        self.ensembles = ensembles
        self.last_aux = last_aux

    def collect(self):
        for aux in self.last_aux.values():
            if isinstance(aux, dict):
                for a in aux.values():
                    jax.block_until_ready(a.losses["loss"])
            else:
                jax.block_until_ready(aux.losses["loss"])
        return self.last_aux


def dispatch_lite(ensembles: Sequence[Ensemble | EnsembleGroup],
                  chunk: np.ndarray, batch_size: int = 1024,
                  seed: int = 0, sharding=None) -> LiteJob:
    """Enqueue a full chunk pass without waiting (device work proceeds while
    the host e.g. loads the next chunk)."""
    rng = np.random.default_rng(seed)
    last_aux: dict[str, Any] = {}
    for batch in device_prefetch(shuffled_batches(chunk, batch_size, rng), sharding):
        for j, ens in enumerate(ensembles):
            last_aux[str(j)] = ens.step_batch(batch)
    return LiteJob(ensembles, last_aux)


def collect_lite(job: LiteJob):
    return job.collect()
