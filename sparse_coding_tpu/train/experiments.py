"""Experiment registry: functions building ensembles for `sweep()`.

Replaces the reference's 1.3k-line registry (reference:
big_sweep_experiments.py) with parameterized builders. The reference
hand-assigns GPUs per ensemble (e.g. :51,68 `devices.pop()`); here device
placement is the mesh's job, so an "experiment" is just the grid definition.

Each builder returns `[(Ensemble|EnsembleGroup, member_hyperparams, name)]` —
the 4-tuple contract of the reference (big_sweep_experiments.py:208-228)
minus the device bookkeeping.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from sparse_coding_tpu.config import EnsembleArgs
from sparse_coding_tpu.ensemble import Ensemble, EnsembleGroup
from sparse_coding_tpu.models.sae import (
    FunctionalMaskedTiedSAE,
    FunctionalSAE,
    FunctionalTiedSAE,
)
from sparse_coding_tpu.models.topk import TopKEncoder

DEFAULT_L1_RANGE = list(np.logspace(-4, -2, 16))  # big_sweep_experiments.py:295


def _sentinel(cfg: EnsembleArgs) -> bool:
    """cfg.sentinel with a default for ad-hoc config objects (the in-graph
    anomaly sentinel is on unless explicitly disabled — config.py)."""
    return bool(getattr(cfg, "sentinel", True))


def _engine_kwargs(cfg: EnsembleArgs) -> dict:
    """Fused-kernel engine knobs from the sweep config (config.py, ISSUE
    11) — one home so every builder passes the same set and the fault
    matrix can pin a sweep to e.g. the tiled path with fused_interpret
    on CPU. Defaults reproduce the pre-knob behavior (auto admission)."""
    use_fused = {"on": True, "off": False}.get(
        str(getattr(cfg, "use_fused", "auto")), "auto")
    return dict(
        sentinel=_sentinel(cfg),
        use_fused=use_fused,
        fused_path=getattr(cfg, "fused_path", None),
        fused_batch_tile=getattr(cfg, "fused_batch_tile", None),
        fused_feat_tile=getattr(cfg, "fused_feat_tile", None),
        fused_interpret=bool(getattr(cfg, "fused_interpret", False)))


def _activation_dim(cfg: EnsembleArgs) -> int:
    from sparse_coding_tpu.data.shard_store import open_store

    return open_store(cfg.dataset_folder).activation_dim


def dense_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                              l1_range: Optional[Sequence[float]] = None,
                              activation_dim: Optional[int] = None):
    """16-point l1 sweep at one dict ratio, tied or untied
    (reference: big_sweep_experiments.py:294-340)."""
    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    d = activation_dim or _activation_dim(cfg)
    n_dict = int(d * cfg.learned_dict_ratio)
    sig = FunctionalTiedSAE if cfg.tied_ae else FunctionalSAE
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(l1s))
    members = [sig.init(k, d, n_dict, l1_alpha=float(l1))
               for k, l1 in zip(keys, l1s)]
    ens = Ensemble(members, sig, lr=cfg.lr, adam_eps=cfg.adam_epsilon,
                   mesh=mesh, **_engine_kwargs(cfg))
    hypers = [{"l1_alpha": float(l1), "dict_size": n_dict, "tied": cfg.tied_ae}
              for l1 in l1s]
    return [(ens, hypers, "dense_l1_range")]


def tied_vs_not_experiment(cfg: EnsembleArgs, mesh=None,
                           l1_range: Optional[Sequence[float]] = None,
                           activation_dim: Optional[int] = None):
    """Tied and untied ensembles over the same l1 grid
    (reference: big_sweep_experiments.py:42-229)."""
    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    d = activation_dim or _activation_dim(cfg)
    n_dict = int(d * cfg.learned_dict_ratio)
    out = []
    for tied, sig, name in [(True, FunctionalTiedSAE, "tied"),
                            (False, FunctionalSAE, "untied")]:
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed + tied), len(l1s))
        members = [sig.init(k, d, n_dict, l1_alpha=float(l1))
                   for k, l1 in zip(keys, l1s)]
        ens = Ensemble(members, sig, lr=cfg.lr, adam_eps=cfg.adam_epsilon,
                   mesh=mesh, **_engine_kwargs(cfg))
        hypers = [{"l1_alpha": float(l1), "dict_size": n_dict, "tied": tied}
                  for l1 in l1s]
        out.append((ens, hypers, name))
    return out


def topk_experiment(cfg: EnsembleArgs, mesh=None,
                    ks: Sequence[int] = (4, 8, 16, 32, 64, 128),
                    activation_dim: Optional[int] = None):
    """TopK sweep across k — ragged shapes bucketed per k
    (reference: big_sweep_experiments.py:232-292, which falls back to
    no_stacking)."""
    d = activation_dim or _activation_dim(cfg)
    n_dict = int(d * cfg.learned_dict_ratio)
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(ks))
    members = [TopKEncoder.init(k_rng, d, n_dict, k=int(k))
               for k_rng, k in zip(keys, ks)]
    group = EnsembleGroup.build(TopKEncoder, members, lr=cfg.lr, mesh=mesh,
                                sentinel=_sentinel(cfg))
    # hypers must follow bucket-flattening order (group.to_learned_dicts
    # iterates buckets in insertion order), not sorted(ks)
    hypers = [{"k": dict(ens.state.static_buffers)["k"], "dict_size": n_dict}
              for ens in group.ensembles.values()
              for _ in range(ens.n_members)]
    return [(group, hypers, "topk")]


def dict_ratio_experiment(cfg: EnsembleArgs, mesh=None,
                          ratios: Sequence[float] = (0.5, 1, 2, 4, 8, 16, 32),
                          l1_alpha: float = 8.577e-4,
                          activation_dim: Optional[int] = None):
    """Mixed dict sizes in ONE vmapped ensemble via masking
    (reference: big_sweep_experiments.py:543-618 with FunctionalMaskedTiedSAE;
    l1 default is the reference's canonical operating point,
    interpret.py:791)."""
    d = activation_dim or _activation_dim(cfg)
    sizes = [int(d * r) for r in ratios]
    n_stack = max(sizes)
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(sizes))
    members = [FunctionalMaskedTiedSAE.init(k, d, n, n_stack, l1_alpha=l1_alpha)
               for k, n in zip(keys, sizes)]
    ens = Ensemble(members, FunctionalMaskedTiedSAE, lr=cfg.lr,
                   adam_eps=cfg.adam_epsilon, mesh=mesh,
                   **_engine_kwargs(cfg))
    hypers = [{"l1_alpha": l1_alpha, "dict_size": n, "dict_ratio": r}
              for n, r in zip(sizes, ratios)]
    return [(ens, hypers, "dict_ratio")]


def zero_l1_baseline_experiment(cfg: EnsembleArgs, mesh=None,
                                activation_dim: Optional[int] = None):
    """l1=0 pure-reconstruction baseline member next to a small l1 grid
    (reference: big_sweep_experiments.py:497-541)."""
    l1s = [0.0, 1e-4, 1e-3]
    return dense_l1_range_experiment(cfg, mesh, l1_range=l1s,
                                     activation_dim=activation_dim)


def long_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                             activation_dim: Optional[int] = None):
    """32-point l1 grid (reference: big_sweep_experiments.py:341-433
    residual_denoising/long variants use wider grids)."""
    l1s = list(np.logspace(-5, -2, 32))
    return dense_l1_range_experiment(cfg, mesh, l1_range=l1s,
                                     activation_dim=activation_dim)


def residual_denoising_experiment(cfg: EnsembleArgs, mesh=None,
                                  l1_range: Optional[Sequence[float]] = None,
                                  n_hidden_layers: int = 2,
                                  activation_dim: Optional[int] = None):
    """LISTA-denoising encoder sweep
    (reference: big_sweep_experiments.py:341-433)."""
    from sparse_coding_tpu.models.lista import FunctionalLISTADenoisingSAE

    l1s = list(l1_range if l1_range is not None else np.logspace(-4, -2, 8))
    d = activation_dim or _activation_dim(cfg)
    n_dict = int(d * cfg.learned_dict_ratio)
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(l1s))
    members = [FunctionalLISTADenoisingSAE.init(
        k, d, n_dict, l1_alpha=float(l1), n_hidden_layers=n_hidden_layers)
        for k, l1 in zip(keys, l1s)]
    group = EnsembleGroup.build(FunctionalLISTADenoisingSAE, members,
                                lr=cfg.lr, mesh=mesh,
                                sentinel=_sentinel(cfg))
    hypers = [{"l1_alpha": float(l1), "dict_size": n_dict,
               "n_hidden_layers": n_hidden_layers} for l1 in l1s]
    return [(group, hypers, "residual_denoising")]


def centered_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                                 l1_range: Optional[Sequence[float]] = None,
                                 activation_dim: Optional[int] = None,
                                 whiten: bool = True,
                                 centering=None):
    """Centered/whitened TiedSAE sweep — the reference's mlp-center workflow
    (big_sweep.py:359-364 computes the transform from the dataset;
    plotting/fvu_sparsity_plot_mlp_center.py consumes it): a PCA whitening
    transform fitted on the dataset's first chunk becomes fixed
    rotation/translation/scaling buffers of every member, so the SAE trains
    in whitened space. Pass `centering=(mean, rot, scale)` to skip the PCA
    fit (tests, precomputed transforms); whiten=False keeps the rotation but
    unit scaling (pure centering)."""
    import jax.numpy as jnp

    from sparse_coding_tpu.models.pca import BatchedPCA

    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    if getattr(cfg, "center_activations", False):
        raise ValueError(
            "centered_l1_range centers via member buffers; combining it with "
            "cfg.center_activations would double-shift the data relative to "
            "the stored transform")
    if centering is None:
        from sparse_coding_tpu.data.shard_store import (
            first_sound_chunk,
            open_store,
        )

        store = open_store(cfg.dataset_folder)
        acts = store.load_chunk(first_sound_chunk(store))
        pca = BatchedPCA(acts.shape[-1])
        pca.train_batch(acts)
        mean, rot, inv_std = pca.get_centering_transform()
        # get_centering_transform returns eigvecs as columns; center() applies
        # rot as rows (x @ rot.T), so transpose into row-vector form
        rot = rot.T
    else:
        mean, rot, inv_std = centering
    d = activation_dim or int(mean.shape[-1])
    scale = inv_std if whiten else jnp.ones_like(inv_std)
    n_dict = int(d * cfg.learned_dict_ratio)
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(l1s))
    members = [FunctionalTiedSAE.init(k, d, n_dict, l1_alpha=float(l1),
                                      rotation=rot, translation=mean,
                                      scaling=scale)
               for k, l1 in zip(keys, l1s)]
    ens = Ensemble(members, FunctionalTiedSAE, lr=cfg.lr,
                   adam_eps=cfg.adam_epsilon, mesh=mesh,
                   **_engine_kwargs(cfg))
    hypers = [{"l1_alpha": float(l1), "dict_size": n_dict, "tied": True,
               "centered": True, "whitened": whiten} for l1 in l1s]
    return [(ens, hypers, "centered_l1_range")]


def _simple_grid_experiment(sig, name, cfg: EnsembleArgs, mesh, l1s, d,
                            init_kwargs=None, hyper_key: str = "l1_alpha"):
    """Shared shape of the one-signature l1-grid builders below."""
    n_dict = int(d * cfg.learned_dict_ratio)
    keys = jax.random.split(jax.random.PRNGKey(cfg.seed), len(l1s))
    members = [sig.init(k, d, n_dict, float(l1), **(init_kwargs or {}))
               for k, l1 in zip(keys, l1s)]
    group = EnsembleGroup.build(sig, members, lr=cfg.lr, mesh=mesh,
                                adam_eps=cfg.adam_epsilon,
                                sentinel=_sentinel(cfg))
    hypers = [{hyper_key: float(l1), "dict_size": n_dict} for l1 in l1s]
    return [(group, hypers, name)]


def reverse_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                                l1_range: Optional[Sequence[float]] = None,
                                activation_dim: Optional[int] = None):
    """ReverseSAE (bias-subtracting decode) sweep
    (reference: big_sweep_experiments.py reverse-SAE runs via
    sae_ensemble.py:447-503)."""
    from sparse_coding_tpu.models.sae import FunctionalReverseSAE

    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    d = activation_dim or _activation_dim(cfg)
    return _simple_grid_experiment(FunctionalReverseSAE, "reverse_l1_range",
                                   cfg, mesh, l1s, d)


def positive_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                                 l1_range: Optional[Sequence[float]] = None,
                                 activation_dim: Optional[int] = None):
    """Nonnegative-dictionary shifted-input TiedSAE sweep
    (reference: mlp_tests.py:80-115 positive SAE workflow)."""
    from sparse_coding_tpu.models.positive import FunctionalPositiveTiedSAE

    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    d = activation_dim or _activation_dim(cfg)
    return _simple_grid_experiment(FunctionalPositiveTiedSAE,
                                   "positive_l1_range", cfg, mesh, l1s, d)


def semilinear_l1_range_experiment(cfg: EnsembleArgs, mesh=None,
                                   l1_range: Optional[Sequence[float]] = None,
                                   activation_dim: Optional[int] = None):
    """Two-layer-encoder SemiLinearSAE sweep
    (reference: semilinear autoencoder runs, big_sweep_experiments.py)."""
    from sparse_coding_tpu.models.semilinear import SemiLinearSAE

    l1s = list(l1_range if l1_range is not None else DEFAULT_L1_RANGE)
    d = activation_dim or _activation_dim(cfg)
    return _simple_grid_experiment(SemiLinearSAE, "semilinear_l1_range",
                                   cfg, mesh, l1s, d)


def rica_experiment(cfg: EnsembleArgs, mesh=None,
                    sparsity_range: Optional[Sequence[float]] = None,
                    activation_dim: Optional[int] = None):
    """RICA (reconstruction ICA) sweep over the sparsity coefficient
    (reference: untied_ica_topk et al., big_sweep_experiments.py RICA runs)."""
    from sparse_coding_tpu.models.rica import RICA

    coefs = list(sparsity_range if sparsity_range is not None
                 else np.logspace(-4, -2, 8))
    d = activation_dim or _activation_dim(cfg)
    return _simple_grid_experiment(RICA, "rica", cfg, mesh, coefs, d,
                                   hyper_key="sparsity_coef")


EXPERIMENTS = {
    "dense_l1_range": dense_l1_range_experiment,
    "tied_vs_not": tied_vs_not_experiment,
    "topk": topk_experiment,
    "dict_ratio": dict_ratio_experiment,
    "zero_l1_baseline": zero_l1_baseline_experiment,
    "long_l1_range": long_l1_range_experiment,
    "residual_denoising": residual_denoising_experiment,
    "centered_l1_range": centered_l1_range_experiment,
    "reverse_l1_range": reverse_l1_range_experiment,
    "positive_l1_range": positive_l1_range_experiment,
    "semilinear_l1_range": semilinear_l1_range_experiment,
    "rica": rica_experiment,
}


def get_experiment(name: str):
    return EXPERIMENTS[name]


# ---------------------------------------------------------------------------
# Concrete launchers: named configurations binding the reference's canonical
# scales (reference: big_sweep_experiments.py:435-1280 run_* functions).
# Each returns (experiment_fn, EnsembleArgs) ready for train.sweep.sweep().
# ---------------------------------------------------------------------------

def _cfg(model_name: str, layer: int, layer_loc: str, ratio: float,
         tied: bool = True, n_chunks: int = 10, **overrides) -> EnsembleArgs:
    base = dict(
        output_folder=f"output_{model_name.split('/')[-1]}_{layer_loc}_l{layer}_r{ratio:g}",
        dataset_folder=f"activation_data/{layer_loc}.{layer}",
        layer=layer, layer_loc=layer_loc, learned_dict_ratio=ratio,
        tied_ae=tied, batch_size=1024, lr=1e-3, n_chunks=n_chunks)
    base.update(overrides)
    return EnsembleArgs(**base)


def run_pythia70m_resid(layer: int = 2, ratio: float = 4.0):
    """Pythia-70M residual sweep — the paper's canonical config
    (reference: big_sweep_experiments.py:620-676)."""
    return dense_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                           layer, "residual", ratio)


def run_pythia70m_mlp(layer: int = 2, ratio: float = 4.0):
    return dense_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                           layer, "mlp", ratio)


def run_pythia410m_mlpout_topk(layer: int = 12):
    """Pythia-410M MLP-out TopK sweep (BASELINE.json config #3)."""
    return topk_experiment, _cfg("EleutherAI/pythia-410m-deduped", layer,
                                 "mlpout", 4.0)


def run_pythia14b_resid(layer: int = 6, ratio: float = 6.0):
    """Largest reference sweep: Pythia-1.4B residual
    (reference: big_sweep_experiments.py:851-907)."""
    return dense_l1_range_experiment, _cfg("EleutherAI/pythia-1.4b-deduped",
                                           layer, "residual", ratio,
                                           n_chunks=30, n_repetitions=10)


def run_gpt2sm_resid(layer: int = 0, ratio: float = 32.0):
    """GPT-2-small residual sweeps at ratios 32/64/96
    (reference: big_sweep_experiments.py:1239-1269)."""
    return dense_l1_range_experiment, _cfg("gpt2", layer, "residual", ratio)


def run_dict_ratio_series(layer: int = 2):
    """Masked mixed-size series 0.5-32x (reference:
    big_sweep_experiments.py:543-618 + standard_metrics.py:745 ratios)."""
    return dict_ratio_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                       layer, "residual", 32.0)


def run_pythia70m_mlp_center(layer: int = 2, ratio: float = 4.0):
    """Whitened-centered MLP sweep — the reference's _mlp_center workflow
    (big_sweep.py:359-364 + plotting/fvu_sparsity_plot_mlp_center.py)."""
    return centered_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                              layer, "mlp", ratio)


def run_pythia70m_resid_denoising(layer: int = 2):
    """LISTA residual-denoising sweep at the canonical location
    (reference: big_sweep_experiments.py:341-433 residual_denoising runs)."""
    return residual_denoising_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                               layer, "residual", 4.0)


def run_pythia70m_zero_l1(layer: int = 2):
    """Pure-reconstruction control next to small l1s
    (reference: big_sweep_experiments.py:497-541)."""
    return zero_l1_baseline_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                             layer, "residual", 4.0)


def run_pythia70m_long_l1(layer: int = 2):
    """32-point l1 grid (reference's wider-grid sweeps)."""
    return long_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                          layer, "residual", 4.0)


def run_pythia70m_reverse(layer: int = 2):
    """ReverseSAE family at the canonical location
    (reference: sae_ensemble.py:447-503 consumers)."""
    return reverse_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                             layer, "residual", 4.0)


def run_pythia70m_positive_mlp(layer: int = 2):
    """Positive (nonneg-dict, shifted-input) SAEs on MLP activations
    (reference: mlp_tests.py:80-115)."""
    return positive_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                              layer, "mlp", 4.0)


def run_pythia70m_semilinear(layer: int = 2):
    return semilinear_l1_range_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                                layer, "residual", 4.0)


def run_pythia70m_rica(layer: int = 2):
    """RICA family (reference: big_sweep_experiments.py RICA/ICA-topk runs)."""
    return rica_experiment, _cfg("EleutherAI/pythia-70m-deduped",
                                 layer, "residual", 4.0)


LAUNCHERS = {
    "pythia70m_resid": run_pythia70m_resid,
    "pythia70m_mlp": run_pythia70m_mlp,
    "pythia70m_mlp_center": run_pythia70m_mlp_center,
    "pythia70m_resid_denoising": run_pythia70m_resid_denoising,
    "pythia70m_zero_l1": run_pythia70m_zero_l1,
    "pythia70m_long_l1": run_pythia70m_long_l1,
    "pythia70m_reverse": run_pythia70m_reverse,
    "pythia70m_positive_mlp": run_pythia70m_positive_mlp,
    "pythia70m_semilinear": run_pythia70m_semilinear,
    "pythia70m_rica": run_pythia70m_rica,
    "pythia410m_mlpout_topk": run_pythia410m_mlpout_topk,
    "pythia14b_resid": run_pythia14b_resid,
    "gpt2sm_resid": run_gpt2sm_resid,
    "dict_ratio_series": run_dict_ratio_series,
}
