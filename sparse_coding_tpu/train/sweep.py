"""The full sweep driver — the heart of the framework.

TPU-native re-design of the reference's `sweep()` pipeline
(reference: big_sweep.py:298-386) and its process-per-GPU chunk scheduler
(cluster_runs.py:100-157). The reference pins a 2 GB chunk into POSIX shared
memory and forks one OS process per ensemble; here every ensemble's step is
an async-dispatched jitted computation on a shared device mesh, so "dispatch
a chunk to all ensembles" is just interleaved step calls — XLA pipelines
them, and the host stays a thin orchestrator.

Flow (mirroring big_sweep.py:298-386):
  1. seed + logger init
  2. dataset: existing ChunkStore, or synthetic generator materialized to disk
  3. ensemble_init_fn(cfg, mesh) -> [(Ensemble|EnsembleGroup, member_hyperparams, name)]
  4. chunk order shuffled ×n_repetitions; optional first-chunk-mean centering
  5. per chunk: stream shuffled batches through every ensemble
  6. save learned_dicts + config at power-of-two chunk counts and at the end
  7. full-state checkpoint each chunk for exact resume (beyond the reference)
"""

from __future__ import annotations

import json
import logging
import shutil
from pathlib import Path
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu import obs
from sparse_coding_tpu.config import EnsembleArgs, SyntheticEnsembleArgs
from sparse_coding_tpu.data.chunk_store import (
    ChunkStore,
    ChunkWriter,
    window_stacks,
)
from sparse_coding_tpu.data.ingest import chunk_stream, device_batches
from sparse_coding_tpu.data.shard_store import first_sound_chunk, open_store
from sparse_coding_tpu.ensemble import Ensemble, EnsembleGroup
from sparse_coding_tpu.metrics.core import (
    fraction_variance_unexplained,
    mean_l0,
    mean_nonzero_activations,
    mmcs_from_list,
)
from sparse_coding_tpu.parallel import agree_any
from sparse_coding_tpu.parallel.mesh import batch_sharding, make_mesh
from sparse_coding_tpu.resilience import lease
from sparse_coding_tpu.resilience.atomic import atomic_save_npy, atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site
from sparse_coding_tpu.resilience.errors import CheckpointCorruptionError
from sparse_coding_tpu.resilience.preempt import PreemptionGuard, SweepPreempted
from sparse_coding_tpu.train.guardian import Guardian, GuardianRollback
from sparse_coding_tpu.utils.artifacts import save_learned_dicts
from sparse_coding_tpu.utils.checkpoint import restore_ensemble, save_ensemble
from sparse_coding_tpu.utils.orbax_ckpt import checkpoint_path
from sparse_coding_tpu.utils.logging import MetricsLogger
from sparse_coding_tpu.utils.profiling import StepTimer

logger_mod = logging.getLogger(__name__)

register_crash_site("sweep.chunk",
                    "end of one sweep chunk's train+checkpoint+artifact "
                    "block (train/sweep.py)")
register_crash_site("ckpt.swap",
                    "mid checkpoint-set swap: old set renamed to "
                    "ckpt_prev/, new set not yet renamed in "
                    "(_swap_in_checkpoint_set)")

EnsembleLike = Union[Ensemble, EnsembleGroup]
# ensemble_init_fn(cfg, mesh) -> list of (ensemble, per-member hyperparams, name)
EnsembleInitFn = Callable[..., list[tuple[EnsembleLike, list[dict], str]]]


def init_synthetic_dataset(cfg: SyntheticEnsembleArgs) -> ChunkStore:
    """Materialize a synthetic dataset to chunk files
    (reference: big_sweep.py:269-295 init_synthetic_dataset)."""
    from sparse_coding_tpu.data.synthetic import RandomDatasetGenerator

    folder = Path(cfg.dataset_folder)
    if (folder / "meta.json").exists():
        return ChunkStore(folder)
    gen = RandomDatasetGenerator.create(
        jax.random.PRNGKey(cfg.seed), cfg.activation_dim,
        cfg.n_ground_truth_features, cfg.feature_num_nonzero,
        cfg.feature_prob_decay, correlated=cfg.correlated_components)
    writer = ChunkWriter(folder, cfg.activation_dim,
                         chunk_size_gb=max(cfg.dataset_size * cfg.activation_dim
                                           * 2 / cfg.n_chunks / 2**30, 1e-6),
                         dtype="float16")
    key = jax.random.PRNGKey(cfg.seed + 1)
    remaining = cfg.dataset_size
    while remaining > 0:
        key, sub = jax.random.split(key)
        n = min(remaining, 65536)
        writer.add(jax.device_get(gen.batch(sub, n)))
        remaining -= n
    writer.finalize({"synthetic": True})
    atomic_save_npy(folder / "ground_truth_feats.npy",
                    jax.device_get(gen.feats))
    return ChunkStore(folder)


def _member_names(hypers: Sequence[dict], n_members: int) -> list[str]:
    """Stable, UNIQUE per-member stream names from hyperparams
    (reference: make_hyperparam_name, big_sweep.py:75-83). Colliding names
    (equal scalars, or floats rounding to the same %.2e) get an index suffix
    so log streams never silently merge."""
    from sparse_coding_tpu.utils.logging import make_hyperparam_name

    names = []
    for i in range(n_members):
        name = f"member{i}"
        if i < len(hypers):
            scalars = {k: v for k, v in hypers[i].items()
                       if isinstance(v, (int, float)) and not isinstance(v, bool)}
            if scalars:
                name = make_hyperparam_name(scalars)
        names.append(name)
    seen: dict[str, int] = {}
    unique = []
    for i, name in enumerate(names):
        if names.count(name) > 1:
            name = f"{name}_{i}"
        unique.append(name)
    return unique


def _ensembles_of(e: EnsembleLike) -> list[Ensemble]:
    return list(e.ensembles.values()) if isinstance(e, EnsembleGroup) else [e]


def _agree_preempted(local_flag: bool) -> bool:
    """Cross-host consensus on the preemption flag (identity single-host).
    SIGTERM may reach only ONE process of a multi-host sweep; the
    checkpoint branch below contains collective barriers, so every host
    must take it (or not) together — any host preempted preempts all.
    The rule itself now lives in ``parallel.agree_any`` (shared with the
    guardian's anomaly/rollback decisions, train/guardian.py)."""
    return agree_any(local_flag, "sweep-preempt")


def _sync_hosts(tag: str) -> None:
    """Cross-host barrier (no-op single-host): checkpoint-set directory
    mutations are process-0-only, so every host must agree the set is
    durable before the swap and see the swap before reusing the staging
    name."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_processes(tag)


def _swap_in_checkpoint_set(out_dir: Path, staging: Path) -> None:
    """Rename-swap a COMPLETE staged checkpoint set into ckpt/. The old set
    is RETAINED as ckpt_prev/: it covers both a crash at any instant during
    the swap (at least one complete consistent set always exists, ADVICE r1
    #5) and post-hoc corruption of ckpt/ — resume_sweep_state falls back to
    it when the newest set fails its digest manifest (docs/ARCHITECTURE.md
    §10), at the cost of one extra set on disk. Multi-host callers gate
    this on process 0 + barriers."""
    ckpt_dir = out_dir / "ckpt"
    prev = out_dir / "ckpt_prev"
    with obs.span("sweep.ckpt_swap"):
        if ckpt_dir.exists():
            shutil.rmtree(prev, ignore_errors=True)
            ckpt_dir.rename(prev)
        # the swap's worst instant: ckpt/ is gone, the new set not yet named
        # in — a kill here must leave resume falling back to ckpt_prev/
        # (chaos matrix site; tests/test_pipeline_chaos.py)
        crash_barrier("ckpt.swap")
        staging.rename(ckpt_dir)


def _flat_dicts(e: EnsembleLike) -> list:
    if isinstance(e, EnsembleGroup):
        return [d for ds in e.to_learned_dicts().values() for d in ds]
    return e.to_learned_dicts()


def sweep(
    ensemble_init_fn: EnsembleInitFn,
    cfg: EnsembleArgs,
    store: Optional[ChunkStore] = None,
    mesh=None,
    log_every: int = 100,
    image_metrics_every: Optional[int] = 10,
    resume: bool = False,
) -> dict[str, list]:
    """Run the sweep; returns {name: [(LearnedDict, hyperparams), ...]}.

    `cfg.n_chunks` limits chunks per repetition; saves happen at chunk counts
    {7, 15, 31, ...} and at the end (reference: big_sweep.py:378-384 saves at
    i ∈ {7,15,…,2^9−1} and the final chunk), or every
    `cfg.save_every_chunks` when set. `resume=True` restores ensemble state +
    the batch RNG from the newest checkpoints and skips completed chunks."""
    out_dir = Path(cfg.output_folder)
    out_dir.mkdir(parents=True, exist_ok=True)
    cfg.save(out_dir / "config.json")  # YAML-dump analogue (big_sweep.py:382-384)

    if store is None:
        if isinstance(cfg, SyntheticEnsembleArgs):
            store = init_synthetic_dataset(cfg)
        else:
            # layout-agnostic: a store-level manifest.json opens the
            # sharded reader, anything else the flat ChunkStore.
            # quarantine_corrupt: a scrub-repaired store (chunks moved
            # aside, ledger knows) must train through positional Nones,
            # not crash the sweep the scrub just healed
            store = open_store(cfg.dataset_folder, quarantine_corrupt=True)

    if mesh is None and (cfg.mesh_data > 1 or cfg.mesh_model > 1):
        mesh = make_mesh(cfg.mesh_model, cfg.mesh_data)

    ensembles = ensemble_init_fn(cfg, mesh)
    member_names = [_member_names(hypers, len(hypers))
                    for _, hypers, _ in ensembles]
    logger = MetricsLogger(out_dir, use_wandb=cfg.use_wandb,
                           run_name=out_dir.name, config=cfg.to_dict())

    # the training health guardian (train/guardian.py, §16): host half of
    # the divergence ladder — member quarantine ledger, rollback
    # escalation, typed halt. The in-graph sentinel in the step programs
    # feeds it through the aux; cfg.guardian=False runs bare (the aux
    # fields also vanish with cfg.sentinel=False, the bench A/B knob).
    guardian: Optional[Guardian] = None
    if getattr(cfg, "guardian", True):
        guardian = Guardian(
            out_dir, ensembles, member_names,
            member_fraction=getattr(cfg, "guardian_member_fraction", 0.5),
            rollback_budget=getattr(cfg, "guardian_rollback_budget", 4),
            fresh=not resume)
        # the rollback contract needs the positional-hole reader: a chunk
        # the guardian quarantines must REPLAY as None (synthetic and
        # caller-provided stores default to the strict reader)
        store.quarantine_corrupt = True

    rng = np.random.default_rng(cfg.seed)
    n_chunks = min(cfg.n_chunks, store.n_chunks)
    chunk_order = np.concatenate([rng.permutation(n_chunks)
                                  for _ in range(cfg.n_repetitions)])
    # the batch-RNG state at chunk 0 — the rollback target when an
    # incident lands before the first checkpoint set exists
    rng0_state = rng.bit_generator.state

    chunks_done = 0
    if resume:
        chunks_done, rng_state = resume_sweep_state(ensembles, out_dir)
        if rng_state is not None:
            rng.bit_generator.state = rng_state
        if guardian is not None:
            # ledgered quarantines must outlive the process: a restored
            # checkpoint predates the freeze it is resumed past
            guardian.refreeze()

    center = None
    if cfg.center_activations:
        # reference centers on chunk 0 (big_sweep.py:359-364); over a
        # scrub-repaired store the first SOUND chunk stands in — the
        # sweep must train through the holes the scrub just healed, not
        # crash at startup (same contract as run_eval's batch pick)
        center = store.chunk_mean(first_sound_chunk(store))

    # bf16 keeps activations half-width from disk through the host→device
    # pipe; the jitted step promotes to f32 against the f32 params, so only
    # input precision (not accumulation) drops
    if cfg.train_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"train_dtype must be 'float32' or 'bfloat16', got "
            f"{cfg.train_dtype!r}")
    if cfg.checkpoint_backend not in ("msgpack", "orbax"):
        raise ValueError(
            f"checkpoint_backend must be 'msgpack' or 'orbax', got "
            f"{cfg.checkpoint_backend!r}")
    if cfg.checkpoint_backend == "msgpack" and jax.process_count() > 1:
        raise ValueError(
            "checkpoint_backend='msgpack' gathers the full state to one "
            "host and is single-host only; use checkpoint_backend='orbax' "
            "for multi-host runs (sharded per-host writes)")
    train_np_dtype = (jnp.bfloat16 if cfg.train_dtype == "bfloat16"
                      else np.dtype(cfg.train_dtype))
    orbax_ckptr = None
    if cfg.checkpoint_backend == "orbax":
        from sparse_coding_tpu.utils.orbax_ckpt import AsyncEnsembleCheckpointer

        orbax_ckptr = AsyncEnsembleCheckpointer(use_async=True)

    sharding = batch_sharding(mesh) if mesh is not None else None
    if cfg.save_every_chunks:
        save_points = set(range(cfg.save_every_chunks - 1, len(chunk_order),
                                cfg.save_every_chunks))
    else:
        save_points = {2**k - 1 for k in range(3, 10)}
    step = 0
    last_log = 0
    # scan_steps > 1: fuse K steps into one device program (lax.scan via
    # run_steps) — same update sequence, one dispatch per window. Through
    # the axon tunnel (~54 ms/dispatch measured r4) this is the difference
    # between a dispatch-bound and a compute-bound sweep.
    scan_k = max(1, int(getattr(cfg, "scan_steps", 1)))
    # the timer ticks once per window, so warmup is denominated in windows;
    # one window of K steps is already past compile+dispatch warmth (a chunk
    # with a single window still logs 0 — raise batches/chunk or lower
    # scan_steps if the throughput stream matters at debug scale)
    timer = StepTimer(warmup=3 if scan_k == 1 else 1)
    # orbax: a fully-issued async checkpoint set whose swap is deferred so
    # its disk writes overlap the next chunk's training
    pending_staging: Optional[Path] = None
    # cfg.profile_steps > 0: one managed trace window (obs/trace.py —
    # crash-safe: tmp-then-atomic finalize, counted skip on error, and a
    # guaranteed close in the finally below) opens once the first program
    # has compiled — step 2 per-step, the SECOND window under scan (the
    # first window compiles the scanned program; starting there would
    # trace minutes of XLA compile instead of steady-state steps) — and
    # closes profile_steps later, on a window boundary, so it covers AT
    # LEAST profile_steps steps.
    profile_start = 2 if scan_k == 1 else scan_k + 1
    profiling = False
    profile_done = False
    tracer = (obs.TraceCapture(out_dir / "trace")
              if cfg.profile_steps > 0 else None)
    # device-time perf evidence (obs/perf.py, §12): every Nth window is
    # bracketed with block_until_ready timing → train.mfu + roofline-gap
    # instruments in this run's report; 0 disables
    probe_every = max(0, int(getattr(cfg, "perf_probe_every", 0)))
    perf_probe = (obs.DeviceStepProbe("train", every=probe_every)
                  if probe_every else None)

    # warm start (docs/ARCHITECTURE.md §13): with the executable cache
    # enabled, compile-or-load every step program this sweep will
    # dispatch BEFORE the first chunk is read or the device touched — a
    # respawned child (the crash-only restart path) then pays disk loads,
    # not XLA compiles, and each program lands in the warmup manifest as
    # the record of what a restart must have warm
    from sparse_coding_tpu import xcache

    if xcache.enabled() and chunks_done < len(chunk_order):
        t_warm = obs.monotime()
        batch_shape = ((scan_k, cfg.batch_size, store.activation_dim)
                       if scan_k > 1
                       else (cfg.batch_size, store.activation_dim))
        n_warm = 0
        for ensemble, _, name in ensembles:
            for j, sub in enumerate(_ensembles_of(ensemble)):
                sub.precompile(batch_shape, dtype=train_np_dtype,
                               label=f"sweep/{name}_{j}")
                n_warm += 1
        obs.record_span("sweep.warmstart", obs.monotime() - t_warm,
                        programs=n_warm, shape=list(batch_shape))

    # remaining chunks stream through the async ingest pipeline
    # (data/ingest.py): up to cfg.ingest_streams decodes overlap the
    # current chunk's training, each on the store's hardened read path; a
    # dying stream degrades to the foreground single-stream reader and
    # the epoch completes with identical data. streams<=1 keeps the
    # native 1-slab readahead contract (chunkio.cpp background threads).
    def _open_reader(from_chunk: int):
        """(todo, reader) over positions from_chunk..end — re-opened by a
        guardian rollback with the quarantined chunk now a ledger-known
        positional hole."""
        positions = list(range(from_chunk, len(chunk_order)))
        return positions, chunk_stream(
            store, [int(chunk_order[ci]) for ci in positions],
            dtype=train_np_dtype, streams=cfg.ingest_streams or None)

    def _reinit_states() -> None:
        """Rollback target when no checkpoint set exists yet: member init
        is keyed on cfg.seed, so a fresh ensemble_init_fn reproduces the
        chunk-0 state bitwise; only the device states move (the compiled
        step programs on the existing objects stay)."""
        for (e_old, _, _), (e_new, _, _) in zip(ensembles,
                                                ensemble_init_fn(cfg, mesh)):
            for s_old, s_new in zip(_ensembles_of(e_old),
                                    _ensembles_of(e_new)):
                s_old.state = s_new.state

    todo, reader = _open_reader(chunks_done)
    # SIGTERM (preemptible capacity, the unattended recovery loop) sets a
    # flag polled at chunk boundaries: the in-flight chunk finishes, a
    # checkpoint set is forced regardless of cadence, and SweepPreempted
    # propagates — resume=True then continues bitwise-identically
    # (resilience/preempt.py; the graceful twin of the crash-resume path).
    preempt = PreemptionGuard()
    preempt.__enter__()  # paired in the finally (keeps the loop unindented)
    try:
        # the rollback loop: one pass is the whole sweep; a guardian
        # escalation (GuardianRollback) restores the last-good
        # checkpoint set and replays from there with the offending
        # chunk quarantined (docs/ARCHITECTURE.md §16)
        while True:
            try:
                for ci, chunk in zip(todo, reader):
                    # fresh throughput window per chunk: checkpoint/artifact wall
                    # time between chunks must not dilute the training-rate signal
                    timer.reset()
                    t_chunk = obs.monotime()
                    if chunk is not None and center is not None:
                        # cast the mean down rather than the chunk up: keeps the
                        # bf16 path bf16 end to end (host RAM + host→device traffic
                        # halved). In place: load_chunk returns a fresh array, and
                        # out-of-place would briefly hold two full chunks in RAM
                        chunk -= center.astype(train_np_dtype)
                    # chunk is None when the store quarantined it
                    # (quarantine_corrupt=True): no batches to train, but the
                    # boundary bookkeeping below (checkpoint cadence, preemption
                    # consensus) still runs at this ci so indices stay aligned
                    batches = (iter(()) if chunk is None
                               else store.batches(chunk, cfg.batch_size, rng))
                    if guardian is not None:
                        # fault site sweep.anomaly: the divergence drill's
                        # injection point — every host batch passes through
                        # (no-op without an active plan)
                        batches = map(guardian.inject_anomaly, batches)
                    if scan_k > 1:
                        batches = window_stacks(batches, scan_k)
                        window_sharding = (batch_sharding(mesh, stacked=True)
                                           if mesh is not None else None)
                    else:
                        window_sharding = sharding
                    for batch in device_batches(batches, window_sharding):
                        k_steps = batch.shape[0] if scan_k > 1 else 1
                        step += k_steps
                        if (cfg.profile_steps > 0 and not profiling
                                and not profile_done and step >= profile_start):
                            profiling = tracer.begin()
                            # a counted begin-skip must not retry per step
                            profile_done = not profiling
                        elif profiling and step >= profile_start + cfg.profile_steps:
                            tracer.end()
                            profiling = False
                            profile_done = True
                        do_log = step - last_log >= log_every
                        if do_log:
                            last_log = step
                        # perf sample (obs/perf.py): bracket this window —
                        # drain in-flight work, dispatch, sync — so the
                        # measured wall is pure device time. Log windows
                        # (their device_get syncs mid-window) and trace
                        # windows are skipped.
                        sample_perf = (perf_probe is not None and not do_log
                                       and not profiling
                                       and perf_probe.should_sample())
                        window_aux = []
                        if sample_perf:
                            jax.block_until_ready(
                                [sub.state.params for e, _, _ in ensembles
                                 for sub in _ensembles_of(e)])
                            t_perf = obs.monotime()
                        for ens_idx, (ensemble, hypers, name) in enumerate(ensembles):
                            is_group = isinstance(ensemble, EnsembleGroup)
                            if scan_k > 1:
                                # aux comes back stacked [K, ...]; the window's last
                                # step is sliced out ONLY when logging (the slice is
                                # its own device dispatch — paying it per window
                                # would re-import the overhead scan_steps removes)
                                stepper = ensemble.run_steps
                                last = lambda aux: jax.tree.map(lambda a: a[-1], aux)
                            else:
                                stepper = ensemble.step_batch
                                last = lambda aux: aux
                            if is_group:
                                raw_items = list(stepper(batch).items())
                            else:
                                raw_items = [(name, stepper(batch))]
                            if sample_perf:
                                window_aux.extend(a for _, a in raw_items)
                            if guardian is not None:
                                # per-window anomaly accumulation: a tiny
                                # async device combine, host-synced only at
                                # the chunk boundary (check_boundary)
                                for sub_name, raw_aux in raw_items:
                                    guardian.observe(ens_idx, sub_name,
                                                     raw_aux)
                            if do_log:
                                aux_items = [(n, last(a)) for n, a in raw_items]
                                for sub_name, aux in aux_items:
                                    losses = jax.device_get(aux.losses["loss"])
                                    l0 = jax.device_get(aux.l0)
                                    # quarantined members' NaN losses must
                                    # not poison the aggregate streams —
                                    # masked out (and counted) here; their
                                    # per-member streams below still log,
                                    # so the divergence stays diagnosable
                                    mask = np.ones(len(losses), np.bool_)
                                    if guardian is not None:
                                        dead = guardian.dead_indices(
                                            ens_idx, sub_name)
                                        mask[dead] = False
                                    rec = {}
                                    if mask.any():
                                        rec = {f"{sub_name}/loss_mean":
                                               float(np.mean(losses[mask])),
                                               f"{sub_name}/loss_max":
                                               float(np.max(losses[mask])),
                                               f"{sub_name}/l0_mean":
                                               float(np.mean(l0[mask]))}
                                    if not mask.all():
                                        rec[f"{sub_name}/quarantined"] = int(
                                            (~mask).sum())
                                    # per-member streams, named from hyperparams like
                                    # the reference's per-model wandb logs
                                    # (big_sweep.py:173-197). Group buckets use
                                    # positional names — the flat hypers list doesn't
                                    # align with bucket-local member indices (the
                                    # bucket name carries the static hyperparameter
                                    # already).
                                    names_i = member_names[ens_idx]
                                    for mi, (loss_i, l0_i) in enumerate(zip(losses, l0)):
                                        member = (f"member{mi}" if is_group
                                                  else names_i[mi] if mi < len(names_i)
                                                  else f"member{mi}")
                                        rec[f"{sub_name}/{member}/loss"] = float(loss_i)
                                        rec[f"{sub_name}/{member}/l0"] = float(l0_i)
                                    logger.log(rec, step=step)
                        if sample_perf:
                            jax.block_until_ready(window_aux)
                            rows = (batch.shape[1] if scan_k > 1
                                    else batch.shape[0])
                            perf_probe.record(
                                obs.monotime() - t_perf,
                                cost=obs.combine_costs(
                                    [e.step_cost(rows)
                                     for e, _, _ in ensembles]),
                                steps=k_steps)
                        timer.tick(batch.shape[0] * (batch.shape[1]
                                                     if scan_k > 1 else 1))
                        # supervised runs: each completed training window is
                        # progress (throttled inside; a hang anywhere in the
                        # dispatch→sync path stops these beats)
                        lease.beat()
                        if do_log:
                            logger.log({"activations_per_sec": timer.items_per_sec},
                                       step=step)
                    # checkpoint + periodic artifact saves; the RNG state makes the
                    # data stream resume exactly where it stopped. The whole
                    # checkpoint SET is written to a staging dir and swapped in by
                    # renames, so a crash mid-save can never leave ensembles at
                    # mixed chunks_done (ADVICE r1 #5); cadence is
                    # cfg.checkpoint_every_chunks (VERDICT r1 weak#6). Orbax sets
                    # are issued async and swapped in at the NEXT round (or in the
                    # finally below), so their disk writes overlap a full chunk of
                    # training; msgpack sets swap immediately.
                    # the guardian's one host sync per chunk — BEFORE the
                    # checkpoint block, so a poisoned chunk's advanced
                    # state is never checkpointed: an input incident or a
                    # member-fraction breach raises GuardianRollback (or a
                    # typed DivergenceHaltError when the ladder is spent),
                    # a plain member divergence freezes + ledgers here
                    if guardian is not None:
                        guardian.check_boundary(ci, int(chunk_order[ci]),
                                                store)
                    last_chunk = ci == len(chunk_order) - 1
                    cadence = cfg.checkpoint_every_chunks
                    # sample the preemption flag ONCE per boundary (a signal landing
                    # mid-checkpoint is honored at the next chunk's boundary) and
                    # agree on it cross-host BEFORE gating the barrier-containing
                    # branch — a host-local flag would desync the collectives
                    preempted = _agree_preempted(preempt.requested)
                    if ((cadence > 0 and (ci + 1) % cadence == 0) or last_chunk
                            or preempted):
                        rng_state = rng.bit_generator.state
                        staging = out_dir / "ckpt_staging"
                        if pending_staging is not None:
                            # previous round's writes overlapped this chunk's
                            # training; make them the current set before reusing
                            # the staging dir
                            orbax_ckptr.wait()
                            _sync_hosts("ckpt-durable")
                            if jax.process_index() == 0:
                                _swap_in_checkpoint_set(out_dir, pending_staging)
                            _sync_hosts("ckpt-swapped")
                            pending_staging = None
                        if jax.process_index() == 0:
                            shutil.rmtree(staging, ignore_errors=True)
                        _sync_hosts("ckpt-staging-clean")
                        for ensemble, hypers, name in ensembles:
                            for j, sub in enumerate(_ensembles_of(ensemble)):
                                extra = {"chunks_done": ci + 1, "rng_state": rng_state}
                                if orbax_ckptr is not None:
                                    orbax_ckptr.save(
                                        sub, checkpoint_path(staging, f"{name}_{j}"),
                                        extra=extra)
                                else:
                                    save_ensemble(sub, staging / f"{name}_{j}.msgpack",
                                                  extra=extra)
                        if orbax_ckptr is not None:
                            # fully issued — safe to swap once durable (next round
                            # or the finally below); a crash mid-save-loop leaves
                            # pending_staging unset and the staged set is discarded
                            pending_staging = staging
                        elif jax.process_index() == 0:
                            _swap_in_checkpoint_set(out_dir, staging)
                    if (ci in save_points or ci == len(chunk_order) - 1) \
                            and chunk is not None:
                        _save_artifacts(ensembles, out_dir / f"_{ci}", chunk, cfg,
                                        logger,
                                        image_metrics=image_metrics_every is not None
                                        and (ci + 1) % image_metrics_every == 0,
                                        guardian=guardian)
                    # chunk telemetry BEFORE the barrier: a kill at the barrier
                    # leaves the span + metrics snapshot as durable as the chunk's
                    # artifacts. StepTimer.snapshot() is the single throughput
                    # surface (bench shares it), published as the sweep gauge.
                    snap = timer.snapshot()
                    timer.publish(prefix="sweep")
                    obs.record_span("sweep.chunk", obs.monotime() - t_chunk,
                                    index=ci, chunk=int(chunk_order[ci]),
                                    steps=snap["steps"],
                                    acts_per_sec=round(snap["items_per_sec"], 1))
                    obs.flush_metrics()
                    # one chunk's full train+checkpoint+artifact block is durable —
                    # the crash-resume unit the chaos matrix kills at
                    crash_barrier("sweep.chunk")
                    if preempted and not last_chunk:
                        # checkpoint for chunks 0..ci is issued (and for msgpack
                        # already swapped in); exit cleanly so resume continues
                        raise SweepPreempted(ci + 1)
            except GuardianRollback as rollback:
                # guardian escalation (train/guardian.py): the incident
                # record + chunk quarantine are already durable; close the
                # stream, make any fully-issued async set current (it is
                # the NEWEST last-good state), cross the guardian.rollback
                # crash barrier, restore, and replay — bitwise the run
                # that never saw the poisoned chunk
                reader.close()
                if pending_staging is not None:
                    orbax_ckptr.wait()
                    _sync_hosts("ckpt-durable")
                    if jax.process_index() == 0:
                        _swap_in_checkpoint_set(out_dir, pending_staging)
                    _sync_hosts("ckpt-swapped")
                    pending_staging = None

                def _restore():
                    done, rng_state = resume_sweep_state(ensembles, out_dir)
                    if done == 0 and rng_state is None:
                        # incident before the first checkpoint set: the
                        # last-good state is the chunk-0 init, reproduced
                        # bitwise from cfg.seed
                        _reinit_states()
                        rng_state = rng0_state
                    return done, rng_state

                chunks_done, rng_state = guardian.rollback_restore(_restore)
                if rng_state is not None:
                    rng.bit_generator.state = rng_state
                logger_mod.warning(
                    "guardian rollback (%s at %s): resuming from chunk %d "
                    "with chunk %d quarantined", rollback.incident,
                    rollback.site, chunks_done, rollback.chunk_index)
                todo, reader = _open_reader(chunks_done)
                continue
            break
        clean_exit = True
    except SweepPreempted:
        # a preemption exit IS clean: the staged orbax set (if any) is
        # fully issued and must be swapped in below like a normal finish
        clean_exit = True
        raise
    except BaseException:
        clean_exit = False
        raise
    finally:
        preempt.__exit__(None, None, None)
        reader.close()  # release any in-flight native chunk read
        if profiling:
            # short sweeps / crashes inside the window: the capture is
            # still finalized (atomically) so the steps it did record are
            # viewable; a failed finalize is a counted skip, not a crash
            tracer.end()
        if orbax_ckptr is not None:
            # a FULLY-ISSUED async set is waited on and swapped in even on
            # a crash (it reflects completed training) — but cross-host
            # barriers only run on a clean exit: an exception may be
            # host-local, and a barrier in the error path would deadlock
            # the healthy hosts (a dead process is the jax.distributed
            # coordinator's job to detect). A skipped swap just means
            # resume falls back to the previous complete set. close() then
            # guarantees no background write outlives this run to race a
            # later resume's staging cleanup.
            try:
                if pending_staging is not None and (
                        clean_exit or jax.process_count() == 1):
                    orbax_ckptr.wait()
                    _sync_hosts("ckpt-final-durable")
                    if jax.process_index() == 0:
                        _swap_in_checkpoint_set(out_dir, pending_staging)
                    _sync_hosts("ckpt-final-swapped")
            finally:
                orbax_ckptr.close()
        logger.close()
    result = {}
    for ensemble, hypers, name in ensembles:
        dicts = _flat_dicts(ensemble)
        tagged = list(zip(dicts, hypers))
        if guardian is not None:
            # quarantined members ship tagged diverged=True — the same
            # flag every periodic artifact carries, so downstream loads
            # (load_learned_dicts(skip_diverged=True), eval, serving
            # registries) can filter them uniformly
            tagged = guardian.tag_hypers(name, tagged)
        result[name] = tagged
    return result


def _save_artifacts(ensembles, folder: Path, chunk: np.ndarray,
                    cfg: EnsembleArgs, logger: MetricsLogger,
                    image_metrics: bool = False, guardian=None) -> None:
    """Save learned dicts + quick evals (reference: big_sweep.py:368-384 +
    log_standard_metrics :86-156). Members the guardian quarantined are
    tagged ``diverged=True`` in the artifact, skipped (and counted) by the
    quick evals, and excluded from the MMCS/sparsity panels — a NaN
    dictionary must never poison a sweep's eval surface."""
    folder.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(0)
    # evals always run in f32 even when training streams bf16 activations
    eval_batch = jnp.asarray(chunk[rng.permutation(chunk.shape[0])[:4096]],
                             jnp.float32)
    for ensemble, hypers, name in ensembles:
        dicts = _flat_dicts(ensemble)
        tagged = list(zip(dicts, hypers))
        if guardian is not None:
            tagged = guardian.tag_hypers(name, tagged)
        save_learned_dicts(tagged, folder / f"{name}_learned_dicts.pkl")
        evals = []
        for ld, hyper in tagged:
            scalars = {k: v for k, v in hyper.items()
                       if isinstance(v, (int, float, str))}
            if hyper.get("diverged"):
                evals.append({**scalars, "skipped": True})
                continue
            evals.append({**scalars,
                          "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
                          "l0": float(mean_l0(ld, eval_batch))})
        atomic_write_text(folder / f"{name}_eval.json",
                          json.dumps(evals, indent=2))
        if image_metrics:
            # MMCS grid + per-dict sparsity histograms (reference's wandb
            # image panels, big_sweep.py:86-156, as files); diverged
            # members are excluded — one NaN row would blank the panels
            from sparse_coding_tpu.plotting.helpers import plot_hist

            live_dicts = [ld for ld, hyper in tagged
                          if not hyper.get("diverged")]
            if len(live_dicts) > 1:
                grid = np.asarray(
                    mmcs_from_list(live_dicts[: min(len(live_dicts), 8)]))
                atomic_save_npy(folder / f"{name}_mmcs_grid.npy", grid)
            for di, (ld, hyper) in enumerate(tagged):
                if hyper.get("diverged"):
                    continue
                freqs = mean_nonzero_activations(ld, eval_batch)
                plot_hist(jnp.log10(jnp.clip(freqs, 1e-6)),
                          x_label="log10 firing frequency", y_label="features",
                          save_path=folder / f"{name}_{di}_sparsity_hist.png")


def main(argv=None) -> None:
    """CLI: python -m sparse_coding_tpu.train.sweep --experiment dense_l1_range
    --dataset_folder chunks/ --output_folder out/ [--synthetic true ...]"""
    import argparse
    import sys

    from sparse_coding_tpu.config import _parse_value
    from sparse_coding_tpu.train.experiments import EXPERIMENTS

    argv_list = list(argv) if argv is not None else sys.argv[1:]
    if "-h" in argv_list or "--help" in argv_list:
        # the config parser prints the dataclass-field options and exits;
        # document the driver-level flags it doesn't know about first
        print(f"driver flags: --experiment {{{','.join(sorted(EXPERIMENTS))}}} "
              "--synthetic BOOL --resume BOOL\nconfig flags:")
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument("--experiment", default="dense_l1_range",
                        choices=sorted(EXPERIMENTS))
    parser.add_argument("--synthetic", default="false")
    parser.add_argument("--resume", default="false")
    ns, rest = parser.parse_known_args(argv_list)

    synthetic = _parse_value(ns.synthetic, bool)
    cfg = (SyntheticEnsembleArgs if synthetic else EnsembleArgs).from_cli(rest)
    try:
        result = sweep(EXPERIMENTS[ns.experiment], cfg,
                       resume=_parse_value(ns.resume, bool))
    except SweepPreempted as e:
        # SIGTERM shutdown is a SUCCESS for the driver: state is durable,
        # `--resume true` continues bitwise-identically
        print(f"sweep: {e}")
        return
    for name, dicts in result.items():
        print(f"{name}: {len(dicts)} dicts -> {cfg.output_folder}")


def _restore_checkpoint_set(
        targets: Sequence[tuple[Ensemble, Path]]) -> tuple[int, Optional[dict]]:
    chunks_done: Optional[int] = None
    rng_state = None
    for sub, path in targets:
        if path.suffix == ".orbax":
            from sparse_coding_tpu.utils.orbax_ckpt import restore_ensemble_orbax

            meta = restore_ensemble_orbax(sub, path)
        else:
            meta = restore_ensemble(sub, path)
        done = int(meta.get("chunks_done", 0))
        if chunks_done is None or done < chunks_done:
            chunks_done = done
            rng_state = meta.get("rng_state", rng_state)
    return (chunks_done or 0), rng_state


def resume_sweep_state(ensembles: Sequence[tuple[EnsembleLike, list, str]],
                       out_dir: str | Path) -> tuple[int, Optional[dict]]:
    """Restore all ensembles from the newest COMPLETE checkpoint set; returns
    (chunks_done, batch-rng bit-generator state) — (0, None) without
    checkpoints. `ckpt/` only ever holds a consistent set (staged rename
    swap); `ckpt_prev/` covers a crash inside the swap itself. Resuming uses
    min(chunks_done) across the set as a final guard so no ensemble ever
    skips a chunk it never trained on (ADVICE r1 #5).

    Corruption fallback (docs/ARCHITECTURE.md §10): a set whose digest
    manifest fails raises a typed CheckpointCorruptionError from the
    backend; this walks back to the `ckpt_prev/` last-good set instead of
    resuming from damaged state. Only when EVERY present set is corrupt
    does the error propagate — never a silent restart-from-scratch."""
    out_dir = Path(out_dir)
    last_err: Optional[CheckpointCorruptionError] = None
    for ckpt_dir in (out_dir / "ckpt", out_dir / "ckpt_prev"):
        if not ckpt_dir.exists():
            continue

        def find(name: str, j: int) -> Optional[Path]:
            # either backend's file may be present (a sweep resumed after a
            # checkpoint_backend change still restores the old set)
            for p in (ckpt_dir / f"{name}_{j}.msgpack",
                      checkpoint_path(ckpt_dir, f"{name}_{j}")):
                if p.exists():
                    return p
            return None

        targets = [(sub, find(name, j))
                   for ensemble, hypers, name in ensembles
                   for j, sub in enumerate(_ensembles_of(ensemble))]
        if not all(path is not None for _, path in targets):
            continue  # incomplete set: fall through to the older set
        try:
            return _restore_checkpoint_set(targets)
        except CheckpointCorruptionError as e:
            last_err = e
            logger_mod.warning(
                "checkpoint set %s is corrupt (%s); falling back to the "
                "previous set", ckpt_dir.name, e)
    if last_err is not None:
        raise last_err
    return 0, None  # no/incomplete set: restart from scratch, untouched


if __name__ == "__main__":
    main()
