"""Minimal single-host L1 sweep over a directory of activation chunks.

Re-design of the reference's `basic_l1_sweep` (reference:
basic_l1_sweep.py:46-115): one vmapped tied-SAE ensemble over an l1 grid,
fed from a ChunkStore with device prefetch, saving learned dicts + FVU/L0
per epoch. This is the framework's "minimum end-to-end slice"
(SURVEY.md §7).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.config import EnsembleArgs
from sparse_coding_tpu.data.chunk_store import ChunkStore, device_prefetch
from sparse_coding_tpu.data.shard_store import open_store
from sparse_coding_tpu.ensemble import Ensemble
from sparse_coding_tpu.metrics.core import fraction_variance_unexplained, mean_l0
from sparse_coding_tpu.models.sae import FunctionalSAE, FunctionalTiedSAE
from sparse_coding_tpu.parallel.mesh import batch_sharding, make_mesh
from sparse_coding_tpu.utils.artifacts import save_learned_dicts
from sparse_coding_tpu.utils.logging import MetricsLogger


def basic_l1_sweep(
    dataset_dir: str | Path,
    output_dir: str | Path,
    l1_values: Sequence[float],
    dict_ratio: float = 4.0,
    batch_size: int = 1024,
    lr: float = 1e-3,
    n_epochs: int = 1,
    tied: bool = True,
    adam_epsilon: float = 1e-8,
    seed: int = 0,
    mesh=None,
    use_wandb: bool = False,
    scan_steps: int = 1,
) -> list:
    """Train one ensemble member per l1 value; save per-epoch artifacts.
    Returns the final list of (LearnedDict, hyperparams). scan_steps > 1
    fuses K steps per device program (see EnsembleArgs.scan_steps)."""
    # layout-agnostic: a store-level manifest.json opens the sharded
    # reader, anything else the flat ChunkStore. quarantine_corrupt: a
    # scrub-repaired store trains through positional holes (same
    # contract as the ensemble sweep)
    store = open_store(dataset_dir, quarantine_corrupt=True)
    d = store.activation_dim  # inferred from chunk 0, as basic_l1_sweep.py:59-62
    n_dict = int(d * dict_ratio)
    sig = FunctionalTiedSAE if tied else FunctionalSAE

    keys = jax.random.split(jax.random.PRNGKey(seed), len(l1_values))
    members = [sig.init(k, d, n_dict, l1_alpha=float(l1))
               for k, l1 in zip(keys, l1_values)]
    ens = Ensemble(members, sig, lr=lr, adam_eps=adam_epsilon, mesh=mesh)

    logger = MetricsLogger(output_dir, use_wandb=use_wandb, run_name="basic_l1_sweep")
    rng = np.random.default_rng(seed)
    sharding = batch_sharding(mesh) if mesh is not None else None

    step = 0
    last_log = 0
    scan_k = max(1, int(scan_steps))
    if scan_k > 1:
        from sparse_coding_tpu.data.chunk_store import window_stacks

        if mesh is not None:
            sharding = batch_sharding(mesh, stacked=True)
    for epoch in range(n_epochs):
        batches = store.epoch(batch_size, rng)
        if scan_k > 1:
            batches = window_stacks(batches, scan_k)
        for batch in device_prefetch(batches, sharding):
            if scan_k > 1:
                aux = ens.run_steps(batch)
                step += batch.shape[0]
            else:
                aux = ens.step_batch(batch)
                step += 1
            if step - last_log >= 100:
                last_log = step
                if scan_k > 1:
                    aux = jax.tree.map(lambda a: a[-1], aux)
                # ONE host sync for all members' stacked metrics per log
                # window (rule host-sync: per-member float() reads would
                # cost 2×members device round-trips per log step)
                losses, l0 = jax.device_get((aux.losses, aux.l0))
                for i, l1 in enumerate(l1_values):
                    logger.log({f"l1={l1:.2e}/loss": float(losses["loss"][i]),
                                f"l1={l1:.2e}/l0": float(l0[i])}, step=step)
        _save_epoch(ens, l1_values, dict_ratio, store, output_dir, epoch, rng)
    logger.close()

    dicts = ens.to_learned_dicts()
    return [(ld, {"l1_alpha": float(l1), "dict_size": n_dict})
            for ld, l1 in zip(dicts, l1_values)]


def _save_epoch(ens: Ensemble, l1_values, dict_ratio, store: ChunkStore,
                output_dir, epoch: int, rng) -> None:
    out = Path(output_dir) / f"epoch_{epoch}"
    dicts = ens.to_learned_dicts()
    tagged = [(ld, {"l1_alpha": float(l1), "dict_ratio": dict_ratio})
              for ld, l1 in zip(dicts, l1_values)]
    save_learned_dicts(tagged, out / "learned_dicts.pkl")
    # quick eval on a fresh slab (reference logs fvu/sparsity per save).
    # Same RNG draw whatever the store's health; only a draw that lands
    # on a scrub-repaired hole falls through to the first sound chunk
    ci = int(rng.integers(store.n_chunks))
    if ci in (store.quarantined or set()):
        from sparse_coding_tpu.data.shard_store import first_sound_chunk

        ci = first_sound_chunk(store)
    chunk = store.load_chunk(ci)
    eval_batch = jnp.asarray(chunk[rng.permutation(chunk.shape[0])[:4096]])
    stats = []
    for ld, hyper in tagged:
        stats.append({"l1_alpha": hyper["l1_alpha"],
                      "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
                      "l0": float(mean_l0(ld, eval_batch))})
    import json

    from sparse_coding_tpu.resilience.atomic import atomic_write_text

    atomic_write_text(out / "eval.json", json.dumps(stats, indent=2))


def main(argv=None) -> None:
    cfg = EnsembleArgs.from_cli(argv)
    l1_values = list(np.logspace(-4, -2, 16))
    mesh = None
    if cfg.mesh_data > 1 or cfg.mesh_model > 1:
        mesh = make_mesh(cfg.mesh_model, cfg.mesh_data)
    basic_l1_sweep(cfg.dataset_folder, cfg.output_folder, l1_values,
                   dict_ratio=cfg.learned_dict_ratio, batch_size=cfg.batch_size,
                   lr=cfg.lr, tied=cfg.tied_ae, adam_epsilon=cfg.adam_epsilon,
                   seed=cfg.seed, mesh=mesh, use_wandb=cfg.use_wandb,
                   scan_steps=cfg.scan_steps)


if __name__ == "__main__":
    main()
