"""Large single-SAE trainer with dead-feature resurrection.

TPU-native re-design of the reference's DDP trainer
(reference: experiments/huge_batch_size.py): the gloo process group +
DistributedDataParallel + DistributedSampler machinery (:259-363) collapses
into ONE jitted step over a ("model", "data") mesh — batch sharded over
"data" (gradient reduction = XLA psum over ICI), and for dictionaries too
big for one chip, the feature axis sharded over "model" (tensor parallelism
the reference doesn't have).

Dead-feature resurrection (reference: process_reinit, :150-256): track
per-feature activation totals and the worst-reconstructed examples; dead
encoder columns are reinitialized to worst examples (scaled by
0.2/mean-encoder-norm, :224-232) and their Adam state zeroed (:242-250 — in
optax this is a masked state reset rather than the reference's in-place
surgery on optimizer.state). Here both tracking and resurrection are pure
jitted functions, so they run on device with no host sync.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from sparse_coding_tpu.models import learned_dict as ld
from sparse_coding_tpu.parallel import partition

Array = jax.Array

ENCODER_NORM_RATIO = 0.2  # reference: huge_batch_size.py:231


class BigSAEState(struct.PyTreeNode):
    """Params + optimizer + dead-feature tracking, all device-resident."""

    params: dict
    opt_state: optax.OptState
    c_totals: Array  # [n] activation mass per feature since last resurrection
    worst_losses: Array  # [K] highest per-example MSEs seen
    worst_vectors: Array  # [K, d] the examples themselves
    step: Array
    tied: bool = struct.field(pytree_node=False, default=False)


def init_big_sae(key: Array, activation_size: int, n_feats: int,
                 l1_alpha: float, lr: float = 1e-3, tied: bool = False,
                 n_worst: int = 1024, dtype=jnp.float32
                 ) -> tuple[BigSAEState, optax.GradientTransformation, Array]:
    """(reference: SAE/UntiedSAE __init__, huge_batch_size.py:25-101).
    Returns (state, optimizer, l1_alpha array)."""
    k_dict, k_enc = jax.random.split(key)
    dictionary = jax.random.normal(k_dict, (n_feats, activation_size), dtype)
    dictionary = dictionary / jnp.linalg.norm(dictionary, axis=-1, keepdims=True)
    params = {
        "dict": dictionary,
        "encoder": (dictionary.T if tied
                    else jax.random.normal(k_enc, (activation_size, n_feats), dtype)),
        "threshold": jnp.zeros((n_feats,), dtype),
        "centering": jnp.zeros((activation_size,), dtype),
    }
    optimizer = optax.adam(lr, eps_root=0.0)
    state = BigSAEState(
        params=params, opt_state=optimizer.init(params),
        c_totals=jnp.zeros((n_feats,), dtype),
        worst_losses=jnp.full((n_worst,), -jnp.inf, dtype),
        worst_vectors=jnp.zeros((n_worst, activation_size), dtype),
        step=jnp.zeros((), jnp.int32), tied=tied)
    return state, optimizer, jnp.asarray(l1_alpha, dtype)


def _sae_loss(params: dict, batch: Array, l1_alpha: Array, tied: bool):
    """(reference: SAE.forward / UntiedSAE.forward, huge_batch_size.py:50-59,
    88-98 — note the untied variant does NOT add centering back to x_hat,
    :91, which we mirror)."""
    normed_dict = params["dict"] / jnp.linalg.norm(params["dict"], axis=-1,
                                                  keepdims=True)
    x_centered = batch - params["centering"]
    c = jax.nn.relu(x_centered @ params["encoder"] + params["threshold"])
    x_hat = c @ normed_dict
    if tied:
        x_hat = x_hat + params["centering"]
    mse_losses = jnp.mean(jnp.square(batch - x_hat), axis=-1)  # per example
    mse = jnp.mean(mse_losses)
    sparsity = l1_alpha * jnp.mean(jnp.sum(jnp.abs(c), axis=-1))
    return mse + sparsity, (mse, sparsity, c, mse_losses)


# auto-mode threshold for the flash kernels: per-device [local_b, local_n]
# codes bytes the autodiff path would have to materialize before auto
# switches to the never-materialize kernels (v5e HBM is 16 GiB; XLA's 2-3
# resident copies of a >=2 GiB codes block start crowding out params/opt
# state and activation slabs)
FUSED_AUTO_CODES_BYTES = 2 * 2**30


def fused_auto_choice(use_fused, fused_possible: bool,
                      local_b: int, local_n: int,
                      codes_itemsize: int = 4) -> bool:
    """The fused-vs-autodiff decision given admissibility: explicit True
    always takes the kernels, explicit False never does; auto takes them
    only when the per-device codes block autodiff would materialize
    (local_b × local_n × codes_itemsize — pass the promoted batch/params
    itemsize for bf16 SAEs) crosses FUSED_AUTO_CODES_BYTES (they run at
    measured parity below it — BENCH_SUITE_TPU.json)."""
    if use_fused is False or not fused_possible:
        return False
    return (use_fused is True
            or local_b * local_n * codes_itemsize >= FUSED_AUTO_CODES_BYTES)


def make_big_sae_step(optimizer: optax.GradientTransformation,
                      l1_alpha: Array, mesh: Optional[Mesh] = None,
                      use_fused: str | bool = "auto",
                      fused_interpret: bool = False,
                      fused_compute_dtype: str = "float32"):
    """Jitted (state, batch) -> (state, metrics). With a mesh, the batch is
    data-sharded; grads reduce via XLA collectives (replacing DDP all-reduce,
    huge_batch_size.py:274,322).

    use_fused: "auto" routes TPU steps through the flash-style kernel pair
    (ops/fused_big_sae.py — codes recomputed per tile, never materialized
    in HBM) whenever VMEM-fitting tiles exist for the PER-DEVICE shapes;
    True fails fast if they don't; False always uses XLA autodiff. With a
    mesh the kernels run per shard under shard_map (features over "model",
    batch over "data" — _sharded_fused_loss_and_grads)."""
    from sparse_coding_tpu.ops.fused_big_sae import (
        fused_big_sae_loss_and_grads,
        pick_big_sae_tiles,
    )

    fused_wanted = use_fused is True or use_fused == "auto"

    def step(state: BigSAEState, batch: Array):
        if mesh is not None:
            # pin the batch to the data axis even if the caller forgot to
            # device_put it — grads then reduce over "data" as documented
            batch = jax.lax.with_sharding_constraint(
                batch, partition.batch_sharding(mesh))
        n, d = state.params["dict"].shape
        # the fused kernels see PER-DEVICE shapes under shard_map: features
        # sharded over "model", batch over "data" — which also requires the
        # global shapes to divide the mesh axes (GSPMD pads for autodiff,
        # shard_map does not)
        divisible = (mesh is None
                     or (batch.shape[0] % mesh.shape["data"] == 0
                         and n % mesh.shape["model"] == 0))
        local_b = (batch.shape[0] // mesh.shape["data"] if mesh is not None
                   else batch.shape[0])
        local_n = n // mesh.shape["model"] if mesh is not None else n
        # shapes are static at trace time, so the path choice re-resolves
        # per compiled batch shape, like ensemble._resolve_step
        # same derivation the kernel's own tile pick uses, so the gate and
        # the inner admission can never disagree
        compute_itemsize = jnp.dtype(fused_compute_dtype).itemsize
        fused_possible = (fused_wanted and divisible
                          and (fused_interpret
                               or jax.default_backend() == "tpu")
                          and pick_big_sae_tiles(
                              local_b, local_n, d,
                              compute_itemsize=compute_itemsize) is not None)
        if use_fused is True and not fused_possible:
            raise ValueError(
                f"use_fused=True but the fused big-SAE step is unavailable "
                f"(backend={jax.default_backend()}, per-device "
                f"batch={local_b}, n={local_n}, d={d} — shapes must divide "
                "the mesh axes and d must be a multiple of 128 with "
                "VMEM-fitting tiles)")
        # auto mode gates on HBM CAPACITY, not bandwidth: measured on a v5e
        # (BENCH_SUITE_TPU.json) XLA autodiff and the flash kernels run at
        # parity (~0.67 MFU) while the codes matrix fits — XLA overlaps its
        # HBM round trips well — so the kernels' win is enabling per-device
        # codes blocks autodiff could not even allocate. Below the threshold
        # auto keeps the (marginally faster, simpler) autodiff path;
        # use_fused=True still forces the kernels at any scale.
        codes_itemsize = jnp.promote_types(
            batch.dtype, state.params["dict"].dtype).itemsize
        fused_ok = fused_auto_choice(use_fused, fused_possible,
                                     local_b, local_n, codes_itemsize)
        if fused_ok:
            fused_fn = (functools.partial(_sharded_fused_loss_and_grads,
                                          mesh=mesh)
                        if mesh is not None else fused_big_sae_loss_and_grads)
            loss, aux, grads = fused_fn(state.params, batch, l1_alpha,
                                        state.tied,
                                        interpret=fused_interpret,
                                        compute_dtype=fused_compute_dtype)
            mse, sparsity = aux["mse"], aux["sparsity"]
            mse_losses = aux["mse_losses"]
            c_totals_delta = aux["c_totals_delta"]
            l0 = aux["l0_mean"]
        else:
            (loss, (mse, sparsity, c, mse_losses)), grads = jax.value_and_grad(
                _sae_loss, has_aux=True)(state.params, batch, l1_alpha,
                                         state.tied)
            c_totals_delta = jnp.sum(c, axis=0)
            l0 = jnp.mean(jnp.sum(c > 0, axis=-1).astype(jnp.float32))
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        # dead-feature tracking (reference: c_totals += c.sum(0), :206;
        # WorstIndices.update streaming top-k, :120-146 — here one fused
        # top_k over the merged buffer)
        c_totals = state.c_totals + c_totals_delta
        all_losses = jnp.concatenate([state.worst_losses, mse_losses])
        all_vectors = jnp.concatenate([state.worst_vectors,
                                       batch.astype(state.worst_vectors.dtype)])
        top_losses, top_idx = jax.lax.top_k(all_losses, state.worst_losses.shape[0])
        worst_vectors = all_vectors[top_idx]

        new_state = state.replace(params=params, opt_state=opt_state,
                                  c_totals=c_totals, worst_losses=top_losses,
                                  worst_vectors=worst_vectors,
                                  step=state.step + 1)
        metrics = {"loss": loss, "mse": mse, "sparsity": sparsity,
                   "l0": l0,
                   "center_norm": jnp.linalg.norm(params["centering"])}
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,))


@jax.jit
def resurrect_dead_features(state: BigSAEState) -> tuple[BigSAEState, Array]:
    """Reinit never-fired features to the worst-reconstructed examples and
    zero their Adam state (reference: huge_batch_size.py:224-250). Pure and
    shape-static: dead features are handled by masking, so this jits even
    though the dead count is data-dependent. Returns (state, n_dead)."""
    params = state.params
    dead = state.c_totals == 0.0  # [n]
    n_dead = jnp.sum(dead)

    # i-th dead feature (in feature order) takes the i-th worst example
    order = jnp.argsort(-state.worst_losses)
    worst_sorted = state.worst_vectors[order]  # [K, d] best-first
    rank = jnp.clip(jnp.cumsum(dead) - 1, 0, worst_sorted.shape[0] - 1)
    candidate = worst_sorted[rank]  # [n, d]

    av_enc_norm = jnp.mean(jnp.linalg.norm(params["encoder"], axis=0))
    new_cols = (candidate * ENCODER_NORM_RATIO / av_enc_norm).T  # [d, n]
    encoder = jnp.where(dead[None, :], new_cols, params["encoder"])

    new_params = dict(params, encoder=encoder)

    # masked Adam-state reset for the dead features' slices
    def reset_moments(moment_tree):
        def reset(name, m):
            if name == "encoder":
                return jnp.where(dead[None, :], 0.0, m)
            if name == "dict":
                return jnp.where(dead[:, None], 0.0, m)
            if name == "threshold":
                return jnp.where(dead, 0.0, m)
            return m
        return {k: reset(k, v) for k, v in moment_tree.items()}

    adam_state = state.opt_state[0]
    adam_state = adam_state._replace(mu=reset_moments(adam_state.mu),
                                     nu=reset_moments(adam_state.nu))
    opt_state = (adam_state,) + tuple(state.opt_state[1:])

    new_state = state.replace(
        params=new_params, opt_state=opt_state,
        c_totals=jnp.zeros_like(state.c_totals),
        worst_losses=jnp.full_like(state.worst_losses, -jnp.inf),
        worst_vectors=jnp.zeros_like(state.worst_vectors))
    return new_state, n_dead


def _sharded_fused_loss_and_grads(params: dict, batch: Array, l1_alpha,
                                  tied: bool, mesh: Mesh,
                                  interpret: bool = False,
                                  compute_dtype: str = "float32"):
    """Mesh-composed fused big-SAE loss/grads: under shard_map each device
    owns n/mesh_model FEATURES (tensor parallel — dict rows, encoder
    columns, thresholds) and B/mesh_data batch rows. Per-shard flash
    kernels compute partial x̂ (psum over "model" completes the decode sum),
    then per-shard backward; grads reduce over "data" only (feature-sharded
    leaves stay local to their shard), the centering grad and scalar
    metrics over both axes. Same global-batch normalization convention as
    ensemble.make_fused_tied_step_sharded."""
    from sparse_coding_tpu.parallel.mesh import compat_shard_map

    from sparse_coding_tpu.ops.fused_big_sae import (
        big_sae_backward,
        big_sae_forward,
        pick_big_sae_tiles,
    )
    from sparse_coding_tpu.ops.fused_sae import normalize_with_vjp

    total_b = batch.shape[0]
    n, d = params["dict"].shape
    tiles = pick_big_sae_tiles(
        total_b // mesh.shape["data"], n // mesh.shape["model"], d,
        compute_itemsize=jnp.dtype(compute_dtype).itemsize)
    if tiles is None:
        raise ValueError(
            f"no VMEM-fitting (batch, feature) tiles for per-device "
            f"batch={total_b // mesh.shape['data']} "
            f"n_feats={n // mesh.shape['model']} d={d}; use the autodiff "
            "path")
    bt, ft = tiles

    def local_fn(p, alpha, local_batch):
        local_batch = local_batch.astype(jnp.float32)
        xc = local_batch - p["centering"]
        partial = big_sae_forward(p, xc, bt, ft, interpret=interpret,
                                  compute_dtype=compute_dtype)
        x_hat = jax.lax.psum(partial, "model")  # decode sums over features
        if tied:
            x_hat = x_hat + p["centering"]
        r = x_hat - local_batch  # replicated over "model"
        # per-row losses leave as an EXPLICITLY replicated [B] array (one
        # all_gather over "data", out_spec P()): under this container's
        # older shard_map, a P("data") output that is merely replicated
        # over "model" (check_rep off) gets re-partitioned by SUMMING over
        # every mesh axis when a downstream op (the worst-loss concat in
        # make_big_sae_step) needs it replicated — each worst-loss entry
        # came back as a sum of ~mesh_size different rows. Replicated-P()
        # outputs ride the same proven path as the psum'd scalars.
        mse_losses = jax.lax.all_gather(jnp.mean(jnp.square(r), axis=-1),
                                        "data", tiled=True)
        mse = jax.lax.psum(jnp.sum(jnp.square(r)), "data") / (total_b * d)
        de, dwn, dt, dctr_enc, c_totals, scal = big_sae_backward(
            p, alpha, xc, r, bt, ft, interpret=interpret,
            total_batch=total_b, compute_dtype=compute_dtype)
        de, dwn, dt, c_totals = jax.lax.psum((de, dwn, dt, c_totals), "data")
        scal = jax.lax.psum(scal, ("model", "data"))
        dctr = jax.lax.psum(dctr_enc, ("model", "data"))
        if tied:
            coef = 2.0 / (total_b * d)
            dctr = dctr + jax.lax.psum(coef * jnp.sum(r, axis=0), "data")
        l1_sum, l0_sum = scal[0], scal[1]
        sparsity = alpha * l1_sum / total_b
        grads = {"dict": normalize_with_vjp(p["dict"], dwn),
                 "encoder": de, "threshold": dt, "centering": dctr}
        aux = {"mse": mse, "sparsity": sparsity,
               "c_totals_delta": c_totals, "mse_losses": mse_losses,
               "l0_mean": l0_sum / total_b}
        return mse + sparsity, aux, grads

    # placement vocabulary from the partition rule layer (§19): the param
    # spec tree resolves from the SAME rule set shard_big_sae places with,
    # so program specs and state placement can never drift
    param_specs = partition.match_partition_rules(
        partition.BIG_SAE_PARAM_RULES, params)
    aux_specs = {"mse": partition.REPLICATED, "sparsity": partition.REPLICATED,
                 "c_totals_delta": partition.MEMBER,
                 "mse_losses": partition.REPLICATED,
                 "l0_mean": partition.REPLICATED}
    grad_specs = dict(param_specs)
    fn = compat_shard_map(local_fn, mesh,
                          in_specs=(param_specs, partition.REPLICATED,
                                    partition.BATCH),
                          out_specs=(partition.REPLICATED, aux_specs,
                                     grad_specs))
    return fn(params, jnp.asarray(l1_alpha, jnp.float32), batch)


def shard_big_sae(state: BigSAEState, mesh: Mesh) -> BigSAEState:
    """Feature-axis tensor parallelism over "model" + replicated small
    leaves, placed through the partition rule layer
    (parallel/partition.py BIG_SAE_STATE_RULES, §19): dict [n, d] →
    ("model", None); encoder [d, n] → (None, "model"); threshold /
    c_totals and the mirrored Adam moments [n] → ("model"); everything
    else replicated. One ``partition.place`` fault-sited device_put."""
    return partition.place_tree(state, mesh, partition.BIG_SAE_STATE_RULES)


class BigSAEDict(ld.LearnedDict):
    """Inference export matching the training objective exactly: encode on
    centered input; the untied objective reconstructs raw x (no uncenter,
    mirroring the reference's UntiedSAE.forward which leaves '+ centering'
    commented out, huge_batch_size.py:91), the tied one adds the center back.
    """

    dictionary: Array  # [n, d]
    encoder: Array  # [d, n]
    threshold: Array  # [n]
    centering: Array  # [d]
    add_center_back: bool = struct.field(pytree_node=False, default=False)

    def get_learned_dict(self) -> Array:
        return ld.normalize_rows(self.dictionary)

    def center(self, x: Array) -> Array:
        return x - self.centering

    def uncenter(self, x: Array) -> Array:
        return x + self.centering if self.add_center_back else x

    def encode(self, x: Array) -> Array:
        return jax.nn.relu(x @ self.encoder + self.threshold)


def to_learned_dict(state: BigSAEState) -> BigSAEDict:
    return BigSAEDict(dictionary=state.params["dict"],
                      encoder=state.params["encoder"],
                      threshold=state.params["threshold"],
                      centering=state.params["centering"],
                      add_center_back=state.tied)


def train_big_sae(cfg, store=None, mesh: Optional[Mesh] = None,
                  logger=None) -> BigSAEState:
    """Chunk-driven training loop (reference: process_main/process_reinit
    loops, huge_batch_size.py:150-335) with periodic resurrection."""
    from sparse_coding_tpu.data.chunk_store import device_prefetch
    from sparse_coding_tpu.data.shard_store import open_store

    # layout-agnostic: a store-level manifest.json opens the sharded
    # reader, anything else the flat ChunkStore. quarantine_corrupt: a
    # scrub-repaired store trains through positional holes (same
    # contract as the ensemble sweep)
    store = store or open_store(cfg.dataset_folder, quarantine_corrupt=True)
    state, optimizer, l1 = init_big_sae(
        jax.random.PRNGKey(cfg.seed), cfg.activation_dim, cfg.n_feats,
        cfg.l1_alpha, lr=cfg.lr)
    if mesh is not None:
        state = shard_big_sae(state, mesh)
    step_fn = make_big_sae_step(optimizer, l1, mesh)

    rng = np.random.default_rng(cfg.seed)
    scan_k = max(1, int(getattr(cfg, "scan_steps", 1)))
    if scan_k > 1:
        # K steps per device program; [K, B, d] windows sharded P(None,
        # "data"). Same update sequence — resurrection and logging move to
        # window boundaries (see BigSAEArgs.scan_steps).
        from sparse_coding_tpu.data.chunk_store import window_stacks

        window_fn = jax.jit(
            lambda s, stack: jax.lax.scan(step_fn, s, stack),
            donate_argnums=(0,))
        sharding = (partition.batch_sharding(mesh, stacked=True)
                    if mesh is not None else None)
    else:
        window_fn = None
        sharding = (partition.batch_sharding(mesh)
                    if mesh is not None else None)
    steps = 0
    last_log = 0
    last_resurrect = 0
    for epoch in range(cfg.n_epochs):
        batches = store.epoch(cfg.batch_size, rng)
        if scan_k > 1:
            batches = window_stacks(batches, scan_k)
        for batch in device_prefetch(batches, sharding):
            if scan_k > 1:
                state, metrics = window_fn(state, batch)
                steps += batch.shape[0]
            else:
                state, metrics = step_fn(state, batch)
                steps += 1
            if logger is not None and steps - last_log >= 100:
                last_log = steps
                if scan_k > 1:
                    # slice the window's last step only when logging — the
                    # slice is its own device dispatch
                    metrics = {k: v[-1] for k, v in metrics.items()}
                # ONE host sync for the whole metrics dict per log window
                # (a float() per key is a device→host round-trip per key,
                # which stalls XLA pipelining — rule host-sync)
                host_metrics = jax.device_get(metrics)
                logger.log({k: float(v) for k, v in host_metrics.items()},
                           step=steps)
            if (cfg.resurrect_every
                    and steps - last_resurrect >= cfg.resurrect_every):
                last_resurrect = steps
                state, n_dead = resurrect_dead_features(state)
                if logger is not None:
                    # single scalar at cfg.resurrect_every cadence, not a
                    # per-step sync
                    logger.log({"n_dead_feats": int(n_dead)}, step=steps)  # lint: allow-host-sync resurrection-cadence scalar read, orders of magnitude rarer than steps
    return state
