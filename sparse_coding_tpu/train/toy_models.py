"""Toy-models replication: SAE recovery of known synthetic dictionaries.

Re-design of the reference's `replicate_toy_models.py` (565 LoC reproducing
the original LessWrong toy-models post, reference :1-5,208-253): generate a
ground-truth sparse dataset, train SAEs at several l1 values in one vmapped
ensemble, report MMCS/representedness vs the true dictionary, and render the
recovery plot. This is also the stage-1 acceptance gate (SURVEY.md §7).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.config import ToyArgs
from sparse_coding_tpu.data.synthetic import RandomDatasetGenerator
from sparse_coding_tpu.ensemble import Ensemble
from sparse_coding_tpu.metrics.core import (
    fraction_variance_unexplained,
    mmcs_to_fixed,
    representedness,
)
from sparse_coding_tpu.models.sae import FunctionalTiedSAE


def run_toy_replication(cfg: ToyArgs, l1_values=None,
                        output_folder: Optional[str] = None) -> list[dict]:
    """Train an l1 ensemble on a toy ground-truth dataset; return per-member
    recovery metrics (reference: replicate_toy_models.py:208-253)."""
    l1_values = list(l1_values) if l1_values is not None else [
        cfg.l1_alpha / 3, cfg.l1_alpha, cfg.l1_alpha * 3]
    key = jax.random.PRNGKey(cfg.seed)
    k_gen, k_init, k_train = jax.random.split(key, 3)
    gen = RandomDatasetGenerator.create(
        k_gen, cfg.activation_dim, cfg.n_ground_truth_features,
        cfg.feature_num_nonzero, cfg.feature_prob_decay,
        correlated=cfg.correlated_components)

    n_dict = int(cfg.n_ground_truth_features * cfg.learned_dict_ratio)
    keys = jax.random.split(k_init, len(l1_values))
    members = [FunctionalTiedSAE.init(k, cfg.activation_dim, n_dict,
                                      l1_alpha=float(l1))
               for k, l1 in zip(keys, l1_values)]
    ens = Ensemble(members, FunctionalTiedSAE, lr=cfg.lr)

    steps = cfg.epochs * cfg.dataset_size // cfg.batch_size
    train_key = k_train
    for _ in range(steps):
        train_key, sub = jax.random.split(train_key)
        ens.step_batch(gen.batch(sub, cfg.batch_size))

    train_key, sub = jax.random.split(train_key)
    eval_batch = gen.batch(sub, 4096)
    results = []
    for ld, l1 in zip(ens.to_learned_dicts(), l1_values):
        results.append({
            "l1_alpha": float(l1),
            "mmcs_to_truth": float(mmcs_to_fixed(ld, gen.feats)),
            "representedness": float(jnp.mean(representedness(gen.feats, ld))),
            "fvu": float(fraction_variance_unexplained(ld, eval_batch)),
        })

    if output_folder is not None:
        import json

        from sparse_coding_tpu.resilience.atomic import atomic_write_text

        out = Path(output_folder)
        out.mkdir(parents=True, exist_ok=True)
        atomic_write_text(out / "toy_recovery.json",
                          json.dumps(results, indent=2))
        _plot_recovery(results, out / "toy_recovery.png")
    return results


def _plot_recovery(results, save_path):
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6, 4))
    l1s = [r["l1_alpha"] for r in results]
    ax.plot(l1s, [r["representedness"] for r in results], marker="o",
            label="representedness")
    ax.plot(l1s, [r["mmcs_to_truth"] for r in results], marker="s",
            label="MMCS to truth")
    ax.plot(l1s, [r["fvu"] for r in results], marker="^", label="FVU")
    ax.set_xscale("log")
    ax.set_xlabel("l1_alpha")
    ax.legend()
    fig.tight_layout()
    fig.savefig(save_path, dpi=150)
    plt.close(fig)


def main(argv=None):
    cfg = ToyArgs.from_cli(argv)
    results = run_toy_replication(cfg, output_folder="toy_output")
    for r in results:
        print(r)


if __name__ == "__main__":
    main()
