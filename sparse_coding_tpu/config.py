"""Typed config/flag system.

Re-designs the reference's dataclass-as-CLI pattern (reference: config.py:7-27,
where `BaseArgs.__post_init__` builds an argparse parser from dataclass fields)
with the same field vocabulary but *no implicit fields*: everything the
reference attaches ad hoc (`cfg.n_repetitions`, `cfg.center_activations`,
read at big_sweep.py:351,359) is declared here explicitly.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass, field, fields
from pathlib import Path
from typing import Any, Optional, Sequence, Type, TypeVar

T = TypeVar("T", bound="BaseArgs")

_PRIMITIVES = (int, float, str, bool)


def _parse_value(raw: str, ftype: Any) -> Any:
    if ftype is bool:
        return raw.lower() in ("1", "true", "t", "yes", "y")
    if ftype in (int, float, str):
        return ftype(raw)
    # lists / optionals / anything else: accept JSON
    return json.loads(raw)


@dataclass
class BaseArgs:
    """Base config: every subclass gets `from_cli()` and `to_dict()` for free."""

    @classmethod
    def from_cli(cls: Type[T], argv: Optional[Sequence[str]] = None) -> T:
        parser = argparse.ArgumentParser(description=cls.__name__)
        for f in fields(cls):
            parser.add_argument(f"--{f.name}", type=str, default=None)
        ns, _ = parser.parse_known_args(argv)
        overrides = {}
        for f in fields(cls):
            raw = getattr(ns, f.name)
            if raw is not None:
                overrides[f.name] = _parse_value(raw, f.type if isinstance(f.type, type) else _field_runtime_type(cls, f.name))
        return cls(**overrides)

    def to_dict(self) -> dict[str, Any]:
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Path):
                v = str(v)
            out[f.name] = v
        return out

    def save(self, path: str | Path) -> None:
        from sparse_coding_tpu.resilience.atomic import atomic_write_text

        Path(path).parent.mkdir(parents=True, exist_ok=True)
        atomic_write_text(path, json.dumps(self.to_dict(), indent=2,
                                           default=str))

    @classmethod
    def load(cls: Type[T], path: str | Path) -> T:
        data = json.loads(Path(path).read_text())
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    def replace(self: T, **kwargs: Any) -> T:
        return dataclasses.replace(self, **kwargs)


def _field_runtime_type(cls: type, name: str) -> Any:
    """Resolve a dataclass field's runtime type from string annotations."""
    import typing

    hints = typing.get_type_hints(cls)
    t = hints.get(name, str)
    origin = typing.get_origin(t)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(t) if a is not type(None)]
        t = args[0] if args else str
    return t if t in _PRIMITIVES else list


# ---------------------------------------------------------------------------
# Workload configs (field vocabulary mirrors reference config.py:29-143)
# ---------------------------------------------------------------------------

LAYER_LOCS = ("residual", "mlp", "attn", "attn_concat", "mlpout")


@dataclass
class DataArgs(BaseArgs):
    """Activation-harvesting / dataset config (reference: config.py TrainArgs
    fields + generate_test_data.py GenTestArgs)."""

    model_name: str = "EleutherAI/pythia-70m-deduped"
    dataset_name: str = "NeelNanda/pile-10k"
    dataset_folder: str = "activation_data"
    layers: list[int] = field(default_factory=lambda: [2])
    layer_loc: str = "residual"
    context_len: int = 256
    model_batch_size: int = 4
    chunk_size_gb: float = 2.0
    n_chunks: int = 1
    skip_chunks: int = 0
    center_dataset: bool = False
    activation_dtype: str = "bfloat16"
    max_docs: Optional[int] = None
    seed: int = 0
    # LM forwards fused per device program during harvesting (lax.scan) —
    # the harvesting twin of EnsembleArgs.scan_steps: at model_batch_size=4
    # through the axon tunnel, per-dispatch overhead (~54 ms) dominates the
    # forward itself; K=8 amortizes it 8x. Results are bit-identical to 1.
    scan_batches: int = 1


@dataclass
class EnsembleArgs(BaseArgs):
    """Ensemble sweep config (reference: config.py EnsembleArgs:54-79 plus
    implicit fields declared explicitly)."""

    output_folder: str = "output"
    dataset_folder: str = "activation_data"
    batch_size: int = 1024
    lr: float = 1e-3
    adam_epsilon: float = 1e-8
    use_wandb: bool = False
    wandb_images: bool = False
    dtype: str = "float32"
    layer: int = 2
    layer_loc: str = "residual"
    tied_ae: bool = False
    seed: int = 0
    learned_dict_ratio: float = 4.0
    n_chunks: int = 10
    # implicit in the reference (big_sweep.py:351,359) — explicit here:
    n_repetitions: int = 1
    center_activations: bool = False
    # TPU additions
    mesh_data: int = 1  # data-parallel axis size (1 = single chip)
    mesh_model: int = 1  # ensemble-parallel axis size
    save_every_chunks: Optional[int] = None  # default: powers of two, like ref
    # full-state checkpoint cadence: every chunk by default (exact resume for
    # small sweeps); raise for big-SAE scale where serializing params+opt
    # state per 2 GB chunk would dominate wall time; <=0 checkpoints only
    # after the final chunk (VERDICT r1 weak#6)
    checkpoint_every_chunks: int = 1
    # activation dtype through host RAM + host→device transfer during
    # training ("float32" | "bfloat16"); params/optimizer stay f32 and the
    # jitted step promotes, so only input precision drops
    train_dtype: str = "float32"
    # "msgpack" (host-gathered, single file — fine for small sweeps) or
    # "orbax" (sharded per-host async writes, restores straight onto the
    # mesh — the right choice at big-SAE/multi-host scale; utils/orbax_ckpt)
    checkpoint_backend: str = "msgpack"
    # >0: capture a jax.profiler device trace of that many training steps
    # (after compile/warmup) into <output_folder>/trace — TensorBoard/XProf
    # readable, the on-hardware tuning loop's first artifact. Captures are
    # crash-safe and bounded (obs/trace.py: tmp-then-atomic finalize; an
    # error or kill mid-capture costs only the trace, never the sweep)
    profile_steps: int = 0
    # device-time perf probe cadence (obs/perf.py, ARCHITECTURE.md §12):
    # every Nth training window is bracketed with block_until_ready timing
    # — measured device wall → train.mfu gauge + the counted
    # perf.roofline_gap predicted-vs-achieved ratio in every run report.
    # Steady state between samples keeps full dispatch pipelining;
    # overhead at the default cadence is within noise (bench_suite.py
    # perf_probe A/B). 0 disables sampling entirely.
    perf_probe_every: int = 32
    # steps fused into one device program via lax.scan (Ensemble.run_steps).
    # Per-dispatch overhead through the axon tunnel measured ~54 ms (r4), so
    # scan_steps=50 turns a dispatch-bound sweep into a compute-bound one —
    # same update sequence, numerically equivalent training (XLA may fuse
    # the scanned program differently at ULP level); logging/profiling
    # granularity becomes per-window and host RAM briefly holds a
    # [scan_steps, batch, d] stack (~200 MB at 50x2048x512 f32)
    scan_steps: int = 1
    # concurrent chunk-decode streams feeding the sweep (data/ingest.py
    # chunk_stream). 0 = auto: bounded by usable cores AND by free host
    # RAM vs decoded chunk size (the pipeline holds up to streams+2
    # decoded chunks resident; auto never exceeds half of available RAM,
    # dropping to the serial reader's two-chunk bound when chunks are
    # huge). 1 pins the foreground single-stream reader with the native
    # 1-slab readahead — also the path a dying stream degrades to when a
    # worker dies mid-epoch
    ingest_streams: int = 0
    # training health guardian (train/guardian.py, docs/ARCHITECTURE.md
    # §16): divergence detection → per-member quarantine → last-good
    # rollback → typed halt. False runs bare (no ledger, no rollback).
    guardian: bool = True
    # quarantined-member fraction that escalates from freezing individual
    # members to rolling the whole sweep back (a systemic incident)
    guardian_member_fraction: float = 0.5
    # total rollbacks before the guardian halts with a typed diagnosis —
    # every rollback quarantines one chunk, so this also bounds how much
    # of the store an unattended run may discard before a human looks
    guardian_rollback_budget: int = 4
    # in-graph anomaly sentinel (ensemble.py §16): per-member finite
    # flags/grad norms in the aux + the non-finite-update freeze. False
    # rebuilds the exact pre-sentinel step programs — the bench A/B knob
    # (guardian_soak measures the sentinel's step overhead against it)
    sentinel: bool = True
    # fused-kernel engine knobs (ensemble.py / ops/roofline.py — ISSUE 11).
    # use_fused: "auto" (roofline admission picks the path per shape,
    # autodiff only when nothing admits), "on" (fail fast if ineligible),
    # "off" (pure XLA autodiff)
    use_fused: str = "auto"
    # pin the kernel path (None = roofline auto): "two_stage" |
    # "train_step" | "two_stage_tiled" | "train_step_tiled" — the
    # bench/tune/fault-drill A/B knob
    fused_path: Optional[str] = None
    # explicit kernel tiles (None = admission picks). fused_feat_tile pins
    # resolution to the feature-axis-TILED kernels (it has no meaning for
    # the untiled ones)
    fused_batch_tile: Optional[int] = None
    fused_feat_tile: Optional[int] = None
    # run the Pallas kernels in interpret mode (CPU tests/drills only —
    # the fault matrix exercises quarantine semantics on the tiled path
    # with this)
    fused_interpret: bool = False


@dataclass
class SyntheticEnsembleArgs(EnsembleArgs):
    """Synthetic-data sweep (reference: config.py SyntheticEnsembleArgs:60-69)."""

    n_ground_truth_features: int = 512
    activation_dim: int = 256
    feature_prob_decay: float = 0.99
    feature_num_nonzero: int = 5
    correlated_components: bool = False
    noise_magnitude_scale: float = 0.0
    dataset_size: int = 200_000


@dataclass
class ToyArgs(BaseArgs):
    """Toy-model replication (reference: config.py ToyArgs:81-110)."""

    n_ground_truth_features: int = 256
    activation_dim: int = 128
    feature_prob_decay: float = 0.99
    feature_num_nonzero: int = 5
    correlated_components: bool = False
    learned_dict_ratio: float = 1.0
    l1_alpha: float = 1e-3
    lr: float = 1e-3
    batch_size: int = 256
    epochs: int = 1
    dataset_size: int = 100_000
    seed: int = 0


@dataclass
class InterpArgs(BaseArgs):
    """Auto-interpretation config (reference: config.py InterpArgs:112-127,
    interpret.py:50-57 constants)."""

    model_name: str = "EleutherAI/pythia-70m-deduped"
    layer: int = 2
    layer_loc: str = "residual"
    learned_dict_path: str = ""
    output_folder: str = "interp_output"
    n_feats_to_explain: int = 10
    fragment_len: int = 64
    n_fragments: int = 5000
    top_k_fragments: int = 10
    n_random_fragments: int = 10
    batch_size: int = 20
    provider: str = "offline"  # offline | openai — no import-time secrets (unlike interpret.py:30-32)
    explainer_model: str = "gpt-4"
    simulator_model: str = "text-davinci-003"
    seed: int = 0
    # fragment batches fused per device program during activation recording
    # (lax.scan; see DataArgs.scan_batches — the same tunnel dispatch-
    # amortization lever, applied to the reference's ~2500-dispatch
    # fragment pass)
    scan_batches: int = 1


@dataclass
class ErasureArgs(BaseArgs):
    """Concept-erasure eval (reference: config.py ErasureArgs:71-79; the
    reference's compute script is missing — see SURVEY.md §2.6 — so this
    framework reconstructs the capability)."""

    model_name: str = "EleutherAI/pythia-410m-deduped"
    layers: list[int] = field(default_factory=lambda: [4])
    layer_loc: str = "residual"
    dict_path: str = ""
    output_folder: str = "erasure_output"
    max_edit_feats: int = 64
    seed: int = 0


@dataclass
class InterpGraphArgs(BaseArgs):
    """Ablation-graph interpretation config (reference: config.py
    InterpGraphArgs:129-136)."""

    model_name: str = "EleutherAI/pythia-70m-deduped"
    layers: list[int] = field(default_factory=lambda: [0, 2])
    layer_loc: str = "residual"
    dict_paths: list[str] = field(default_factory=list)
    output_folder: str = "interp_graph_output"
    n_fragments: int = 64
    fragment_len: int = 32
    positional: bool = False
    seed: int = 0


@dataclass
class InvestigateArgs(BaseArgs):
    """Single-feature investigation config (reference: config.py
    InvestigateArgs:137-143)."""

    model_name: str = "EleutherAI/pythia-70m-deduped"
    layer: int = 2
    layer_loc: str = "residual"
    learned_dict_path: str = ""
    feature_indices: list[int] = field(default_factory=list)
    n_fragments: int = 1000
    fragment_len: int = 64
    output_folder: str = "investigate_output"
    seed: int = 0


@dataclass
class BigSAEArgs(BaseArgs):
    """Large single-SAE trainer (reference: experiments/huge_batch_size.py
    config at :163-175,259-274): big batch, dead-feature resurrection."""

    activation_dim: int = 1024
    n_feats: int = 16384
    l1_alpha: float = 1e-3
    lr: float = 1e-3
    batch_size: int = 65536
    dataset_folder: str = "activation_data"
    output_folder: str = "big_sae_output"
    n_chunks: int = 10
    n_epochs: int = 1
    dead_feature_window: int = 100  # steps with no activation => dead
    resurrect_every: int = 500
    mesh_data: int = 1
    seed: int = 0
    # steps fused per device program (lax.scan) — see EnsembleArgs.scan_steps.
    # Resurrection checks run on window boundaries, so the effective interval
    # rounds up to a multiple of scan_steps.
    scan_steps: int = 1
