"""LM architecture configs with presets for the reference's model zoo
(BASELINE.md: pythia-70m/160m/410m/1.4b-deduped, gpt2-small)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class LMConfig:
    arch: str  # "gptneox" | "gpt2"
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    d_mlp: int
    max_seq_len: int = 2048
    rotary_pct: float = 0.25  # gptneox only
    layernorm_eps: float = 1e-5
    parallel_residual: bool = True  # gptneox only
    eos_token_id: int = 0

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def _pythia(d_model: int, n_layers: int, n_heads: int) -> LMConfig:
    return LMConfig(arch="gptneox", vocab_size=50304, d_model=d_model,
                    n_layers=n_layers, n_heads=n_heads, d_mlp=4 * d_model,
                    max_seq_len=2048, rotary_pct=0.25, eos_token_id=0)


PRESETS: dict[str, LMConfig] = {
    # EleutherAI Pythia family (deduped variants share the architecture)
    "EleutherAI/pythia-70m-deduped": _pythia(512, 6, 8),
    "EleutherAI/pythia-70m": _pythia(512, 6, 8),
    "EleutherAI/pythia-160m-deduped": _pythia(768, 12, 12),
    "EleutherAI/pythia-160m": _pythia(768, 12, 12),
    "EleutherAI/pythia-410m-deduped": _pythia(1024, 24, 16),
    "EleutherAI/pythia-410m": _pythia(1024, 24, 16),
    "EleutherAI/pythia-1b-deduped": _pythia(2048, 16, 8),
    "EleutherAI/pythia-1.4b-deduped": _pythia(2048, 24, 16),
    "EleutherAI/pythia-1.4b": _pythia(2048, 24, 16),
    "gpt2": LMConfig(arch="gpt2", vocab_size=50257, d_model=768, n_layers=12,
                     n_heads=12, d_mlp=3072, max_seq_len=1024,
                     eos_token_id=50256),
    "gpt2-medium": LMConfig(arch="gpt2", vocab_size=50257, d_model=1024,
                            n_layers=24, n_heads=16, d_mlp=4096,
                            max_seq_len=1024, eos_token_id=50256),
}


def get_config(model_name: str) -> LMConfig:
    if model_name not in PRESETS:
        raise KeyError(f"no preset for {model_name!r}; known: {sorted(PRESETS)}")
    return PRESETS[model_name]


def tiny_test_config(arch: str = "gptneox") -> LMConfig:
    """A deterministic micro-model for tests (SURVEY.md §4: replace the
    reference's network-bound integration tests with tiny random-weight
    models)."""
    return LMConfig(arch=arch, vocab_size=128, d_model=32, n_layers=3,
                    n_heads=4, d_mlp=128, max_seq_len=64,
                    eos_token_id=0 if arch == "gptneox" else 127)
