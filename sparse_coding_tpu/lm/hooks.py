"""Hook-point (tap) vocabulary.

Mirrors the reference's hook naming layer (reference:
activation_dataset.py:39-106): a tap is `(layer_loc, layer)` with
layer_loc ∈ {residual, mlp, attn, attn_concat, mlpout}. The reference maps
these to transformer_lens hook strings; here they map to tap keys collected
directly by the pure-JAX forward pass (lm/gptneox.py, lm/gpt2.py).

Semantics (validated against transformer_lens conventions):
- residual:    post-block residual stream            [d_model]
- mlp:         post-activation inside the MLP        [d_mlp]
- attn:        post-block residual stream (the reference aliases "attn" to
               hook_resid_post too, activation_dataset.py:96-100)  [d_model]
- attn_concat: pre-W_O per-head z vectors, heads flattened  [n_heads*d_head]
- mlpout:      MLP branch output before residual add  [d_model]
"""

from __future__ import annotations

from typing import Sequence

LAYER_LOCS = ("residual", "mlp", "attn", "attn_concat", "mlpout")


def check_layer_loc(layer_loc: str) -> None:
    if layer_loc not in LAYER_LOCS:
        raise ValueError(f"layer_loc {layer_loc!r} not in {LAYER_LOCS}")


def get_activation_size(layer_loc: str, cfg) -> int:
    """Width of a tapped activation (reference: activation_dataset.py:39-58)."""
    check_layer_loc(layer_loc)
    if layer_loc in ("residual", "mlpout"):
        return cfg.d_model
    if layer_loc == "mlp":
        return cfg.d_mlp
    return cfg.n_heads * cfg.d_head  # attn, attn_concat


def tap_name(layer: int, layer_loc: str) -> str:
    """Canonical tap key (replaces transformer_lens tensor names,
    reference: activation_dataset.py:69-106)."""
    check_layer_loc(layer_loc)
    return f"{layer_loc}.{layer}"


def parse_tap_name(name: str) -> tuple[str, int]:
    loc, layer = name.rsplit(".", 1)
    check_layer_loc(loc)
    return loc, int(layer)


def taps_for(layers: Sequence[int], layer_loc: str) -> tuple[str, ...]:
    return tuple(tap_name(l, layer_loc) for l in layers)


def max_tap_layer(taps: Sequence[str]) -> int:
    return max(parse_tap_name(t)[1] for t in taps)
