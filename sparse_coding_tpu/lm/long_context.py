"""Sequence-parallel GPT-NeoX forward for long-context harvesting.

Shards the SEQUENCE axis of a forward pass across a mesh axis with
`jax.shard_map`: every device holds S/P tokens, attention is exact full-
sequence causal attention via ring_attention (KV blocks rotate over ICI), and
all other ops (LN, MLP, embeddings) are token-local. This lets activation
harvesting run at context lengths that don't fit one chip — a capability the
reference lacks entirely (contexts capped at 256-2048,
activation_dataset.py:27,516).

Taps come back sequence-sharded and are reassembled by the caller (the
harvest writer consumes [b·s, d] rows, so order within a fragment is
preserved by construction).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparse_coding_tpu.lm.gptneox import (
    _layernorm,
    _mlp,
    _rotary_cos_sin,
    _apply_rotary,
)
from sparse_coding_tpu.lm.model_config import LMConfig
from sparse_coding_tpu.lm.ring_attention import ring_attention

Array = jax.Array

SEQ_AXIS = "data"  # sequence parallelism rides the data axis of the mesh


def _sp_attention(x_ln: Array, layer: dict, cfg: LMConfig, cos: Array,
                  sin: Array, axis_name: str) -> tuple[Array, Array]:
    """Sequence-sharded attention: local qkv projection + ring attention."""
    b, s_local, _ = x_ln.shape
    h, dh = cfg.n_heads, cfg.d_head
    qkv = x_ln @ layer["qkv_w"].T + layer["qkv_b"]
    qkv = qkv.reshape(b, s_local, h, 3 * dh)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    rotary_ndims = int(dh * cfg.rotary_pct)
    q, k = _apply_rotary(q, k, cos, sin, rotary_ndims)
    z = ring_attention(q, k, v, axis_name, scale=dh ** -0.5)
    z_flat = z.reshape(b, s_local, h * dh)
    return z_flat @ layer["dense_w"].T + layer["dense_b"], z_flat


def _sp_forward_local(params: dict, tokens: Array, cfg: LMConfig,
                      taps: Sequence[str], stop_at_layer: Optional[int],
                      axis_name: str):
    """Per-shard body run under shard_map; tokens: [B, S/P]."""
    collected = {}
    s_local = tokens.shape[1]
    shard = jax.lax.axis_index(axis_name)
    offset = shard * s_local

    x = params["embed_in"][tokens]
    rotary_ndims = int(cfg.d_head * cfg.rotary_pct)
    from sparse_coding_tpu.parallel.mesh import compat_axis_size

    total_s = s_local * compat_axis_size(axis_name)
    cos_full, sin_full = _rotary_cos_sin(total_s, rotary_ndims, dtype=x.dtype)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, offset, s_local)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, offset, s_local)

    n_layers = cfg.n_layers if stop_at_layer is None else min(stop_at_layer,
                                                              cfg.n_layers)
    for i in range(n_layers):
        layer = params["layers"][i]
        x_ln1 = _layernorm(x, layer["ln1_w"], layer["ln1_b"], cfg.layernorm_eps)
        attn_out, z_flat = _sp_attention(x_ln1, layer, cfg, cos, sin, axis_name)
        if f"attn_concat.{i}" in taps:
            collected[f"attn_concat.{i}"] = z_flat
        if cfg.parallel_residual:
            x_ln2 = _layernorm(x, layer["ln2_w"], layer["ln2_b"], cfg.layernorm_eps)
            mlp_out, post_act = _mlp(x_ln2, layer)
            if f"mlp.{i}" in taps:
                collected[f"mlp.{i}"] = post_act
            if f"mlpout.{i}" in taps:
                collected[f"mlpout.{i}"] = mlp_out
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            x_ln2 = _layernorm(x, layer["ln2_w"], layer["ln2_b"], cfg.layernorm_eps)
            mlp_out, post_act = _mlp(x_ln2, layer)
            if f"mlp.{i}" in taps:
                collected[f"mlp.{i}"] = post_act
            if f"mlpout.{i}" in taps:
                collected[f"mlpout.{i}"] = mlp_out
            x = x + mlp_out
        if f"residual.{i}" in taps:
            collected[f"residual.{i}"] = x
        if f"attn.{i}" in taps:
            collected[f"attn.{i}"] = x

    if stop_at_layer is not None and stop_at_layer < cfg.n_layers:
        return None, collected
    x = _layernorm(x, params["final_ln_w"], params["final_ln_b"],
                   cfg.layernorm_eps)
    logits = x @ params["embed_out"].T
    return logits, collected


@lru_cache(maxsize=32)
def _sp_program(cfg: LMConfig, mesh: Mesh, taps: tuple,
                stop_at_layer: Optional[int], axis_name: str):
    """Build-and-cache the JITTED shard_map program for one (config, mesh,
    taps) combination. The jit wrapper is load-bearing on TPU: run eagerly,
    shard_map executes its body op by op and every op becomes its own
    XLA compilation — behind the axon tunnel that is hundreds of remote
    compile round-trips and presents as an indefinite hang (measured:
    jitted tiny-NeoX compiles+runs in ~10s where the eager form exceeded a
    5-minute watchdog; scripts/repro_seqpar_hang.py). Caching keeps repeat
    calls from re-tracing through a fresh jit wrapper."""
    body = partial(_sp_forward_local, cfg=cfg, taps=taps,
                   stop_at_layer=stop_at_layer, axis_name=axis_name)
    seq_sharded = P(None, axis_name)
    early_stop = stop_at_layer is not None and stop_at_layer < cfg.n_layers

    from sparse_coding_tpu.parallel.mesh import compat_shard_map

    if early_stop:
        return early_stop, jax.jit(compat_shard_map(
            lambda p, t: body(p, t)[1],  # taps only; logits is None
            mesh, in_specs=(P(), seq_sharded), out_specs=seq_sharded))
    return early_stop, jax.jit(compat_shard_map(
        lambda p, t: body(p, t),
        mesh, in_specs=(P(), seq_sharded),
        out_specs=(seq_sharded, seq_sharded)))


def sequence_parallel_forward(params: dict, tokens: Array, cfg: LMConfig,
                              mesh: Mesh, taps: Sequence[str] = (),
                              stop_at_layer: Optional[int] = None,
                              axis_name: str = SEQ_AXIS):
    """Exact GPT-NeoX forward with the sequence axis sharded over
    mesh[axis_name]. tokens: [B, S] with S divisible by the axis size.
    Returns (logits or None, {tap: [B, S, width]}) with outputs sharded along
    the sequence axis."""
    n_shards = mesh.shape[axis_name]
    if tokens.shape[1] % n_shards != 0:
        raise ValueError(f"sequence length {tokens.shape[1]} not divisible by "
                         f"mesh axis {axis_name}={n_shards}")

    early_stop, fn = _sp_program(cfg, mesh, tuple(taps), stop_at_layer,
                                 axis_name)
    if early_stop:
        return None, fn(params, tokens)
    logits, tapped = fn(params, tokens)
    return logits, tapped
