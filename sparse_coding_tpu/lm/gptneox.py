"""Pure-JAX GPT-NeoX (Pythia) forward pass with activation taps.

Replaces the reference's transformer_lens `run_with_cache` harvesting path
(reference: activation_dataset.py:323-391) and `run_with_hooks` intervention
path (standard_metrics.py:36-53,693-699) with a single jittable function:

    logits, taps = forward(params, tokens, cfg, taps=("residual.2",),
                           stop_at_layer=3, edit=None)

- `taps` collects activations named by lm/hooks.py's vocabulary.
- `stop_at_layer` mirrors `run_with_cache(stop_at_layer=...)`
  (activation_dataset.py:361): later layers are simply not traced.
- `edit=(tap, fn)` applies `fn` to the named activation in-flight — the
  pure-functional form of the reference's hook interventions, used for
  perplexity-under-reconstruction and ablation graphs.

Numerics match HF's GPTNeoXForCausalLM (float32 softmax/LN, exact GeLU,
NeoX-style rotate-half rotary on the leading rotary_pct dims); parity is
tested against transformers' torch implementation on random weights in
tests/test_lm_parity.py.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from sparse_coding_tpu.lm.model_config import LMConfig

Array = jax.Array
EditFn = tuple[str, Callable[[Array], Array]]


def _layernorm(x: Array, w: Array, b: Array, eps: float) -> Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * w + b).astype(x.dtype)


def _rotary_cos_sin(seq_len: int, rotary_ndims: int, dtype=jnp.float32,
                    base: float = 10000.0) -> tuple[Array, Array]:
    inv_freq = 1.0 / (base ** (jnp.arange(0, rotary_ndims, 2, dtype=jnp.float32) / rotary_ndims))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(pos, inv_freq)  # [s, rd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [s, rd]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x: Array) -> Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _apply_rotary(q: Array, k: Array, cos: Array, sin: Array,
                  rotary_ndims: int) -> tuple[Array, Array]:
    # q, k: [b, s, h, dh]; cos/sin: [s, rd] — NeoX rotates the first rd dims
    q_rot, q_pass = q[..., :rotary_ndims], q[..., rotary_ndims:]
    k_rot, k_pass = k[..., :rotary_ndims], k[..., rotary_ndims:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    q_rot = q_rot * cos + _rotate_half(q_rot) * sin
    k_rot = k_rot * cos + _rotate_half(k_rot) * sin
    return (jnp.concatenate([q_rot, q_pass], axis=-1),
            jnp.concatenate([k_rot, k_pass], axis=-1))


def _attention_z(x_ln: Array, layer: dict, cfg: LMConfig,
                 cos: Array, sin: Array) -> Array:
    """Pre-W_O z vectors, heads flattened [b, s, h*dh] (the attn_concat tap
    point). Kept separate from the output projection so edits at this hook
    propagate into the block output."""
    b, s, _ = x_ln.shape
    h, dh = cfg.n_heads, cfg.d_head
    qkv = x_ln @ layer["qkv_w"].T + layer["qkv_b"]  # [b, s, 3d] in HF head-blocked layout
    qkv = qkv.reshape(b, s, h, 3 * dh)
    q, k, v = jnp.split(qkv, 3, axis=-1)  # each [b, s, h, dh]

    rotary_ndims = int(dh * cfg.rotary_pct)
    q, k = _apply_rotary(q, k, cos, sin, rotary_ndims)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / dh ** 0.5
    causal = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(causal, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    z = jnp.einsum("bhqk,bkhd->bqhd", probs, v)  # [b, s, h, dh]
    return z.reshape(b, s, h * dh)


def _mlp_post_act(x_ln: Array, layer: dict) -> Array:
    """Post-activation hidden [b, s, d_mlp] (the mlp tap point), kept
    separate from the down-projection so edits at this hook propagate."""
    h = x_ln @ layer["h_to_4h_w"].T + layer["h_to_4h_b"]
    return jax.nn.gelu(h, approximate=False)  # HF pythia uses exact gelu


def _mlp_out(post_act: Array, layer: dict) -> Array:
    return post_act @ layer["fourh_to_h_w"].T + layer["fourh_to_h_b"]


def _mlp(x_ln: Array, layer: dict) -> tuple[Array, Array]:
    """Returns (mlp branch output [b,s,d], post-activation [b,s,d_mlp])."""
    post_act = _mlp_post_act(x_ln, layer)
    return _mlp_out(post_act, layer), post_act


def forward(
    params: dict,
    tokens: Array,
    cfg: LMConfig,
    taps: Sequence[str] = (),
    stop_at_layer: Optional[int] = None,
    edit: Optional[EditFn] = None,
) -> tuple[Optional[Array], dict[str, Array]]:
    """Run GPT-NeoX; collect `taps`; optionally apply an in-flight edit.

    Returns (logits or None if stopped early, {tap_name: [b, s, width]}).
    """
    taps = tuple(taps)
    collected: dict[str, Array] = {}
    edit_name = edit[0] if edit is not None else None

    def maybe_edit(name: str, value: Array) -> Array:
        if edit_name == name:
            value = edit[1](value)
        if name in taps:
            collected[name] = value
        return value

    x = params["embed_in"][tokens]
    s = tokens.shape[1]
    rotary_ndims = int(cfg.d_head * cfg.rotary_pct)
    cos, sin = _rotary_cos_sin(s, rotary_ndims, dtype=x.dtype)

    n_layers = cfg.n_layers if stop_at_layer is None else min(stop_at_layer, cfg.n_layers)
    for i in range(n_layers):
        layer = params["layers"][i]
        x_ln1 = _layernorm(x, layer["ln1_w"], layer["ln1_b"], cfg.layernorm_eps)
        z_flat = _attention_z(x_ln1, layer, cfg, cos, sin)
        # edit BEFORE the output projection so attn_concat interventions
        # actually reach the residual stream
        z_flat = maybe_edit(f"attn_concat.{i}", z_flat)
        attn_out = z_flat @ layer["dense_w"].T + layer["dense_b"]

        if cfg.parallel_residual:
            x_ln2 = _layernorm(x, layer["ln2_w"], layer["ln2_b"], cfg.layernorm_eps)
            post_act = maybe_edit(f"mlp.{i}", _mlp_post_act(x_ln2, layer))
            mlp_out = maybe_edit(f"mlpout.{i}", _mlp_out(post_act, layer))
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            x_ln2 = _layernorm(x, layer["ln2_w"], layer["ln2_b"], cfg.layernorm_eps)
            post_act = maybe_edit(f"mlp.{i}", _mlp_post_act(x_ln2, layer))
            mlp_out = maybe_edit(f"mlpout.{i}", _mlp_out(post_act, layer))
            x = x + mlp_out

        x = maybe_edit(f"residual.{i}", x)
        # "attn" aliases the post-block residual, as in the reference
        # (activation_dataset.py:96-100)
        x = maybe_edit(f"attn.{i}", x)

    if stop_at_layer is not None and stop_at_layer < cfg.n_layers:
        return None, collected

    x = _layernorm(x, params["final_ln_w"], params["final_ln_b"], cfg.layernorm_eps)
    logits = x @ params["embed_out"].T
    return logits, collected


def init_params(key: Array, cfg: LMConfig, dtype=jnp.float32) -> dict:
    """Random-weight init (for tests and parity checks; real checkpoints come
    from lm/convert.py)."""
    d, v, dm = cfg.d_model, cfg.vocab_size, cfg.d_mlp
    keys = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))

    def norm(k, *shape):
        return 0.02 * jax.random.normal(k, shape, dtype)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1_w": jnp.ones(d, dtype), "ln1_b": jnp.zeros(d, dtype),
            "ln2_w": jnp.ones(d, dtype), "ln2_b": jnp.zeros(d, dtype),
            "qkv_w": norm(next(keys), 3 * d, d), "qkv_b": jnp.zeros(3 * d, dtype),
            "dense_w": norm(next(keys), d, d), "dense_b": jnp.zeros(d, dtype),
            "h_to_4h_w": norm(next(keys), dm, d), "h_to_4h_b": jnp.zeros(dm, dtype),
            "fourh_to_h_w": norm(next(keys), d, dm), "fourh_to_h_b": jnp.zeros(d, dtype),
        })
    return {
        "embed_in": norm(next(keys), v, d),
        "layers": layers,
        "final_ln_w": jnp.ones(d, dtype), "final_ln_b": jnp.zeros(d, dtype),
        "embed_out": norm(next(keys), v, d),
    }
