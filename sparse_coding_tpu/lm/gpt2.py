"""Pure-JAX GPT-2 forward pass with activation taps.

Same tap/edit interface as lm/gptneox.py; covers the reference's GPT-2-small
sweeps (BASELINE.md; reference big_sweep_experiments.py:1239-1269). Serial
residual, learned positional embeddings, tanh-approx GeLU, tied unembedding —
parity-tested against HF's torch GPT2LMHeadModel on random weights.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from sparse_coding_tpu.lm.model_config import LMConfig

Array = jax.Array
EditFn = tuple[str, Callable[[Array], Array]]


def _layernorm(x: Array, w: Array, b: Array, eps: float) -> Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * w + b).astype(x.dtype)


def _attention_z(x_ln: Array, layer: dict, cfg: LMConfig) -> Array:
    """Pre-c_proj z vectors [b, s, h*dh] (the attn_concat tap point), kept
    separate from the output projection so edits at this hook propagate."""
    b, s, d = x_ln.shape
    h, dh = cfg.n_heads, cfg.d_head
    # HF GPT-2 Conv1D: y = x @ W + b with W [d, 3d]; heads blocked q|k|v
    qkv = x_ln @ layer["c_attn_w"] + layer["c_attn_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, h, dh)
    k = k.reshape(b, s, h, dh)
    v = v.reshape(b, s, h, dh)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / dh ** 0.5
    causal = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(causal, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    z = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return z.reshape(b, s, h * dh)


def forward(
    params: dict,
    tokens: Array,
    cfg: LMConfig,
    taps: Sequence[str] = (),
    stop_at_layer: Optional[int] = None,
    edit: Optional[EditFn] = None,
) -> tuple[Optional[Array], dict[str, Array]]:
    taps = tuple(taps)
    collected: dict[str, Array] = {}
    edit_name = edit[0] if edit is not None else None

    def maybe_edit(name: str, value: Array) -> Array:
        if edit_name == name:
            value = edit[1](value)
        if name in taps:
            collected[name] = value
        return value

    b, s = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:s]

    n_layers = cfg.n_layers if stop_at_layer is None else min(stop_at_layer, cfg.n_layers)
    for i in range(n_layers):
        layer = params["layers"][i]
        x_ln1 = _layernorm(x, layer["ln1_w"], layer["ln1_b"], cfg.layernorm_eps)
        z_flat = _attention_z(x_ln1, layer, cfg)
        # edit BEFORE the output projection so attn_concat interventions
        # actually reach the residual stream
        z_flat = maybe_edit(f"attn_concat.{i}", z_flat)
        attn_out = z_flat @ layer["c_proj_w"] + layer["c_proj_b"]
        x = x + attn_out

        x_ln2 = _layernorm(x, layer["ln2_w"], layer["ln2_b"], cfg.layernorm_eps)
        h = x_ln2 @ layer["c_fc_w"] + layer["c_fc_b"]
        post_act = jax.nn.gelu(h, approximate=True)  # gelu_new
        post_act = maybe_edit(f"mlp.{i}", post_act)  # pre-projection: edits propagate
        mlp_out = post_act @ layer["mlp_c_proj_w"] + layer["mlp_c_proj_b"]
        mlp_out = maybe_edit(f"mlpout.{i}", mlp_out)
        x = x + mlp_out

        x = maybe_edit(f"residual.{i}", x)
        x = maybe_edit(f"attn.{i}", x)

    if stop_at_layer is not None and stop_at_layer < cfg.n_layers:
        return None, collected

    x = _layernorm(x, params["final_ln_w"], params["final_ln_b"], cfg.layernorm_eps)
    logits = x @ params["wte"].T  # tied unembedding
    return logits, collected


def init_params(key: Array, cfg: LMConfig, dtype=jnp.float32) -> dict:
    d, v, dm = cfg.d_model, cfg.vocab_size, cfg.d_mlp
    keys = iter(jax.random.split(key, 3 + 4 * cfg.n_layers))

    def norm(k, *shape):
        return 0.02 * jax.random.normal(k, shape, dtype)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1_w": jnp.ones(d, dtype), "ln1_b": jnp.zeros(d, dtype),
            "ln2_w": jnp.ones(d, dtype), "ln2_b": jnp.zeros(d, dtype),
            "c_attn_w": norm(next(keys), d, 3 * d), "c_attn_b": jnp.zeros(3 * d, dtype),
            "c_proj_w": norm(next(keys), d, d), "c_proj_b": jnp.zeros(d, dtype),
            "c_fc_w": norm(next(keys), d, dm), "c_fc_b": jnp.zeros(dm, dtype),
            "mlp_c_proj_w": norm(next(keys), dm, d), "mlp_c_proj_b": jnp.zeros(d, dtype),
        })
    return {
        "wte": norm(next(keys), v, d),
        "wpe": norm(next(keys), cfg.max_seq_len, d),
        "layers": layers,
        "final_ln_w": jnp.ones(d, dtype), "final_ln_b": jnp.zeros(d, dtype),
    }
