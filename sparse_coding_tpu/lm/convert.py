"""HF-checkpoint → JAX param-tree conversion.

Replaces the reference's dependency on transformer_lens's checkpoint loading
(reference: big_sweep.py:28-40 `get_model`): torch state dicts (from local HF
caches or freshly-initialized `transformers` models in tests) are mapped to
the param trees consumed by lm/gptneox.py and lm/gpt2.py. Torch stays on the
host CPU; arrays stream to device lazily.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.lm.model_config import LMConfig, get_config


def _np(t: Any) -> np.ndarray:
    return t.detach().cpu().numpy()


def convert_gptneox_state_dict(sd: dict, cfg: LMConfig, dtype=jnp.float32) -> dict:
    """Map a HF GPTNeoXForCausalLM state dict to our param tree."""
    def g(name):
        return jnp.asarray(_np(sd[name]), dtype)

    prefix = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    layers = []
    for i in range(cfg.n_layers):
        p = f"{prefix}layers.{i}."
        layers.append({
            "ln1_w": g(p + "input_layernorm.weight"),
            "ln1_b": g(p + "input_layernorm.bias"),
            "ln2_w": g(p + "post_attention_layernorm.weight"),
            "ln2_b": g(p + "post_attention_layernorm.bias"),
            "qkv_w": g(p + "attention.query_key_value.weight"),
            "qkv_b": g(p + "attention.query_key_value.bias"),
            "dense_w": g(p + "attention.dense.weight"),
            "dense_b": g(p + "attention.dense.bias"),
            "h_to_4h_w": g(p + "mlp.dense_h_to_4h.weight"),
            "h_to_4h_b": g(p + "mlp.dense_h_to_4h.bias"),
            "fourh_to_h_w": g(p + "mlp.dense_4h_to_h.weight"),
            "fourh_to_h_b": g(p + "mlp.dense_4h_to_h.bias"),
        })
    return {
        "embed_in": g(prefix + "embed_in.weight"),
        "layers": layers,
        "final_ln_w": g(prefix + "final_layer_norm.weight"),
        "final_ln_b": g(prefix + "final_layer_norm.bias"),
        "embed_out": g("embed_out.weight"),
    }


def convert_gpt2_state_dict(sd: dict, cfg: LMConfig, dtype=jnp.float32) -> dict:
    """Map a HF GPT2LMHeadModel state dict to our param tree (HF Conv1D
    weights are already [in, out] — no transpose needed for our x @ W)."""
    def g(name):
        return jnp.asarray(_np(sd[name]), dtype)

    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    layers = []
    for i in range(cfg.n_layers):
        p = f"{prefix}h.{i}."
        layers.append({
            "ln1_w": g(p + "ln_1.weight"), "ln1_b": g(p + "ln_1.bias"),
            "ln2_w": g(p + "ln_2.weight"), "ln2_b": g(p + "ln_2.bias"),
            "c_attn_w": g(p + "attn.c_attn.weight"),
            "c_attn_b": g(p + "attn.c_attn.bias"),
            "c_proj_w": g(p + "attn.c_proj.weight"),
            "c_proj_b": g(p + "attn.c_proj.bias"),
            "c_fc_w": g(p + "mlp.c_fc.weight"), "c_fc_b": g(p + "mlp.c_fc.bias"),
            "mlp_c_proj_w": g(p + "mlp.c_proj.weight"),
            "mlp_c_proj_b": g(p + "mlp.c_proj.bias"),
        })
    return {
        "wte": g(prefix + "wte.weight"),
        "wpe": g(prefix + "wpe.weight"),
        "layers": layers,
        "final_ln_w": g(prefix + "ln_f.weight"),
        "final_ln_b": g(prefix + "ln_f.bias"),
    }


def load_model(model_name: str, dtype=jnp.float32) -> tuple[dict, LMConfig]:
    """Load a pretrained checkpoint via transformers (local cache; the image
    has no network egress, so this requires a pre-populated HF cache) and
    convert. Returns (params, cfg)."""
    cfg = get_config(model_name)
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_name)
    sd = model.state_dict()
    if cfg.arch == "gptneox":
        return convert_gptneox_state_dict(sd, cfg, dtype), cfg
    if cfg.arch == "gpt2":
        return convert_gpt2_state_dict(sd, cfg, dtype), cfg
    raise ValueError(f"unknown arch {cfg.arch}")


def forward_fn(cfg: LMConfig):
    """Dispatch to the right architecture's forward."""
    if cfg.arch == "gptneox":
        from sparse_coding_tpu.lm import gptneox
        return gptneox.forward
    if cfg.arch == "gpt2":
        from sparse_coding_tpu.lm import gpt2
        return gpt2.forward
    raise ValueError(f"unknown arch {cfg.arch}")
