"""Ring attention: causal attention over a sequence-sharded axis.

The reference caps harvesting contexts at 256-2048 tokens and has no
long-context machinery (SURVEY.md §5); this framework makes long-context
harvesting first-class. Sequences shard across a mesh axis; each device holds
a query block and the key/value blocks rotate around the ring via
`jax.lax.ppermute`, with flash-style numerically-stable online-softmax
accumulation — O(S/P) memory per device, full-sequence attention semantics,
and compute/communication overlap left to XLA's scheduler.

Used by lm/long_context.py's sequence-parallel GPT-NeoX forward; correctness
is tested against full attention on the virtual CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

_NEG_INF = -1e30


def _block_attend(q: Array, k: Array, v: Array, q_offset: Array,
                  kv_offset: Array, scale: float,
                  m: Array, l: Array, o: Array):
    """One (q-block × kv-block) flash-attention update.

    q: [B, Sq, H, Dh]; k, v: [B, Sk, H, Dh]; m, l: [B, H, Sq]; o like q.
    Global causal mask: position(q)=q_offset+i attends position(kv)=kv_offset+j
    iff q_pos >= kv_pos."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    sq, sk = q.shape[1], k.shape[1]
    q_pos = q_offset + jnp.arange(sq)
    kv_pos = kv_offset + jnp.arange(sk)
    causal = q_pos[:, None] >= kv_pos[None, :]
    scores = jnp.where(causal[None, None], scores, _NEG_INF)

    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked rows: p is exp(-1e30 - m) ≈ 0 — harmless
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q: Array, k: Array, v: Array, axis_name: str,
                   scale: float | None = None) -> Array:
    """Causal ring attention inside shard_map.

    q, k, v: [B, S_local, H, Dh], sequence-sharded over `axis_name`.
    Returns [B, S_local, H, Dh]."""
    from sparse_coding_tpu.parallel.mesh import compat_axis_size

    n_shards = compat_axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q_offset = my_idx * s_local

    b, sq, h, dh = q.shape
    m = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    o = jnp.zeros((b, sq, h, dh), jnp.float32)

    # step 0: the local block (no rotation needed)
    m, l, o = _block_attend(q, k, v, q_offset, q_offset, scale, m, l, o)

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        # rotate kv to the next device (device i sends to i+1), then attend;
        # rotating first means exactly n_shards-1 transfers total
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kv_idx = (my_idx - step) % n_shards
        kv_offset = kv_idx * s_local
        m, l, o = _block_attend(q, k_blk, v_blk, q_offset, kv_offset, scale,
                                m, l, o)
        return m, l, o, k_blk, v_blk

    m, l, o, _, _ = jax.lax.fori_loop(1, n_shards, body, (m, l, o, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)
