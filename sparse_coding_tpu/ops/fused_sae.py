"""Fused tied-SAE train-step kernel (Pallas/TPU).

The vmapped ensemble step's HBM traffic is dominated by the [batch, n_feats]
code matrix: XLA materializes it in the forward, again for the ReLU mask in
the backward, plus the reconstruction and residual — ~4 round trips of
batch×n_feats×4B per member per step. This kernel computes the tied-SAE loss
AND its exact parameter gradients in ONE pass per (member, batch-tile): codes,
reconstruction, and residual live only in VMEM; HBM sees x once and the
[n, d] gradient accumulators once.

Math (matching models/sae.py FunctionalTiedSAE.loss with identity centering,
reference: sae_ensemble.py:134-162):
    W = E / ‖E‖₂ (rows)        (normalization grads applied OUTSIDE, cheap)
    pre = x Wᵀ + b,  c = relu(pre),  x̂ = c W,  r = x̂ − x
    L = mean(r²) + α·mean(Σ|c|)
    ∂L/∂pre = (2/(B·d) · r Wᵀ + α/B) ⊙ [pre > 0]
    ∂L/∂W   = ∂L/∂preᵀ x  +  2/(B·d) · cᵀ r
    ∂L/∂b   = Σ_batch ∂L/∂pre

Grid: (n_members, n_batch_tiles); batch tiles accumulate into member-indexed
output blocks (TPU sequential grid revisiting). Shapes whose per-member
working set exceeds the VMEM budget — the paper's canonical ratio-16/96
dict shapes — ride the feature-axis-tiled kernels in ops/fused_sae_tiled.py
instead (flash-style blocked recompute); the roofline admission model in
ops/roofline.py picks between the two families per shape. Only shapes with
no admissible tile at all (e.g. a batch no candidate tile divides) fall
back to the jax.grad path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# Mosaic's DEFAULT scoped-VMEM window is only 16 MiB — far below the
# 128 MiB/core of v4/v5e. The kernels request a larger window via
# CompilerParams(vmem_limit_bytes=VMEM_LIMIT_BYTES); the admission model
# below keeps modeled usage under VMEM_BUDGET_BYTES (margin left for
# compiler scratch). Real usage ≈ single-buffered block bytes × 2 because
# Mosaic double-buffers every grid-varying input/output block — measured on
# a v5e: 20.8 MiB actual vs an 11.3 MiB single-buffer estimate at tile 128,
# bench shapes (n=2048, d=512); the model's _DB factor reproduces that.
VMEM_LIMIT_BYTES = 100 * 2**20  # requested scoped-VMEM window per kernel
VMEM_BUDGET_BYTES = 80 * 2**20  # admission ceiling for the modeled set
_DB = 2  # Mosaic double-buffer factor on in/out blocks


def tpu_compiler_params(**kwargs):
    """Version-portable ``pltpu.CompilerParams`` (older jax releases name
    the class ``TPUCompilerParams``; the container's baked toolchain is one
    of those). Single home so every kernel file stays lowerable on either."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)

# batch-tile candidates in preference order (the first VMEM-fitting,
# batch-dividing entry wins); an explicit tile (Ensemble fused_batch_tile /
# tune.py's tile scan) bypasses this list via tile_fits. 1024 leads since
# r11: at the canonical bench shape (n=2048, d=512) it fits with ~36 MiB
# of headroom and halves the grid revisits of tile 512.
PREFERRED_TILES: tuple = (1024, 512, 256, 128, 64)


def _working_set(batch_tile: int, n_feats: int, d: int,
                 batch_itemsize: int = 4, compute_itemsize: int = 4,
                 n_mats: int = 1) -> int:
    f32 = 4
    # a sub-f32 x tile is cast up INSIDE the kernel, so its single f32 copy
    # coexists with the half-width input block; the double-buffered block's
    # saving (_DB × 2 B/elem) offsets the +4 B/elem copy, so bf16 streams
    # never cost extra VMEM. n_mats: [n, d] weight matrices resident per
    # member (1 = tied kernel's W; 2 = untied's E + Wn), each with a grad
    # accumulator block.
    cast_copy = f32 if batch_itemsize < f32 else 0
    extra = 0
    if compute_itemsize < f32:
        # compute_dtype=bf16 materializes bf16 copies of the dot operands:
        # each weight matrix, rc, the c/dpre casts, and xc (free when the
        # input tile already IS the compute dtype — the kernel reuses it)
        extra = (n_feats * d * compute_itemsize * n_mats   # weight casts
                 + batch_tile * d * compute_itemsize       # rc
                 + batch_tile * n_feats * compute_itemsize * 2  # c, dpre
                 + (0 if batch_itemsize == compute_itemsize
                    else batch_tile * d * compute_itemsize))    # xc
    # in/out BLOCKS are double-buffered by Mosaic's pipeline (×_DB);
    # in-kernel intermediates and scratch are single copies
    blocks = (
        n_feats * d * f32 * 2 * n_mats  # weights in + grad accumulators out
        + batch_tile * d * batch_itemsize  # x tile (stream width)
        + n_feats * f32 * 3             # b, db, activity (+tiny losses)
    )
    interm = (
        batch_tile * n_feats * f32 * 2  # c and r@Wᵀ/dpre
        + batch_tile * d * (cast_copy + 2 * f32)  # x upcast, x̂, r
        + extra
        + n_feats * d * f32             # wn scratch (in-kernel normalization)
    )
    return _DB * blocks + interm


def pick_batch_tile(batch: int, n_feats: int, d: int,
                    batch_itemsize: int = 4,
                    compute_itemsize: int = 4,
                    n_mats: int = 1) -> Optional[int]:
    """Largest batch tile (≥64) that fits the VMEM budget and divides the
    batch; None if even 64 doesn't fit. `batch_itemsize` is the on-HBM width
    of the activation stream (2 for bf16); `compute_itemsize` the in-kernel
    dot-operand width (2 for compute_dtype=bfloat16); `n_mats` the per-member
    weight-matrix count (2 for the untied kernel). All in-VMEM cast copies
    are accounted for, so an admitted tile always fits."""
    for tile in PREFERRED_TILES:
        if batch % tile == 0 and _working_set(
                tile, n_feats, d, batch_itemsize,
                compute_itemsize, n_mats) <= VMEM_BUDGET_BYTES:
            return tile
    return None


def tile_fits(batch: int, tile: int, n_feats: int, d: int,
              batch_itemsize: int = 4, compute_itemsize: int = 4,
              n_mats: int = 1) -> bool:
    """Would this EXPLICIT batch tile work for these shapes? (divides the
    batch and fits the VMEM budget — the admission rule pick_batch_tile
    applies to its candidates, exposed for callers forcing a tile.)"""
    return (batch % tile == 0
            and _working_set(tile, n_feats, d, batch_itemsize,
                             compute_itemsize, n_mats) <= VMEM_BUDGET_BYTES)


def fused_supported(n_members: int, batch: int, n_feats: int, d: int) -> bool:
    return pick_batch_tile(batch, n_feats, d) is not None


def kernel_batch_itemsize(dtype) -> int:
    """On-HBM itemsize of the batch AS THE KERNEL SEES IT: bf16 passes
    through half-width; every other dtype is cast to f32 before the kernel
    (fused_tied_sae_loss_and_grads). The single source of truth for VMEM
    admission checks — keep callers (ensemble._resolve_step) on this helper
    so the tile check can never disagree with the kernel's input dtype."""
    return 2 if dtype == jnp.bfloat16 else 4


def _tied_tile_grads(x_in, w, b, alpha, coef_mask=None, *, total_batch: int,
                     d_act: int, compute_dtype):
    """The torch-parity-locked per-tile math of the tied-SAE kernels (loss
    partials + exact grads for one batch tile) — single copy shared by the
    two-stage kernel and the whole-step train kernel.

    coef_mask ([n] 0/1, or None): the masked family's per-member coefficient
    mask (models/sae.py FunctionalMaskedTiedSAE; reference:
    sae_ensemble.py:309-373) — multiplied into the codes and the pre-act
    gradient, exactly autodiff through c = where(mask, relu(pre), 0).

    compute_dtype=bf16 runs every dot on the MXU's native bf16 path
    (~2x f32 throughput) with f32 accumulation — the in-kernel analogue
    of jax.default_matmul_precision("bfloat16"), which does NOT reach
    Pallas dots. Elementwise math and accumulators stay f32. A bf16
    activation stream rides HBM→VMEM half-width and is cast up HERE
    (exact, f32 ⊃ bf16): the f32 copy never exists outside VMEM; bf16
    stream + bf16 compute reuses the input tile as the dot operand."""
    xb = x_in.astype(jnp.float32)
    xc = x_in if x_in.dtype == compute_dtype else xb.astype(compute_dtype)

    pre = jnp.dot(xc, w.T, preferred_element_type=jnp.float32) + b[None, :]
    c = jnp.maximum(pre, 0.0)
    mask = (pre > 0.0).astype(jnp.float32)
    if coef_mask is not None:
        c = c * coef_mask[None, :]
        mask = mask * coef_mask[None, :]
    x_hat = jnp.dot(c.astype(compute_dtype), w,
                    preferred_element_type=jnp.float32)
    r = x_hat - xb

    coef = 2.0 / (total_batch * d_act)
    rc = r.astype(compute_dtype)
    dpre = (coef * jnp.dot(rc, w.T, preferred_element_type=jnp.float32)
            + alpha / total_batch) * mask
    dw = (jnp.dot(dpre.astype(compute_dtype).T, xc,
                  preferred_element_type=jnp.float32)
          + coef * jnp.dot(c.astype(compute_dtype).T, rc,
                           preferred_element_type=jnp.float32))
    db = jnp.sum(dpre, axis=0)
    activity = jnp.sum(mask, axis=0)  # [n] samples activating each feature
    mse_part = jnp.sum(r * r) / (total_batch * d_act)
    l1_part = alpha * jnp.sum(c) / total_batch
    l0_part = jnp.sum(mask) / total_batch
    part = jnp.stack([mse_part, l1_part, l0_part])[None, None, :]
    return dw, db, activity, part


def _kernel(alpha_ref, x_ref, e_ref, b_ref, *rest,
            total_batch: int, d_act: int, compute_dtype, masked: bool = False):
    import jax.experimental.pallas as pl

    if masked:
        mask_ref, dw_ref, db_ref, act_ref, loss_ref, wn_s = rest
    else:
        mask_ref, (dw_ref, db_ref, act_ref, loss_ref, wn_s) = None, rest
    m = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _norm():
        # row-normalize the RAW dictionary into VMEM scratch once per member
        # — the XLA prologue that used to produce w_normed read+wrote the
        # whole [N, n, d] stack in HBM every step
        e = e_ref[0]
        norms = jnp.sqrt(jnp.sum(e * e, axis=-1, keepdims=True))
        wn_s[...] = e / jnp.clip(norms, 1e-8)

    dw, db, activity, part = _tied_tile_grads(
        x_ref[...], wn_s[...].astype(compute_dtype), b_ref[0, 0],
        alpha_ref[m], None if mask_ref is None else mask_ref[0, 0],
        total_batch=total_batch, d_act=d_act, compute_dtype=compute_dtype)

    @pl.when(i == 0)
    def _init():
        dw_ref[0] = dw
        db_ref[0, 0] = db
        act_ref[0, 0] = activity
        loss_ref[...] = part

    @pl.when(i > 0)
    def _acc():
        dw_ref[0] += dw
        db_ref[0, 0] += db
        act_ref[0, 0] += activity
        loss_ref[...] += part


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "interpret", "total_batch",
                                    "compute_dtype"))
def fused_tied_sae_grads(encoder: Array, bias: Array, alphas: Array,
                         batch: Array, batch_tile: int = 256,
                         interpret: bool = False,
                         total_batch: Optional[int] = None,
                         compute_dtype: str = "float32",
                         coef_mask: Optional[Array] = None):
    """All-member losses and gradients wrt (normalized W, bias). The row
    normalization W = E/‖E‖ happens IN-KERNEL (VMEM scratch, once per
    member) — no XLA prologue materializes w_normed in HBM; the returned dW
    is still wrt the normalized W (chain through normalize_with_vjp for dE).

    Args:
      encoder: [N, n, d] RAW (unnormalized) dictionaries.
      bias: [N, n]; alphas: [N] l1 coefficients; batch: [B, d] shared
        (f32 or bf16 — bf16 is read half-width and cast up in VMEM).
      total_batch: loss-normalization denominator; defaults to the batch
        actually passed. A shard_map caller hands each device its LOCAL batch
        slice but the GLOBAL size here, so per-device partial sums psum to
        the exact full-batch loss/grads (see ensemble.make_fused_tied_step_sharded).
      compute_dtype: "float32" (exact) or "bfloat16" — dot operands cast to
        bf16 in VMEM for the MXU's native fast path, f32 accumulation (the
        in-kernel analogue of jax.default_matmul_precision("bfloat16")).
      coef_mask: optional [N, n] per-member coefficient mask (the masked
        family, FunctionalMaskedTiedSAE) — one extra VMEM vector per member.
    Returns:
      (losses {mse [N], l1 [N], l0 [N]}, dW [N, n, d], db [N, n],
       activity [N, n] per-feature active-sample counts)
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    if total_batch is None:
        total_batch = batch.shape[0]
    local_batch = batch.shape[0]  # == total_batch except under shard_map
    n_tiles = local_batch // batch_tile
    assert n_tiles * batch_tile == local_batch

    masked = coef_mask is not None
    kernel = functools.partial(_kernel, total_batch=total_batch, d_act=d,
                               compute_dtype=jnp.dtype(compute_dtype),
                               masked=masked)

    # [N, n] operands ride as [N, 1, n]: a (1, n) 2-D block would violate
    # Mosaic's sublane rule (1 ∤ 8 and 1 != N)
    vec = pl.BlockSpec((1, 1, n_feats), lambda m, i, *_: (m, 0, 0))
    # alphas ride scalar prefetch (SMEM, whole [N] array) — ordinary SMEM
    # blocks can't tile a [N, 1] array per-member (Mosaic requires the
    # sublane dim to match or divide by 8, caught by AOT TPU lowering)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_members, n_tiles),
        in_specs=[
            pl.BlockSpec((batch_tile, d), lambda m, i, *_: (i, 0)),  # x
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),  # E
            vec,  # b
        ] + ([vec] if masked else []),
        out_specs=[
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),
            vec, vec,
            pl.BlockSpec((1, 1, 3), lambda m, i, *_: (m, 0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((n_feats, d), jnp.float32)],  # wn
    )

    # member axis is embarrassingly parallel (each m owns disjoint output
    # blocks); batch-tile axis accumulates into them and must stay
    # sequential. "parallel" lets Mosaic split members across cores on
    # multi-core chips (e.g. v4); harmless on single-core generations.
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))

    operands = [alphas.astype(jnp.float32), batch, encoder,
                bias.reshape(n_members, 1, n_feats)]
    if masked:
        operands.append(coef_mask.astype(jnp.float32)
                        .reshape(n_members, 1, n_feats))
    dw, db, activity, losses = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_members, n_feats, d), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, 3), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=compiler_params,
    )(*operands)

    db = db.reshape(n_members, n_feats)
    activity = activity.reshape(n_members, n_feats)
    losses = losses.reshape(n_members, 3)
    loss_dict = {"mse": losses[:, 0], "l1": losses[:, 1], "l0": losses[:, 2]}
    return loss_dict, dw, db, activity


def prepare_kernel_batch(batch: Array, n_feats: int, d: int,
                         batch_tile: Optional[int], compute_dtype: str,
                         n_mats: int = 1, picker=None) -> tuple[Array, int]:
    """Shared entry contract for every fused-kernel wrapper: bf16 batches
    pass through half-width (cast up per-tile in VMEM), anything else is cast
    to f32; then the batch tile is picked by `picker` (pick_batch_tile for
    the two-stage kernels, pick_train_step_tile for the whole-step kernel)
    unless the caller forced one. One copy of the cast rule so the admission
    checks and the kernels can never disagree."""
    if batch.dtype != jnp.bfloat16:
        batch = batch.astype(jnp.float32)
    if batch_tile is None:
        batch_tile = (picker or pick_batch_tile)(
            batch.shape[0], n_feats, d,
            batch_itemsize=batch.dtype.itemsize,
            compute_itemsize=jnp.dtype(compute_dtype).itemsize, n_mats=n_mats)
        if batch_tile is None:
            raise ValueError(
                f"no VMEM-fitting batch tile for shapes n={n_feats} "
                f"d={d} batch={batch.shape[0]}; use the autodiff path")
    return batch, batch_tile


def normalize_with_vjp(e: Array, dw: Array, eps: float = 1e-8):
    """Chain dL/dW (W = row-normalized E) back to dL/dE:
    dE = (dW − Ŵ·⟨dW, Ŵ⟩_row) / ‖E‖. Cheap [N, n, d] elementwise+reduce,
    left outside the kernel."""
    norms = jnp.clip(jnp.linalg.norm(e, axis=-1, keepdims=True), eps)
    w_hat = e / norms
    radial = jnp.sum(dw * w_hat, axis=-1, keepdims=True)
    return (dw - w_hat * radial) / norms


def fused_tied_sae_loss_and_grads(params_stacked: dict, alphas: Array,
                                  batch: Array, batch_tile: Optional[int] = None,
                                  interpret: bool = False,
                                  total_batch: Optional[int] = None,
                                  compute_dtype: str = "float32",
                                  psum_axis: Optional[str] = None,
                                  coef_mask: Optional[Array] = None):
    """Drop-in producer of (aux-style losses, grads wrt raw stacked params)
    for the ensemble engine's fused path. params_stacked:
    {"encoder": [N, n, d], "encoder_bias": [N, n]}. total_batch: see
    fused_tied_sae_grads (global batch size when called on a shard);
    compute_dtype: bf16 runs the dots on the MXU's native fast path;
    psum_axis: reduce the per-shard partial sums over this mesh axis inside
    the wrapper (shard_map callers — same convention as the untied family);
    coef_mask: [N, n] for masked buckets (FunctionalMaskedTiedSAE)."""
    e = params_stacked["encoder"]
    batch, batch_tile = prepare_kernel_batch(
        batch, e.shape[1], e.shape[2], batch_tile, compute_dtype)
    losses, dw, db, activity = fused_tied_sae_grads(
        e, params_stacked["encoder_bias"], alphas, batch,
        batch_tile=batch_tile, interpret=interpret, total_batch=total_batch,
        compute_dtype=compute_dtype, coef_mask=coef_mask)
    if psum_axis is not None:
        # the normalization VJP below is linear in dw and e is replicated
        # across the data axis, so psum-then-chain equals chain-then-psum
        losses, dw, db, activity = jax.lax.psum((losses, dw, db, activity),
                                                psum_axis)
    grads = {"encoder": normalize_with_vjp(e, dw),
             "encoder_bias": db}
    return losses, grads, activity


# --- fully-fused train-step kernel (tied family) -----------------------------
#
# The two-stage fused path still leaves part of the step to XLA: the dW HBM
# round trip and the Adam + normalization-VJP epilogue (~940 MB of f32 state
# traffic at bench scale; normalization itself moved in-kernel above). This
# kernel runs the ENTIRE training step per member in one Pallas pass:
#   i == 0:       normalize the resident E block into VMEM scratch
#   every tile:   loss + grads, dW accumulated in scratch (never HBM)
#   i == last:    chain dW through the normalization VJP, then apply the
#                 exact optax scale_by_adam update (bias corrections
#                 prefetched) to E and b — moments stream through member-
#                 indexed blocks whose DMA hides under the MXU time of the
#                 NEXT member's tiles.
# HBM per step: x once, params+moments read+written once. No XLA prologue or
# epilogue remains. Single-device only: under shard_map the data-axis psum
# must happen between grads and Adam, so mesh buckets ride the whole-step
# FACTORING instead — grads kernel → psum("data") → the fused Adam/VJP
# epilogue kernels below (ensemble.make_fullfused_step_sharded, ISSUE 15).


def _train_working_set(batch_tile: int, n_feats: int, d: int,
                       batch_itemsize: int = 4, compute_itemsize: int = 4,
                       n_mats: int = 1, moments_itemsize: int = 4) -> int:
    """VMEM model for the train-step kernel: the two-stage model plus the
    moment in/out blocks and the wn/dW scratch, minus the dW output block.
    moments_itemsize=2 models bf16 Adam-moment storage (the blocks ride
    half-width; the in-kernel f32 upcasts are transient VPU registers, not
    resident copies, matching how Mosaic materializes elementwise chains)."""
    f32 = 4
    cast_copy = f32 if batch_itemsize < f32 else 0
    extra = 0
    if compute_itemsize < f32:
        extra = (n_feats * d * compute_itemsize * n_mats
                 + batch_tile * d * compute_itemsize
                 + batch_tile * n_feats * compute_itemsize * 2
                 + (0 if batch_itemsize == compute_itemsize
                    else batch_tile * d * compute_itemsize))
    big = n_feats * d * f32
    big_m = n_feats * d * moments_itemsize
    in_blocks = (n_mats * (big + 2 * big_m)    # params + 2 moments per matrix
                 + batch_tile * d * batch_itemsize
                 + n_feats * f32 * 3)          # b, mu_b, nu_b
    out_blocks = (n_mats * (big + 2 * big_m)   # updated params + moments
                  + n_feats * f32 * 5)         # b', mu_b', nu_b', act, losses
    scratch = (1 + n_mats) * big + n_feats * f32  # wn + grad accum(s) + db
    interm = (batch_tile * n_feats * f32 * 2
              + batch_tile * d * (cast_copy + 2 * f32)
              + extra)
    return _DB * (in_blocks + out_blocks) + scratch + interm


def pick_train_step_tile(batch: int, n_feats: int, d: int,
                         batch_itemsize: int = 4, compute_itemsize: int = 4,
                         n_mats: int = 1,
                         moments_itemsize: int = 4) -> Optional[int]:
    for tile in PREFERRED_TILES:
        if batch % tile == 0 and _train_working_set(
                tile, n_feats, d, batch_itemsize, compute_itemsize,
                n_mats, moments_itemsize) <= VMEM_BUDGET_BYTES:
            return tile
    return None


def train_tile_fits(batch: int, tile: int, n_feats: int, d: int,
                    batch_itemsize: int = 4, compute_itemsize: int = 4,
                    n_mats: int = 1, moments_itemsize: int = 4) -> bool:
    return (batch % tile == 0
            and _train_working_set(tile, n_feats, d, batch_itemsize,
                                   compute_itemsize, n_mats,
                                   moments_itemsize)
            <= VMEM_BUDGET_BYTES)


def _tied_train_kernel(alpha_ref, lr_ref, bc1_ref, bc2_ref,
                       x_ref, e_ref, b_ref, mu_ref, nu_ref, mub_ref, nub_ref,
                       *rest,
                       total_batch: int, d_act: int, compute_dtype,
                       n_tiles: int, b1: float, b2: float, eps: float):
    # plain tied family only — masked buckets (coef_mask) ride the two-stage
    # kernel, which the engine prefers anyway (see ensemble._resolve_step)
    import jax.experimental.pallas as pl

    (e_out, b_out, mu_out, nu_out, mub_out, nub_out,
     act_ref, loss_ref, wn_s, dw_s, db_s) = rest
    m = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _norm():
        e = e_ref[0]
        norms = jnp.sqrt(jnp.sum(e * e, axis=-1, keepdims=True))
        wn_s[...] = e / jnp.clip(norms, 1e-8)

    dw, db_row, activity, part = _tied_tile_grads(
        x_ref[...], wn_s[...].astype(compute_dtype), b_ref[0, 0],
        alpha_ref[m], None,
        total_batch=total_batch, d_act=d_act, compute_dtype=compute_dtype)
    db = db_row[None, :]

    @pl.when(i == 0)
    def _init():
        dw_s[...] = dw
        db_s[...] = db
        act_ref[0, 0] = activity
        loss_ref[...] = part

    @pl.when(i > 0)
    def _acc():
        dw_s[...] += dw
        db_s[...] += db
        act_ref[0, 0] += activity
        loss_ref[...] += part

    @pl.when(i == n_tiles - 1)
    def _update():
        # normalization VJP: dE = (dW − Ŵ·⟨dW, Ŵ⟩_row)/‖E‖ — Ŵ is the wn
        # scratch, ‖E‖ recomputed from the still-resident E block
        e = e_ref[0]
        w_hat = wn_s[...]
        norms = jnp.clip(jnp.sqrt(jnp.sum(e * e, axis=-1, keepdims=True)),
                         1e-8)
        dw_acc = dw_s[...]
        radial = jnp.sum(dw_acc * w_hat, axis=-1, keepdims=True)
        de = (dw_acc - w_hat * radial) / norms
        # exact optax scale_by_adam (eps_root=0) + engine lr application
        lr = lr_ref[m]
        bc1 = bc1_ref[m]
        bc2 = bc2_ref[m]
        # moments may be stored sub-f32 (bf16 halves their HBM traffic —
        # opt-in, Ensemble fused_moments_dtype); math always runs f32
        mu = b1 * mu_ref[0].astype(jnp.float32) + (1.0 - b1) * de
        nu = b2 * nu_ref[0].astype(jnp.float32) + (1.0 - b2) * de * de
        mu_out[0] = mu.astype(mu_out.dtype)
        nu_out[0] = nu.astype(nu_out.dtype)
        e_out[0] = e - lr * (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
        db_acc = db_s[...][0]
        mub = b1 * mub_ref[0, 0] + (1.0 - b1) * db_acc
        nub = b2 * nub_ref[0, 0] + (1.0 - b2) * db_acc * db_acc
        mub_out[0, 0] = mub
        nub_out[0, 0] = nub
        b_out[0, 0] = (b_ref[0, 0]
                       - lr * (mub / bc1) / (jnp.sqrt(nub / bc2) + eps))


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "interpret", "compute_dtype",
                                    "b1", "b2", "eps"))
def fused_tied_sae_train_step(encoder: Array, bias: Array,
                              mu_e: Array, nu_e: Array,
                              mu_b: Array, nu_b: Array,
                              alphas: Array, lrs: Array,
                              bc1: Array, bc2: Array, batch: Array,
                              batch_tile: int = 256, interpret: bool = False,
                              compute_dtype: str = "float32",
                              b1: float = 0.9, b2: float = 0.999,
                              eps: float = 1e-8):
    """One COMPLETE tied-SAE ensemble training step in a single Pallas pass:
    losses + exact grads + normalization VJP + per-member Adam update.

    Args:
      encoder: [N, n, d] RAW (unnormalized) dictionaries; bias [N, n];
      mu_e/nu_e/mu_b/nu_b: optax scale_by_adam moments for encoder and bias;
      alphas/lrs: [N] per-member l1 coefficient and learning rate;
      bc1/bc2: [N] bias corrections 1−β^count_inc, precomputed by the caller
        from the optimizer count so the in-kernel math is exactly optax's.
    Returns:
      (losses {mse, l1, l0} [N], new_encoder, new_bias, new_mu_e, new_nu_e,
       new_mu_b, new_nu_b, activity [N, n])
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    total_batch = batch.shape[0]
    n_tiles = total_batch // batch_tile
    assert n_tiles * batch_tile == total_batch

    kernel = functools.partial(
        _tied_train_kernel, total_batch=total_batch, d_act=d,
        compute_dtype=jnp.dtype(compute_dtype), n_tiles=n_tiles,
        b1=b1, b2=b2, eps=eps)

    big = pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0))
    vec = pl.BlockSpec((1, 1, n_feats), lambda m, i, *_: (m, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_members, n_tiles),
        in_specs=[
            pl.BlockSpec((batch_tile, d), lambda m, i, *_: (i, 0)),  # x
            big, vec,            # E, b
            big, big, vec, vec,  # mu_e, nu_e, mu_b, nu_b
        ],
        out_specs=[
            big, vec,            # E', b'
            big, big, vec, vec,  # mu', nu', mu_b', nu_b'
            vec,                                              # activity
            pl.BlockSpec((1, 1, 3), lambda m, i, *_: (m, 0, 0)),  # losses
        ],
        scratch_shapes=[
            pltpu.VMEM((n_feats, d), jnp.float32),  # wn
            pltpu.VMEM((n_feats, d), jnp.float32),  # dW accumulator
            pltpu.VMEM((1, n_feats), jnp.float32),  # db accumulator
        ],
    )
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))

    vec3 = lambda a: a.reshape(n_members, 1, n_feats)
    e2, b2_, mu2, nu2, mub2, nub2, act, losses = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_members, n_feats, d), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            # moment outputs keep their STORAGE dtype (bf16 when the engine
            # opted into half-width moments; math inside the kernel is f32)
            jax.ShapeDtypeStruct((n_members, n_feats, d), mu_e.dtype),
            jax.ShapeDtypeStruct((n_members, n_feats, d), nu_e.dtype),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, 3), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=compiler_params,
    )(alphas.astype(jnp.float32), lrs.astype(jnp.float32),
      bc1.astype(jnp.float32), bc2.astype(jnp.float32),
      batch, encoder, vec3(bias), mu_e, nu_e, vec3(mu_b), vec3(nu_b))

    losses = losses.reshape(n_members, 3)
    loss_dict = {"mse": losses[:, 0], "l1": losses[:, 1], "l0": losses[:, 2]}
    unvec = lambda a: a.reshape(n_members, n_feats)
    return (loss_dict, e2, unvec(b2_), mu2, nu2, unvec(mub2), unvec(nub2),
            unvec(act))


# --- untied kernel -----------------------------------------------------------

def _untied_kernel(alpha_ref, x_ref, e_ref, d_ref, b_ref,
                   de_ref, dw_ref, db_ref, act_ref, loss_ref, wn_s,
                   *, total_batch: int, d_act: int, compute_dtype):
    """Per-(member, batch-tile) fused loss+grads for the UNTIED SAE
    (models/sae.py FunctionalSAE.loss; reference: sae_ensemble.py:41-56):
        pre = x Eᵀ + b,  c = relu(pre),  x̂ = c Wn   (Wn = decoder normalized)
        L = mean(r²) + α·mean(Σ|c|)           (bias decay added OUTSIDE)
        ∂L/∂pre = (2/(B·d) · r Wnᵀ + α/B) ⊙ [pre > 0]
        ∂L/∂E   = ∂L/∂preᵀ x     ∂L/∂Wn = 2/(B·d) · cᵀ r
        ∂L/∂b   = Σ_batch ∂L/∂pre
    The decoder arrives RAW and is row-normalized into VMEM scratch once per
    member (no XLA prologue in HBM). Same dtype contract as the tied kernel:
    bf16 x streams cast up per-tile, compute_dtype=bf16 runs the dots on the
    MXU bf16 path, f32 accumulation."""
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _norm():
        dec = d_ref[0]
        norms = jnp.sqrt(jnp.sum(dec * dec, axis=-1, keepdims=True))
        wn_s[...] = dec / jnp.clip(norms, 1e-8)

    e = e_ref[0].astype(compute_dtype)   # [n, d] raw encoder
    w = wn_s[...].astype(compute_dtype)  # [n, d] normalized decoder
    x_in = x_ref[...]
    xb = x_in.astype(jnp.float32)
    xc = x_in if x_in.dtype == compute_dtype else xb.astype(compute_dtype)
    b = b_ref[0, 0]
    alpha = alpha_ref[m]

    pre = jnp.dot(xc, e.T, preferred_element_type=jnp.float32) + b[None, :]
    c = jnp.maximum(pre, 0.0)
    x_hat = jnp.dot(c.astype(compute_dtype), w,
                    preferred_element_type=jnp.float32)
    r = x_hat - xb

    coef = 2.0 / (total_batch * d_act)
    mask = (pre > 0.0).astype(jnp.float32)
    rc = r.astype(compute_dtype)
    dpre = (coef * jnp.dot(rc, w.T, preferred_element_type=jnp.float32)
            + alpha / total_batch) * mask
    de = jnp.dot(dpre.astype(compute_dtype).T, xc,
                 preferred_element_type=jnp.float32)
    dw = coef * jnp.dot(c.astype(compute_dtype).T, rc,
                        preferred_element_type=jnp.float32)
    db = jnp.sum(dpre, axis=0)
    activity = jnp.sum(mask, axis=0)
    mse_part = jnp.sum(r * r) / (total_batch * d_act)
    l1_part = alpha * jnp.sum(c) / total_batch
    l0_part = jnp.sum(mask) / total_batch
    part = jnp.stack([mse_part, l1_part, l0_part])[None, None, :]

    @pl.when(i == 0)
    def _init():
        de_ref[0] = de
        dw_ref[0] = dw
        db_ref[0, 0] = db
        act_ref[0, 0] = activity
        loss_ref[...] = part

    @pl.when(i > 0)
    def _acc():
        de_ref[0] += de
        dw_ref[0] += dw
        db_ref[0, 0] += db
        act_ref[0, 0] += activity
        loss_ref[...] += part


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "interpret", "total_batch",
                                    "compute_dtype"))
def fused_untied_sae_grads(encoder: Array, decoder: Array, bias: Array,
                           alphas: Array, batch: Array, batch_tile: int = 256,
                           interpret: bool = False,
                           total_batch: Optional[int] = None,
                           compute_dtype: str = "float32"):
    """All-member losses and gradients wrt (raw encoder E, normalized decoder
    Wn, bias) for the untied SAE. The decoder arrives RAW — row normalization
    happens in-kernel (VMEM scratch), dWn is wrt the normalized matrix (chain
    through normalize_with_vjp for the raw-decoder grad). Same
    grid/blocking/accumulation scheme as fused_tied_sae_grads with a second
    weight matrix resident (VMEM admission uses n_mats=2).
    Returns (losses {mse, l1, l0}, dE, dWn, db, activity)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    if total_batch is None:
        total_batch = batch.shape[0]
    local_batch = batch.shape[0]
    n_tiles = local_batch // batch_tile
    assert n_tiles * batch_tile == local_batch

    kernel = functools.partial(_untied_kernel, total_batch=total_batch,
                               d_act=d, compute_dtype=jnp.dtype(compute_dtype))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_members, n_tiles),
        in_specs=[
            pl.BlockSpec((batch_tile, d), lambda m, i, *_: (i, 0)),      # x
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),   # E
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),   # D raw
            pl.BlockSpec((1, 1, n_feats), lambda m, i, *_: (m, 0, 0)),   # b
        ],
        out_specs=[
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),   # dE
            pl.BlockSpec((1, n_feats, d), lambda m, i, *_: (m, 0, 0)),   # dWn
            pl.BlockSpec((1, 1, n_feats), lambda m, i, *_: (m, 0, 0)),   # db
            pl.BlockSpec((1, 1, n_feats), lambda m, i, *_: (m, 0, 0)),   # act
            pl.BlockSpec((1, 1, 3), lambda m, i, *_: (m, 0, 0)),         # loss
        ],
        scratch_shapes=[pltpu.VMEM((n_feats, d), jnp.float32)],  # wn
    )
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))
    de, dw, db, activity, losses = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_members, n_feats, d), jnp.float32),
            jax.ShapeDtypeStruct((n_members, n_feats, d), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, n_feats), jnp.float32),
            jax.ShapeDtypeStruct((n_members, 1, 3), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=compiler_params,
    )(alphas.astype(jnp.float32), batch, encoder, decoder,
      bias.reshape(n_members, 1, n_feats))

    db = db.reshape(n_members, n_feats)
    activity = activity.reshape(n_members, n_feats)
    losses = losses.reshape(n_members, 3)
    loss_dict = {"mse": losses[:, 0], "l1": losses[:, 1], "l0": losses[:, 2]}
    return loss_dict, de, dw, db, activity


def fused_untied_sae_loss_and_grads(params_stacked: dict, alphas: Array,
                                    bias_decays: Array, batch: Array,
                                    batch_tile: Optional[int] = None,
                                    interpret: bool = False,
                                    total_batch: Optional[int] = None,
                                    compute_dtype: str = "float32",
                                    psum_axis: Optional[str] = None):
    """Fused-path producer for untied FunctionalSAE buckets. params_stacked:
    {"encoder": [N, n, d], "encoder_bias": [N, n], "decoder": [N, n, d]}.
    The bias-decay term (bd·‖b‖₂-safe, models/sae.py _safe_norm) is applied
    OUTSIDE the kernel — cheap [N, n] elementwise — so any bias_decay value
    is exact; losses gains a "bias_decay" entry folded into the total by the
    ensemble tail.

    psum_axis: when called on a data shard inside shard_map, the kernel's
    per-shard partial sums must be psum'd BEFORE the batch-independent
    bias-decay terms are added (psumming those too would scale them by the
    shard count) — pass the data axis name here instead of psumming the
    result at the call site."""
    e = params_stacked["encoder"]
    dec = params_stacked["decoder"]
    batch, batch_tile = prepare_kernel_batch(
        batch, e.shape[1], e.shape[2], batch_tile, compute_dtype, n_mats=2)
    losses, de, dw, db, activity = fused_untied_sae_grads(
        e, dec, params_stacked["encoder_bias"], alphas, batch,
        batch_tile=batch_tile, interpret=interpret, total_batch=total_batch,
        compute_dtype=compute_dtype)
    if psum_axis is not None:
        losses, de, dw, db, activity = jax.lax.psum(
            (losses, de, dw, db, activity), psum_axis)
    bias = params_stacked["encoder_bias"]
    decay_loss, db = untied_bias_decay_terms(bias, bias_decays, db)
    losses["bias_decay"] = decay_loss
    grads = {"encoder": de,
             "encoder_bias": db,
             "decoder": normalize_with_vjp(dec, dw)}
    return losses, grads, activity


def untied_bias_decay_terms(bias: Array, bias_decays: Array,
                            db: Array) -> tuple[Array, Array]:
    """The untied family's bias-decay loss term and its gradient folded into
    db — SINGLE-SOURCED for the two-stage wrapper above and the whole-step
    builder (ensemble.make_fullfused_untied_step). Uses the documented
    safe-norm deviation sqrt(Σb² + eps²) (models/sae.py::_safe_norm,
    PARITY.md) so the gradient at b = 0 is finite; parity locked by
    tests/test_torch_loss_parity.py."""
    safe = jnp.sqrt(jnp.sum(bias * bias, axis=-1) + 1e-8 ** 2)  # [N]
    return bias_decays * safe, db + (bias_decays / safe)[:, None] * bias


# --- fused Adam(+normalization-VJP) epilogue (untied whole-step path) --------
#
# The tied family fuses its whole step into ONE kernel because a single
# [n, d] matrix (+ its two moments) fits VMEM alongside the batch tiles. The
# untied family carries TWO matrices × (param + grad + 2 moments) = 12 big
# blocks — double-buffered that exceeds VMEM at canonical shapes, so its
# whole-step path is two Pallas passes instead: the grads kernel above
# (normalization already in-kernel), then THIS feature-tiled kernel applying
# the normalization VJP and the exact optax-Adam update to both matrices —
# one HBM read and one write per tensor, replacing the XLA epilogue's
# multi-pass traffic. Feature tiles keep VMEM tiny; the d-axis row reductions
# the VJP needs are local to a [ftile, d] block.

EPILOGUE_TILES: tuple = (1024, 512, 256, 128, 64, 32, 16, 8)


def pick_epilogue_tile(n_feats: int, d: int) -> Optional[int]:
    """Largest feature tile that divides n_feats AND fits the epilogue
    kernel's VMEM: 14 grid-varying [ftile, d] f32 blocks (8 in + 6 out),
    double-buffered — ~59 MiB at ftile=1024, d=512, so large-d shapes must
    shrink the tile. None when n_feats has no dividing tile that fits
    (admission falls back to the two-stage path)."""
    f32 = 4
    for t in EPILOGUE_TILES:
        if n_feats % t == 0 and (
                _DB * 14 * t * d * f32 <= VMEM_BUDGET_BYTES):
            return t
    return None


def _adam_vjp_kernel(lr_ref, bc1_ref, bc2_ref,
                     e_ref, de_ref, mue_ref, nue_ref,
                     d_ref, dwn_ref, mud_ref, nud_ref,
                     e_out, mue_out, nue_out, d_out, mud_out, nud_out,
                     un_out,
                     *, b1: float, b2: float, eps: float):
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    f = pl.program_id(1)
    lr = lr_ref[m]
    bc1 = bc1_ref[m]
    bc2 = bc2_ref[m]

    def adam(p, g, mu_in, nu_in):
        # exact optax scale_by_adam (eps_root=0) + the engine's lr scaling;
        # moments may be STORED sub-f32 (bf16 halves their HBM traffic) —
        # the math always runs f32. The update u is formed explicitly so
        # the sentinel epilogue below can fold its squared norm into a
        # per-member reduction; p + u is bitwise p - lr·(...) (IEEE
        # a − b ≡ a + (−b)), so parity with the pre-r11 kernel holds.
        mu = b1 * mu_in.astype(jnp.float32) + (1.0 - b1) * g
        nu = b2 * nu_in.astype(jnp.float32) + (1.0 - b2) * g * g
        u = -lr * (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
        return p + u, mu, nu, u

    e2, mue, nue, ue = adam(e_ref[0], de_ref[0], mue_ref[0], nue_ref[0])
    e_out[0] = e2
    mue_out[0] = mue.astype(mue_out.dtype)
    nue_out[0] = nue.astype(nue_out.dtype)

    # decoder: dL/dWn → dL/dD through the row-normalization VJP, then Adam
    dmat = d_ref[0]
    norms = jnp.clip(jnp.sqrt(jnp.sum(dmat * dmat, axis=-1, keepdims=True)),
                     1e-8)
    w_hat = dmat / norms
    dwn = dwn_ref[0]
    radial = jnp.sum(dwn * w_hat, axis=-1, keepdims=True)
    dd = (dwn - w_hat * radial) / norms
    d2, mud, nud, ud = adam(dmat, dd, mud_ref[0], nud_ref[0])
    d_out[0] = d2
    mud_out[0] = mud.astype(mud_out.dtype)
    nud_out[0] = nud.astype(nud_out.dtype)

    # sentinel epilogue (ISSUE 11): the per-member update squared norm
    # accumulates across feature tiles in VMEM — the whole-step paths'
    # update-norm sentinel input comes out of the kernel for free instead
    # of a second XLA delta-norm pass over the [N, n, d] params in HBM
    part = jnp.stack([jnp.sum(ue * ue) + jnp.sum(ud * ud),
                      jnp.zeros((), jnp.float32)])[None, None, :]

    @pl.when(f == 0)
    def _un_init():
        un_out[...] = part

    @pl.when(f > 0)
    def _un_acc():
        un_out[...] += part


@functools.partial(jax.jit,
                   static_argnames=("ftile", "interpret", "b1", "b2", "eps"))
def fused_adam_vjp_update(encoder: Array, de: Array, mu_e: Array, nu_e: Array,
                          decoder: Array, dwn: Array, mu_d: Array,
                          nu_d: Array, lrs: Array, bc1: Array, bc2: Array,
                          ftile: int, interpret: bool = False,
                          b1: float = 0.9, b2: float = 0.999,
                          eps: float = 1e-8):
    """Fused optimizer epilogue for the untied whole-step path: applies plain
    Adam to the encoder and normalization-VJP + Adam to the raw decoder, all
    matrices feature-tiled ([1, ftile, d] blocks). bc1/bc2: [N] bias
    corrections 1−β^count_inc precomputed by the caller (exactly optax's).
    Returns (new_encoder, new_mu_e, new_nu_e, new_decoder, new_mu_d,
    new_nu_d, update_sq_norm [N]) — the last is the sentinel's per-member
    update squared norm (both matrices), accumulated in the kernel epilogue
    so the whole-step sentinel costs no extra HBM pass (ISSUE 11). Bias
    updates stay outside — [N, n] is negligible traffic."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    assert n_feats % ftile == 0

    kernel = functools.partial(_adam_vjp_kernel, b1=b1, b2=b2, eps=eps)
    blk = pl.BlockSpec((1, ftile, d), lambda m, f, *_: (m, f, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_members, n_feats // ftile),
        in_specs=[blk] * 8,
        out_specs=[blk] * 6 + [
            pl.BlockSpec((1, 1, 2), lambda m, f, *_: (m, 0, 0))],  # unorm
    )
    # the unorm block is shared across the feature axis (every tile
    # accumulates into it), so only the member axis may be parallel
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))

    def big(dtype=jnp.float32):
        return jax.ShapeDtypeStruct((n_members, n_feats, d), dtype)

    # moment outputs keep their STORAGE dtype (bf16 when the engine opted
    # into half-width moments); params always f32
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[big(), big(mu_e.dtype), big(nu_e.dtype),
                   big(), big(mu_d.dtype), big(nu_d.dtype),
                   jax.ShapeDtypeStruct((n_members, 1, 2), jnp.float32)],
        interpret=interpret,
        compiler_params=compiler_params,
    )(lrs.astype(jnp.float32), bc1.astype(jnp.float32),
      bc2.astype(jnp.float32),
      encoder, de, mu_e, nu_e, decoder, dwn, mu_d, nu_d)
    return (*out[:6], out[6][:, 0, 0])


# --- tied feature-tiled Adam(+normalization-VJP) epilogue (r11) --------------
#
# The tied whole-step ONE-kernel path (fused_tied_sae_train_step) needs the
# full [n, d] matrix resident, so exactly the canonical high-ratio shapes it
# matters for don't admit it. The tiled tied whole-step instead runs the
# feature-tiled grads kernels (ops/fused_sae_tiled.py) followed by THIS
# kernel: per [1, ftile, d] block, chain dL/dW (W = row-normalized E)
# through the normalization VJP and apply the exact optax-Adam update — the
# Adam moment blocks stream through VMEM feature-tiled, one HBM read+write
# per tensor, any n_feats.

TIED_EPILOGUE_BLOCKS = 7  # e, dw, mu, nu in + e', mu', nu' out


def pick_tied_epilogue_tile(n_feats: int, d: int) -> Optional[int]:
    """Largest feature tile dividing n_feats whose 7 grid-varying
    [ftile, d] f32 blocks (4 in + 3 out) fit VMEM double-buffered."""
    f32 = 4
    for t in EPILOGUE_TILES:
        if n_feats % t == 0 and (
                _DB * TIED_EPILOGUE_BLOCKS * t * d * f32 <= VMEM_BUDGET_BYTES):
            return t
    return None


def _tied_adam_vjp_kernel(lr_ref, bc1_ref, bc2_ref,
                          e_ref, dw_ref, mu_ref, nu_ref,
                          e_out, mu_out, nu_out, un_out,
                          *, b1: float, b2: float, eps: float):
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    f = pl.program_id(1)
    lr = lr_ref[m]
    bc1 = bc1_ref[m]
    bc2 = bc2_ref[m]

    # normalization VJP per row (rows live wholly inside a [ftile, d]
    # block, so the reduction is tile-local): dE = (dW − Ŵ⟨dW, Ŵ⟩)/‖E‖
    e = e_ref[0]
    norms = jnp.clip(jnp.sqrt(jnp.sum(e * e, axis=-1, keepdims=True)), 1e-8)
    w_hat = e / norms
    dw = dw_ref[0]
    radial = jnp.sum(dw * w_hat, axis=-1, keepdims=True)
    de = (dw - w_hat * radial) / norms
    # exact optax scale_by_adam (eps_root=0) + engine lr; f32 math, moments
    # stored at their own width (bf16 opt-in halves their HBM traffic)
    mu = b1 * mu_ref[0].astype(jnp.float32) + (1.0 - b1) * de
    nu = b2 * nu_ref[0].astype(jnp.float32) + (1.0 - b2) * de * de
    u = -lr * (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
    e_out[0] = e + u
    mu_out[0] = mu.astype(mu_out.dtype)
    nu_out[0] = nu.astype(nu_out.dtype)

    part = jnp.stack([jnp.sum(u * u),
                      jnp.zeros((), jnp.float32)])[None, None, :]

    @pl.when(f == 0)
    def _un_init():
        un_out[...] = part

    @pl.when(f > 0)
    def _un_acc():
        un_out[...] += part


@functools.partial(jax.jit,
                   static_argnames=("ftile", "interpret", "b1", "b2", "eps"))
def fused_tied_adam_vjp_update(encoder: Array, dw: Array,
                               mu_e: Array, nu_e: Array,
                               lrs: Array, bc1: Array, bc2: Array,
                               ftile: int, interpret: bool = False,
                               b1: float = 0.9, b2: float = 0.999,
                               eps: float = 1e-8):
    """Feature-tiled normalization-VJP + exact optax-Adam update for the
    tied family's RAW dictionary (the tiled whole-step path's pass 2).
    Returns (new_encoder, new_mu_e, new_nu_e, update_sq_norm [N]); bias
    updates stay outside (negligible [N, n] traffic)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    assert n_feats % ftile == 0

    kernel = functools.partial(_tied_adam_vjp_kernel, b1=b1, b2=b2, eps=eps)
    blk = pl.BlockSpec((1, ftile, d), lambda m, f, *_: (m, f, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_members, n_feats // ftile),
        in_specs=[blk] * 4,
        out_specs=[blk] * 3 + [
            pl.BlockSpec((1, 1, 2), lambda m, f, *_: (m, 0, 0))],
    )
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))

    def big(dtype=jnp.float32):
        return jax.ShapeDtypeStruct((n_members, n_feats, d), dtype)

    e2, mu2, nu2, un = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[big(), big(mu_e.dtype), big(nu_e.dtype),
                   jax.ShapeDtypeStruct((n_members, 1, 2), jnp.float32)],
        interpret=interpret,
        compiler_params=compiler_params,
    )(lrs.astype(jnp.float32), bc1.astype(jnp.float32),
      bc2.astype(jnp.float32), encoder, dw, mu_e, nu_e)
    return e2, mu2, nu2, un[:, 0, 0]
